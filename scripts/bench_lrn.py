"""LRN implementation shootout on the real chip: XLA banded-matmul
form (ops/lrn.py) vs the single-pass pallas kernels
(ops/lrn_pallas.py), forward and forward+backward, at AlexNet's two
LRN shapes.

Measured verdict (v5e, 2026-07-30, recorded in docs/perf.md): XLA wins
at these shapes — the pallas path stays opt-in
(VELES_TPU_LRN_PALLAS=1).

Timing method: chained calls (each consumes the previous output) ended
by a small data-FETCH of the result.  ``block_until_ready`` does not
reliably block on the tunneled axon platform — timings taken with it
were off by 100x and impossibly above HBM bandwidth; only a
device->host fetch of bytes that depend on the computation is a real
barrier (same lesson as bench.py's honesty contract).
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")


def sync(a):
    return np.asarray(a[(0,) * (a.ndim - 1)])  # data-dependent fetch


def timeit_chain(fn, x, reps=20):
    out = fn(x)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(out)
    sync(out)
    return (time.perf_counter() - t0) / reps


def main():
    import jax
    import jax.numpy as jnp
    from veles_tpu.ops import lrn as lrn_mod
    from veles_tpu.ops import lrn_pallas

    mb = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    u = lrn_mod.LRNormalizer(alpha=1e-4, beta=0.75, n=5, k=2.0)
    gd = lrn_mod.GDLRNormalizer(forward=u)
    rng = np.random.default_rng(0)
    for (h, w, c) in ((55, 55, 96), (27, 27, 256)):
        shape = (mb, h, w, c)
        x = jnp.asarray(rng.standard_normal(shape, np.float32),
                        jnp.bfloat16)

        fwd_xla = jax.jit(
            lambda v: u.apply_fwd({}, v)[0].astype(v.dtype))
        fwd_pl = jax.jit(
            lambda v: lrn_pallas.lrn_fwd(v, u.n, u.k, u.alpha))

        @jax.jit
        def fb_xla(v):
            y, res = u.apply_fwd({}, v)
            ei, _ = gd.backward_from_saved({}, res, y)
            return ei.astype(v.dtype)

        @jax.jit
        def fb_pl(v):
            # feed the forward's OUTPUT to the backward as the error
            # signal: a data dependency, so jit cannot dead-code-
            # eliminate the side-effect-free forward pallas_call (an
            # earlier version discarded y and timed the backward only)
            y = lrn_pallas.lrn_fwd(v, u.n, u.k, u.alpha)
            ei = lrn_pallas.lrn_bwd(v, y, u.n, u.k, u.alpha)
            return ei.astype(v.dtype)

        # numerics check at bf16 tolerance before timing
        d = jnp.max(jnp.abs(fwd_xla(x).astype(jnp.float32)
                            - fwd_pl(x).astype(jnp.float32)))
        assert float(d) < 0.05, float(d)

        for name, f in (("xla fwd", fwd_xla), ("pallas fwd", fwd_pl),
                        ("xla f+b", fb_xla), ("pallas f+b", fb_pl)):
            t = timeit_chain(f, x)
            print(f"{shape} {name:12s}: {t * 1e3:7.3f}ms")


if __name__ == "__main__":
    main()
