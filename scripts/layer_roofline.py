"""Per-layer analytic roofline for the AlexNet fused step (round-4
VERDICT next #3: pin the MFU ceiling or find the next lever).

For every forward layer this prints analytic training FLOPs, a
minimum-HBM-traffic estimate, the implied MXU-time and HBM-time floors
(v5e: 197 TFLOP/s bf16, 819 GB/s), which of the two binds, and the
layer's floor share of the whole step.  The sum of per-layer floors is
the step's analytic lower bound; analytic-train-FLOPs over that bound
is the model's MFU CEILING on this chip — what a perfect scheduler
could reach, independent of XLA.

Traffic model (bf16 activations, f32 master params + momentum),
per sample, assuming perfect elementwise fusion (optimistic — real
XLA materializes more, so the printed ceiling is an upper bound):

- weighted layers (conv/dense): fwd reads in + weights, writes out;
  bwd reads err_out + residual(in) + weights (dgrad) + residual(in)
  again (wgrad), writes err_in; optimizer traffic is
  16 B/param / minibatch (f32 read+write of weights and velocity).
- LRN: fwd reads in, writes out + den residual; bwd reads err_out +
  in + den, writes err_in.
- pooling: fwd read in / write out; bwd read err_out + in, write
  err_in (select-and-scatter needs the argmax source).
- activation/dropout: fused into their producers — zero extra traffic
  (dropout's bf16 mask residual counted: one write + one read).

Usage: python scripts/layer_roofline.py [mb]
"""

from __future__ import annotations

import sys

import numpy as np

sys.path.insert(0, ".")

PEAK_FLOPS = 197e12     # v5e bf16
HBM_BPS = 819e9         # v5e HBM bandwidth
ACT = 2                 # bf16 activation bytes
P32 = 4                 # f32 param bytes


def build_forwards(mb: int):
    from veles_tpu import prng
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.loader.synthetic import SyntheticClassificationLoader
    from veles_tpu.models.alexnet import alexnet_layers
    from veles_tpu.ops.standard_workflow import StandardWorkflow

    prng.seed_all(1234)
    w = StandardWorkflow(
        loader_factory=lambda wf: SyntheticClassificationLoader(
            wf, name="loader", minibatch_size=mb, n_train=mb,
            n_valid=0, shape=(227, 227, 3), n_classes=1000,
            seed=227227),
        layers=alexnet_layers(1000),
        loss_function="softmax",
        decision_config={"max_epochs": 1},
        name="RooflineShapes")
    w.initialize(device=NumpyDevice())   # shape resolution only
    return w.forwards


def layer_rows(forwards, mb: int):
    from veles_tpu import profiling

    rows = []
    for i, u in enumerate(forwards):
        kind = type(u).__name__
        fwd_flops = profiling.forward_flops_per_sample(u)
        weighted = profiling.unit_has_weights(u)
        train_flops = fwd_flops * (3.0 if weighted else 2.0)
        in_b = int(np.prod(u.input.shape[1:])) * ACT
        out_b = int(np.prod(u.output.shape[1:])) * ACT
        params = (int(np.prod(u.weights.shape)) if weighted else 0) + \
            (int(np.prod(u.bias.shape))
             if weighted and u.bias else 0)
        w_b = params * ACT              # bf16 cast the step computes in
        first = i == 0                  # chain head skips err_input
        if weighted:
            # fwd: in + weights(bf16) + out; bwd: err_out + in (dgrad
            # src) + weights + in again (wgrad) + err_in write.  ALL
            # weight traffic amortizes over the minibatch: one batched
            # matmul reads the weights once for mb samples.  Optimizer
            # traffic is f32 read+write of weights and velocity
            # (16 B/param), also once per minibatch.
            wpm = w_b / mb
            bytes_s = (in_b + wpm + out_b
                       + out_b + in_b + wpm + in_b
                       + (0 if first else in_b)
                       + 16.0 * params / mb)
        elif "LRN" in kind:
            bytes_s = (in_b + out_b + out_b * 2            # fwd + den
                       + out_b + in_b + out_b * 2 + in_b)  # bwd
        elif "Pooling" in kind:
            bytes_s = in_b + out_b + out_b + in_b + in_b
        elif "Dropout" in kind:
            bytes_s = out_b * 2                            # mask w+r
        else:                                              # activation
            bytes_s = 0.0
        # MXU time only for matmul-family work; VPU elementwise is
        # bandwidth-modelled, not FLOPs-modelled
        mxu_flops = train_flops if weighted else 0.0
        if "LRN" in kind:   # banded matmul rides the MXU
            mxu_flops = train_flops
        t_mxu = mxu_flops / PEAK_FLOPS
        t_hbm = bytes_s / HBM_BPS
        rows.append({
            "name": u.name, "kind": kind,
            "out": tuple(int(s) for s in u.output.shape[1:]),
            "params": params,
            "train_gflops": train_flops / 1e9,
            "mb_bytes": bytes_s / 2 ** 20,
            "t_mxu_us": t_mxu * 1e6,
            "t_hbm_us": t_hbm * 1e6,
            "bound": "mxu" if t_mxu >= t_hbm else "hbm",
            "floor_us": max(t_mxu, t_hbm) * 1e6,
        })
    return rows


def main():
    mb = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    forwards = build_forwards(mb)
    rows = layer_rows(forwards, mb)
    total_floor = sum(r["floor_us"] for r in rows)
    total_flops = sum(r["train_gflops"] for r in rows)
    print(f"# per-sample, mb={mb}; floors vs v5e peaks "
          f"(197 TF bf16, 819 GB/s)")
    hdr = (f"{'layer':<22}{'out':<16}{'tGFLOP':>8}{'MB':>7}"
           f"{'t_mxu':>8}{'t_hbm':>8}{'bound':>6}{'floor':>8}"
           f"{'share':>7}")
    print(hdr)
    for r in rows:
        print(f"{r['name']:<22}{str(r['out']):<16}"
              f"{r['train_gflops']:>8.3f}{r['mb_bytes']:>7.2f}"
              f"{r['t_mxu_us']:>8.2f}{r['t_hbm_us']:>8.2f}"
              f"{r['bound']:>6}{r['floor_us']:>8.2f}"
              f"{100 * r['floor_us'] / total_floor:>6.1f}%")
    ceiling = total_flops * 1e9 / PEAK_FLOPS / (total_floor * 1e-6)
    print(f"\ntotal: {total_flops:.3f} train GFLOP/sample, "
          f"floor {total_floor:.1f} us/sample "
          f"-> analytic MFU ceiling {100 * ceiling:.1f}%")
    if mb == 512:
        # the round-5 measured reference point at this exact config
        # (bench.py mb=512 ss=8, real chip) — only meaningful against
        # mb=512 floors
        print(f"measured at mb=512 (round-5 bench): ~14100 img/s = "
              f"~70.9 us/sample -> ~48.9% MFU; gap to floor = "
              f"{70.9 / total_floor:.2f}x")


if __name__ == "__main__":
    main()
