"""Per-layer analytic roofline for the AlexNet fused step (round-4
VERDICT next #3: pin the MFU ceiling or find the next lever).

For every forward layer this prints analytic training FLOPs, a
minimum-HBM-traffic estimate, the implied MXU-time and HBM-time floors
(v5e: 197 TFLOP/s bf16, 819 GB/s), which of the two binds, and the
layer's floor share of the whole step.  The sum of per-layer floors is
the step's analytic lower bound; analytic-train-FLOPs over that bound
is the model's MFU CEILING on this chip — what a perfect scheduler
could reach, independent of XLA.

Traffic model (bf16 activations, f32 master params + momentum),
per sample, assuming perfect elementwise fusion (optimistic — real
XLA materializes more, so the printed ceiling is an upper bound):

- weighted layers (conv/dense): fwd reads in + weights, writes out;
  bwd reads err_out + residual(in) + weights (dgrad) + residual(in)
  again (wgrad), writes err_in; optimizer traffic is
  16 B/param / minibatch (f32 read+write of weights and velocity).
- LRN: fwd reads in, writes out + den residual; bwd reads err_out +
  in + den, writes err_in.
- pooling: fwd read in / write out; bwd read err_out + in, write
  err_in (select-and-scatter needs the argmax source).
- activation/dropout: fused into their producers — zero extra traffic
  (dropout's bf16 mask residual counted: one write + one read).

Usage: python scripts/layer_roofline.py [mb] [--measure] [--iters K]

``--measure`` (round-5 VERDICT next #3 — finish the ceiling proof):
runs each AlexNet conv's fwd+bwd ALONE on the default jax device at
the same shapes/dtypes the fused step uses (bf16 compute on TPU, f32
master params, per-iteration param carry inside a lax.scan so XLA
cannot hoist the loop-invariant work) and prints measured us/sample
next to the analytic floor — per-layer MEASURED MXU efficiency
replacing the previously inferred ~62% residual in docs/perf.md.
"""

from __future__ import annotations

import sys

import numpy as np

sys.path.insert(0, ".")

PEAK_FLOPS = 197e12     # v5e bf16
HBM_BPS = 819e9         # v5e HBM bandwidth
ACT = 2                 # bf16 activation bytes
P32 = 4                 # f32 param bytes


def build_workflow(mb: int):
    from veles_tpu import prng
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.loader.synthetic import SyntheticClassificationLoader
    from veles_tpu.models.alexnet import alexnet_layers
    from veles_tpu.ops.standard_workflow import StandardWorkflow

    prng.seed_all(1234)
    w = StandardWorkflow(
        loader_factory=lambda wf: SyntheticClassificationLoader(
            wf, name="loader", minibatch_size=mb, n_train=mb,
            n_valid=0, shape=(227, 227, 3), n_classes=1000,
            seed=227227),
        layers=alexnet_layers(1000),
        loss_function="softmax",
        decision_config={"max_epochs": 1},
        name="RooflineShapes")
    w.initialize(device=NumpyDevice())   # shape resolution only
    return w


def build_forwards(mb: int):
    return build_workflow(mb).forwards


def layer_rows(forwards, mb: int):
    from veles_tpu import profiling

    rows = []
    for i, u in enumerate(forwards):
        kind = type(u).__name__
        fwd_flops = profiling.forward_flops_per_sample(u)
        weighted = profiling.unit_has_weights(u)
        train_flops = fwd_flops * (3.0 if weighted else 2.0)
        in_b = int(np.prod(u.input.shape[1:])) * ACT
        out_b = int(np.prod(u.output.shape[1:])) * ACT
        params = (int(np.prod(u.weights.shape)) if weighted else 0) + \
            (int(np.prod(u.bias.shape))
             if weighted and u.bias else 0)
        w_b = params * ACT              # bf16 cast the step computes in
        first = i == 0                  # chain head skips err_input
        if weighted:
            # fwd: in + weights(bf16) + out; bwd: err_out + in (dgrad
            # src) + weights + in again (wgrad) + err_in write.  ALL
            # weight traffic amortizes over the minibatch: one batched
            # matmul reads the weights once for mb samples.  Optimizer
            # traffic is f32 read+write of weights and velocity
            # (16 B/param), also once per minibatch.
            wpm = w_b / mb
            bytes_s = (in_b + wpm + out_b
                       + out_b + in_b + wpm + in_b
                       + (0 if first else in_b)
                       + 16.0 * params / mb)
        elif "LRN" in kind:
            bytes_s = (in_b + out_b + out_b * 2            # fwd + den
                       + out_b + in_b + out_b * 2 + in_b)  # bwd
        elif "Pooling" in kind:
            bytes_s = in_b + out_b + out_b + in_b + in_b
        elif "Dropout" in kind:
            bytes_s = out_b * 2                            # mask w+r
        else:                                              # activation
            bytes_s = 0.0
        # MXU time only for matmul-family work; VPU elementwise is
        # bandwidth-modelled, not FLOPs-modelled
        mxu_flops = train_flops if weighted else 0.0
        if "LRN" in kind:   # banded matmul rides the MXU
            mxu_flops = train_flops
        t_mxu = mxu_flops / PEAK_FLOPS
        t_hbm = bytes_s / HBM_BPS
        rows.append({
            "name": u.name, "kind": kind,
            "out": tuple(int(s) for s in u.output.shape[1:]),
            "params": params,
            "train_gflops": train_flops / 1e9,
            "mb_bytes": bytes_s / 2 ** 20,
            "t_mxu_us": t_mxu * 1e6,
            "t_hbm_us": t_hbm * 1e6,
            "bound": "mxu" if t_mxu >= t_hbm else "hbm",
            "floor_us": max(t_mxu, t_hbm) * 1e6,
        })
    return rows


def measure_conv_layers(w, rows, mb: int, iters: int = 8,
                        repeats: int = 3):
    """Each conv's fwd+bwd ALONE on the device, scanned.

    The scan carries the PARAMS (a tiny SGD step per iteration, like
    the fused trace) so the per-iteration work has a genuine data
    dependency — a loop-invariant fwd+bwd would be hoisted out of the
    scan and the timing would measure one iteration no matter what
    ``iters`` says.  The timing barrier is a host fetch of the updated
    bias (bytes-tiny, data-dependent on every iteration).  Chain-head
    convs skip err_input exactly like the production step
    (need_err_input=False), so conv1's number excludes the dgrad the
    real step never computes.
    """
    import time

    import jax
    import jax.numpy as jnp
    from jax import lax

    from veles_tpu.backends import make_device
    from veles_tpu.engine import core as engine_core

    device = make_device("auto")
    if not device.is_jax:
        raise SystemExit("--measure needs a jax device (TPU/XLA:CPU)")
    cd = jnp.dtype(device.compute_dtype)
    mixed = cd != jnp.float32
    floor_by_name = {r["name"]: r for r in rows}
    out = []
    for i, (u, gd) in enumerate(zip(w.forwards, w.gds)):
        if "Conv" not in type(u).__name__ or gd is None:
            continue
        first = i == 0 and gd.can_skip_err_input

        def cast(tree):
            if not mixed:
                return tree
            return jax.tree_util.tree_map(
                lambda a: a.astype(cd) if a.dtype == jnp.float32
                else a, tree)

        def step(params, x, _u=u, _gd=gd, _first=first, _cast=cast):
            def body(p, _):
                cp = _cast(p)
                y, res = _u.apply_fwd(cp, x, rng=None, train=True)
                err = (y * jnp.asarray(1e-3, y.dtype))  # dep chain
                if _first:
                    _, grads = _gd.backward_from_saved(
                        cp, res, err, need_err_input=False)
                else:
                    _, grads = _gd.backward_from_saved(cp, res, err)
                p = {k: p[k] - 1e-6 * grads[k].astype(jnp.float32)
                     for k in p}
                return p, None
            params, _ = lax.scan(body, params, None, length=iters)
            return params

        fn = engine_core.donating_jit(step, donate=(0,))
        params = {k: device.put(np.asarray(v, np.float32))
                  for k, v in u.gather_params().items()}
        x_host = np.random.default_rng(5).standard_normal(
            (mb,) + tuple(u.input.shape[1:])).astype(np.float32)
        x = device.put(x_host.astype(cd) if mixed else x_host)
        params = fn(params, x)               # compile + warmup
        np.asarray(params["bias"])           # drain
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            params = fn(params, x)
            np.asarray(params["bias"])       # the honest barrier
            times.append(time.perf_counter() - t0)
        us = float(np.median(times)) / (iters * mb) * 1e6
        floor = floor_by_name[u.name]
        out.append({
            "name": u.name,
            "floor_us": floor["floor_us"],
            "t_mxu_us": floor["t_mxu_us"],
            "measured_us": us,
            "efficiency": floor["floor_us"] / us if us > 0 else 0.0,
        })
    return out


def print_measured(measured, device_kind: str):
    print(f"\n# measured per-conv fwd+bwd, isolated, scanned "
          f"({device_kind}); efficiency = analytic floor / measured")
    print(f"{'layer':<22}{'floor_us':>10}{'measured_us':>13}"
          f"{'efficiency':>12}")
    for r in measured:
        print(f"{r['name']:<22}{r['floor_us']:>10.2f}"
              f"{r['measured_us']:>13.2f}"
              f"{100 * r['efficiency']:>11.1f}%")
    tot_floor = sum(r["floor_us"] for r in measured)
    tot_meas = sum(r["measured_us"] for r in measured)
    print(f"{'all convs':<22}{tot_floor:>10.2f}{tot_meas:>13.2f}"
          f"{100 * tot_floor / tot_meas:>11.1f}%")


def main():
    measure, iters, positional = False, 8, []
    argv = iter(sys.argv[1:])
    for a in argv:
        if a == "--measure":
            measure = True
        elif a == "--iters":
            iters = int(next(argv))
        else:
            positional.append(a)
    mb = int(positional[0]) if positional else 512
    w = build_workflow(mb)
    forwards = w.forwards
    rows = layer_rows(forwards, mb)
    total_floor = sum(r["floor_us"] for r in rows)
    total_flops = sum(r["train_gflops"] for r in rows)
    print(f"# per-sample, mb={mb}; floors vs v5e peaks "
          f"(197 TF bf16, 819 GB/s)")
    hdr = (f"{'layer':<22}{'out':<16}{'tGFLOP':>8}{'MB':>7}"
           f"{'t_mxu':>8}{'t_hbm':>8}{'bound':>6}{'floor':>8}"
           f"{'share':>7}")
    print(hdr)
    for r in rows:
        print(f"{r['name']:<22}{str(r['out']):<16}"
              f"{r['train_gflops']:>8.3f}{r['mb_bytes']:>7.2f}"
              f"{r['t_mxu_us']:>8.2f}{r['t_hbm_us']:>8.2f}"
              f"{r['bound']:>6}{r['floor_us']:>8.2f}"
              f"{100 * r['floor_us'] / total_floor:>6.1f}%")
    ceiling = total_flops * 1e9 / PEAK_FLOPS / (total_floor * 1e-6)
    print(f"\ntotal: {total_flops:.3f} train GFLOP/sample, "
          f"floor {total_floor:.1f} us/sample "
          f"-> analytic MFU ceiling {100 * ceiling:.1f}%")
    if mb == 512:
        # the round-5 measured reference point at this exact config
        # (bench.py mb=512 ss=8, real chip) — only meaningful against
        # mb=512 floors
        print(f"measured at mb=512 (round-5 bench): ~14100 img/s = "
              f"~70.9 us/sample -> ~48.9% MFU; gap to floor = "
              f"{70.9 / total_floor:.2f}x")
    if measure:
        from veles_tpu.backends import make_device
        measured = measure_conv_layers(w, rows, mb, iters=iters)
        kind = getattr(make_device("auto").jax_device, "device_kind",
                       "cpu")
        print_measured(measured, kind)


if __name__ == "__main__":
    main()
