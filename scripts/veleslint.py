#!/usr/bin/env python3
"""Run veleslint (veles_tpu/analysis) from a source checkout.

Usage::

    python scripts/veleslint.py                  # full-repo scan
    python scripts/veleslint.py --rule atomic-write
    python scripts/veleslint.py --sync-docs      # regen knob table
    python scripts/veleslint.py --write-baseline

See docs/guide.md section 10 for the rule catalog, waiver syntax, and
the baseline workflow.  The installed console entry point
(``veleslint``) is the same program.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from veles_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
