"""Analytic data-parallel scaling model for the v5e-8 north star.

BASELINE.json north-star #2 asks for >=6x scaling on a v5e-8 slice.
This environment exposes ONE chip, so multi-chip throughput cannot be
measured; what CAN be pinned honestly is the communication math the
claim rests on, fed by measured single-chip numbers:

  per-chip step time        T_c   measured (bench.py, real chip)
  gradient allreduce bytes  B     sum of param sizes (the model)
  ring allreduce traffic    2 * B * (N-1)/N per chip per step
  scaling efficiency        T_c / (T_c + T_comm_exposed)

The allreduce is emitted by XLA *inside* the jitted step (the sharded
fused superstep: k minibatches per dispatch, so the gradient exchange
happens once per MINIBATCH inside the scan — XLA overlaps each
layer's reduce with the next layer's backward matmuls).  The table
reports the zero-overlap worst case AND the fully-exposed fraction;
the truth on hardware lies between the two, nearer the overlapped end.

ICI bandwidth is a published-spec parameter, not a measurement, so the
table sweeps a conservative range rather than asserting one number.

Usage: python scripts/scaling_model.py [per_chip_mb] [step_ms]
  step_ms defaults to the last bench.py resident result for mb=512
  (docs/perf.md); pass your own measurement to re-derive.
"""

from __future__ import annotations

import json
import sys

import numpy as np

sys.path.insert(0, ".")


def param_bytes(forwards, dtype_bytes: int = 4) -> int:
    total = 0
    for f in forwards:
        for arr in f.gather_params().values():
            total += int(np.prod(arr.shape)) * dtype_bytes
    return total


def sharded_residency_prediction(n_rows: int, row_bytes: int,
                                 n_devices: int) -> dict:
    """Per-device HBM bytes of the Lattice row-sharded resident
    placement: rows padded to a whole per-device tile, 1/N rows per
    device — the analytic number bench.py's --mesh-only phase checks
    its MEASURED per-device shard bytes against (and the delta it
    records).  A replicated placement costs ``n_rows * row_bytes`` on
    EVERY device; sharding divides it by N at the price of at most
    one tile row of padding per device."""
    rows_padded = -(-int(n_rows) // int(n_devices)) * int(n_devices)
    per_device = rows_padded // int(n_devices) * int(row_bytes)
    return {
        "n_rows": int(n_rows),
        "rows_padded": int(rows_padded),
        "n_devices": int(n_devices),
        "per_device_bytes": int(per_device),
        "replicated_per_device_bytes": int(n_rows) * int(row_bytes),
        "reduction_x": round(
            (int(n_rows) * int(row_bytes)) / max(per_device, 1), 3),
    }


def main() -> None:
    from veles_tpu import prng
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.loader.synthetic import SyntheticClassificationLoader
    from veles_tpu.models.alexnet import alexnet_layers
    from veles_tpu.ops.standard_workflow import StandardWorkflow

    mb = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    # measured single-chip step: 512 images at 14 029 img/s (BENCH_r03
    # era, docs/perf.md) = 36.5 ms per superstep minibatch-equivalent;
    # the scan fires k=8 minibatches per dispatch, but the allreduce
    # count is per minibatch, so model at minibatch granularity.
    step_ms = float(sys.argv[2]) if len(sys.argv) > 2 else mb / 14029.0 * 1000.0

    prng.seed_all(1)
    w = StandardWorkflow(
        loader_factory=lambda wf: SyntheticClassificationLoader(
            wf, name="loader", minibatch_size=8, n_train=16, n_valid=0,
            shape=(227, 227, 3), n_classes=1000, seed=1),
        layers=alexnet_layers(1000), loss_function="softmax",
        decision_config={"max_epochs": 1}, name="ScalingModel")
    w.initialize(device=NumpyDevice())
    bytes_f32 = param_bytes(list(w.forwards))

    n = 8
    rows = []
    for gbps in (100.0, 200.0, 400.0):   # per-chip ICI GB/s sweep
        traffic = 2.0 * bytes_f32 * (n - 1) / n          # ring, per chip
        t_comm_ms = traffic / (gbps * 1e9) * 1000.0
        worst = step_ms / (step_ms + t_comm_ms)          # zero overlap
        rows.append({
            "ici_GBps_per_chip": gbps,
            "allreduce_MB_per_chip_per_step": round(traffic / 1e6, 1),
            "t_comm_ms": round(t_comm_ms, 2),
            "scaling_x_zero_overlap": round(n * worst, 2),
            "scaling_x_full_overlap": float(n),
        })
    # the Lattice residency axis: the bench resident config's dataset
    # (one superstep group of mb*8 distinct 227x227x3 rows) sharded
    # over the same 8 chips — capacity scaling next to the throughput
    # scaling the table above models
    row_b = 227 * 227 * 3 * 4
    print(json.dumps({
        "model": "AlexNet-1000",
        "param_bytes_f32": bytes_f32,
        "per_chip_minibatch": mb,
        "measured_step_ms": round(step_ms, 2),
        "n_chips": n,
        "north_star_x": 6.0,
        "rows": rows,
        "sharded_residency": sharded_residency_prediction(
            mb * 8, row_b, n),
    }, indent=2))
    ok = all(r["scaling_x_zero_overlap"] >= 6.0 for r in rows)
    print(f"# north star >=6x holds even with ZERO comm/compute "
          f"overlap at every swept bandwidth: {ok}")


if __name__ == "__main__":
    main()
