"""Cost attribution for the AlexNet fused step: measure images/sec with
one component ablated at a time (docs/perf.md records the findings).

Not a benchmark — a profiling instrument: the deltas tell us which op
family to optimize (pooling backward's select-and-scatter, LRN, first
-layer dgrad, dropout, f32 gather), which a jax.profiler trace on the
tunneled axon platform cannot (host-side timeline only).

Usage: python scripts/ablate_alexnet.py [mb] [firings] [variant ...]
Variants default to all.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

SUPERSTEP = 8


def variant_layers(name: str, n_classes: int = 1000):
    from veles_tpu.models.alexnet import alexnet_layers
    layers = alexnet_layers(n_classes)
    if name == "base":
        return layers
    if name == "no_lrn":
        return [l for l in layers if l["type"] != "norm"]
    if name == "avg_pool":
        return [dict(l, type="avg_pooling") if l["type"] == "max_pooling"
                else l for l in layers]
    if name == "no_dropout":
        return [l for l in layers if l["type"] != "dropout"]
    if name == "fc_only":
        # drop everything conv-side except one cheap pool to shrink:
        # isolates the FC tail's share
        return [
            {"type": "max_pooling", "->": {"kx": 8, "ky": 8,
                                           "sliding": 8}, "<-": {}},
        ] + [l for l in layers if l["type"].startswith("all2all")
             or l["type"] in ("softmax", "dropout")]
    if name == "conv_only":
        out = [l for l in layers if not (
            l["type"].startswith("all2all") or
            l["type"] in ("softmax", "dropout"))]
        out.append({"type": "softmax", "->": {"output_sample_shape":
                                              n_classes}, "<-": {}})
        return out
    raise ValueError(name)


def measure(name: str, mb: int, firings: int) -> dict:
    from veles_tpu import prng
    from veles_tpu.backends import make_device
    from veles_tpu.loader.synthetic import SyntheticClassificationLoader
    from veles_tpu.ops.standard_workflow import StandardWorkflow
    from veles_tpu import profiling

    prng.seed_all(1234)
    w = StandardWorkflow(
        loader_factory=lambda wf: SyntheticClassificationLoader(
            wf, name="loader", minibatch_size=mb,
            n_train=mb * SUPERSTEP, n_valid=0,
            shape=(227, 227, 3), n_classes=1000, seed=227227),
        layers=variant_layers(name),
        loss_function="softmax",
        decision_config={"max_epochs": 10 ** 9},
        superstep=SUPERSTEP,
        name=f"ablate_{name}")
    w.evaluator.compute_confusion = False
    device = make_device("auto")
    w.initialize(device=device)
    loader, fused = w.loader, w.fused

    def fire():
        loader.run()
        fused.run()

    for _ in range(3):
        fire()
    np.asarray(fused._acc)
    img0 = float(fused.processed_images)
    t0 = time.perf_counter()
    for _ in range(firings):
        fire()
    np.asarray(fused._acc)
    dt = time.perf_counter() - t0
    img = float(fused.processed_images) - img0
    flops = profiling.model_flops_per_sample(w.forwards)
    rate = img / dt
    u = profiling.mfu(rate, flops["train"], device.jax_device)
    w.stop()
    # release this variant's HBM (dataset + params + carries) before
    # the next one builds, or variants accumulate and the chip OOMs
    # (same lesson as bench.py's resident->streaming handoff)
    w.fused.release_device_state()
    w.loader.original_data.reset()
    w.loader.original_labels.reset()
    w.loader.original_targets.reset()
    import gc
    del w, loader, fused
    gc.collect()
    return {"variant": name, "images_per_sec": round(rate, 1),
            "train_gflops_per_image": round(flops["train"] / 1e9, 3),
            "mfu": round(u, 4) if u else None,
            "ms_per_image": round(1000.0 / rate, 4)}


def main():
    import os
    # every variant loads the identical synthetic dataset — memoize it
    os.environ.setdefault("VELES_TPU_SYNTH_CACHE", "1")
    mb = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    firings = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    names = sys.argv[3:] or ["base", "no_lrn", "avg_pool", "no_dropout",
                             "conv_only", "fc_only"]
    out = []
    for name in names:
        r = measure(name, mb, firings)
        out.append(r)
        print(json.dumps(r), flush=True)
    base = next((r for r in out if r["variant"] == "base"), None)
    if base:
        for r in out:
            if r is not base:
                print(f"# {r['variant']}: saves "
                      f"{base['ms_per_image'] - r['ms_per_image']:+.4f}"
                      f" ms/image vs base", flush=True)


if __name__ == "__main__":
    main()
