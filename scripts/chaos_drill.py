"""Chaos drill: run the Faultline fault matrix on CPU and verify the
supervision layer recovers from every injected failure.

Usage::

    JAX_PLATFORMS=cpu python scripts/chaos_drill.py [--json] [--only F]

Each drill arms one (or a pair of) named injection point(s)
(veles_tpu/faults.py), exercises the REAL code path it lives in, and
asserts the documented recovery: a hung evaluator is replaced within
the heartbeat deadline, torn snapshots / GA checkpoints fall back to
the newest intact predecessor, corrupt stream files are skipped and
counted (and abort loudly past the tolerance), an OOMing upload
degrades instead of dying, a dying multihost peer aborts the
survivors cleanly with a final snapshot, a SIGTERM (preemption
notice) stops gracefully — final snapshot inside the grace deadline,
exit 14, supervisor auto-resume, trajectory f32-exact vs the
uninterrupted oracle — and a SIGKILLed GA run resumes from its
per-generation checkpoint bit-identically.

The last stdout line is one JSON record::

    {"fault_drill_ok": bool, "results": [
        {"fault": ..., "ok": bool, "recovery_sec": float, "detail":
         ...}, ...]}

bench.py runs this as its ``fault_drill`` phase, so robustness gets a
measured trajectory in BENCH_r* exactly like performance does.
``--only NAME`` (substring match) runs a subset; the multihost drill
is the only one that spawns a process pair and respects
``CHAOS_SKIP_MULTIHOST=1``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

# the drill is a CPU rehearsal: pin BEFORE any jax import so it can
# run next to (not on) a chip
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

# expected-event names and the exit-code contract come from the
# shared registries — an emitter/asserter typo is a veleslint
# finding, not a mystery drill failure
from veles_tpu import events  # noqa: E402
from veles_tpu.supervisor import EXIT_MULTIHOST_ABORT  # noqa: E402


def log(msg: str) -> None:
    print(f"[chaos] {msg}", file=sys.stderr, flush=True)


def assert_journal_event(name: str, since: int = 0) -> dict:
    """Every drill must leave its expected Sightline event in the run
    journal — a fault that is recovered from but not REPORTED would
    leave the operator blind.  Returns the newest matching event (from
    the in-process ring, which mirrors the journal file)."""
    from veles_tpu import telemetry
    evs = telemetry.recent_events(name)
    assert len(evs) > since, \
        f"no {name!r} event in the telemetry journal " \
        f"(have: {sorted({e['event'] for e in telemetry.recent_events()})})"
    return evs[-1]


def journal_events_from_dir(mdir: str, name: str = None) -> list:
    """Events from every ``journal-*.jsonl`` under ``mdir`` — the way
    to verify what SUBPROCESSES (supervisor, launcher children,
    multihost peers) reported; the in-process ring only mirrors this
    process's journal."""
    import glob
    evs = []
    for jf in glob.glob(os.path.join(mdir, "journal-*.jsonl")):
        with open(jf) as f:
            for line in f:
                try:
                    evs.append(json.loads(line))
                except ValueError:
                    pass
    if name is not None:
        evs = [e for e in evs if e.get("event") == name]
    return sorted(evs, key=lambda e: e.get("ts", 0))


def drill(fn):
    """Run one drill function -> result record (never raises)."""
    name = fn.__name__.replace("drill_", "").replace("__", ".")
    t0 = time.monotonic()
    try:
        detail = fn() or {}
        ok = True
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — record, keep drilling
        detail = {"error": f"{type(e).__name__}: {e}"}
        ok = False
    rec = {"fault": name, "ok": ok,
           "recovery_sec": round(time.monotonic() - t0, 2)}
    rec.update(detail)
    log(f"{name}: {'OK' if ok else 'FAILED'} "
        f"({rec['recovery_sec']}s) {detail}")
    return rec


# -- persistence drills ------------------------------------------------

def drill_snapshot__torn_write():
    from veles_tpu import faults
    from veles_tpu.snapshotter import (SnapshotCorruptError,
                                       load_workflow, save_workflow)
    d = tempfile.mkdtemp(prefix="chaos_snap_")
    p1 = os.path.join(d, "snap_epoch1.pickle.gz")
    p2 = os.path.join(d, "snap_epoch2.pickle.gz")
    save_workflow({"marker": 1}, p1)
    faults.arm("snapshot.torn_write")
    save_workflow({"marker": 2}, p2)
    faults.arm("")
    try:
        load_workflow(p2)
        raise AssertionError("torn snapshot loaded verbatim")
    except SnapshotCorruptError:
        pass
    got = load_workflow(p2, fallback=True)
    assert got == {"marker": 1}, got
    ev = assert_journal_event(events.EV_SNAPSHOT_FALLBACK)
    assert ev["used"] == p1, ev
    return {"fell_back_to": os.path.basename(p1),
            "journal_event": events.EV_SNAPSHOT_FALLBACK}


def drill_checkpoint__corrupt():
    from veles_tpu import faults, prng
    from veles_tpu.genetics import GeneticOptimizer, Tune

    tunes = {"x": Tune(5.0, -10.0, 10.0), "y": Tune(-3.0, -10.0, 10.0)}

    def quad(v):
        return (v["x"] - 2.0) ** 2 + (v["y"] + 1.0) ** 2

    d = tempfile.mkdtemp(prefix="chaos_ckpt_")
    state = os.path.join(d, "ga.json")
    prng.seed_all(4242)
    _, fit_ref = GeneticOptimizer(quad, tunes, population=6,
                                  generations=4,
                                  state_path=state + ".ref").run()
    # the FINAL checkpoint write is torn by the injected fault; the
    # resume must fall back to .prev and still finish bit-identically
    prng.seed_all(4242)
    faults.arm("checkpoint.corrupt@gen=4")
    GeneticOptimizer(quad, tunes, population=6, generations=4,
                     state_path=state).run()
    faults.arm("")
    prng.seed_all(31337)   # irrelevant: resume restores the rng
    _, fit2 = GeneticOptimizer(quad, tunes, population=6,
                               generations=4, state_path=state).run()
    assert abs(fit2 - fit_ref) < 1e-12, (fit2, fit_ref)
    ev = assert_journal_event(events.EV_GA_CHECKPOINT_FALLBACK)
    assert ev["used"].endswith(".prev"), ev
    return {"bit_identical_resume": True,
            "journal_event": events.EV_GA_CHECKPOINT_FALLBACK}


# -- loader drills -----------------------------------------------------

def _make_image_tree(n=12, shape=(8, 8, 3)):
    from PIL import Image
    d = tempfile.mkdtemp(prefix="chaos_imgs_")
    rng = np.random.default_rng(7)
    paths = []
    for i in range(n):
        p = os.path.join(d, f"img_{i:02d}.png")
        Image.fromarray(rng.integers(0, 255, shape, dtype="uint8")) \
            .save(p)
        paths.append((p, i % 3))
    return paths


def drill_stream__corrupt_file():
    from veles_tpu import faults
    from veles_tpu.loader.image import FileListImageLoader

    paths = _make_image_tree()
    # 1/12 corrupt under a 10% tolerance: skipped, counted, zero row
    faults.arm("stream.corrupt_file@index=7")
    ld = FileListImageLoader(train=paths, minibatch_size=4,
                             target_shape=(8, 8, 3), streaming=False,
                             corrupt_tolerance=0.1, name="chaosldr")
    ld.load_data()
    data = ld.original_data.mem
    assert len(ld.corrupt_indices) == 1, ld.corrupt_indices
    assert not data[sorted(ld.corrupt_indices)[0]].any()
    good = [i for i in range(len(paths)) if i not in ld.corrupt_indices]
    assert all(data[i].any() for i in good)
    # 3/12 corrupt blows through the tolerance: must abort loudly
    faults.arm("stream.corrupt_file@index=3,stream.corrupt_file@index=4"
               ",stream.corrupt_file@index=5")
    ld2 = FileListImageLoader(train=paths, minibatch_size=4,
                              target_shape=(8, 8, 3), streaming=False,
                              corrupt_tolerance=0.1, name="chaosldr2")
    try:
        ld2.load_data()
        raise AssertionError("over-threshold corruption did not abort")
    except RuntimeError as e:
        assert "corrupt_tolerance" in str(e)
    finally:
        faults.arm("")
    assert_journal_event(events.EV_LOADER_CORRUPT_FILE)
    assert_journal_event(events.EV_LOADER_CORRUPT_OVER_TOLERANCE)
    return {"skipped": 1, "threshold_aborted": True,
            "journal_event": events.EV_LOADER_CORRUPT_FILE}


def _tiny_workflow(streaming: bool):
    from veles_tpu import prng
    from veles_tpu.datasets import synthetic_classification
    from veles_tpu.loader import ArrayLoader
    from veles_tpu.ops.standard_workflow import StandardWorkflow
    prng.seed_all(1357)
    train, valid, _ = synthetic_classification(
        160, 40, (8, 8, 1), n_classes=4, seed=7)
    kw = {"max_resident_bytes": 0} if streaming else {}
    gd = {"learning_rate": 0.1}
    return StandardWorkflow(
        loader_factory=lambda w: ArrayLoader(
            w, train=train, valid=valid, minibatch_size=20,
            name="loader", **kw),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
             "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": gd},
        ],
        decision_config={"max_epochs": 2}, name="chaos_wf")


def drill_device__oom_on_put_stream():
    from veles_tpu import faults
    from veles_tpu.backends import JaxDevice
    w = _tiny_workflow(streaming=True)
    w.initialize(device=JaxDevice(platform="cpu"))
    assert w.fused.streaming
    faults.arm("device.oom_on_put@site=stream")
    try:
        w.run()
    finally:
        faults.arm("")
    assert w.fused.stream_oom_retries == 1, w.fused.stream_oom_retries
    hist = [h for h in w.decision.history if h["class"] == "validation"]
    assert hist and np.isfinite(hist[-1]["loss"])
    w.stop()
    ev = assert_journal_event(events.EV_DEVICE_OOM_RETRY)
    assert ev["site"] == "stream", ev
    return {"oom_retries": 1, "run_completed": True,
            "journal_event": events.EV_DEVICE_OOM_RETRY}


def drill_device__oom_on_put_resident():
    from veles_tpu import faults
    from veles_tpu.backends import JaxDevice
    w = _tiny_workflow(streaming=False)
    faults.arm("device.oom_on_put@site=resident_dataset")
    try:
        w.initialize(device=JaxDevice(platform="cpu"))
    finally:
        faults.arm("")
    # the budget said resident; the injected OOM degraded to streaming
    assert not w.loader.device_resident
    assert w.fused.streaming
    w.run()
    hist = [h for h in w.decision.history if h["class"] == "validation"]
    assert hist and np.isfinite(hist[-1]["loss"])
    w.stop()
    ev = assert_journal_event(events.EV_DEVICE_OOM_DEGRADED)
    assert ev["site"] == "resident_dataset", ev
    return {"degraded_to_streaming": True,
            "journal_event": events.EV_DEVICE_OOM_DEGRADED}


# -- evaluator drills (real serve-mode child process) ------------------

def _wine_ga_files(d):
    import textwrap
    wf = os.path.join(d, "wf.py")
    with open(wf, "w") as f:
        f.write(textwrap.dedent("""
            from veles_tpu.models import wine

            def run(launcher):
                launcher.create_workflow(wine.create_workflow)
                launcher.initialize()
                launcher.run()
        """))
    cfg = os.path.join(d, "cfg.py")
    with open(cfg, "w") as f:
        f.write(textwrap.dedent("""
            from veles_tpu.config import root
            from veles_tpu.genetics import Tune

            root.wine.decision = {"max_epochs": 3}
            root.wine.layers = [
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 8},
                 "<-": {"learning_rate": Tune(0.3, 0.01, 1.0)}},
                {"type": "softmax", "->": {"output_sample_shape": 3},
                 "<-": {"learning_rate": 0.3}},
            ]
        """))
    return wf, cfg


def drill_evaluator__hang_and_garbage():
    """The headline drill: a real serve-mode evaluator hangs SILENTLY
    mid-genome (heartbeats stop too) and also tears the protocol with
    a garbage line on another genome; the pool must detect the hang
    within the heartbeat deadline, replace the evaluator, re-dispatch
    the genome, and finish the generation with fitness parity against
    an unfaulted pass."""
    from veles_tpu.genetics.pool import ChipEvaluatorPool

    d = tempfile.mkdtemp(prefix="chaos_ga_")
    wf, cfg = _wine_ga_files(d)
    lr = "wine.layers[0]['<-']['learning_rate']"
    values = [{lr: 0.1}, {lr: 0.3}, {lr: 0.6}]
    hb_deadline = float(os.environ.get("CHAOS_HB_DEADLINE", "10"))

    def run_pool(fault_env):
        env_key = "VELES_FAULTS"
        saved = os.environ.get(env_key)
        if fault_env:
            os.environ[env_key] = fault_env
        else:
            os.environ.pop(env_key, None)
        try:
            pool = ChipEvaluatorPool(
                [sys.executable, "-m", "veles_tpu.genetics.worker",
                 "--serve", wf, cfg, "-b", "cpu", "-s", "1234",
                 "--heartbeat-every", "0.5"],
                workers=2, timeout=600,
                heartbeat_deadline=hb_deadline,
                restart_backoff=0.1)
            with pool:
                fits = pool.evaluate_many(values)
            return pool, fits
        finally:
            if saved is None:
                os.environ.pop(env_key, None)
            else:
                os.environ[env_key] = saved

    _, fits_ref = run_pool("")
    assert all(np.isfinite(f) for f in fits_ref), fits_ref
    t0 = time.monotonic()
    # job=2&seq=1: hang exactly once — on the first evaluator (job 2
    # arrives as its second job), not on the replacement (where the
    # retried job 2 comes first)
    pool, fits = run_pool(
        "evaluator.hang@job=2&seq=1&silent=1&seconds=600,"
        "evaluator.garbage_line@job=1")
    wall = time.monotonic() - t0
    assert fits == fits_ref, (fits, fits_ref)
    assert pool.hangs_detected >= 1, pool.hangs_detected
    assert pool.last_hang_kind == "heartbeat", pool.last_hang_kind
    assert pool.last_hang_wait <= hb_deadline + 5.0, pool.last_hang_wait
    ev = assert_journal_event(events.EV_GA_HANG_DETECTED)
    assert ev["kind"] == "heartbeat", ev
    assert_journal_event(events.EV_GA_EVALUATOR_RESTART)
    return {"hang_detect_sec": round(pool.last_hang_wait, 2),
            "heartbeat_deadline": hb_deadline,
            "fitness_parity": True, "wall_sec": round(wall, 1),
            "journal_event": events.EV_GA_HANG_DETECTED}


# -- multihost drill ---------------------------------------------------

def drill_multihost__peer_exit():
    """Process 1 of a 2-process CPU multihost run hard-exits shortly
    after init (injected peer death); process 0 must NOT hang in the
    collective — it aborts cleanly (exit 13) with a final snapshot."""
    if os.environ.get("CHAOS_SKIP_MULTIHOST"):
        return {"skipped": True}
    import socket
    import subprocess
    import textwrap

    d = tempfile.mkdtemp(prefix="chaos_mh_")
    wf = os.path.join(d, "mh_wf.py")
    with open(wf, "w") as f:
        f.write(textwrap.dedent("""
            from veles_tpu.workflow import Workflow


            class PsumLoop(Workflow):
                # keep running collectives until the peer dies under
                # one of them — the watchdog (launcher.run) must abort
                # this cleanly
                def run(self):
                    import time
                    import jax
                    import jax.numpy as jnp
                    assert jax.process_count() == 2
                    for _ in range(600):
                        out = jax.pmap(
                            lambda v: jax.lax.psum(v, "i"),
                            axis_name="i")(
                            jnp.ones(jax.local_device_count()))
                        out.block_until_ready()
                        time.sleep(0.1)


            def create_workflow(launcher):
                return PsumLoop(None, name="mh_chaos")


            def run(launcher):
                launcher.create_workflow(create_workflow)
                launcher.initialize()
                launcher.run()
        """))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    snap_dir = os.path.join(d, "snaps")
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(pid),
            "HOME": d,   # the emergency snapshot lands under $HOME
            "VELES_FAULTS": "multihost.peer_exit@process=1&after=2",
        })
        env.pop("VELES_PLOTS_DIR", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "veles_tpu", "--multihost",
             "-b", "cpu", wf],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO, env=env))
    del snap_dir
    rcs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            rcs.append((p.returncode, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    rc0, err0 = rcs[0]
    rc1, _ = rcs[1]
    assert rc1 == 17, f"peer did not die as injected (rc={rc1})"
    assert rc0 == EXIT_MULTIHOST_ABORT, \
        f"survivor rc={rc0}, wanted clean abort " \
        f"{EXIT_MULTIHOST_ABORT}; stderr: {err0[-800:]}"
    assert "aborting cleanly" in err0, err0[-800:]
    snaps = []
    for root, _, files in os.walk(d):
        # Phoenix named the emergency snapshot INTO the Snapshotter
        # lineage (<prefix>_final_multihost-abort_pid<pid>...), so
        # --snapshot/--supervise resume discovery finds it
        snaps += [f for f in files if "_final_multihost" in f]
    assert snaps, "no final snapshot written by the survivor"
    # the survivor's journal (its own process wrote journal-<pid>.jsonl
    # into the shared metrics dir it inherited via $VELES_METRICS_DIR)
    # must carry the abort record — the drill verifies REPORTING, not
    # just recovery
    from veles_tpu import telemetry
    mdir = telemetry.metrics_dir()
    evs = journal_events_from_dir(
        mdir, events.EV_MULTIHOST_EMERGENCY_SNAPSHOT) if mdir else []
    assert evs, "survivor journal lacks the abort record"
    return {"survivor_exit": rc0, "final_snapshot": snaps[0],
            "journal_event": events.EV_MULTIHOST_EMERGENCY_SNAPSHOT}


# -- Phoenix drills (preemption + supervisor) --------------------------

_PHX_WF = """
import json
import os

import numpy as np

from veles_tpu import prng
from veles_tpu.datasets import synthetic_classification
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow


def create_workflow(launcher):
    prng.seed_all(1357)
    train, valid, _ = synthetic_classification(
        2400, 400, (8, 8, 1), n_classes=4, seed=7)
    gd = {"learning_rate": 0.1, "gradient_moment": 0.9}
    return StandardWorkflow(
        loader_factory=lambda w: ArrayLoader(
            w, train=train, valid=valid, minibatch_size=24,
            name="loader"),
        layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 24}, "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": gd},
        ],
        decision_config={"max_epochs": int(os.environ["PHX_EPOCHS"]),
                         "fail_iterations": 10000},
        snapshotter_config={"directory": os.environ["PHX_SNAP_DIR"],
                            "prefix": "phx", "interval": 1000},
        name="phx_wf")


def run(launcher):
    launcher.create_workflow(create_workflow)
    launcher.initialize()
    launcher.run()
    w = launcher.workflow
    hist = [[h["class"], int(h["n_err"]), float(h["loss"])]
            for h in w.decision.history]
    ws = float(np.abs(np.asarray(
        w.forwards[0].weights.map_read()).astype(np.float64)).sum())
    print(json.dumps({
        "epochs": len([h for h in hist if h[0] == "validation"]),
        "hist": hist, "wsum": ws}))
"""


def _phx_env(d, metrics, epochs, **extra):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PHX_SNAP_DIR": os.path.join(d, "snaps"),
                "PHX_EPOCHS": str(epochs),
                "VELES_METRICS_DIR": metrics})
    env.pop("VELES_FAULTS", None)
    env.pop("VELES_RESUME_MANIFEST", None)
    env.update(extra)
    return env


def _last_json(out: str) -> dict:
    return json.loads(out.strip().splitlines()[-1])


def drill_preempt__sigterm_resume():
    """The Phoenix headline: a real SIGTERM lands mid-training (the
    injected preemption notice); the run must stop at the next
    dispatch boundary, write a final snapshot into the Snapshotter
    lineage INSIDE the grace deadline, and exit 14; the supervisor
    must auto-resume it from that snapshot — and the completed
    trajectory must match the uninterrupted oracle f32-exactly."""
    import subprocess
    d = tempfile.mkdtemp(prefix="chaos_preempt_")
    wf = os.path.join(d, "wf.py")
    with open(wf, "w") as f:
        f.write(_PHX_WF)
    epochs, grace = 200, 20.0

    oracle = subprocess.run(
        [sys.executable, "-m", "veles_tpu", "-b", "cpu", wf],
        env=_phx_env(d, os.path.join(d, "m_oracle"), epochs),
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert oracle.returncode == 0, oracle.stderr[-800:]
    ref = _last_json(oracle.stdout)
    assert ref["epochs"] == epochs, ref["epochs"]

    mdir = os.path.join(d, "m_supervised")
    res = subprocess.run(
        [sys.executable, "-m", "veles_tpu", "--supervise",
         "-b", "cpu", wf],
        env=_phx_env(
            d, mdir, epochs,
            VELES_FAULTS="preempt.sigterm@attempt=0&after=1.5",
            VELES_PREEMPT_GRACE=str(grace)),
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert res.returncode == 0, \
        f"supervised run rc={res.returncode}: {res.stderr[-800:]}"
    got = _last_json(res.stdout)

    # the preempted child left its final snapshot in the lineage
    snaps = [f for f in os.listdir(os.path.join(d, "snaps"))
             if f.startswith("phx_final_preempt")]
    assert snaps, os.listdir(os.path.join(d, "snaps"))
    # journal: requested -> final snapshot (inside grace, never the
    # watchdog's hard path) -> supervisor resumed -> done
    req = journal_events_from_dir(mdir, events.EV_PREEMPT_REQUESTED)
    fin = journal_events_from_dir(mdir,
                                  events.EV_PREEMPT_FINAL_SNAPSHOT)
    assert req and fin, journal_events_from_dir(mdir)
    assert not journal_events_from_dir(
        mdir, events.EV_PREEMPT_DEADLINE_EXCEEDED)
    snapshot_sec = fin[-1]["ts"] - req[-1]["ts"]
    assert 0 <= snapshot_sec <= grace, snapshot_sec
    resumed = journal_events_from_dir(mdir, events.EV_SUPERVISOR_RESUMED)
    assert resumed and resumed[-1]["source"] == "snapshot", resumed
    assert journal_events_from_dir(mdir, events.EV_SUPERVISOR_DONE)

    # trajectory parity: f32-exact on CPU, incl. the weight checksum.
    # Asserted piecewise with a row-level diff — the old single
    # `hist == hist and wsum == wsum` assert could only say "something
    # differed", which made its load-sensitive failure mode (PR 9's
    # noted flake) undiagnosable from the drill output alone.  The
    # flake itself was NOT wall-clock noise: under load the SIGTERM
    # lands mid-class (a legal stop boundary) and the fused runner's
    # on-device metric accumulator used to be dropped by the snapshot,
    # so the interrupted epoch's history row undercounted while the
    # weights stayed bit-exact.  Fixed at the root (FusedStepRunner
    # __getstate__ now carries _acc/_conf; pinned by
    # test_supervisor.py::test_mid_class_stop_preserves_partial_
    # metrics), so exact parity holds at ANY stop point — idle or
    # loaded box alike.
    assert got["wsum"] == ref["wsum"], \
        f"weight checksum diverged: {got['wsum']} != {ref['wsum']}"
    assert got["epochs"] == ref["epochs"], (got["epochs"],
                                            ref["epochs"])
    if got["hist"] != ref["hist"]:
        diffs = [(i, g, r) for i, (g, r) in
                 enumerate(zip(got["hist"], ref["hist"])) if g != r]
        raise AssertionError(
            f"history diverged in {len(diffs)} of {len(ref['hist'])} "
            f"rows (lengths {len(got['hist'])}/{len(ref['hist'])}); "
            f"first: row {diffs[0][0] if diffs else '?'} "
            f"got={diffs[0][1] if diffs else None} "
            f"ref={diffs[0][2] if diffs else None}")
    return {"journal_event": events.EV_PREEMPT_FINAL_SNAPSHOT,
            "trajectory_match": True,
            "preempt_snapshot_sec": round(snapshot_sec, 2),
            "resume_downtime_sec": resumed[-1].get("downtime"),
            "final_snapshot": snaps[0]}


def drill_supervisor__sigkill_ga_resume():
    """A GA run is SIGKILLed mid-generation (after the generation's
    evaluations, before its checkpoint lands — the worst case); the
    supervisor must resume it from the per-generation --ga-state
    checkpoint and the finished run must be bit-identical to the
    uninterrupted oracle (same best/fitness AND the same final
    checkpoint file, RNG state included)."""
    import subprocess
    d = tempfile.mkdtemp(prefix="chaos_sigkill_ga_")
    wf, cfg = _wine_ga_files(d)

    def run_ga(state, metrics, fault=None):
        env = _phx_env(d, metrics, 0)
        if fault:
            env["VELES_FAULTS"] = fault
        cmd = [sys.executable, "-m", "veles_tpu"]
        if fault:
            cmd.append("--supervise")
        cmd += ["--optimize", "5:2", "-b", "tpu-evaluator",
                "--ga-workers", "2", "--ga-state", state, wf, cfg]
        res = subprocess.run(cmd, env=env, capture_output=True,
                             text=True, timeout=420, cwd=REPO)
        assert res.returncode == 0, \
            f"rc={res.returncode}: {res.stderr[-800:]}"
        return _last_json(res.stdout)

    ref = run_ga(os.path.join(d, "oracle.json"),
                 os.path.join(d, "m_oracle"))
    mdir = os.path.join(d, "m_supervised")
    got = run_ga(os.path.join(d, "state.json"), mdir,
                 fault="supervisor.child_crash@attempt=0&gen=2")
    assert got == ref, (got, ref)
    # the final checkpoints must be bit-identical too: population,
    # fitnesses, history, and the GA RNG state all replayed exactly
    with open(os.path.join(d, "oracle.json")) as f:
        st_ref = json.load(f)
    with open(os.path.join(d, "state.json")) as f:
        st_got = json.load(f)
    assert st_got == st_ref, "resumed GA checkpoint diverged"
    restarts = journal_events_from_dir(mdir,
                                       events.EV_SUPERVISOR_RESTART)
    assert restarts and restarts[-1]["kind"] == "crash", restarts
    resumed = journal_events_from_dir(mdir, events.EV_SUPERVISOR_RESUMED)
    assert resumed and resumed[-1]["source"] == "ga_state", resumed
    assert journal_events_from_dir(mdir, events.EV_GA_RESUMED)
    return {"journal_event": events.EV_SUPERVISOR_RESUMED,
            "bit_identical_resume": True,
            "resume_downtime_sec": resumed[-1].get("downtime")}


# -- Sentinel drills (fleet gray failures) -----------------------------

_FLEET_WF = """
from veles_tpu import prng
from veles_tpu.datasets import synthetic_classification
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow

def create_workflow(launcher):
    prng.seed_all(4242)
    train, valid, _ = synthetic_classification(
        64, 16, (6, 6, 1), n_classes=3, seed=5)
    return StandardWorkflow(
        loader_factory=lambda w: ArrayLoader(
            w, train=train, valid=valid, minibatch_size=16,
            name="loader"),
        layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 12},
             "<-": {"learning_rate": 0.1}},
            {"type": "softmax", "->": {"output_sample_shape": 3},
             "<-": {"learning_rate": 0.1}},
        ],
        decision_config={"max_epochs": 2}, name="chaos_fleet_wf")
"""


def _fleet_pkg(d):
    """One tiny Forge ensemble package + its host oracle (the
    test_fleet recipe) for the gray-failure fleet drills."""
    from veles_tpu import prng
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.ensemble.packaging import pack_ensemble
    from veles_tpu.launcher import load_workflow_module

    wf_path = os.path.join(d, "fleet_wf.py")
    with open(wf_path, "w") as f:
        f.write(_FLEET_WF)
    mod = load_workflow_module(wf_path)

    class FL:
        workflow = None

    prng.seed_all(11)
    w = mod.create_workflow(FL())
    w.initialize(device=NumpyDevice())
    base = {fw.name: {k: np.asarray(v) for k, v in
                      fw.gather_params().items()}
            for fw in w.forwards}
    rng = np.random.default_rng(11)
    members = []
    for _ in range(3):
        params = {fn: {pn: (a + 0.05 * rng.standard_normal(a.shape)
                            .astype(np.float32))
                       for pn, a in p.items()}
                  for fn, p in base.items()}
        members.append({"params": params, "valid_error": 0.0,
                        "seed": 11,
                        "forward_names": [fw.name
                                          for fw in w.forwards],
                        "values": None})
    pkg = os.path.join(d, "m.vpkg")
    pack_ensemble(pkg, "m", members, wf_path)

    def oracle(x):
        acc = None
        for m in members:
            out = np.asarray(x, np.float32)
            for fw in w.forwards:
                p = {k: np.asarray(v)
                     for k, v in m["params"][fw.name].items()}
                out, _ = fw.apply_fwd(p, out, rng=None, train=False)
            out = np.asarray(out)
            acc = out if acc is None else acc + out
        return acc / len(members)

    return pkg, oracle


#: metric dirs the fleet drills pointed replicas at — the lock
#: witness pass unions their lockwitness-<pid>.json files at the end
WITNESS_DIRS: list = []


def _gray_fleet(fault, d, **kw):
    """A REAL 2-replica fleet with replica 0 armed via a per-replica
    VELES_FAULTS override (replica 1 explicitly disarmed)."""
    from veles_tpu.serve.router import FleetRouter
    pkg, oracle = _fleet_pkg(d)
    defaults = dict(
        n_replicas=2, backend="cpu", max_batch=16, max_wait_ms=5,
        metrics_dir=os.path.join(d, "metrics"), cwd=REPO,
        env={"VELES_FAULTS": ""},
        env_overrides={0: {"VELES_FAULTS": fault}})
    defaults.update(kw)
    WITNESS_DIRS.append(defaults["metrics_dir"])
    return FleetRouter({"m": pkg}, **defaults), oracle


def _ctr(name):
    from veles_tpu import telemetry
    return telemetry.counter(name).value


def drill_hive__slow_dispatch():
    """The tail-at-scale drill: one replica dispatches at 1.5s while
    staying alive and heartbeating.  Hedges must bridge the detection
    window (every answer clean and fast), the sentinel must EJECT the
    outlier, and — once the fault budget exhausts under probing — the
    probe/reinstate lifecycle must bring it back."""
    d = tempfile.mkdtemp(prefix="chaos_gray_slow_")
    router, oracle = _gray_fleet(
        "hive.slow_dispatch@label=m&times=6&seconds=1.5", d,
        deadline_ms=8000, hedge_min_ms=60, hedge_budget=1.0,
        probe_interval=0.2, probe_ok=2, probe_backoff_cap=0.4)
    hedges0 = _ctr(events.CTR_FLEET_HEDGES)
    eject0 = _ctr(events.CTR_FLEET_EJECTIONS)
    reinst0 = _ctr(events.CTR_FLEET_REINSTATEMENTS)
    try:
        x = np.ones((1, 6, 6, 1), np.float32)
        want = oracle(x)
        for _ in range(30):
            r = router.request("m", x, timeout=30)
            assert "probs" in r, r
            assert np.abs(np.asarray(r["probs"], np.float32)
                          - want).max() < 1e-4
            if _ctr(events.CTR_FLEET_EJECTIONS) > eject0:
                break
        assert _ctr(events.CTR_FLEET_HEDGES) > hedges0
        assert _ctr(events.CTR_FLEET_EJECTIONS) == eject0 + 1
        # post-ejection p99 is bounded: nothing waits out the stall
        post = []
        for _ in range(10):
            t0 = time.monotonic()
            assert "probs" in router.request("m", x, timeout=30)
            post.append(time.monotonic() - t0)
        assert max(post) < 1.0, post
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline \
                and _ctr(events.CTR_FLEET_REINSTATEMENTS) <= reinst0:
            time.sleep(0.25)
        assert _ctr(events.CTR_FLEET_REINSTATEMENTS) == reinst0 + 1
        ev = assert_journal_event(events.EV_FLEET_REPLICA_EJECTED)
        assert ev["replica"] == 0, ev
        assert_journal_event(events.EV_FLEET_REPLICA_REINSTATED)
        return {"hedged": _ctr(events.CTR_FLEET_HEDGES) - hedges0,
                "post_eject_max_ms": round(1000 * max(post), 1),
                "ejected_and_reinstated": True,
                "journal_event": events.EV_FLEET_REPLICA_EJECTED}
    finally:
        router.close(kill=True)


def drill_hive__wedge():
    """A wedged batcher: requests vanish unanswered while heartbeats
    and stats keep flowing — invisible to the heartbeat monitor.  The
    sentinel must detect it (hedge losses), eject it WITHOUT any
    heartbeat loss, and keep it out (probes are swallowed too)."""
    d = tempfile.mkdtemp(prefix="chaos_gray_wedge_")
    router, _oracle = _gray_fleet(
        "hive.wedge@times=*", d,
        deadline_ms=5000, hedge_min_ms=60, hedge_budget=1.0,
        probe_interval=0.25, probe_ok=2, probe_backoff_cap=0.5,
        heartbeat_every=0.2)
    eject0 = _ctr(events.CTR_FLEET_EJECTIONS)
    probe_fail0 = _ctr(events.CTR_FLEET_PROBES_FAILED)
    try:
        x = np.ones((1, 6, 6, 1), np.float32)
        for _ in range(25):
            assert "probs" in router.request("m", x, timeout=30)
            if _ctr(events.CTR_FLEET_EJECTIONS) > eject0:
                break
        assert _ctr(events.CTR_FLEET_EJECTIONS) == eject0 + 1
        # detection WITHOUT heartbeat loss: the monitor saw no death
        assert router.replicas[0].deaths == 0
        assert router.replicas[0].healthy
        assert router.replicas[0].client.heartbeats > 0
        # the wedged replica can never pass its canary probe
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline \
                and _ctr(events.CTR_FLEET_PROBES_FAILED) \
                <= probe_fail0:
            time.sleep(0.1)
        assert _ctr(events.CTR_FLEET_PROBES_FAILED) > probe_fail0
        st = router.sentinel.status(router.replicas[0])
        assert st["state"] in ("ejected", "probing"), st
        ev = assert_journal_event(events.EV_FLEET_REPLICA_EJECTED)
        assert ev["replica"] == 0, ev
        return {"heartbeats_flowed": router.replicas[0]
                .client.heartbeats,
                "deaths": 0, "stays_ejected": True,
                "journal_event": events.EV_FLEET_REPLICA_EJECTED}
    finally:
        router.close(kill=True)


def drill_hive__garbage_response():
    """Corrupt responses: a replica garbles every probability payload
    AFTER its crc echo was computed from the clean one.  The router's
    integrity check must strike + retry on the peer so ZERO corrupt
    answers reach a client (oracle parity held), and the replica must
    eject and stay out (its probes read garbage too)."""
    d = tempfile.mkdtemp(prefix="chaos_gray_garbage_")
    router, oracle = _gray_fleet(
        "hive.garbage_response@times=*", d,
        deadline_ms=8000, hedge_budget=0.0,
        probe_interval=0.25, probe_ok=2, probe_backoff_cap=0.5)
    strikes0 = _ctr(events.CTR_FLEET_INTEGRITY_STRIKES)
    eject0 = _ctr(events.CTR_FLEET_EJECTIONS)
    try:
        x = np.ones((2, 6, 6, 1), np.float32)
        want = oracle(x)
        corrupt_served = 0
        for _ in range(20):
            r = router.request("m", x, timeout=30)
            assert "probs" in r, r
            if np.abs(np.asarray(r["probs"], np.float32)
                      - want).max() >= 1e-4:
                corrupt_served += 1
        assert corrupt_served == 0, \
            f"{corrupt_served} corrupt answers reached a client"
        assert _ctr(events.CTR_FLEET_INTEGRITY_STRIKES) > strikes0
        assert _ctr(events.CTR_FLEET_EJECTIONS) == eject0 + 1
        st = router.sentinel.status(router.replicas[0])
        assert st["state"] in ("ejected", "probing"), st
        assert st["reinstatements"] == 0, st
        ev = assert_journal_event(events.EV_FLEET_REPLICA_EJECTED)
        assert ev["replica"] == 0, ev
        return {"corrupt_served": 0,
                "integrity_strikes":
                    _ctr(events.CTR_FLEET_INTEGRITY_STRIKES)
                    - strikes0,
                "journal_event": events.EV_FLEET_REPLICA_EJECTED}
    finally:
        router.close(kill=True)


# -- Evergreen drills (online learning) --------------------------------

def _online_hive(d, fault_env, margin="5.0"):
    """A REAL --serve-models --online hive over the tiny fleet
    package, with the learner's knobs tightened for drill speed."""
    from veles_tpu.serve.client import HiveClient
    pkg, oracle = _fleet_pkg(d)
    mdir = os.path.join(d, "metrics")
    WITNESS_DIRS.append(mdir)
    env = {
        "VELES_ONLINE_MICRO_BATCH": "8",
        "VELES_ONLINE_MIN_STEPS": "4",
        "VELES_ONLINE_LR_SCALE": "1.0",
        "VELES_ONLINE_PROMOTE_MARGIN": margin,
        "VELES_ONLINE_HOLDOUT_EVERY": "6",
        "VELES_ONLINE_IDLE_MS": "1",
        "VELES_FAULTS": fault_env,
    }
    client = HiveClient({"m": pkg}, backend="cpu", max_batch=8,
                        max_wait_ms=2, online=True, metrics_dir=mdir,
                        env=env, cwd=REPO)
    return client, oracle, mdir


def _online_rows():
    """The packaged model's own training rows + labels (regenerated —
    synthetic_classification is seed-deterministic)."""
    from veles_tpu.datasets import synthetic_classification
    train, _valid, _ = synthetic_classification(
        64, 16, (6, 6, 1), n_classes=3, seed=5)
    return train


def drill_online__poison_batch():
    """Corrupted tapped labels (the training slot only — the held-out
    slice stays honest, as a trusted-slice deployment would keep it)
    must be CAUGHT BY THE GATE: with clean traffic the incumbent is
    near-perfect on the held-out slice, the garbage-trained shadow
    cannot beat it, and nothing is ever promoted."""
    d = tempfile.mkdtemp(prefix="chaos_online_poison_")
    client, oracle, mdir = _online_hive(
        d, "online.poison_batch@slot=train&times=*")
    try:
        xs, _ys = _online_rows()
        deadline = time.monotonic() + 90
        row = None
        i = 0
        while time.monotonic() < deadline:
            for _ in range(8):
                x = xs[i % len(xs)][None]
                i += 1
                # CLEAN labels: the ensemble's own answer — the
                # incumbent cannot be beaten on this distribution
                lab = [int(np.argmax(oracle(x), axis=-1)[0])]
                r = client.wait_for(
                    client.submit("m", x, label=lab), timeout=60)
                assert "error" not in r, r
            row = client.learn().get("m")
            if row and row["steps"] >= 12 and \
                    row["shadow_error_pct"] is not None:
                break
            time.sleep(0.05)
        assert row and row["steps"] >= 12, row
        assert row["shadow_error_pct"] is not None, row
        assert row["promotions"] == 0, \
            f"poisoned training labels were PROMOTED: {row}"
        gates = journal_events_from_dir(mdir, events.EV_ONLINE_GATE)
        assert gates, "no online.gate round in the journal"
        assert all(g["verdict"] != "promote" for g in gates), gates
        return {"steps": row["steps"],
                "shadow_error_pct": row["shadow_error_pct"],
                "incumbent_error_pct": row["incumbent_error_pct"],
                "promotions": 0,
                "journal_event": events.EV_ONLINE_GATE}
    finally:
        client.close()


def drill_online__swap_mid_request():
    """Promotion races live dispatches (the injected stall widens the
    swap window to 0.5s while a closed loop hammers the model): every
    answer over the whole drill must equal the frozen-package oracle
    or the ONE post-promotion answer — a third distinct payload would
    be torn params."""
    d = tempfile.mkdtemp(prefix="chaos_online_swap_")
    client, oracle, mdir = _online_hive(
        d, "online.swap_mid_request@model=m&seconds=0.5")
    try:
        xs, ys = _online_rows()
        probe = xs[:2]
        want_old = oracle(probe)
        answers = []
        deadline = time.monotonic() + 90
        i = 0
        promoted = False
        while time.monotonic() < deadline:
            for _ in range(6):
                j = i % len(xs)
                i += 1
                # drifted truth: the frozen model is consistently
                # wrong, so the gate has something real to promote
                lab = [int((ys[j] + 1) % 3)]
                r = client.wait_for(
                    client.submit("m", xs[j][None], label=lab),
                    timeout=60)
                assert "error" not in r, r
            r = client.request("m", probe, timeout=60)
            assert "probs" in r, r
            answers.append(np.asarray(r["probs"], np.float32))
            row = client.learn().get("m")
            if row and row["promotions"] >= 1:
                promoted = True
                break
            time.sleep(0.05)
        assert promoted, "promotion never fired under the stall"
        # settle: the post-swap serving answer
        want_new = np.asarray(
            client.request("m", probe, timeout=60)["probs"],
            np.float32)
        assert np.abs(want_new - want_old).max() >= 1e-4, \
            "promotion did not change the served params"
        torn = [a for a in answers
                if np.abs(a - want_old).max() >= 1e-4
                and np.abs(a - want_new).max() >= 1e-4]
        assert not torn, f"{len(torn)} torn answer(s) mid-swap"
        promos = journal_events_from_dir(mdir,
                                         events.EV_ONLINE_PROMOTED)
        assert promos and promos[-1]["model"] == "m", promos
        row = client.learn()["m"]
        return {"answers_checked": len(answers), "torn": 0,
                "time_to_serve_ms": row.get("time_to_serve_ms"),
                "journal_event": events.EV_ONLINE_PROMOTED}
    finally:
        client.close()


def drill_fleet__replica_flap():
    """The Gauntlet's pathological member: replica 0 SIGKILLs itself
    shortly after EVERY hello (``times=*`` — the respawn inherits the
    arming and flaps again, forever).  The respawn backoff and the
    scale controller's cooldown must COMPOSE: the monitor's
    exponential backoff bounds the spawn rate (backoffs grow, never a
    spawn hot-loop), the healthy peer answers every request with zero
    loss, and the autoscaler — watching the least-loaded HEALTHY
    pressure — takes no scale action at all (a flapping member is a
    health problem, not a capacity signal)."""
    from veles_tpu.serve.autoscale import (FleetAutoscaler,
                                           ScaleController)
    d = tempfile.mkdtemp(prefix="chaos_flap_")
    mdir = os.path.join(d, "metrics")
    router, oracle = _gray_fleet(
        "fleet.replica_flap@times=*&after=0.6", d,
        respawn_backoff=0.4, heartbeat_every=0.2,
        heartbeat_deadline=2.0)
    scaler = FleetAutoscaler(
        router,
        controller=ScaleController(
            min_replicas=2, max_replicas=3, up_ms=400.0,
            down_ms=10.0, up_sustain_s=1.0, down_sustain_s=2.0,
            cooldown_s=3.0),
        interval_s=0.2)
    try:
        x = np.ones((1, 6, 6, 1), np.float32)
        want = oracle(x)
        scaler.start()
        window = 12.0
        stop_at = time.monotonic() + window
        answered = 0
        while time.monotonic() < stop_at:
            r = router.request("m", x, timeout=30)
            assert "probs" in r, r
            assert np.abs(np.asarray(r["probs"], np.float32)
                          - want).max() < 1e-4
            answered += 1
            time.sleep(0.05)
        deaths = [e for e in journal_events_from_dir(
            mdir, events.EV_FLEET_REPLICA_DIED)
            if e.get("replica") == 0]
        assert len(deaths) >= 2, \
            f"replica 0 flapped only {len(deaths)}x in {window}s"
        # the backoff GROWS with consecutive deaths — no spawn storm:
        # each flap costs >= after + the current backoff, so the
        # window bounds the death count from above too
        backoffs = [e.get("backoff", 0.0) for e in deaths]
        assert backoffs == sorted(backoffs), backoffs
        assert backoffs[-1] > backoffs[0], backoffs
        assert len(deaths) <= int(window / 0.6) + 1, \
            f"{len(deaths)} deaths in {window}s is a spawn hot-loop"
        # the cooldown composes: a flapping member never reads as a
        # capacity signal, so the fleet's shape is untouched
        assert not journal_events_from_dir(
            mdir, events.EV_FLEET_SCALE_UP)
        assert not journal_events_from_dir(
            mdir, events.EV_FLEET_SCALE_DOWN)
        assert len(router.replicas) == 2
        assert answered > 0
        return {"answered": answered, "lost": 0,
                "flap_deaths": len(deaths),
                "backoff_first_s": round(backoffs[0], 2),
                "backoff_last_s": round(backoffs[-1], 2),
                "scale_actions": 0,
                "journal_event": events.EV_FLEET_REPLICA_DIED}
    finally:
        scaler.close()
        router.close(kill=True)


DRILLS = [
    drill_snapshot__torn_write,
    drill_checkpoint__corrupt,
    drill_stream__corrupt_file,
    drill_device__oom_on_put_stream,
    drill_device__oom_on_put_resident,
    drill_evaluator__hang_and_garbage,
    drill_multihost__peer_exit,
    drill_preempt__sigterm_resume,
    drill_supervisor__sigkill_ga_resume,
    drill_hive__slow_dispatch,
    drill_hive__wedge,
    drill_hive__garbage_response,
    drill_online__poison_batch,
    drill_online__swap_mid_request,
    drill_fleet__replica_flap,
]


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(prog="chaos_drill")
    p.add_argument("--json", action="store_true",
                   help="stdout carries ONLY the final JSON record")
    p.add_argument("--only", default=None,
                   help="substring filter on drill names")
    args = p.parse_args(argv)

    # every drill also verifies its fault REPORTS into the Sightline
    # journal; arm a scratch metrics dir when the caller did not
    # (child processes inherit it through $VELES_METRICS_DIR)
    from veles_tpu import telemetry
    if telemetry.metrics_dir() is None:
        telemetry.configure(tempfile.mkdtemp(prefix="chaos_metrics_"))
    log(f"journal/metrics dir: {telemetry.metrics_dir()}")

    # Lockstep pass: the whole matrix runs under the lock-order
    # witness — every child process inherits the arming, and at the
    # end every runtime-observed acquisition edge must be declared in
    # the static locking law (analysis/lock_order.json)
    os.environ.setdefault("VELES_LOCK_WITNESS", "1")

    todo = [f for f in DRILLS
            if not args.only or args.only in f.__name__]
    results = [drill(f) for f in todo]
    ok = all(r["ok"] for r in results)

    from veles_tpu.analysis import flow, witness
    observed = set(witness.observed_edges())
    for mdir in [telemetry.metrics_dir()] + WITNESS_DIRS:
        if mdir and os.path.isdir(mdir):
            observed |= set(witness.read_snapshots(mdir))
    law = flow.load_lock_order(os.path.join(
        REPO, "veles_tpu", "analysis", "lock_order.json"))
    undeclared = sorted(observed - flow.declared_edges(law or {}))
    witness_ok = law is not None and not undeclared
    if undeclared:
        log(f"LOCK WITNESS: undeclared runtime edges {undeclared} — "
            f"the static locking law has a gap")
    else:
        log(f"lock witness: {len(observed)} observed edge(s), all "
            f"declared in the locking law")
    ok = ok and witness_ok

    # Flightline pass: the flight recorder is always armed, so every
    # ejection / promotion / rollback the matrix provoked must have
    # left a flightrec-*.json dump next to its journal — a verdict
    # with no dump means the crash-proof ring is not actually wired
    # to that trigger
    import glob as _glob

    from veles_tpu import events as _events
    reason_of = {_events.EV_FLEET_REPLICA_EJECTED: "ejection",
                 _events.EV_ONLINE_PROMOTED: "promote",
                 _events.EV_ONLINE_ROLLBACK: "rollback"}
    dirs = []
    for mdir in [telemetry.metrics_dir()] + WITNESS_DIRS:
        if mdir and os.path.isdir(mdir):
            real = os.path.realpath(mdir)
            if real not in dirs:
                dirs.append(real)
    # drop dirs nested under another (the recursive walk below would
    # double count their journals and dumps)
    dirs = [d for d in dirs
            if not any(d != o and (d + os.sep).startswith(o + os.sep)
                       for o in dirs)]
    need: dict = {}
    dump_reasons: list = []
    for mdir in dirs:
        for jf in _glob.glob(os.path.join(mdir, "**",
                                          "journal-*.jsonl"),
                             recursive=True):
            try:
                with open(jf) as f:
                    for line in f:
                        try:
                            ev = json.loads(line)
                        except ValueError:
                            continue
                        r = reason_of.get(ev.get("event"))
                        if r:
                            need[r] = need.get(r, 0) + 1
            except OSError:
                continue
        for fp in _glob.glob(os.path.join(mdir, "**",
                                          "flightrec-*.json"),
                             recursive=True):
            try:
                with open(fp) as f:
                    dump_reasons.append(json.load(f).get("reason"))
            except (OSError, ValueError):
                continue
    missing = {r: n for r, n in sorted(need.items())
               if dump_reasons.count(r) < n}
    flightrec_ok = not missing
    if missing:
        log(f"FLIGHT RECORDER: events without a matching dump "
            f"{missing} (dumps on disk: {sorted(dump_reasons)})")
    else:
        log(f"flight recorder: {len(dump_reasons)} dump(s) cover "
            f"{sum(need.values())} eject/promote/rollback event(s)")
    ok = ok and flightrec_ok

    record = {
        "fault_drill_ok": ok,
        "fault_drill_journal_verified": bool(results) and all(
            r.get("journal_event") or r.get("skipped")
            for r in results),
        "lock_witness_ok": witness_ok,
        "lock_witness_edges": len(observed),
        "flight_recorder_ok": flightrec_ok,
        "flight_recorder_dumps": len(dump_reasons),
        "results": results,
    }
    print(json.dumps(record), flush=True)
    if not args.json:
        log(f"{'ALL OK' if ok else 'FAILURES'} "
            f"({sum(r['ok'] for r in results)}/{len(results)})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
