"""Roofline check for the fused AlexNet train step.

Compares the measured steady-state superstep time against compute- and
HBM-bound floors derived from TWO flop/byte sources:

- the analytic per-layer count (veles_tpu/profiling.py) — trusted;
- XLA's own ``compiled.cost_analysis()`` — reported for reference but
  NOT trusted on TPU: it undercounts convolution FLOPs after fusion
  (measured ~0.8 GFLOP/image where the analytic count is ~2.3 fwd /
  6.8 train — docs/perf.md), so floors derived from it are labeled.

Distinguishes "the kernels are inefficient" (measured >> both floors)
from "we are at a roof" (measured ~= floor) — the decision input for
docs/perf.md.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

V5E_HBM_BW = 819e9           # bytes/sec (xla-floor reference only)


def main():
    mb = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    ss = int(sys.argv[2]) if len(sys.argv) > 2 else 8

    from veles_tpu import profiling, prng
    from veles_tpu.backends import make_device
    from veles_tpu.loader.synthetic import SyntheticClassificationLoader
    from veles_tpu.models.alexnet import alexnet_layers
    from veles_tpu.ops.standard_workflow import StandardWorkflow

    prng.seed_all(1234)
    w = StandardWorkflow(
        loader_factory=lambda wf: SyntheticClassificationLoader(
            wf, name="loader", minibatch_size=mb, n_train=mb * ss,
            n_valid=0, shape=(227, 227, 3), n_classes=1000,
            seed=227227),
        layers=alexnet_layers(1000),
        loss_function="softmax",
        decision_config={"max_epochs": 10 ** 9},
        superstep=ss, name="Roofline")
    w.evaluator.compute_confusion = False
    device = make_device("auto")
    w.initialize(device=device)
    loader, fused = w.loader, w.fused

    def fire():
        loader.run()
        fused.run()

    for _ in range(3):
        fire()
    np.asarray(fused._acc)     # the honest barrier (bench.py contract)

    # steady-state superstep time: median of repeats, amortized firings
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(8):
            fire()
        np.asarray(fused._acc)
        times.append((time.perf_counter() - t0) / 8)
    dt = float(np.median(times))

    n_img = mb * ss
    analytic = profiling.model_flops_per_sample(w.forwards)["train"]
    a_flops = analytic * n_img
    # peak resolved from the ACTUAL device (None on CPU/unknown —
    # same helper bench.py trusts), not a hardcoded v5e constant
    peak = profiling.device_peak_flops(device.jax_device)
    u = profiling.mfu(n_img / dt, analytic, device.jax_device)
    out = {"mb": mb, "superstep": ss,
           "measured_superstep_sec": round(dt, 4),
           "images_per_sec": round(n_img / dt, 1),
           "analytic_train_gflops_per_image": round(analytic / 1e9, 3),
           "analytic_compute_floor_sec":
               round(a_flops / peak, 4) if peak else None,
           "mfu": round(u, 4) if u is not None else None}

    try:
        ld = loader
        args = (fused._params, fused._opt, fused._acc, fused._conf,
                ld.original_data.unmap(), fused._target_store(),
                ld.superstep_indices, ld.superstep_mask,
                fused._lr_rates_array(ld.superstep_indices.shape[0]),
                fused._rng_counter)
        ca = fused._train_step.lower(*args).compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        if "flops" not in ca:
            # no FLOP count at all on this backend: emit the raw dict,
            # derive nothing (a zero would fire the undercount note)
            out["cost_analysis"] = {k: ca[k] for k in sorted(ca)[:12]}
        else:
            flops = float(ca["flops"])
            nbytes = float(ca.get("bytes accessed", 0))
            out.update({
                "xla_tflops_per_superstep": round(flops / 1e12, 3),
                "xla_gbytes_per_superstep": round(nbytes / 1e9, 3),
                "xla_hbm_floor_sec": round(nbytes / V5E_HBM_BW, 4),
                "xla_transcendentals": ca.get("transcendentals"),
                "xla_flops_vs_analytic": round(flops / a_flops, 3),
            })
            if flops < 0.5 * a_flops:
                out["note"] = ("xla cost_analysis undercounts fused "
                               "conv FLOPs on TPU; trust the analytic "
                               "floor")
    except Exception as e:  # noqa: BLE001 — reference data only
        out["cost_analysis_error"] = str(e)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
