"""Roofline check for the fused AlexNet train step: XLA's own
cost_analysis (FLOPs + bytes accessed) vs measured step time.

Prints the compiler's numbers, the implied compute-bound and
HBM-bound floors, and where the measured time sits.  Distinguishes
"the kernels are inefficient" (measured >> both floors) from "we are
at the HBM roof" (measured ~= bytes/bandwidth) — the decision input
for docs/perf.md.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

V5E_PEAK_FLOPS = 197e12      # bf16
V5E_HBM_BW = 819e9           # bytes/sec


def main():
    mb = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    ss = int(sys.argv[2]) if len(sys.argv) > 2 else 8

    from veles_tpu import prng
    from veles_tpu.backends import make_device
    from veles_tpu.loader.synthetic import SyntheticClassificationLoader
    from veles_tpu.models.alexnet import alexnet_layers
    from veles_tpu.ops.standard_workflow import StandardWorkflow

    prng.seed_all(1234)
    w = StandardWorkflow(
        loader_factory=lambda wf: SyntheticClassificationLoader(
            wf, name="loader", minibatch_size=mb, n_train=mb * ss,
            n_valid=0, shape=(227, 227, 3), n_classes=1000,
            seed=227227),
        layers=alexnet_layers(1000),
        loss_function="softmax",
        decision_config={"max_epochs": 10 ** 9},
        superstep=ss, name="Roofline")
    w.evaluator.compute_confusion = False
    device = make_device("auto")
    w.initialize(device=device)
    loader, fused = w.loader, w.fused

    def fire():
        loader.run()
        fused.run()

    fire()
    np.asarray(fused._acc)

    # measured steady-state superstep time
    n = 6
    t0 = time.perf_counter()
    for _ in range(n):
        fire()
    np.asarray(fused._acc)
    dt = (time.perf_counter() - t0) / n

    cost = {}
    try:
        # the jitted step was executed: pull its compiled cost analysis
        entries = fused._train_step._cache_size()  # noqa: F841 probe
    except Exception:
        pass
    try:
        lowered = None
        for key in ("cost_analysis",):
            pass
        # AOT route: trace again with the live args via .lower()
        ld = loader
        args = (fused._params, fused._opt, fused._acc, fused._conf,
                ld.original_data.unmap(), fused._target_store(),
                ld.superstep_indices, ld.superstep_mask,
                fused._lr_rates_array(ld.superstep_indices.shape[0]),
                fused._rng_counter)
        compiled = fused._train_step.lower(*args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        cost = {k: ca[k] for k in
                ("flops", "bytes accessed", "transcendentals")
                if k in ca}
    except Exception as e:  # noqa: BLE001
        cost = {"error": str(e)}

    out = {"mb": mb, "superstep": ss,
           "measured_superstep_sec": round(dt, 4),
           "images_per_sec": round(mb * ss / dt, 1)}
    if "flops" in cost:
        flops = float(cost["flops"])
        nbytes = float(cost.get("bytes accessed", 0))
        out.update({
            "xla_tflops_per_superstep": round(flops / 1e12, 3),
            "xla_gbytes_per_superstep": round(nbytes / 1e9, 3),
            "compute_floor_sec": round(flops / V5E_PEAK_FLOPS, 4),
            "hbm_floor_sec": round(nbytes / V5E_HBM_BW, 4),
            "transcendentals": cost.get("transcendentals"),
        })
        out["bound"] = ("hbm" if out["hbm_floor_sec"] >
                        out["compute_floor_sec"] else "compute")
        floor = max(out["compute_floor_sec"], out["hbm_floor_sec"])
        out["efficiency_vs_floor"] = round(floor / dt, 3)
    else:
        out["cost_analysis"] = cost
    print(json.dumps(out))


if __name__ == "__main__":
    main()
