"""Gauntlet: one accountable production day for the elastic fleet.

Usage::

    JAX_PLATFORMS=cpu python scripts/gauntlet.py [--json]
        [--duration S] [--trace FILE]

The drill every serving PR rehearsed one organ at a time, run as a
whole body instead: a seeded OPEN-LOOP day of traffic (diurnal swing,
Poisson bursts, a Zipf model mix — veles_tpu/serve/traffic.py) is
fired at a FleetRouter whose replica count is owned by the
FleetAutoscaler (veles_tpu/serve/autoscale.py), with Evergreen armed
on every replica and chaos injected mid-day: a gray slow-dispatch
blip on the founding replica and a coordinated SIGTERM preemption in
the middle of a traffic burst.  The fleet must track the load curve
(scale up under the morning ramp, scale down through the evening
trough), hold its p99 in the non-degraded windows, and lose ZERO
answers.

Then the books are balanced.  The post-run ACCOUNTABILITY CHECK
replays the day from the outcome ledger plus the merged Sightline
journals (router process + every ``replica-*/`` subdir) and demands:

- every arrival in the trace has exactly one recorded outcome, and
  none of them is an error (sheds are honest, errors are lost answers);
- every ``probs`` payload's crc32 matches its echo, and a random
  sample of answers matches the host ensemble oracle bit-close;
- every scale-up/scale-down/degradation/retirement/ejection/
  promotion/rollback event in the journals carries its recorded
  cause — an unexplained fleet mutation fails the day.

The last stdout line is one JSON record (adopted by ``bench.py
--gauntlet-only`` as the BENCH_r15 gauntlet phase).  Sizing knobs are
``GAUNTLET_*`` env vars; the CI day is ~3 minutes, the ``-m slow``
pytest wrapper raises GAUNTLET_DURATION to an hours-long soak.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import textwrap
import threading
import time
import zlib

# the gauntlet is a CPU rehearsal: pin BEFORE any jax import so it
# can run next to (not on) a chip
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def log(msg: str) -> None:
    print(f"[gauntlet] {msg}", file=sys.stderr, flush=True)


def _env_f(name: str, default: float) -> float:
    return float(os.environ.get(name, default))


def _env_i(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


# -- the workload ------------------------------------------------------

#: fixed rows per request: one dispatch shape, one compile per replica
ROWS_PER_REQUEST = 8
INPUT_SHAPE = (6, 6, 1)

WF_TEXT = textwrap.dedent(f"""
    from veles_tpu import prng
    from veles_tpu.datasets import synthetic_classification
    from veles_tpu.loader import ArrayLoader
    from veles_tpu.ops.standard_workflow import StandardWorkflow

    def create_workflow(launcher):
        prng.seed_all(2020)
        train, valid, _ = synthetic_classification(
            64, 16, {INPUT_SHAPE}, n_classes=3, seed=9)
        return StandardWorkflow(
            loader_factory=lambda w: ArrayLoader(
                w, train=train, valid=valid, minibatch_size=16,
                name="loader"),
            layers=[
                {{"type": "all2all_tanh",
                  "->": {{"output_sample_shape": 64}},
                  "<-": {{"learning_rate": 0.1}}}},
                {{"type": "softmax", "->": {{"output_sample_shape": 3}},
                  "<-": {{"learning_rate": 0.1}}}},
            ],
            decision_config={{"max_epochs": 2}}, name="gauntlet_wf")
""")


def _build_package(d: str, members: int = 2):
    """One Forge ensemble package + the host-oracle ingredients (the
    test_serve recipe); registered under all three Zipf model names."""
    from veles_tpu import prng
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.ensemble.packaging import pack_ensemble
    from veles_tpu.launcher import load_workflow_module

    wf_path = os.path.join(d, "wf_gauntlet.py")
    with open(wf_path, "w") as f:
        f.write(WF_TEXT)
    mod = load_workflow_module(wf_path)

    class _FL:
        workflow = None

    prng.seed_all(33)
    w = mod.create_workflow(_FL())
    w.initialize(device=NumpyDevice())
    base = {fw.name: {k: np.asarray(v) for k, v in
                      fw.gather_params().items()}
            for fw in w.forwards}
    rng = np.random.default_rng(33)
    ms = []
    for _ in range(members):
        params = {fn: {pn: (a + 0.05 * rng.standard_normal(a.shape)
                            .astype(np.float32))
                       for pn, a in p.items()}
                  for fn, p in base.items()}
        ms.append({"params": params, "valid_error": 0.0, "seed": 33,
                   "forward_names": [fw.name for fw in w.forwards],
                   "values": None})
    pkg = os.path.join(d, "gauntlet.vpkg")
    pack_ensemble(pkg, "gauntlet", ms, wf_path)
    return {"pkg": pkg, "members": ms, "workflow": w}


def _host_oracle(model, x):
    acc = None
    for m in model["members"]:
        out = np.asarray(x, np.float32)
        for fw in model["workflow"].forwards:
            p = {k: np.asarray(v)
                 for k, v in m["params"][fw.name].items()}
            out, _ = fw.apply_fwd(p, out, rng=None, train=False)
        out = np.asarray(out)
        acc = out if acc is None else acc + out
    return acc / len(model["members"])


def _row_for(arrival) -> np.ndarray:
    """The arrival's input rows, regenerated from its trace seed —
    what makes every oracle spot check replayable after the fact."""
    rng = np.random.default_rng(arrival.row_seed)
    return rng.standard_normal(
        (ROWS_PER_REQUEST,) + INPUT_SHAPE).astype(np.float32)


# -- the journals ------------------------------------------------------

def _journal_events(mdir: str, name: str = None) -> list:
    """Events from every ``journal-*.jsonl`` under ``mdir`` —
    INCLUDING the per-replica subdirs (``replica-<i>/``), so the
    accountability check sees what the whole process tree reported."""
    evs = []
    pats = [os.path.join(mdir, "journal-*.jsonl"),
            os.path.join(mdir, "*", "journal-*.jsonl")]
    for pat in pats:
        for jf in glob.glob(pat):
            with open(jf) as f:
                for line in f:
                    try:
                        evs.append(json.loads(line))
                    except ValueError:
                        pass
    if name is not None:
        evs = [e for e in evs if e.get("event") == name]
    return sorted(evs, key=lambda e: e.get("ts", 0))


def accountability_check(mdir: str, preemptions: list) -> dict:
    """Balance the day's books: every fleet mutation in the merged
    journals must carry its recorded cause.  Returns the verdict
    record; ``unexplained`` non-empty fails the gauntlet."""
    from veles_tpu import events

    unexplained = []
    explained = 0

    #: events whose contract is an explicit ``cause`` field
    caused = [events.EV_FLEET_SCALE_UP, events.EV_FLEET_SCALE_DOWN,
              events.EV_FLEET_DEGRADE_ENGAGE,
              events.EV_FLEET_DEGRADE_RELEASE]
    for name in caused:
        for e in _journal_events(mdir, name):
            if e.get("cause"):
                explained += 1
            else:
                unexplained.append({"event": name, "record": e})

    # a retirement must tie back to a scale-down of the same replica
    downs = {e.get("replica")
             for e in _journal_events(mdir, events.EV_FLEET_SCALE_DOWN)}
    for e in _journal_events(mdir, events.EV_FLEET_REPLICA_RETIRED):
        if e.get("replica") in downs:
            explained += 1
        else:
            unexplained.append({"event": "orphan retirement",
                                "record": e})

    # an ejection's cause is its recorded score + strike count; a
    # reinstatement's is its clean-probe streak
    for e in _journal_events(mdir, events.EV_FLEET_REPLICA_EJECTED):
        if e.get("score") is not None and e.get("strikes") is not None:
            explained += 1
        else:
            unexplained.append({"event": "uncaused ejection",
                                "record": e})
    for e in _journal_events(mdir,
                             events.EV_FLEET_REPLICA_REINSTATED):
        if e.get("probes_ok"):
            explained += 1
        else:
            unexplained.append({"event": "uncaused reinstatement",
                                "record": e})

    # a promotion/rollback must carry the gate's measured standings
    for name in (events.EV_ONLINE_PROMOTED, events.EV_ONLINE_ROLLBACK):
        for e in _journal_events(mdir, name):
            if e.get("shadow_error_pct") is not None:
                explained += 1
            else:
                unexplained.append({"event": name, "record": e})

    # every replica death must be explained: a retirement (SIGTERM
    # drain), a coordinated preemption we injected, or — failing
    # those — a monitor respawn of the same slot AFTER the death
    # (crash + recovery, the journal pair the operator reads)
    retired = {e.get("replica") for e in _journal_events(
        mdir, events.EV_FLEET_REPLICA_RETIRED)}
    preempted = {p["replica"] for p in preemptions}
    spawns = _journal_events(mdir, events.EV_FLEET_REPLICA_SPAWNED)
    for e in _journal_events(mdir, events.EV_FLEET_REPLICA_DIED):
        idx = e.get("replica")
        if idx in retired or idx in preempted:
            explained += 1
        elif any(s.get("replica") == idx
                 and s.get("ts", 0) >= e.get("ts", 0)
                 for s in spawns):
            explained += 1
        else:
            unexplained.append({"event": "unexplained death",
                                "record": e})

    return {"explained": explained,
            "unexplained": unexplained,
            "accounted": not unexplained}


# -- the day -----------------------------------------------------------

def _spec():
    """The CI-sized day (every figure GAUNTLET_*-overridable): a
    >=10x diurnal swing with 2.5x bursts over ~3 minutes."""
    from veles_tpu.serve.traffic import TrafficSpec
    duration = _env_f("GAUNTLET_DURATION", 150.0)
    return TrafficSpec(
        seed=_env_i("GAUNTLET_SEED", 20),
        duration_s=duration,
        peak_rps=_env_f("GAUNTLET_PEAK_RPS", 30.0),
        swing=_env_f("GAUNTLET_SWING", 12.0),
        period_s=duration,
        burst_every_s=_env_f("GAUNTLET_BURST_EVERY", 25.0),
        burst_len_s=_env_f("GAUNTLET_BURST_LEN", 5.0),
        burst_mult=_env_f("GAUNTLET_BURST_MULT", 2.5),
        models=["hot", "warm", "tail"],
        zipf_s=_env_f("GAUNTLET_ZIPF_S", 1.1))


def _determinism_pin(spec, d: str) -> bool:
    """The replay contract, pinned on every run: the same seeded spec
    writes a byte-identical trace file twice."""
    import filecmp

    from veles_tpu.serve.traffic import generate, write_trace
    p1, p2 = os.path.join(d, "day_a.jsonl"), os.path.join(
        d, "day_b.jsonl")
    write_trace(p1, spec, generate(spec))
    write_trace(p2, spec, generate(spec))
    return filecmp.cmp(p1, p2, shallow=False)


def run_gauntlet(trace_path: str = None) -> dict:
    from veles_tpu import events, telemetry
    from veles_tpu.serve.autoscale import (FleetAutoscaler,
                                           ScaleController)
    from veles_tpu.serve.router import FleetRouter
    from veles_tpu.serve.traffic import (OpenLoopDriver,
                                         _burst_windows, generate,
                                         read_trace, write_trace)

    t_start = time.perf_counter()
    d = tempfile.mkdtemp(prefix="gauntlet_")
    mdir = os.path.join(d, "metrics")

    spec = _spec()
    log(f"day: {spec.duration_s:.0f}s, peak {spec.peak_rps:.0f} rps, "
        f"swing {spec.swing:.0f}x, bursts {spec.burst_mult:.1f}x")
    deterministic = _determinism_pin(spec, d)
    log(f"determinism pin: trace bitwise-equal={deterministic}")

    if trace_path:
        spec, arrivals = read_trace(trace_path)
        log(f"replaying {trace_path}: {len(arrivals)} arrivals")
    else:
        arrivals = generate(spec)
        trace_path = os.path.join(d, "day.jsonl")
        write_trace(trace_path, spec, arrivals)
        log(f"generated {len(arrivals)} arrivals -> {trace_path}")

    log("packing the ensemble (one package, three Zipf names)")
    model = _build_package(d,
                           members=_env_i("GAUNTLET_MEMBERS", 2))
    specs = {name: model["pkg"] for name in spec.models}

    max_batch = _env_i("GAUNTLET_MAX_BATCH", 16)
    max_wait_ms = _env_f("GAUNTLET_MAX_WAIT_MS", 40.0)
    # chaos, leg 1 (the gray blip): the founding replica dispatches
    # slow a few times mid-morning — strikes, hedges, maybe an
    # ejection; the sentinel's N-1 cap keeps the fleet routable
    # (label=warm: the warm-up loop drives "hot", so the blip spends
    # its firings mid-day on live traffic, not on the compile pass)
    gray = os.environ.get(
        "GAUNTLET_GRAY_FAULTS",
        "hive.slow_dispatch@label=warm&times=3&seconds=0.6")
    router = FleetRouter(
        specs, n_replicas=1, backend="cpu", max_batch=max_batch,
        max_wait_ms=max_wait_ms, metrics_dir=mdir, cwd=REPO,
        env={"VELES_ONLINE": "1"},        # Evergreen armed fleet-wide
        env_overrides={0: {"VELES_FAULTS": gray}} if gray else None,
        deadline_ms=60000.0)
    controller = ScaleController(
        min_replicas=_env_i("GAUNTLET_SCALE_MIN", 1),
        max_replicas=_env_i("GAUNTLET_SCALE_MAX", 3),
        up_ms=_env_f("GAUNTLET_UP_MS", 150.0),
        down_ms=_env_f("GAUNTLET_DOWN_MS", 60.0),
        up_sustain_s=_env_f("GAUNTLET_UP_SUSTAIN", 2.0),
        down_sustain_s=_env_f("GAUNTLET_DOWN_SUSTAIN", 4.0),
        cooldown_s=_env_f("GAUNTLET_COOLDOWN", 10.0))
    scaler = FleetAutoscaler(router, controller=controller,
                             interval_s=0.25)

    preemptions = []
    record = {}
    try:
        log("warming the founding replica (compile + baselines)")
        warm_lat = []
        row = _row_for(arrivals[0])
        for i in range(12):
            t0 = time.perf_counter()
            resp = router.request("hot", row, timeout=180)
            if "probs" in resp:
                warm_lat.append(time.perf_counter() - t0)
        assert warm_lat, "warm-up never produced an answer"
        warm_p50 = 1000 * float(np.percentile(warm_lat, 50))
        oracle_diff = float(np.abs(
            np.asarray(resp["probs"])
            - _host_oracle(model, row)).max())
        assert oracle_diff < 1e-3, oracle_diff
        log(f"warm p50 {warm_p50:.1f}ms, oracle diff {oracle_diff:.2e}")

        # chaos, leg 2 (coordinated preemption): a SIGTERM lands on
        # the youngest replica in the middle of a traffic burst —
        # exactly when losing its queue would hurt most.  Drain +
        # monitor respawn + the router's retry-on-peer must make it
        # invisible in the outcome ledger.
        day_wall0 = [None]
        stop_chaos = threading.Event()
        windows = _burst_windows(spec,
                                 np.random.default_rng(spec.seed))

        def _preempt_loop():
            fired = 0
            want = _env_i("GAUNTLET_PREEMPTIONS", 1)
            while not stop_chaos.is_set() and fired < want:
                if day_wall0[0] is None:
                    time.sleep(0.1)
                    continue
                t = time.monotonic() - day_wall0[0]
                mid_burst = any(a + 0.5 <= t < b for a, b in windows)
                live = [r for r in list(router.replicas)
                        if r.healthy and not r.retiring]
                if mid_burst and len(live) >= 2:
                    victim = max(live, key=lambda r: r.idx)
                    log(f"chaos: SIGTERM replica {victim.idx} "
                        f"(pid {victim.pid}) at t={t:.1f}s mid-burst")
                    preemptions.append(
                        {"replica": victim.idx, "t": round(t, 1),
                         "pid": victim.pid})
                    try:
                        victim.client.sigterm()
                    except OSError:
                        pass
                    fired += 1
                stop_chaos.wait(0.25)

        chaos_thread = threading.Thread(
            target=_preempt_loop, name="gauntlet-chaos", daemon=True)
        chaos_thread.start()

        def request_fn(a):
            return router.request(a.model, _row_for(a), timeout=120)

        scaler.start()
        log("the day begins")
        driver = OpenLoopDriver(
            request_fn, workers=_env_i("GAUNTLET_WORKERS", 64))
        wall0_unix = time.time()
        day_wall0[0] = time.monotonic()
        results = driver.run(arrivals)
        day_sec = time.monotonic() - day_wall0[0]
        stop_chaos.set()
        chaos_thread.join(timeout=5)
        log(f"the day ends: {len(results)} outcomes in {day_sec:.0f}s")
        # the epilogue: traffic is over, but the day isn't done until
        # the fleet walks back down to its floor — the scaler keeps
        # running against idle pressure so every spawned replica is
        # RETIRED (journaled, drained, install dir pooled), exactly
        # like the quiet hours after a real peak
        epilogue = _env_f("GAUNTLET_EPILOGUE", 60.0)
        ep_deadline = time.monotonic() + epilogue
        while time.monotonic() < ep_deadline:
            live = [r for r in router.replicas if not r.retiring]
            if len(live) <= scaler.controller.min_replicas \
                    and not scaler.ladder.engaged:
                break
            time.sleep(0.25)
        log(f"epilogue: fleet at "
            f"{len([r for r in router.replicas if not r.retiring])} "
            f"after {epilogue - max(0, ep_deadline - time.monotonic()):.0f}s")
    finally:
        scaler.close()
        router.close(kill=True)
        telemetry.flush()

    # -- the books -----------------------------------------------------
    by_status = {}
    for r in results:
        by_status[r["status"]] = by_status.get(r["status"], 0) + 1
    lost = len(arrivals) - len(results)
    errors = by_status.get("error", 0)

    corrupt = 0
    checked_crc = 0
    oks = [r for r in results if r["status"] == "ok"]
    for r in oks:
        resp = r["response"]
        if resp.get("crc") is not None and "probs" in resp:
            checked_crc += 1
            probs = np.asarray(resp["probs"], np.float32)
            if zlib.crc32(probs.tobytes()) != int(resp["crc"]):
                corrupt += 1

    # oracle spot checks: replay a sample of answered arrivals from
    # their trace seeds and demand bit-closeness to the host ensemble
    arr_by_i = {a.i: a for a in arrivals}
    sample = oks[:: max(1, len(oks) // 24)][:24]
    oracle_max = 0.0
    for r in sample:
        want = _host_oracle(model, _row_for(arr_by_i[r["i"]]))
        got = np.asarray(r["response"]["probs"], np.float32)
        oracle_max = max(oracle_max,
                         float(np.abs(got - want).max()))
    oracle_ok = bool(sample) and oracle_max < 1e-3

    # p99 in the non-degraded windows (outside engage..release spans)
    def _spans(env_name, rel_name):
        opens = _journal_events(mdir, env_name)
        closes = _journal_events(mdir, rel_name)
        spans, open_ts = [], None
        for e in sorted(opens + closes, key=lambda e: e.get("ts", 0)):
            if e.get("event") == env_name and open_ts is None:
                open_ts = e["ts"]
            elif e.get("event") == rel_name and open_ts is not None:
                spans.append((open_ts, e["ts"]))
                open_ts = None
        if open_ts is not None:
            spans.append((open_ts, wall0_unix + spec.duration_s))
        return spans

    degraded_spans = _spans(events.EV_FLEET_DEGRADE_ENGAGE,
                            events.EV_FLEET_DEGRADE_RELEASE)

    def _degraded(r):
        w = wall0_unix + r["t"]
        return any(a <= w <= b for a, b in degraded_spans)

    lat_clear = [r["latency_s"] for r in oks if not _degraded(r)]
    lat_all = [r["latency_s"] for r in oks]
    p99_clear_ms = 1000 * float(np.percentile(lat_clear, 99)) \
        if lat_clear else None
    p99_bar_ms = _env_f("GAUNTLET_P99_BAR_MS", 5000.0)

    ups = _journal_events(mdir, events.EV_FLEET_SCALE_UP)
    dns = _journal_events(mdir, events.EV_FLEET_SCALE_DOWN)
    acct = accountability_check(mdir, preemptions)

    swing_x = spec.peak_rps / spec.trough_rps
    ok = (deterministic and lost == 0 and errors == 0
          and corrupt == 0 and oracle_ok
          and len(ups) >= 2 and len(dns) >= 2
          and (p99_clear_ms is None or p99_clear_ms <= p99_bar_ms)
          and acct["accounted"])
    record = {
        "gauntlet_ok": ok,
        "gauntlet_sec": round(time.perf_counter() - t_start, 1),
        "day_sec": round(spec.duration_s, 1),
        "arrivals": len(arrivals),
        "answered": by_status.get("ok", 0),
        "shed": by_status.get("shed", 0),
        "errors": errors,
        "lost": lost,
        "corrupt": corrupt,
        "crc_checked": checked_crc,
        "oracle_spot_checks": len(sample),
        "oracle_max_abs_diff": oracle_max,
        "diurnal_swing_x": round(swing_x, 1),
        "burst_swing_x": round(swing_x * spec.burst_mult, 1),
        "trace_deterministic": deterministic,
        "scale_ups": len(ups),
        "scale_downs": len(dns),
        "scale_causes": sorted({e.get("cause") for e in ups + dns}),
        "degraded_spans": len(degraded_spans),
        "degraded_sec": round(sum(b - a
                                  for a, b in degraded_spans), 1),
        "preemptions": preemptions,
        "warm_p50_ms": round(warm_p50, 1),
        "p99_nondegraded_ms": p99_clear_ms
        and round(p99_clear_ms, 1),
        "p99_all_ms": lat_all
        and round(1000 * float(np.percentile(lat_all, 99)), 1),
        "p99_bar_ms": p99_bar_ms,
        "late_sends": telemetry.counter(
            events.CTR_TRAFFIC_LATE).value,
        "accountability": {
            "explained": acct["explained"],
            "unexplained": acct["unexplained"][:8],
            "accounted": acct["accounted"]},
    }
    log(f"verdict: ok={ok} answered={record['answered']} "
        f"shed={record['shed']} lost={lost} errors={errors} "
        f"corrupt={corrupt} ups={len(ups)} downs={len(dns)} "
        f"p99_clear={p99_clear_ms and round(p99_clear_ms)}ms "
        f"accounted={acct['accounted']}")
    return record


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true",
                    help="print only the final JSON record on stdout")
    ap.add_argument("--duration", type=float, default=None,
                    help="day length in seconds (GAUNTLET_DURATION)")
    ap.add_argument("--trace", default=None,
                    help="replay THIS trace file instead of "
                         "generating the day")
    args = ap.parse_args()
    if args.duration:
        os.environ["GAUNTLET_DURATION"] = str(args.duration)
    record = run_gauntlet(trace_path=args.trace)
    print(json.dumps(record), flush=True)
    return 0 if record.get("gauntlet_ok") else 1


if __name__ == "__main__":
    sys.exit(main())
