"""Sightline report: render a run's metrics dir into the human-readable
observability summary.

Usage::

    python scripts/obs_report.py METRICS_DIR [--json] [--events N]

The loading/merging/rendering internals live in ``veles_tpu/obs.py``
(shared with the ``web_status.py --metrics-dir`` live dashboard); this
script is the CLI: counter/gauge tables, a quantile table per
histogram (count, mean, p50, p90, p99, max) — the per-dispatch /
per-genome / per-request latency distributions the serving and
multi-chip SLOs hang on — derived per-engine throughput, and the
interleaved multi-process event timeline.  ``--json`` emits the merged
snapshot (plus the event count) as one JSON object for machines.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from veles_tpu.obs import (fleet_model_rows, fleet_rows,  # noqa: E402
                           learner_rows, load_dir, render,
                           render_fleet)
from veles_tpu.telemetry import Histogram  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="obs_report")
    p.add_argument("metrics_dir")
    p.add_argument("--json", action="store_true",
                   help="emit the merged snapshot as one JSON object")
    p.add_argument("--fleet", action="store_true",
                   help="render the fleet view: per-replica rows "
                        "(pid, resident models, queue depth, qps, "
                        "p99) from the replica-* child dirs plus the "
                        "per-model canary traffic split")
    p.add_argument("--events", type=int, default=40,
                   help="timeline length (default 40)")
    args = p.parse_args(argv)

    if not os.path.isdir(args.metrics_dir):
        print(f"obs_report: {args.metrics_dir!r} is not a directory",
              file=sys.stderr)
        return 2
    reg, snaps, journals, events = load_dir(args.metrics_dir)
    if not snaps and not events \
            and not fleet_rows(args.metrics_dir):
        print(f"obs_report: no metrics-*.json or journal-*.jsonl in "
              f"{args.metrics_dir} (run with --metrics-dir DIR or "
              f"$VELES_METRICS_DIR)", file=sys.stderr)
        return 1
    if args.json:
        merged = reg.snapshot()
        merged["snapshots"] = len(snaps)
        merged["journal_events"] = len(events)
        if args.fleet:
            merged["fleet"] = {
                "replicas": fleet_rows(args.metrics_dir),
                "models": fleet_model_rows(reg, events)}
        learners = learner_rows(reg, events)
        if learners:
            merged["learner"] = learners
        print(json.dumps(merged))
        return 0
    if args.fleet:
        fleet = render_fleet(args.metrics_dir)
        if not fleet:
            print(f"obs_report: no replica-* child dirs in "
                  f"{args.metrics_dir} — not a fleet metrics dir "
                  f"(spawn with --serve-fleet N --metrics-dir DIR)",
                  file=sys.stderr)
            return 1
        print(fleet)
        print()
    print(render(args.metrics_dir, reg, snaps, journals, events,
                 max_events=args.events))
    return 0


if __name__ == "__main__":
    sys.exit(main())


# re-exported for tests (quantile sanity against a raw histogram)
__all__ = ["load_dir", "render", "main", "Histogram"]
