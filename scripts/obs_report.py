"""Sightline report: render a run's metrics dir into the human-readable
observability summary.

Usage::

    python scripts/obs_report.py METRICS_DIR [--json] [--events N]

The loading/merging/rendering internals live in ``veles_tpu/obs.py``
(shared with the ``web_status.py --metrics-dir`` live dashboard); this
script is the CLI: counter/gauge tables, a quantile table per
histogram (count, mean, p50, p90, p99, max) — the per-dispatch /
per-genome / per-request latency distributions the serving and
multi-chip SLOs hang on — derived per-engine throughput, and the
interleaved multi-process event timeline.  ``--json`` emits the merged
snapshot (plus the event count) as one JSON object for machines.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from veles_tpu.obs import (assemble_traces,  # noqa: E402
                           fleet_model_rows, fleet_rows, learner_rows,
                           load_dir, load_tree, render, render_fleet,
                           render_trace)
from veles_tpu.telemetry import Histogram  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="obs_report")
    p.add_argument("metrics_dir")
    p.add_argument("--json", action="store_true",
                   help="emit the merged snapshot as one JSON object")
    p.add_argument("--fleet", action="store_true",
                   help="render the fleet view: per-replica rows "
                        "(pid, resident models, queue depth, qps, "
                        "p99) from the replica-* child dirs plus the "
                        "per-model canary traffic split")
    p.add_argument("--trace", metavar="TRACE_ID", default=None,
                   help="render ONE assembled Flightline trace (hop "
                        "timeline + critical path) by trace id; "
                        "merges the replica-* child journals")
    p.add_argument("--traces", action="store_true",
                   help="list every assembled trace id with its "
                        "outcome and total latency, slowest first")
    p.add_argument("--events", type=int, default=40,
                   help="timeline length (default 40)")
    args = p.parse_args(argv)

    if not os.path.isdir(args.metrics_dir):
        print(f"obs_report: {args.metrics_dir!r} is not a directory",
              file=sys.stderr)
        return 2
    if args.trace or args.traces:
        from veles_tpu.obs import critical_path
        _reg, merged = load_tree(args.metrics_dir)
        traces = assemble_traces(merged)
        if args.trace:
            evs = traces.get(args.trace)
            if not evs:
                print(f"obs_report: no events for trace "
                      f"{args.trace!r} (have {len(traces)} traces)",
                      file=sys.stderr)
                return 1
            print(render_trace(evs))
            return 0
        rows = sorted((critical_path(evs) for evs in traces.values()),
                      key=lambda c: c.get("total_s") or 0.0,
                      reverse=True)
        for cp in rows:
            total = cp.get("total_s")
            print(f"{cp.get('trace')}  {cp.get('model') or '-':<12} "
                  f"{cp.get('outcome') or '-':<8} "
                  f"{1000.0 * total:9.2f}ms  legs={cp['legs']}"
                  f"{' hedged' if cp['hedged'] else ''}"
                  f"{' retried' if cp['retried'] else ''}"
                  if total is not None else
                  f"{cp.get('trace')}  (no root event)")
        return 0
    reg, snaps, journals, events = load_dir(args.metrics_dir)
    if not snaps and not events \
            and not fleet_rows(args.metrics_dir):
        print(f"obs_report: no metrics-*.json or journal-*.jsonl in "
              f"{args.metrics_dir} (run with --metrics-dir DIR or "
              f"$VELES_METRICS_DIR)", file=sys.stderr)
        return 1
    if args.json:
        merged = reg.snapshot()
        merged["snapshots"] = len(snaps)
        merged["journal_events"] = len(events)
        if args.fleet:
            merged["fleet"] = {
                "replicas": fleet_rows(args.metrics_dir),
                "models": fleet_model_rows(reg, events)}
        learners = learner_rows(reg, events)
        if learners:
            merged["learner"] = learners
        print(json.dumps(merged))
        return 0
    if args.fleet:
        fleet = render_fleet(args.metrics_dir)
        if not fleet:
            print(f"obs_report: no replica-* child dirs in "
                  f"{args.metrics_dir} — not a fleet metrics dir "
                  f"(spawn with --serve-fleet N --metrics-dir DIR)",
                  file=sys.stderr)
            return 1
        print(fleet)
        print()
    print(render(args.metrics_dir, reg, snaps, journals, events,
                 max_events=args.events))
    return 0


if __name__ == "__main__":
    sys.exit(main())


# re-exported for tests (quantile sanity against a raw histogram)
__all__ = ["load_dir", "render", "main", "Histogram"]
