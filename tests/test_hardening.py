"""Round-1 VERDICT next #8: loud op registry, idempotent multihost
init, strict forge manifests."""

import subprocess
import sys

import pytest


class TestRegistryLoudness:
    def test_all_families_registered(self):
        from veles_tpu.ops.registry import forward_registry
        for name in ("all2all", "all2all_tanh", "all2all_relu",
                     "softmax", "conv", "conv_tanh", "conv_relu",
                     "max_pooling", "avg_pooling", "stochastic_pooling",
                     "activation_tanh", "activation_relu",
                     "activation_sigmoid", "activation_log",
                     "activation_strict_relu", "dropout", "norm",
                     "deconv", "depooling"):
            assert name in forward_registry, name

    def test_broken_family_import_fails_loudly(self):
        """A transitive ImportError inside an op family must fail AT
        REGISTRY IMPORT with the family named, not surface later as
        'unknown layer type' (round-1 VERDICT weak #5)."""
        code = r"""
import importlib.abc
import sys

class Block(importlib.abc.MetaPathFinder):
    def find_spec(self, name, path, target=None):
        if name == "veles_tpu.ops.lrn":
            raise ImportError("synthetic lrn breakage")

sys.meta_path.insert(0, Block())
try:
    import veles_tpu.ops.registry  # noqa
except ImportError as e:
    assert "lrn" in str(e) and "registry" in str(e) or \
        "silently missing" in str(e), str(e)
    print("LOUD_FAILURE_OK")
else:
    print("IMPORTED_SILENTLY")
"""
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=120,
                           cwd="/root/repo")
        assert "LOUD_FAILURE_OK" in r.stdout, (r.stdout, r.stderr)


class TestMultihostGuard:
    def test_initialize_called_once(self, monkeypatch):
        import jax

        from veles_tpu import launcher
        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda *a, **k: calls.append(1))
        monkeypatch.setattr(launcher, "_multihost_initialized", False)
        launcher.init_multihost()
        launcher.init_multihost()
        assert calls == [1]

    def test_no_backend_touch_before_initialize(self, monkeypatch):
        """Round-2 advisor high: jax.process_count() initializes the
        XLA backend, after which distributed.initialize() always
        raises.  init_multihost must never call it (or jax.devices)
        before initialize."""
        import jax

        from veles_tpu import launcher

        def boom(*a, **k):
            raise AssertionError("backend touched before initialize")
        monkeypatch.setattr(jax, "process_count", boom)
        monkeypatch.setattr(jax, "devices", boom)
        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda *a, **k: calls.append(1))
        monkeypatch.setattr(launcher, "_multihost_initialized", False)
        launcher.init_multihost()
        assert calls == [1]

    def test_already_initialized_client_detected(self, monkeypatch):
        """When the distributed client already exists, initialize()
        must not be called again."""
        from jax._src import distributed

        from veles_tpu import launcher
        monkeypatch.setattr(distributed.global_state, "client",
                            object(), raising=False)
        calls = []
        import jax
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda *a, **k: calls.append(1))
        monkeypatch.setattr(launcher, "_multihost_initialized", False)
        launcher.init_multihost()
        assert calls == []

    def test_refused_initialize_fails_loudly(self, monkeypatch):
        """A RuntimeError from initialize (backend already up) on a
        --multihost launch must FAIL LOUDLY (a silent single-process
        continuation would train 1/N of the data and checkpoint a
        state no peer can join), journal ``multihost.init_refused``,
        and continue solo only under VELES_MULTIHOST_ALLOW_SOLO=1."""
        import jax

        from veles_tpu import launcher, telemetry

        def refuse(*a, **k):
            raise RuntimeError("must be called before any JAX calls")
        monkeypatch.setattr(jax.distributed, "initialize", refuse)
        monkeypatch.setattr(launcher, "_multihost_initialized", False)
        monkeypatch.delenv("VELES_MULTIHOST_ALLOW_SOLO", raising=False)
        with pytest.raises(RuntimeError,
                           match="VELES_MULTIHOST_ALLOW_SOLO"):
            launcher.init_multihost()
        assert telemetry.recent_events("multihost.init_refused")
        # the explicit opt-in keeps the old continue-solo semantics
        monkeypatch.setenv("VELES_MULTIHOST_ALLOW_SOLO", "1")
        monkeypatch.setattr(launcher, "_multihost_initialized", False)
        launcher.init_multihost()  # must not raise
        assert launcher._multihost_initialized


class TestForgeStrictManifest:
    def test_unmanifested_member_rejected(self, tmp_path):
        """An archive member missing from the manifest's sha256 map
        must abort the install (smuggled unverified code)."""
        import io
        import tarfile

        from veles_tpu.forge import ForgePackage

        wf = tmp_path / "wf.py"
        wf.write_text("def run(launcher):\n    pass\n")
        out = str(tmp_path / "pkg.vpkg")
        ForgePackage.pack(out, "demo", str(wf), [], author="t")

        # append a file that the manifest does not cover
        evil = str(tmp_path / "evil.vpkg")
        with tarfile.open(out, "r:gz") as src, \
                tarfile.open(evil, "w:gz") as dst:
            for m in src.getmembers():
                dst.addfile(m, src.extractfile(m))
            payload = b"import os\n"
            info = tarfile.TarInfo("smuggled.py")
            info.size = len(payload)
            dst.addfile(info, io.BytesIO(payload))

        with pytest.raises(ValueError, match="not listed in the "
                                             "manifest"):
            ForgePackage.install(evil, str(tmp_path / "store"))


class TestForgeMarketplace:
    def test_publish_list_fetch_install_roundtrip(self, tmp_path):
        """The HTTP marketplace (reference: VelesForge upload/download)
        round-trips a package: publish -> list -> fetch -> install."""
        import threading

        from veles_tpu import forge

        wf = tmp_path / "wf.py"
        wf.write_text("def run(launcher):\n    pass\n")
        cfg = tmp_path / "cfg.py"
        cfg.write_text("root.demo.n = 1\n")
        pkg = str(tmp_path / "demo.vpkg")
        forge.ForgePackage.pack(pkg, "demo", str(wf), [str(cfg)],
                                version="1.2.0", author="t")

        server = forge.make_forge_server(str(tmp_path / "store"),
                                         port=0, host="127.0.0.1")
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        url = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            m = forge.publish(pkg, url)
            assert m["name"] == "demo" and m["file"] == "demo.vpkg"
            got = forge.fetch("demo", url, str(tmp_path / "dl"))
            inst = forge.ForgePackage.install(
                got, str(tmp_path / "inst"))
            assert inst["version"] == "1.2.0"
            import os
            assert os.path.isfile(os.path.join(inst["root"], "wf.py"))
            with pytest.raises(FileNotFoundError, match="available"):
                forge.fetch("nope", url)
        finally:
            server.shutdown()
            t.join(timeout=5)

    def test_fetch_rejects_malicious_listing_filename(self, tmp_path,
                                                      monkeypatch):
        """A compromised server's listing can claim "file":
        "../../x.vpkg" — fetch() must refuse before any path is built
        (round-3 ADVICE medium: arbitrary-path write on the client)."""
        import io
        import json as _json

        from veles_tpu import forge

        listing = _json.dumps([{"name": "demo", "version": "1.0.0",
                                "file": "../../escape.vpkg"}]).encode()

        class _Resp(io.BytesIO):
            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

        def fake_urlopen(url, timeout=None):
            assert url.endswith("/forge/list"), \
                "fetch must not request a package with an unsafe name"
            return _Resp(listing)

        monkeypatch.setattr("urllib.request.urlopen", fake_urlopen)
        with pytest.raises(ValueError, match="bad package file name"):
            forge.fetch("demo", "http://evil:1", str(tmp_path / "dl"))
        assert not (tmp_path.parent / "escape.vpkg").exists()

    def test_store_listing_survives_bad_manifest_member(self, tmp_path):
        """A crafted archive whose manifest.json member is a directory
        must not crash list_store for everyone (round-3 ADVICE low)."""
        import tarfile

        from veles_tpu import forge

        store = tmp_path / "store"
        store.mkdir()
        wf = tmp_path / "wf.py"
        wf.write_text("def run(launcher):\n    pass\n")
        good = str(store / "good.vpkg")
        forge.ForgePackage.pack(good, "good", str(wf), [])
        bad = str(store / "bad.vpkg")
        with tarfile.open(bad, "w:gz") as tar:
            info = tarfile.TarInfo("manifest.json")
            info.type = tarfile.DIRTYPE
            tar.addfile(info)
        listed = forge.ForgePackage.list_store(str(store))
        assert [m["name"] for m in listed] == ["good"]

    def test_server_defaults_to_loopback(self, tmp_path):
        """The unauthenticated upload endpoint must not bind all
        interfaces unless explicitly asked (round-3 ADVICE low)."""
        from veles_tpu import forge

        server = forge.make_forge_server(str(tmp_path / "store"), port=0)
        try:
            assert server.server_address[0] == "127.0.0.1"
        finally:
            server.server_close()

    def test_upload_rejects_garbage_and_bad_names(self, tmp_path):
        import threading
        from urllib.request import Request, urlopen
        from urllib.error import HTTPError

        from veles_tpu import forge

        server = forge.make_forge_server(str(tmp_path / "store"),
                                         port=0, host="127.0.0.1")
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        url = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            for path in ("/forge/upload/../../etc.vpkg",
                         "/forge/upload/notatar.vpkg",
                         "/forge/upload/wrongext.txt"):
                req = Request(url + path, data=b"not a tarball")
                with pytest.raises(HTTPError):
                    urlopen(req, timeout=10)
            import os
            store = tmp_path / "store"
            assert not any(os.scandir(store)), \
                "rejected uploads must leave nothing in the store"
        finally:
            server.shutdown()
            t.join(timeout=5)


class TestAtomicCompileCacheWrites:
    """PR-3 hardening: jax's LRUCache.put (eviction disabled — the
    default) writes persistent compile-cache entries with a bare
    write_bytes, so concurrent same-key compiles tear the entry and
    every later reader hard-aborts deserializing it (reproduced
    deterministically on this box).  backends.py patches the write to
    pid-tempfile + os.replace."""

    def test_patch_applied_and_atomic(self, tmp_path):
        from veles_tpu.backends import _harden_compile_cache_writes
        _harden_compile_cache_writes()      # idempotent
        _harden_compile_cache_writes()      # second call = no-op
        from jax._src import lru_cache as lc
        assert getattr(lc.LRUCache.put, "_veles_atomic", False)
        cache = lc.LRUCache(str(tmp_path / "c"), max_size=-1)
        assert not cache.eviction_enabled   # the unlocked path
        cache.put("k1", b"\x01" * 64)
        suffix = lc._CACHE_SUFFIX
        files = sorted(p.name for p in (tmp_path / "c").iterdir())
        assert f"k1{suffix}" in files
        assert not any(".tmp" in f for f in files)  # replace, not write
        assert cache.get("k1") == b"\x01" * 64
        # existing entries are never rewritten (jax's documented put
        # semantics survive the patch)
        cache.put("k1", b"\x02" * 64)
        assert cache.get("k1") == b"\x01" * 64

    def test_cache_dir_is_era_namespaced(self):
        """The default dir retires anything the old non-atomic writers
        could have torn: version + `-aw` era tag."""
        import jax

        from veles_tpu.backends import _compile_cache_default_dir
        d = _compile_cache_default_dir()
        assert d.endswith("-aw")
        assert jax.__version__ in d

    def test_cpu_process_never_enables_the_cache(self):
        """Faultline root cause: XLA:CPU executables round-tripped
        through the persistent cache deserialize to numerically WRONG
        programs (nondeterministic NaN trainings + the GPF/SIGABRT
        family).  A CPU-backend process must leave the cache off."""
        import jax

        from veles_tpu.backends import _enable_persistent_compile_cache
        assert jax.default_backend() == "cpu"   # the test suite's pin
        _enable_persistent_compile_cache()
        assert jax.config.jax_compilation_cache_dir in (None, "")
