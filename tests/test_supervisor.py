"""Phoenix: the exit-code contract, the run supervisor's
interpretation of it (13/14 always resume and never charge the crash
budget; crash-loops give up), flag-less resume-state discovery, the
graceful-stop dispatch boundary, and a REAL subprocess
SIGTERM -> final snapshot -> auto-resume round trip (CPU, bounded)."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from veles_tpu import supervisor, telemetry
from veles_tpu.supervisor import (EXIT_DONE, EXIT_MULTIHOST_ABORT,
                                  EXIT_PREEMPTED, RESUME_CODES,
                                  Supervisor, _normalize_rc)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_supervisor(tmp_path, script, **kw):
    """A Supervisor over a stub ``python -c`` child (no jax import —
    each spawn is milliseconds)."""
    kw.setdefault("restart_backoff", 0.01)
    kw.setdefault("restart_backoff_cap", 0.05)
    return Supervisor([], command=[sys.executable, "-c", script],
                      manifest_path=str(tmp_path / "manifest.json"),
                      **kw)


def counting_script(counter, codes):
    """A stub child that exits ``codes[n]`` on its n-th spawn (sticky
    on the last entry) and records spawn count in ``counter``."""
    return (
        "import os, sys\n"
        f"p = {str(counter)!r}\n"
        "n = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(n + 1))\n"
        f"codes = {codes!r}\n"
        "sys.exit(codes[min(n, len(codes) - 1)])\n"
    )


def spawns(counter) -> int:
    return int(open(counter).read()) if os.path.exists(counter) else 0


class TestExitCodeContract:
    def test_constants_pinned(self):
        """The exit-code contract is API: 0 done, 13 multihost abort,
        14 preempted — launcher and supervisor must agree, and only
        13/14 resume without charging the crash budget."""
        from veles_tpu.launcher import Launcher
        assert EXIT_DONE == 0
        assert Launcher.MULTIHOST_ABORT_EXIT == 13
        assert Launcher.PREEMPT_EXIT == 14
        assert EXIT_MULTIHOST_ABORT == Launcher.MULTIHOST_ABORT_EXIT
        assert EXIT_PREEMPTED == Launcher.PREEMPT_EXIT
        assert RESUME_CODES == frozenset((13, 14))

    def test_signal_rc_normalized_to_shell_convention(self):
        assert _normalize_rc(0) == 0
        assert _normalize_rc(3) == 3
        assert _normalize_rc(-9) == 137    # SIGKILL
        assert _normalize_rc(-15) == 143   # SIGTERM

    def test_done_exits_zero_no_restarts(self, tmp_path):
        sup = make_supervisor(tmp_path, "raise SystemExit(0)")
        assert sup.run() == 0
        assert sup.restarts == 0
        assert telemetry.recent_events("supervisor.done")

    @pytest.mark.parametrize("code", sorted(RESUME_CODES))
    def test_13_and_14_always_resume_without_charging_budget(
            self, tmp_path, code):
        """Three preempt/abort exits in a row with a crash budget of
        ONE: if 13/14 charged the budget the supervisor would give up
        after the first — it must instead resume every time and land
        the final clean exit."""
        counter = str(tmp_path / "count")
        sup = make_supervisor(
            tmp_path, counting_script(counter, [code, code, code, 0]),
            max_crashes=1, crash_window=3600)
        assert sup.run() == 0
        assert spawns(counter) == 4
        assert sup.restarts == 3
        evs = telemetry.recent_events("supervisor.restart")
        assert len(evs) == 3
        kind = "preempt" if code == EXIT_PREEMPTED else \
            "multihost_abort"
        assert all(e["kind"] == kind and not e["budget_charged"]
                   for e in evs)
        assert telemetry.counter("supervisor.restarts").value == 3

    def test_crash_resumes_then_succeeds(self, tmp_path):
        """Other nonzero codes are crashes: resumed (budget charged)
        as long as the budget holds."""
        counter = str(tmp_path / "count")
        sup = make_supervisor(
            tmp_path, counting_script(counter, [3, 0]),
            max_crashes=3, crash_window=3600)
        assert sup.run() == 0
        assert spawns(counter) == 2
        ev = telemetry.recent_events("supervisor.restart")[-1]
        assert ev["kind"] == "crash" and ev["budget_charged"]

    def test_crash_loop_exhausts_budget_and_gives_up(self, tmp_path):
        """The acceptance pin: N failures inside the window give up
        LOUDLY — child exit code propagated, supervisor.giveup
        journaled, exactly N spawns."""
        counter = str(tmp_path / "count")
        sup = make_supervisor(
            tmp_path, counting_script(counter, [3]),
            max_crashes=3, crash_window=3600)
        assert sup.run() == 3
        assert spawns(counter) == 3
        ev = telemetry.recent_events("supervisor.giveup")[-1]
        assert ev["rc"] == 3 and ev["crashes"] == 3

    def test_signal_death_is_a_crash(self, tmp_path):
        sup = make_supervisor(
            tmp_path,
            "import os, signal; os.kill(os.getpid(), signal.SIGKILL)",
            max_crashes=2, crash_window=3600)
        assert sup.run() == 137
        ev = telemetry.recent_events("supervisor.giveup")[-1]
        assert ev["rc"] == 137

    def test_usage_error_gives_up_immediately(self, tmp_path):
        """argparse errors (2) are deterministic — a restart loop
        would fail identically forever."""
        counter = str(tmp_path / "count")
        sup = make_supervisor(tmp_path,
                              counting_script(counter, [2]))
        assert sup.run() == 2
        assert spawns(counter) == 1
        ev = telemetry.recent_events("supervisor.giveup")[-1]
        assert ev["reason"] == "usage_error"

    def test_backoff_shape_matches_pool(self, tmp_path):
        """First restart immediate, then exponential with +-25%
        deterministic jitter, capped — the pool.py shape."""
        sup = make_supervisor(tmp_path, "raise SystemExit(0)",
                              restart_backoff=0.5,
                              restart_backoff_cap=4.0)
        assert sup._backoff(1) == 0.0
        for n, base in ((2, 0.5), (3, 1.0), (4, 2.0), (5, 4.0),
                        (9, 4.0)):
            d = sup._backoff(n)
            assert 0.75 * base <= d <= 1.25 * base, (n, d)


class TestResumeStateDiscovery:
    def _lineage(self, tmp_path):
        from veles_tpu.snapshotter import save_workflow
        d = tmp_path / "snaps"
        d.mkdir()
        older = str(d / "run_epoch1.pickle.gz")
        newest = str(d / "run_epoch2.pickle.gz")
        save_workflow({"marker": 1}, older)
        time.sleep(0.02)
        save_workflow({"marker": 2}, newest)
        return older, newest

    def test_verify_snapshot_probes_without_unpickling(self, tmp_path):
        from veles_tpu.faults import truncate_file
        from veles_tpu.snapshotter import verify_snapshot
        older, newest = self._lineage(tmp_path)
        assert verify_snapshot(older) and verify_snapshot(newest)
        truncate_file(newest)
        assert not verify_snapshot(newest)
        garbage = str(tmp_path / "g.pickle.gz")
        with open(garbage, "wb") as f:
            f.write(b"\x00" * 64)
        assert not verify_snapshot(garbage)

    def test_newest_intact_candidate_walks_lineage(self, tmp_path):
        """The manifest points at the newest snapshot; when that one
        is torn the supervisor walks siblings newest-first to the
        newest INTACT candidate."""
        from veles_tpu.faults import truncate_file
        from veles_tpu.snapshotter import write_resume_manifest
        older, newest = self._lineage(tmp_path)
        manifest = str(tmp_path / "manifest.json")
        os.environ["VELES_RESUME_MANIFEST"] = manifest
        try:
            write_resume_manifest(snapshot=newest)
        finally:
            del os.environ["VELES_RESUME_MANIFEST"]
        sup = Supervisor([], manifest_path=manifest)
        assert sup.newest_intact_snapshot() == newest
        truncate_file(newest)
        assert sup.newest_intact_snapshot() == older

    def test_argv_rewritten_to_newest_intact(self, tmp_path):
        from veles_tpu.snapshotter import write_resume_manifest
        older, newest = self._lineage(tmp_path)
        manifest = str(tmp_path / "manifest.json")
        os.environ["VELES_RESUME_MANIFEST"] = manifest
        try:
            write_resume_manifest(snapshot=newest)
        finally:
            del os.environ["VELES_RESUME_MANIFEST"]
        # an existing --snapshot value is REPLACED
        sup = Supervisor(["--snapshot", older, "wf.py"],
                         manifest_path=manifest)
        argv = sup._argv_for_attempt(1, downtime=0.5)
        assert argv == ["--snapshot", newest, "wf.py"]
        ev = telemetry.recent_events("supervisor.resumed")[-1]
        assert ev["source"] == "snapshot" and ev["state"] == newest
        assert ev["downtime"] == 0.5
        # no --snapshot flag: appended
        sup2 = Supervisor(["wf.py"], manifest_path=manifest)
        assert sup2._argv_for_attempt(1, None) == \
            ["wf.py", "--snapshot", newest]
        # attempt 0 (first spawn) never rewrites
        assert sup._argv_for_attempt(0, None) == \
            ["--snapshot", older, "wf.py"]

    def test_ga_runs_resume_via_their_own_state_file(self, tmp_path):
        """--optimize argv is left untouched (the child's --ga-state
        resumes by itself); the manifest's ga_state is reported as the
        resume source."""
        from veles_tpu.snapshotter import write_resume_manifest
        manifest = str(tmp_path / "manifest.json")
        os.environ["VELES_RESUME_MANIFEST"] = manifest
        try:
            write_resume_manifest(ga_state=str(tmp_path / "ga.json"))
        finally:
            del os.environ["VELES_RESUME_MANIFEST"]
        argv = ["--optimize", "4:2", "--ga-state",
                str(tmp_path / "ga.json"), "wf.py"]
        sup = Supervisor(list(argv), manifest_path=manifest)
        assert sup._argv_for_attempt(1, None) == argv
        ev = telemetry.recent_events("supervisor.resumed")[-1]
        assert ev["source"] == "ga_state"

    def test_manifest_merges_fields(self, tmp_path):
        """Snapshot and GA-state updates must not clobber each other —
        one manifest records the whole run's resume state."""
        from veles_tpu.snapshotter import (read_resume_manifest,
                                           write_resume_manifest)
        snap = str(tmp_path / "s" / "run_epoch1.pickle.gz")
        os.makedirs(os.path.dirname(snap))
        open(snap, "wb").close()
        manifest = str(tmp_path / "manifest.json")
        os.environ["VELES_RESUME_MANIFEST"] = manifest
        try:
            write_resume_manifest(snapshot=snap)
            write_resume_manifest(ga_state=str(tmp_path / "ga.json"))
        finally:
            del os.environ["VELES_RESUME_MANIFEST"]
        m = read_resume_manifest(manifest)
        assert m["snapshot"] == snap
        assert m["ga_state"] == str(tmp_path / "ga.json")
        # the copy next to the snapshot exists too (operator resume)
        sibling = read_resume_manifest(
            os.path.join(os.path.dirname(snap),
                         "resume_manifest.json"))
        assert sibling and sibling["snapshot"] == snap


def _tiny_workflow(max_epochs=6, snap_dir=None):
    from veles_tpu import prng
    from veles_tpu.datasets import synthetic_classification
    from veles_tpu.loader import ArrayLoader
    from veles_tpu.ops.standard_workflow import StandardWorkflow
    prng.seed_all(1357)
    train, valid, _ = synthetic_classification(
        160, 40, (8, 8, 1), n_classes=4, seed=7)
    gd = {"learning_rate": 0.1, "gradient_moment": 0.9}
    snap_cfg = None if snap_dir is None else \
        {"directory": str(snap_dir), "prefix": "phx",
         "interval": 1000}
    return StandardWorkflow(
        loader_factory=lambda w: ArrayLoader(
            w, train=train, valid=valid, minibatch_size=20,
            name="loader"),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
             "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": gd},
        ],
        decision_config={"max_epochs": max_epochs},
        snapshotter_config=snap_cfg, name="phx_t")


class TestGracefulStopBoundary:
    def test_mid_run_stop_snapshot_resume_is_bit_identical(
            self, tmp_path):
        """request_stop() mid-epoch stops at the iteration boundary
        (the Repeater), where a snapshot resumes EXACTLY: the
        completed run matches the uninterrupted oracle bit for bit —
        the property the SIGTERM drill's trajectory check rests on."""
        from veles_tpu.backends import JaxDevice
        from veles_tpu.snapshotter import load_workflow, save_workflow
        ref = _tiny_workflow()
        ref.initialize(device=JaxDevice(platform="cpu"))
        ref.run()
        ref_hist = [(h["class"], h["n_err"], float(h["loss"]))
                    for h in ref.decision.history]
        ref_w = np.asarray(
            ref.forwards[0].weights.map_read()).copy()

        w1 = _tiny_workflow()
        w1.initialize(device=JaxDevice(platform="cpu"))
        orig, calls = w1.loader.run, {"n": 0}

        def counting():
            orig()
            calls["n"] += 1
            if calls["n"] == 3:     # mid-run, mid-epoch
                w1.request_stop()
        w1.loader.run = counting
        w1.run()
        del w1.loader.__dict__["run"]
        assert w1.stop_requested
        epochs_done = len([h for h in w1.decision.history
                           if h["class"] == "validation"])
        assert 0 < epochs_done < 6   # genuinely interrupted
        path = str(tmp_path / "stop.pickle.gz")
        save_workflow(w1, path)

        w2 = load_workflow(path)
        # a graceful-stop snapshot must NOT carry the stale request
        # into the resumed run
        assert not w2.stop_requested
        w2.initialize(device=JaxDevice(platform="cpu"))
        w2.run()
        got_hist = [(h["class"], h["n_err"], float(h["loss"]))
                    for h in w2.decision.history]
        got_w = np.asarray(w2.forwards[0].weights.map_read())
        assert got_hist == ref_hist
        assert np.array_equal(got_w, ref_w)

    def test_run_clears_prior_stop_request(self):
        from veles_tpu.backends import NumpyDevice
        w = _tiny_workflow(max_epochs=1)
        w.initialize(device=NumpyDevice())
        w.request_stop()
        w.run()   # the request predates run(): must not stop at fire 0
        assert len([h for h in w.decision.history
                    if h["class"] == "validation"]) == 1

    def test_mid_class_stop_preserves_partial_metrics(self, tmp_path):
        """A class spanning SEVERAL superstep firings can be stopped
        BETWEEN them (any iteration boundary is a legal stop point).
        The fused runner's on-device metric accumulator must ride the
        snapshot: before the _acc/_conf carry existed, the resumed
        epoch's history row counted only post-resume minibatches — the
        chaos drill's load-sensitive `preempt.sigterm_resume`
        hist-parity flake (weights were exact; metrics were not)."""
        from veles_tpu import prng
        from veles_tpu.backends import JaxDevice
        from veles_tpu.datasets import synthetic_classification
        from veles_tpu.loader import ArrayLoader
        from veles_tpu.ops.standard_workflow import StandardWorkflow
        from veles_tpu.snapshotter import load_workflow, save_workflow

        def build(max_epochs=3):
            prng.seed_all(2468)
            # 480/20 = 24 train minibatches = THREE superstep-8
            # firings per class: firings 1 and 2 end mid-class
            train, valid, _ = synthetic_classification(
                480, 40, (8, 8, 1), n_classes=4, seed=9)
            gd = {"learning_rate": 0.1, "gradient_moment": 0.9}
            return StandardWorkflow(
                loader_factory=lambda w: ArrayLoader(
                    w, train=train, valid=valid, minibatch_size=20,
                    name="loader"),
                layers=[
                    {"type": "all2all_tanh",
                     "->": {"output_sample_shape": 16}, "<-": gd},
                    {"type": "softmax",
                     "->": {"output_sample_shape": 4}, "<-": gd},
                ],
                decision_config={"max_epochs": max_epochs},
                name="midclass_wf")

        ref = build()
        ref.initialize(device=JaxDevice(platform="cpu"))
        ref.run()
        ref_hist = [(h["class"], h["n_err"], float(h["loss"]))
                    for h in ref.decision.history]

        w1 = build()
        w1.initialize(device=JaxDevice(platform="cpu"))
        orig, calls = w1.loader.run, {"n": 0}

        def counting():
            orig()
            calls["n"] += 1
            if calls["n"] == 2:     # mid-TRAIN-class, mid-epoch 1
                w1.request_stop()
        w1.loader.run = counting
        w1.run()
        del w1.loader.__dict__["run"]
        assert w1.stop_requested
        assert not bool(w1.loader.class_ended)   # genuinely mid-class
        path = str(tmp_path / "midclass.pickle.gz")
        save_workflow(w1, path)

        w2 = load_workflow(path)
        w2.initialize(device=JaxDevice(platform="cpu"))
        w2.run()
        got_hist = [(h["class"], h["n_err"], float(h["loss"]))
                    for h in w2.decision.history]
        assert got_hist == ref_hist


class TestFinalSnapshotLineage:
    def test_final_snapshot_lands_in_lineage_with_manifest(
            self, tmp_path, monkeypatch):
        """final_snapshot(reason) names the file into the Snapshotter
        prefix lineage (snapshot_candidates discovers it) and points
        the resume manifest at it."""
        from veles_tpu.backends import NumpyDevice
        from veles_tpu.launcher import Launcher
        from veles_tpu.snapshotter import (read_resume_manifest,
                                           snapshot_candidates)
        w = _tiny_workflow(max_epochs=1, snap_dir=tmp_path)
        w.initialize(device=NumpyDevice())
        launcher = Launcher(backend="numpy")
        launcher.workflow = w
        out = launcher.final_snapshot("preempt-SIGTERM")
        assert out is not None
        base = os.path.basename(out)
        assert base.startswith("phx_final_preempt-SIGTERM_pid")
        # discovered from a hypothetical periodic sibling AND from the
        # final snapshot itself (both stems collapse to "phx")
        assert out in snapshot_candidates(
            str(tmp_path / "phx_epoch9.pickle.gz"))
        ev = telemetry.recent_events("preempt.final_snapshot")[-1]
        assert ev["path"] == out
        m = read_resume_manifest(
            str(tmp_path / "resume_manifest.json"))
        assert m["snapshot"] == out and m["reason"] == "preempt-SIGTERM"

    def test_multihost_reason_keeps_emergency_event(self, tmp_path):
        """The PR-6 _emergency_snapshot alias journals the multihost
        event name the existing drills/report assert on."""
        from veles_tpu.backends import NumpyDevice
        from veles_tpu.launcher import Launcher
        w = _tiny_workflow(max_epochs=1, snap_dir=tmp_path)
        w.initialize(device=NumpyDevice())
        launcher = Launcher(backend="numpy")
        launcher.workflow = w
        out = launcher._emergency_snapshot()
        assert "_final_multihost-abort_pid" in os.path.basename(out)
        ev = telemetry.recent_events(
            "multihost.emergency_snapshot")[-1]
        assert ev["path"] == out


class TestGAGracefulStop:
    def test_sigterm_stops_at_generation_boundary_exit_14(self):
        """install_ga_stop + GeneticOptimizer(stop_check=...): a real
        SIGTERM to this process halts breeding at the next generation
        boundary and finish() returns 14."""
        from veles_tpu import prng
        from veles_tpu.genetics import GeneticOptimizer, Tune
        from veles_tpu.supervisor import install_ga_stop
        stop_check, finish = install_ga_stop(grace=60.0)
        try:
            assert not stop_check()
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.monotonic() + 5.0
            while not stop_check() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert stop_check()
            prng.seed_all(4242)
            tunes = {"x": Tune(5.0, -10.0, 10.0)}
            opt = GeneticOptimizer(
                lambda v: (v["x"] - 2.0) ** 2, tunes, population=4,
                generations=5, stop_check=stop_check)
            opt.run()
            # initial population evaluated, then the loop halted at
            # its first boundary: exactly one (final) history entry
            assert len(opt.history) == 1
            assert telemetry.recent_events("preempt.ga_stop")
        finally:
            code = finish()
        assert code == EXIT_PREEMPTED
        assert telemetry.recent_events("preempt.ga_exit")


class TestChildCrashFault:
    def test_supervisor_child_crash_is_a_real_sigkill(self):
        code = (
            "from veles_tpu import faults\n"
            "faults.arm('supervisor.child_crash@attempt=0')\n"
            "faults.maybe_inject_child_crash(attempt='0')\n"
            "print('survived')\n"
        )
        env = dict(os.environ)
        env.pop("VELES_FAULTS", None)
        r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                           env=env, capture_output=True, text=True,
                           timeout=60)
        assert r.returncode == -signal.SIGKILL, (r.returncode,
                                                 r.stdout)
        # and the qualifier gate: attempt=1 must NOT crash
        code2 = code.replace("maybe_inject_child_crash(attempt='0')",
                             "maybe_inject_child_crash(attempt='1')")
        r2 = subprocess.run([sys.executable, "-c", code2], cwd=REPO,
                            env=env, capture_output=True, text=True,
                            timeout=60)
        assert r2.returncode == 0 and "survived" in r2.stdout


_RT_WF = """
import json
import os

from veles_tpu import prng
from veles_tpu.datasets import synthetic_classification
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow


def create_workflow(launcher):
    prng.seed_all(1357)
    train, valid, _ = synthetic_classification(
        2400, 400, (8, 8, 1), n_classes=4, seed=7)
    gd = {"learning_rate": 0.1, "gradient_moment": 0.9}
    return StandardWorkflow(
        loader_factory=lambda w: ArrayLoader(
            w, train=train, valid=valid, minibatch_size=24,
            name="loader"),
        layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 24}, "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": gd},
        ],
        decision_config={"max_epochs": 150,
                         "fail_iterations": 10000},
        snapshotter_config={"directory": os.environ["RT_SNAP_DIR"],
                            "prefix": "rt", "interval": 1000},
        name="rt_wf")


def run(launcher):
    launcher.create_workflow(create_workflow)
    launcher.initialize()
    launcher.run()
    w = launcher.workflow
    epochs = len([h for h in w.decision.history
                  if h["class"] == "validation"])
    print(json.dumps({"rt_epochs": epochs}))
"""


class TestSigtermResumeRoundTrip:
    def test_real_subprocess_sigterm_then_auto_resume(self, tmp_path):
        """The bounded end-to-end pin (PR-6 hang-test style): a real
        ``--supervise`` run is SIGTERMed mid-training by the injected
        preemption fault; the child must write its final snapshot
        inside the grace deadline and exit 14, and the supervisor must
        auto-resume it to completion (exit 0, all epochs trained).
        Full trajectory parity vs the oracle lives in the chaos drill;
        this tier-1 test pins the mechanics in bounded time."""
        wf = tmp_path / "wf.py"
        wf.write_text(_RT_WF)
        snaps = tmp_path / "snaps"
        mdir = tmp_path / "metrics"
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "RT_SNAP_DIR": str(snaps),
            "VELES_METRICS_DIR": str(mdir),
            "VELES_PREEMPT_GRACE": "20",
            "VELES_FAULTS": "preempt.sigterm@attempt=0&after=1.2",
        })
        env.pop("VELES_RESUME_MANIFEST", None)
        res = subprocess.run(
            [sys.executable, "-m", "veles_tpu", "--supervise",
             "-b", "cpu", str(wf)],
            env=env, capture_output=True, text=True, timeout=240,
            cwd=REPO)
        assert res.returncode == 0, \
            (res.returncode, res.stderr[-1200:])
        out = json.loads(res.stdout.strip().splitlines()[-1])
        assert out["rt_epochs"] == 150
        # the final snapshot landed in the lineage
        assert any(f.startswith("rt_final_preempt-SIGTERM")
                   for f in os.listdir(snaps)), os.listdir(snaps)
        # journal: requested -> final snapshot inside grace (never the
        # watchdog's hard path) -> supervisor resumed from it
        events = []
        for jf in os.listdir(mdir):
            if jf.startswith("journal-"):
                with open(mdir / jf) as f:
                    events += [json.loads(line) for line in f]
        names = [e["event"] for e in events]
        assert "preempt.requested" in names
        assert "preempt.final_snapshot" in names
        assert "preempt.deadline_exceeded" not in names
        req = [e for e in events
               if e["event"] == "preempt.requested"][-1]
        fin = [e for e in events
               if e["event"] == "preempt.final_snapshot"][-1]
        assert 0 <= fin["ts"] - req["ts"] <= 20.0
        resumed = [e for e in events
                   if e["event"] == "supervisor.resumed"][-1]
        assert resumed["source"] == "snapshot"
        assert "rt_final_preempt" in resumed["state"]
        assert [e for e in events
                if e["event"] == "supervisor.done"]
