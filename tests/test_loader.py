"""Loader epoch/minibatch bookkeeping, shuffling determinism,
normalizers, synthetic datasets (SURVEY.md §7 phase 3)."""

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.loader import ArrayLoader, TRAIN, VALID, TEST
from veles_tpu.normalization import make_normalizer
from veles_tpu import datasets


def make_loader(n_train=10, n_valid=4, mb=4, **kw):
    x = np.arange(n_train * 3, dtype=np.float32).reshape(n_train, 3)
    y = np.arange(n_train, dtype=np.int32) % 2
    vx = -np.arange(n_valid * 3, dtype=np.float32).reshape(n_valid, 3)
    vy = np.arange(n_valid, dtype=np.int32) % 2
    ld = ArrayLoader(train=(x, y), valid=(vx, vy),
                     minibatch_size=mb, **kw)
    ld.initialize(device=None)
    return ld


class TestLoader:
    def test_split_layout(self):
        ld = make_loader()
        assert ld.class_lengths == [0, 4, 10]
        assert ld.class_offset(TRAIN) == 4
        assert ld.total_samples == 14

    def test_epoch_walks_valid_then_train(self):
        ld = make_loader(shuffle=False)
        classes, sizes = [], []
        for _ in range(4):  # 1 valid mb + 3 train mbs (10/4 -> 4,4,2)
            ld.run()
            classes.append(ld.minibatch_class)
            sizes.append(ld.current_minibatch_size)
        assert classes == [VALID, TRAIN, TRAIN, TRAIN]
        assert sizes == [4, 4, 4, 2]
        assert bool(ld.epoch_ended) and bool(ld.last_minibatch)
        assert ld.epoch_number == 1

    def test_remainder_padding_and_mask(self):
        ld = make_loader(shuffle=False)
        for _ in range(4):
            ld.run()
        mask = ld.minibatch_mask.map_read()
        np.testing.assert_array_equal(mask, [1, 1, 0, 0])
        # padded rows hold wrapped indices but mask excludes them
        assert ld.minibatch_indices.map_read().shape == (4,)

    def test_fill_minibatch_content(self):
        ld = make_loader(shuffle=False)
        ld.run()  # first valid minibatch
        got = ld.minibatch_data.map_read()
        np.testing.assert_array_equal(got, ld.original_data.mem[:4])

    def test_shuffle_deterministic_and_reshuffled(self):
        ld = make_loader(shuffle=True)
        order1 = ld._order[TRAIN].copy()
        prng.seed_all(1234)
        ld2 = make_loader(shuffle=True)
        np.testing.assert_array_equal(order1, ld2._order[TRAIN])
        # next epoch must use a different permutation
        for _ in range(4):
            ld2.run()
        assert not np.array_equal(order1, ld2._order[TRAIN])

    def test_train_only(self):
        x = np.zeros((6, 2), np.float32)
        y = np.zeros(6, np.int32)
        ld = ArrayLoader(train=(x, y), minibatch_size=3)
        ld.initialize(device=None)
        ld.run()
        assert ld.minibatch_class == TRAIN

    def test_autoencoder_targets(self):
        x = np.random.default_rng(0).random((6, 2)).astype(np.float32)
        ld = ArrayLoader(train=(x, None), targets_from_labels=True,
                         minibatch_size=3, shuffle=False)
        ld.initialize(device=None)
        ld.run()
        np.testing.assert_array_equal(ld.minibatch_targets.mem,
                                      ld.minibatch_data.map_read())


class TestNormalizers:
    def test_linear(self):
        n = make_normalizer("linear")
        x = np.float32([[0.0], [5.0], [10.0]])
        out = n.fit(x).apply(x)
        np.testing.assert_allclose(out, [[-1], [0], [1]])

    def test_mean_disp(self):
        rng = np.random.default_rng(0)
        x = rng.random((100, 5)).astype(np.float32) * 7 + 3
        out = make_normalizer("mean_disp").fit(x).apply(x)
        np.testing.assert_allclose(out.mean(0), 0, atol=1e-5)
        np.testing.assert_allclose(out.std(0), 1, atol=1e-4)

    def test_external_mean(self):
        x = np.ones((4, 2, 2), np.float32)
        n = make_normalizer("external_mean", mean=np.ones((2, 2)))
        np.testing.assert_allclose(n.apply(x), 0)

    def test_pointwise(self):
        x = np.float32([[0, 10], [4, 20]])
        out = make_normalizer("pointwise").fit(x).apply(x)
        np.testing.assert_allclose(out, [[-1, -1], [1, 1]])


class TestSyntheticDatasets:
    def test_deterministic(self):
        (x1, y1), _, _ = datasets.mnist(200, 50, force_synthetic=True)
        (x2, y2), _, _ = datasets.mnist(200, 50, force_synthetic=True)
        np.testing.assert_array_equal(x1, x2)
        np.testing.assert_array_equal(y1, y2)

    def test_shapes_and_ranges(self):
        (x, y), (vx, vy), _ = datasets.mnist(100, 20, force_synthetic=True)
        assert x.shape == (100, 28, 28, 1) and y.shape == (100,)
        assert vx.shape == (20, 28, 28, 1)
        assert 0 <= x.min() and x.max() <= 1
        assert set(np.unique(y)) <= set(range(10))

    def test_cifar_shape(self):
        (x, y), _, _ = datasets.cifar10(50, 10, force_synthetic=True)
        assert x.shape == (50, 32, 32, 3)

    def test_learnable(self):
        """A linear classifier on raw pixels must beat chance easily —
        guards against an unlearnable generator."""
        (x, y), (vx, vy), _ = datasets.mnist(2000, 400,
                                             force_synthetic=True)
        xf = x.reshape(len(x), -1)
        vxf = vx.reshape(len(vx), -1)
        # one-hot ridge regression
        onehot = np.eye(10, dtype=np.float32)[y]
        A = xf.T @ xf + 10.0 * np.eye(xf.shape[1], dtype=np.float32)
        W = np.linalg.solve(A, xf.T @ onehot)
        acc = (vxf @ W).argmax(1) == vy
        assert acc.mean() > 0.9, acc.mean()


class TestDeviceSyntheticLoader:
    """The loader the headline benchmark depends on (round-4 advisor:
    it shipped untested).  Device path, every fallback predicate, and
    the mesh-replicated generation (round-4 VERDICT next #7)."""

    def _make(self, device, **kw):
        from veles_tpu.loader.synthetic import DeviceSyntheticLoader
        kw.setdefault("n_train", 32)
        kw.setdefault("n_valid", 8)
        kw.setdefault("shape", (8, 8, 1))
        ld = DeviceSyntheticLoader(minibatch_size=8, seed=7, **kw)
        ld.initialize(device=device)
        return ld

    def test_device_born(self):
        from veles_tpu.backends import JaxDevice
        ld = self._make(JaxDevice(platform="cpu"))
        # born in device memory: devmem bound, no host copy ever made
        assert ld.original_data.devmem is not None
        assert ld.original_data._mem is None
        assert ld.original_labels.devmem is not None
        assert ld.class_lengths == [0, 8, 32]  # [test|valid|train]
        y = np.asarray(ld.original_labels.devmem)
        assert y.shape == (40,) and set(np.unique(y)) <= set(range(10))
        x = np.asarray(ld.original_data.devmem)
        assert x.shape == (40, 8, 8, 1)
        assert 0.0 <= x.min() and x.max() <= 1.0

    def test_mesh_replicated_generation(self):
        from veles_tpu.parallel import MeshJaxDevice, make_mesh
        ld = self._make(MeshJaxDevice(make_mesh(8)))
        data = ld.original_data.devmem
        assert data is not None, "mesh device must not fall back to host"
        assert ld.original_data._mem is None
        assert data.sharding.is_fully_replicated
        assert np.isfinite(np.asarray(data)).all()

    def test_fallback_numpy_device(self):
        ld = self._make(None)
        assert ld.original_data.mem is not None  # host generator ran

    def test_fallback_normalization(self):
        from veles_tpu.backends import JaxDevice
        ld = self._make(JaxDevice(platform="cpu"),
                        normalization_type="mean_disp")
        # the normalizer fit reads host arrays -> host generator path
        assert ld.original_data.mem is not None
        assert ld.normalizer is not None

    def test_fallback_residency_budget(self):
        from veles_tpu.backends import JaxDevice
        ld = self._make(JaxDevice(platform="cpu"), max_resident_bytes=64)
        # over-budget sets must stay host-side (streaming by design)
        assert ld.original_data.mem is not None
        assert not ld.device_resident

    def test_device_matches_host_structure(self):
        """Device and host generators express the same task family:
        both learnable, same shapes, same label distribution support."""
        from veles_tpu.backends import JaxDevice
        dev_ld = self._make(JaxDevice(platform="cpu"), n_train=64)
        host_ld = self._make(None, n_train=64)
        assert np.asarray(dev_ld.original_data.devmem).shape == \
            host_ld.original_data.mem.shape
