"""Lockstep (ISSUE 13): the flow-aware concurrency rules catch their
seeded fixtures and pass the clean twins, the checked-in locking law
(analysis/lock_order.json) is cycle-free and drift-free, and the
runtime witness — armed over a REAL serving subprocess plus in-process
batcher/sentinel traffic — observes only edges the static law
declares (at least 3 distinct ones, proving it actually recorded),
while costing nothing when disabled (the factories return the bare
threading primitives)."""

import json
import os
import threading
import time

import numpy as np
import pytest

from veles_tpu.analysis import Config, repo_root, scan_source
from veles_tpu.analysis import flow, witness
from veles_tpu.analysis.engine import ModuleContext

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "veleslint")


def fixture(name: str) -> str:
    with open(os.path.join(FIXTURES, name), encoding="utf-8") as f:
        return f.read()


def project_for(sources, config=None):
    """{path: source} -> (Project, config) for the flow analyses."""
    config = config or Config()
    ctxs = [ModuleContext(p, s, config) for p, s in sources.items()]
    return flow.build_project(ctxs), config


LAW_PATH = os.path.join(repo_root(), "veles_tpu", "analysis",
                        "lock_order.json")


# -- blocking-under-lock -----------------------------------------------

class TestBlockingUnderLock:
    PATH = "veles_tpu/serve/_fx_blocking.py"

    def _scan(self, name):
        project, _ = project_for({self.PATH: fixture(name)})
        return flow.blocking_findings(project, [self.PATH])

    def test_catches_seeded(self):
        got = self._scan("blocking_bad.py")
        whats = {f.detail.split(":", 1)[1].split(" (")[0]
                 for f in got}
        assert "time.sleep()" in whats
        assert "Queue.get() with no timeout" in whats
        assert ".result() with no timeout" in whats
        assert "Popen.wait() with no timeout" in whats
        # the transitive case: a helper that sleeps, called under
        # the lock — flagged at the call site with the chain
        indirect = [f for f in got
                    if f.detail.startswith("Worker.indirect")]
        assert indirect and "via" in indirect[0].detail, got

    def test_clean(self):
        assert self._scan("blocking_clean.py") == []


# -- waiter-discipline -------------------------------------------------

class TestWaiterDiscipline:
    PATH = "veles_tpu/serve/_fx_waiter.py"

    def _scan(self, name):
        project, _ = project_for({self.PATH: fixture(name)})
        return flow.waiter_findings(project, [self.PATH])

    def test_catches_seeded(self):
        got = self._scan("waiter_bad.py")
        by_fn = {f.detail.split(":", 1)[0].split(".")[-1]
                 for f in got}
        assert by_fn == {"timeout_leak", "branch_leak", "dropped",
                         "future_leak"}, got
        # the PR-12 class specifically: the exception edge
        tl = [f for f in got if "timeout_leak" in f.detail]
        assert "exception path" in tl[0].message

    def test_clean(self):
        assert self._scan("waiter_clean.py") == []


# -- lock-order --------------------------------------------------------

class TestLockOrderGraph:
    PATH = "veles_tpu/serve/_fx_lockorder.py"

    def _graph(self, name):
        project, _ = project_for({self.PATH: fixture(name)})
        return flow.build_lock_graph(project, scope=[self.PATH])

    def test_cycle_detected(self):
        g = self._graph("lockorder_bad.py")
        pairs = g.edge_pairs()
        # the witness-named lock and the derived identity both node
        assert any(a == "fx.alpha" for a, _ in pairs) or \
            any(b == "fx.alpha" for _, b in pairs)
        cycles = g.cycles()
        assert cycles, pairs
        assert set(cycles[0]) == {"fx.alpha",
                                  "veles_tpu/serve/_fx_lockorder"
                                  .replace("veles_tpu/", "")
                                  .replace("/", ".") + "._beta"}

    def test_clean_graph_is_acyclic(self):
        g = self._graph("lockorder_clean.py")
        assert g.edge_pairs(), "edges expected from nesting"
        assert g.cycles() == []

    def test_checked_in_law_is_cycle_free_and_current(self):
        """The committed locking law parses, has no cycle, and
        matches a fresh static build — the PR's reviewable statement
        of the threading model."""
        payload = flow.load_lock_order(LAW_PATH)
        assert payload is not None, "lock_order.json must be present"
        declared = flow.declared_edges(payload)
        assert declared, "the serving tier has nested acquisitions"
        g = flow.LockGraph()
        for e in declared:
            g.add_edge(e[0], e[1], "declared")
        assert g.cycles() == []
        # every declared lock is witness-named: the runtime witness
        # and the static law share identities
        assert all(n.get("witnessed")
                   for n in payload["nodes"]), payload["nodes"]


# -- wire-protocol / thread-lifecycle ----------------------------------

class TestWireProtocol:
    def _scan(self, name):
        cfg = Config(wire_modules=["fx/wire.py"])
        found = scan_source("fx/wire.py", fixture(name), cfg)
        return [f for f in found if f.rule == "wire-protocol"]

    def test_catches_seeded(self):
        got = self._scan("wire_bad.py")
        assert {f.detail for f in got} == \
            {"modle", "bogus_field", "why_not"}, got

    def test_clean(self):
        assert self._scan("wire_clean.py") == []

    def test_registry_covers_live_protocol(self):
        from veles_tpu.serve import protocol
        for key in ("id", "model", "rows", "deadline_ms", "pred",
                    "probs", "rows_n", "crc", "expired", "error",
                    "overloaded", "ready", "hb", "stats", "fleet"):
            assert protocol.known(key), key
        assert not protocol.known("jid")   # internal name, not wire


class TestThreadLifecycle:
    def _scan(self, name):
        cfg = Config(thread_modules=["fx/threads.py"])
        found = scan_source("fx/threads.py", fixture(name), cfg)
        return [f for f in found if f.rule == "thread-lifecycle"]

    def test_catches_seeded(self):
        got = self._scan("thread_bad.py")
        assert len(got) == 1 and got[0].detail == "thread:straggler"

    def test_clean(self):
        assert self._scan("thread_clean.py") == []


# -- the runtime witness -----------------------------------------------

class TestWitnessUnit:
    def test_off_by_default_zero_cost(self):
        """Disabled, the factories return the BARE threading
        primitives — overhead is zero by construction (same object
        type, same C fastpath), pinned here by type identity plus a
        generous timing bound against scheduler noise."""
        assert not witness.enabled()
        lk = witness.lock("x")
        assert type(lk) is type(threading.Lock())
        cond = witness.condition("x")
        assert type(cond) is type(threading.Condition())

        def clock(lock, n=20000):
            t0 = time.perf_counter()
            for _ in range(n):
                with lock:
                    pass
            return time.perf_counter() - t0

        bare = threading.Lock()
        clock(bare), clock(lk)           # warm both
        ratio = clock(lk) / max(clock(bare), 1e-9)
        assert ratio < 1.5, f"disabled witness cost ratio {ratio}"

    def test_edge_recording_and_lifo_release(self, monkeypatch):
        monkeypatch.setenv(witness.ENV_VAR, "1")
        witness.reset()
        a = witness.lock("t.a")
        b = witness.lock("t.b")
        c = witness.rlock("t.c")
        with a:
            with b:
                with c:
                    with c:   # re-entrant: no self edge
                        pass
        assert witness.observed_edges() == [
            ("t.a", "t.b"), ("t.a", "t.c"), ("t.b", "t.c")]
        # releases unwound: a fresh acquisition records no stale edges
        witness.reset()
        with b:
            pass
        assert witness.observed_edges() == []

    def test_condition_wait_releases_for_the_wait(self, monkeypatch):
        monkeypatch.setenv(witness.ENV_VAR, "1")
        witness.reset()
        cond = witness.condition("t.cond")
        hits = []

        def waiter():
            with cond:
                hits.append("waiting")
                cond.wait(2.0)
                hits.append("woke")

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        for _ in range(200):
            if hits:
                break
            time.sleep(0.01)
        with cond:           # acquirable only because wait released
            cond.notify_all()
        t.join(timeout=5.0)
        assert hits == ["waiting", "woke"]
        assert witness.observed_edges() == []   # no nesting happened

    def test_snapshot_roundtrip(self, monkeypatch, tmp_path):
        monkeypatch.setenv(witness.ENV_VAR, "1")
        witness.reset()
        a, b = witness.lock("t.outer"), witness.lock("t.inner")
        with a:
            with b:
                pass
        path = witness.write_snapshot(str(tmp_path))
        assert path and os.path.isfile(path)
        with open(path) as f:
            data = json.load(f)
        assert data["edges"] == [
            {"from": "t.outer", "to": "t.inner", "count": 1}]
        assert witness.read_snapshots(str(tmp_path)) == [
            ("t.outer", "t.inner")]


class TestWitnessAgainstTheLaw:
    """The acceptance property: REAL execution under the witness
    observes only edges the static law declares."""

    def test_in_process_serving_edges_subset_of_law(self,
                                                    monkeypatch):
        from veles_tpu import telemetry
        from veles_tpu.serve.batcher import MicroBatcher
        from veles_tpu.serve.sentinel import Sentinel
        monkeypatch.setenv(witness.ENV_VAR, "1")
        witness.reset()

        mb = MicroBatcher(lambda xb: xb.sum(axis=1), max_batch=8,
                          max_wait_s=0.002, label="lockstep")
        futs = [mb.submit(np.ones((2, 4), np.float32))
                for _ in range(16)]
        for f in futs:
            f.result(timeout=10)
        mb.close()

        class FakeReplica:
            def __init__(self, i):
                self.idx = i
                self.healthy = True
                self.client = None

        s = Sentinel([FakeReplica(0), FakeReplica(1)],
                     probe_fn=lambda r, m, rows: (True, "ok"))
        # a UNIQUE model name so its latency histogram (and its
        # witnessed lock) is created after arming
        model = f"lockstep_m{os.getpid()}"
        h = telemetry.histogram(
            f"fleet.model.{model}.request_seconds")
        for _ in range(40):
            h.record(0.01)
        s.hedge_threshold_ms(model)
        time.sleep(0.6)
        s.hedge_threshold_ms(model)
        s.close()

        observed = set(witness.observed_edges())
        declared = flow.declared_edges(
            flow.load_lock_order(LAW_PATH))
        assert observed, "the witness recorded nothing"
        assert observed <= declared, (
            f"UNDECLARED runtime edges {sorted(observed - declared)}"
            f" — the static model has a gap; review and run "
            f"scripts/veleslint.py --sync-lock-order")
        # the sentinel edge is deterministic here (fresh histogram)
        assert ("sentinel.health", "telemetry.histogram") in observed

    def test_real_hive_under_witness(self, packages_dir,
                                     tmp_path):
        """A real --serve-models subprocess, armed: its lockwitness
        snapshot must exist and stay inside the law; unioned with the
        in-process edges this pins >= 3 distinct observed edges."""
        from veles_tpu.serve.client import HiveClient
        mdir = str(tmp_path / "metrics")
        c = HiveClient(
            {"alpha": packages_dir}, backend="cpu", max_batch=8,
            max_wait_ms=2.0, heartbeat_every=0.5,
            metrics_dir=mdir,
            env={"VELES_LOCK_WITNESS": "1"}, cwd=repo_root(),
            start_timeout=300.0)
        try:
            rows = np.random.default_rng(0).standard_normal(
                (4, 6, 6, 1)).astype(np.float32)
            threads = []
            errs = []

            def one():
                try:
                    r = c.request("alpha", rows, timeout=60.0)
                    assert "probs" in r, r
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            for _ in range(8):
                t = threading.Thread(target=one)
                t.start()
                threads.append(t)
            for t in threads:
                t.join(timeout=120.0)
            assert not errs, errs
        finally:
            c.close()
        observed = set(witness.read_snapshots(mdir))
        assert observed, "hive left no lockwitness snapshot"
        declared = flow.declared_edges(
            flow.load_lock_order(LAW_PATH))
        assert observed <= declared, (
            f"UNDECLARED runtime edges in the hive: "
            f"{sorted(observed - declared)}")
        assert ("batcher.queue", "telemetry.histogram") in observed
        assert ("batcher.queue", "telemetry.registry") in observed
        # >= 3 distinct edges across the witnessed executions: the
        # hive's two batcher edges + the in-process sentinel edge
        # (test above) cover three distinct pairs of the law
        assert len(declared) >= 3


@pytest.fixture(scope="module")
def packages_dir(tmp_path_factory):
    """One Forge ensemble package for the witnessed hive."""
    import test_serve   # pytest puts tests/ on sys.path
    d = str(tmp_path_factory.mktemp("lockstep_pkgs"))
    return test_serve._build_package(d, "alpha", 77)["pkg"]
