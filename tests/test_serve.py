"""Hive serving tier (ISSUE 10 tentpole): dynamic micro-batching,
multi-model HBM residency with LRU spill, the request-level engine
API, and the real subprocess round trip over the
``python -m veles_tpu --serve-models`` CLI surface.

The subprocess tests each spawn ONE server and drive it with
concurrent client threads, asserting (a) responses match the host
member-loop oracle, (b) concurrent requests actually coalesced
(batch-size histogram max > 1), (c) SIGTERM drains in-flight requests
and exits 14, (d) an over-budget model load spills the LRU model and
journals the event.
"""

import json
import os
import textwrap
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WF_TEXT = textwrap.dedent("""
    from veles_tpu import prng
    from veles_tpu.datasets import synthetic_classification
    from veles_tpu.loader import ArrayLoader
    from veles_tpu.ops.standard_workflow import StandardWorkflow

    def create_workflow(launcher):
        prng.seed_all(4242)
        train, valid, _ = synthetic_classification(
            64, 16, (6, 6, 1), n_classes=3, seed=5)
        return StandardWorkflow(
            loader_factory=lambda w: ArrayLoader(
                w, train=train, valid=valid, minibatch_size=16,
                name="loader"),
            layers=[
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 12},
                 "<-": {"learning_rate": 0.1}},
                {"type": "softmax", "->": {"output_sample_shape": 3},
                 "<-": {"learning_rate": 0.1}},
            ],
            decision_config={"max_epochs": 2}, name="hive_wf")
""")


def _build_package(d, name, seed, n_members=3):
    """One Forge ensemble package + its host oracle ingredients."""
    from veles_tpu import prng
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.ensemble.packaging import pack_ensemble
    from veles_tpu.launcher import load_workflow_module

    wf_path = os.path.join(d, f"wf_{name}.py")
    with open(wf_path, "w") as f:
        f.write(WF_TEXT)
    mod = load_workflow_module(wf_path)

    class FL:
        workflow = None

    prng.seed_all(seed)
    w = mod.create_workflow(FL())
    w.initialize(device=NumpyDevice())
    base = {fw.name: {k: np.asarray(v) for k, v in
                      fw.gather_params().items()}
            for fw in w.forwards}
    rng = np.random.default_rng(seed)
    members = []
    for _ in range(n_members):
        params = {fn: {pn: (a + 0.05 * rng.standard_normal(a.shape)
                            .astype(np.float32))
                       for pn, a in p.items()}
                  for fn, p in base.items()}
        members.append({"params": params, "valid_error": 0.0,
                        "seed": seed,
                        "forward_names": [fw.name
                                          for fw in w.forwards],
                        "values": None})
    pkg = os.path.join(d, f"{name}.vpkg")
    pack_ensemble(pkg, name, members, wf_path)
    return {"pkg": pkg, "members": members, "workflow": w}


def _host_oracle(model, x):
    """The numpy member-loop mean-probability oracle."""
    acc = None
    for m in model["members"]:
        out = np.asarray(x, np.float32)
        for fw in model["workflow"].forwards:
            p = {k: np.asarray(v)
                 for k, v in m["params"][fw.name].items()}
            out, _ = fw.apply_fwd(p, out, rng=None, train=False)
        out = np.asarray(out)
        acc = out if acc is None else acc + out
    return acc / len(model["members"])


def _journal_events(metrics_dir, name):
    out = []
    if not os.path.isdir(metrics_dir):
        return out
    for fn in os.listdir(metrics_dir):
        if not fn.startswith("journal-"):
            continue
        with open(os.path.join(metrics_dir, fn)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("event") == name:
                    out.append(ev)
    return out


@pytest.fixture(scope="module")
def packages(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("hive_pkgs"))
    return {"alpha": _build_package(d, "alpha", 11),
            "beta": _build_package(d, "beta", 22)}


class TestMicroBatcher:
    """In-process batching semantics (no subprocess)."""

    def _batcher(self, dispatch, **kw):
        from veles_tpu.serve.batcher import MicroBatcher
        kw.setdefault("max_batch", 8)
        kw.setdefault("max_wait_s", 0.05)
        return MicroBatcher(dispatch, **kw)

    def test_single_request_flushes_at_max_wait(self):
        batches = []

        def dispatch(xb):
            batches.append(xb.shape)
            return xb.sum(axis=tuple(range(1, xb.ndim)))

        b = self._batcher(dispatch, max_batch=8, max_wait_s=0.02)
        t0 = time.perf_counter()
        out = b.submit(np.ones((2, 3))).result(timeout=5)
        dt = time.perf_counter() - t0
        assert out.shape == (2,) and np.allclose(out, 3.0)
        # the lone request waited ~max_wait, not forever — and the
        # dispatch shape is the FIXED max_batch chunk, zero-padded
        assert dt < 2.0
        assert batches == [(8, 3)]
        b.close()

    def test_concurrent_requests_coalesce_in_order(self):
        sizes = []

        def dispatch(xb):
            sizes.append(len(xb))
            return xb * 2.0

        b = self._batcher(dispatch, max_batch=16, max_wait_s=0.25)
        futs = [b.submit(np.full((2, 4), i, np.float32))
                for i in range(4)]
        outs = [f.result(timeout=5) for f in futs]
        for i, out in enumerate(outs):
            assert np.allclose(out, 2.0 * i), (i, out)
        # 8 rows < max_batch: ONE flush carried all four requests
        from veles_tpu import telemetry
        assert sizes == [16]   # fixed shape (padded)
        h = telemetry.histogram("serve.batch_rows")
        assert h.max >= 8
        b.close()

    def test_oversized_request_splits_across_dispatches(self):
        n_dispatches = []

        def dispatch(xb):
            n_dispatches.append(len(xb))
            return xb + 1.0

        b = self._batcher(dispatch, max_batch=4, max_wait_s=0.01)
        rows = np.arange(10, dtype=np.float32).reshape(10, 1)
        out = b.submit(rows).result(timeout=5)
        assert out.shape == (10, 1)
        assert np.allclose(out, rows + 1.0)
        assert len(n_dispatches) == 3   # 4 + 4 + 2 rows
        b.close()

    def test_mismatched_sample_shape_bounces_at_submit(self):
        b = self._batcher(lambda xb: xb, max_batch=4,
                          max_wait_s=0.01, sample_shape=(3,))
        with pytest.raises(ValueError):
            b.submit(np.ones((2, 5), np.float32))
        out = b.submit(np.ones((1, 3), np.float32)).result(timeout=5)
        assert out.shape == (1, 3)
        b.close()

    def test_failed_dispatch_fails_only_its_batch(self):
        calls = {"n": 0}

        def dispatch(xb):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("boom")
            return xb

        b = self._batcher(dispatch, max_batch=4, max_wait_s=0.01)
        f1 = b.submit(np.ones((1, 2), np.float32))
        with pytest.raises(RuntimeError):
            f1.result(timeout=5)
        # the flush loop survived: the next request dispatches fine
        out = b.submit(np.ones((1, 2), np.float32)).result(timeout=5)
        assert out.shape == (1, 2)
        b.close()

    def test_drain_resolves_everything(self):
        def dispatch(xb):
            time.sleep(0.01)
            return xb

        b = self._batcher(dispatch, max_batch=2, max_wait_s=0.5)
        futs = [b.submit(np.ones((1, 2), np.float32))
                for _ in range(7)]
        assert b.drain(timeout=10)
        assert all(f.done() for f in futs)
        b.close()

    def test_expired_request_dropped_before_dispatch(self):
        """Sentinel deadline semantics: a request whose deadline_ms
        passed while it was still fully queued is dropped with
        DeadlineExpired and NEVER dispatched — computing an answer
        nobody is waiting for would steal the window from requests
        that can still make their deadline."""
        from veles_tpu import telemetry
        from veles_tpu.serve.batcher import DeadlineExpired
        dispatched = []

        def dispatch(xb):
            dispatched.append(len(xb))
            return xb

        dropped0 = telemetry.counter("serve.deadline_dropped").value
        b = self._batcher(dispatch, max_batch=4, max_wait_s=0.1)
        # already expired at submit: must never reach the dispatcher
        f_dead = b.submit(np.full((1, 2), 7.0, np.float32),
                          deadline_ms=time.time() * 1000.0 - 50.0)
        with pytest.raises(DeadlineExpired):
            f_dead.result(timeout=5)
        # a live-deadline request on the same batcher still answers
        f_ok = b.submit(np.ones((1, 2), np.float32),
                        deadline_ms=time.time() * 1000.0 + 30000.0)
        assert f_ok.result(timeout=5).shape == (1, 2)
        assert telemetry.counter(
            "serve.deadline_dropped").value == dropped0 + 1
        # the expired request's payload (7.0) never dispatched
        b.drain(timeout=5)
        b.close()


class TestHiveRoundTrip:
    """(a) oracle parity under N concurrent clients and (b) request
    coalescing, through the real ``--serve-models`` CLI subprocess."""

    @pytest.fixture(scope="class")
    def client(self, packages, tmp_path_factory):
        from veles_tpu.serve.client import HiveClient
        mdir = str(tmp_path_factory.mktemp("hive_metrics"))
        c = HiveClient(
            {"alpha": packages["alpha"]["pkg"],
             "beta": packages["beta"]["pkg"]},
            backend="cpu", max_batch=16, max_wait_ms=20,
            metrics_dir=mdir, cwd=REPO)
        c.metrics_dir = mdir
        yield c
        c.close()

    def test_hello_reports_models_resident(self, client):
        h = client.hello
        assert h["ready"] and h["platform"] == "cpu"
        assert set(h["models"]) == {"alpha", "beta"}
        for m in h["models"].values():
            assert m["members"] == 3 and m["resident"]

    def test_concurrent_responses_match_host_oracle(self, client,
                                                    packages):
        errs = []

        def worker(i):
            try:
                rng = np.random.default_rng(100 + i)
                name = "alpha" if i % 2 == 0 else "beta"
                for _ in range(4):
                    x = rng.standard_normal((2, 6, 6, 1)) \
                        .astype(np.float32)
                    r = client.request(name, x, timeout=60)
                    assert "probs" in r, r
                    got = np.asarray(r["probs"], np.float32)
                    want = _host_oracle(packages[name], x)
                    np.testing.assert_allclose(got, want, atol=1e-4)
                    assert r["pred"] == list(
                        np.argmax(want, axis=-1))
            except Exception as e:  # noqa: BLE001 — collected below
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs

    def test_requests_were_coalesced(self, client):
        st = client.stats()
        h = st["histograms"].get("serve.batch_rows")
        assert h, "no serve.batch_rows histogram in the snapshot"
        assert h["max"] > 1, h   # >1 row in one dispatch = coalesced
        # latency histogram present with quantiles — the SLO surface
        lat = st["histograms"]["serve.request_seconds"]
        assert lat["count"] > 0 and lat["p99"] is not None
        # batch-efficiency accounting: valid rows never exceed slots
        c = st["counters"]
        assert 0 < c["serve.rows"] <= c["serve.batch_slots"]

    def test_steady_state_has_zero_recompiles(self, client):
        before = client.stats()["counters"].get("serve.compiles", 0)
        rng = np.random.default_rng(7)
        for _ in range(5):
            x = rng.standard_normal((3, 6, 6, 1)).astype(np.float32)
            assert "probs" in client.request("alpha", x, timeout=60)
        after = client.stats()["counters"].get("serve.compiles", 0)
        # both models compiled exactly once (at their first dispatch);
        # the warm window added nothing
        assert after == before
        assert after <= 2

    def test_bad_requests_answer_errors_not_death(self, client):
        r = client.request("nosuch", np.ones((1, 6, 6, 1)))
        assert "error" in r and "nosuch" in r["error"]
        r = client.request("alpha", np.ones((1, 3, 3, 1)))
        assert "error" in r
        # the process is still serving
        r = client.request("alpha", np.ones((1, 6, 6, 1)))
        assert "probs" in r


class TestHiveSigtermDrain:
    """(c) SIGTERM finishes in-flight requests, journals the drain,
    and exits 14 so --supervise resumes it."""

    def test_sigterm_drains_and_exits_14(self, packages,
                                         tmp_path_factory):
        from veles_tpu.serve.client import HiveClient
        mdir = str(tmp_path_factory.mktemp("hive_term"))
        c = HiveClient({"alpha": packages["alpha"]["pkg"]},
                       backend="cpu", max_batch=8, max_wait_ms=50,
                       metrics_dir=mdir, cwd=REPO)
        try:
            x = np.ones((1, 6, 6, 1), np.float32)
            assert "probs" in c.request("alpha", x)   # warm
            ids = [c.submit("alpha", x) for _ in range(12)]
            c.sigterm()
            for jid in ids:
                r = c.wait_for(jid, timeout=60)
                assert "probs" in r, r   # drained, not dropped
            rc = c.wait(60)
        finally:
            c.close(kill=True)
        from veles_tpu.supervisor import EXIT_PREEMPTED
        assert rc == EXIT_PREEMPTED
        drains = _journal_events(mdir, "serve.drain")
        assert drains and drains[-1]["complete"] is True
        downs = _journal_events(mdir, "serve.shutdown")
        assert downs and downs[-1]["reason"] == "SIGTERM"
        assert downs[-1]["code"] == EXIT_PREEMPTED


class TestHiveResidency:
    """(d) an over-budget model load spills the LRU model to host,
    journals every transition, and spilled models still answer
    (restore = re-upload, not recompile)."""

    def test_lru_spill_restore_roundtrip(self, packages,
                                         tmp_path_factory):
        from veles_tpu.serve.client import HiveClient
        mdir = str(tmp_path_factory.mktemp("hive_lru"))
        one_model = packages["alpha"]["members"]
        bytes_one = sum(
            int(np.prod(a.shape)) * 4
            for m in one_model for p in m["params"].values()
            for a in p.values())
        c = HiveClient(
            {"alpha": packages["alpha"]["pkg"],
             "beta": packages["beta"]["pkg"]},
            backend="cpu", max_batch=8, max_wait_ms=5,
            hbm_budget=bytes_one + 64,   # fits exactly one model
            metrics_dir=mdir, cwd=REPO)
        try:
            assert sum(m["resident"]
                       for m in c.hello["models"].values()) == 1
            x = np.ones((2, 6, 6, 1), np.float32)
            for name in ("alpha", "beta", "alpha", "beta"):
                r = c.request(name, x, timeout=60)
                assert "probs" in r, (name, r)
                want = _host_oracle(packages[name], x)
                np.testing.assert_allclose(
                    np.asarray(r["probs"]), want, atol=1e-4)
            st = c.stats()
            assert st["gauges"]["serve.models_resident"] == 1
            assert st["counters"]["serve.spills"] >= 2
        finally:
            c.close()
        spills = _journal_events(mdir, "serve.model_spilled")
        loads = _journal_events(mdir, "serve.model_loaded")
        restores = _journal_events(mdir, "serve.model_restored")
        assert len(loads) == 2
        assert spills, "no serve.model_spilled journal event"
        assert restores, "no serve.model_restored journal event"
        assert {e["model"] for e in spills} >= {"alpha"}


class TestReplicaDeathClient:
    """Reader-thread death handling (ISSUE 11 satellite): a caller
    blocked on a dead replica must fail IMMEDIATELY with the
    distinguishable ReplicaDied error — never by waiting out its own
    request timeout."""

    def test_kill_mid_request_fails_waiters_immediately(
            self, packages, tmp_path_factory):
        from veles_tpu.serve.client import HiveClient, ReplicaDied
        # max_wait_ms=5000 parks the lone request in the batcher's
        # coalescing window, so it is GUARANTEED still pending when
        # the kill lands
        c = HiveClient({"alpha": packages["alpha"]["pkg"]},
                       backend="cpu", max_batch=8, max_wait_ms=5000,
                       cwd=REPO)
        try:
            jid = c.submit("alpha", np.ones((1, 6, 6, 1), np.float32))
            time.sleep(0.3)
            c.proc.kill()
            t0 = time.perf_counter()
            with pytest.raises(ReplicaDied) as ei:
                c.wait_for(jid, timeout=60.0)
            dt = time.perf_counter() - t0
            # failed the moment the reader saw EOF, not at the 60s
            # (or even the 5s batcher-window) mark
            assert dt < 5.0, dt
            assert not isinstance(ei.value, TimeoutError)
            assert c.dead
            # and a submit against the corpse is the same loud error
            with pytest.raises(ReplicaDied):
                for _ in range(50):   # the pipe may buffer one write
                    c.submit("alpha", np.ones((1, 6, 6, 1),
                                              np.float32))
                    time.sleep(0.05)
        finally:
            c.close(kill=True)

    def test_collect_async_fires_on_death(self, packages):
        from veles_tpu.serve.client import HiveClient
        c = HiveClient({"alpha": packages["alpha"]["pkg"]},
                       backend="cpu", max_batch=8, max_wait_ms=5000,
                       cwd=REPO)
        got = []
        done = threading.Event()
        try:
            jid = c.submit("alpha", np.ones((1, 6, 6, 1), np.float32))
            c.collect_async(jid, lambda msg, err:
                            (got.append((msg, err)), done.set()))
            time.sleep(0.2)
            c.proc.kill()
            assert done.wait(timeout=10), "callback never fired"
            msg, err = got[0]
            assert msg is None and err is not None
            assert type(err).__name__ == "ReplicaDied"
        finally:
            c.close(kill=True)


class TestClientCancelStale:
    """ISSUE 12 satellite: HiveClient.cancel(jid) (the hedge-loser /
    timeout-cleanup path) and the stale/unknown-jid drop — a late
    response must never leak into another waiter, and it is COUNTED
    (`fleet.stale_response`) instead of silently ignored."""

    @pytest.fixture(scope="class")
    def client(self, packages, tmp_path_factory):
        from veles_tpu.serve.client import HiveClient
        # a long coalescing window opens a deterministic gap between
        # submit and response in which to cancel
        c = HiveClient({"alpha": packages["alpha"]["pkg"]},
                       backend="cpu", max_batch=8, max_wait_ms=400,
                       cwd=REPO)
        yield c
        c.close()

    def _stale(self):
        from veles_tpu import telemetry
        return telemetry.counter("fleet.stale_response").value

    def test_cancel_pending_drops_late_response_counted(self, client):
        stale0 = self._stale()
        jid = client.submit("alpha", np.ones((1, 6, 6, 1), np.float32))
        assert client.cancel(jid) is False   # still pending
        # the response lands after the 400ms window — dropped + counted
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline \
                and self._stale() < stale0 + 1:
            time.sleep(0.05)
        assert self._stale() == stale0 + 1
        with client._cond:
            assert jid not in client._results   # nothing leaked

    def test_cancel_after_arrival_drops_and_returns_true(self, client):
        jid = client.submit("alpha", np.ones((1, 6, 6, 1), np.float32))
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            with client._cond:
                if jid in client._results:
                    break
            time.sleep(0.05)
        assert client.cancel(jid) is True
        with client._cond:
            assert jid not in client._results

    def test_unknown_jid_response_counted_stale(self, client):
        stale0 = self._stale()
        # an id this client never drew: the hive answers it, the
        # reader must drop it as stale instead of parking it forever
        client._send({"id": 10 ** 9, "model": "alpha",
                      "rows": np.ones((1, 6, 6, 1),
                                      np.float32).tolist()})
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline \
                and self._stale() < stale0 + 1:
            time.sleep(0.05)
        assert self._stale() == stale0 + 1

    def test_deadline_rides_the_wire(self, client):
        # a deadline shorter than the coalescing window: the hive's
        # batcher drops the queued request and answers expired=True
        resp = client.wait_for(
            client.submit("alpha", np.ones((1, 6, 6, 1), np.float32),
                          deadline_ms=time.time() * 1000.0 + 60.0),
            timeout=30)
        assert resp.get("expired") is True, resp
        assert "error" in resp
        # with a generous deadline the same request answers normally
        resp = client.wait_for(
            client.submit("alpha", np.ones((1, 6, 6, 1), np.float32),
                          deadline_ms=time.time() * 1000.0 + 30000.0),
            timeout=30)
        assert "probs" in resp, resp


class TestEngineSubmitApi:
    """The request-level EnsembleEvalEngine facade in-process: the
    refactor the serving tier rides (submit -> Future instead of
    whole-dataset calls)."""

    def test_submit_without_batcher_raises(self, packages):
        from veles_tpu.backends import JaxDevice
        from veles_tpu.ops.fused import EnsembleEvalEngine
        model = packages["alpha"]
        eng = EnsembleEvalEngine(
            model["workflow"].forwards,
            [m["params"] for m in model["members"]],
            JaxDevice(platform="cpu"))
        with pytest.raises(RuntimeError):
            eng.submit(np.ones((1, 6, 6, 1), np.float32))
        eng.release()

    def test_submit_matches_predict_proba(self, packages):
        from veles_tpu.backends import JaxDevice
        from veles_tpu.ops.fused import EnsembleEvalEngine
        model = packages["alpha"]
        eng = EnsembleEvalEngine(
            model["workflow"].forwards,
            [m["params"] for m in model["members"]],
            JaxDevice(platform="cpu"))
        eng.attach_batcher(max_batch=8, max_wait_s=0.01)
        x = np.random.default_rng(3).standard_normal(
            (5, 6, 6, 1)).astype(np.float32)
        got = eng.submit(x).result(timeout=30)
        want = _host_oracle(model, x)
        np.testing.assert_allclose(got, want, atol=1e-4)
        # spill/restore keeps answers identical (and the jit cache)
        eng.spill_params()
        assert not eng.resident
        eng.restore_params([m["params"] for m in model["members"]])
        got2 = eng.submit(x).result(timeout=30)
        np.testing.assert_allclose(got2, want, atol=1e-4)
        eng.release()
