"""Device-resident ensemble inference (ISSUE 3 tentpole): the
vmapped member-stacked engine (ops/fused.py EnsembleEvalEngine) must
match the numpy member-loop oracle to f32 tolerance in BOTH data paths
(streaming per-batch upload and HBM-resident gather), and
EnsemblePredictor's ``device=`` knob must route between them."""

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import JaxDevice, NumpyDevice
from veles_tpu.datasets import synthetic_classification
from veles_tpu.ensemble import (EnsembleEvalEngine, EnsemblePredictor,
                                EnsembleTrainer)
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow


def conv_member_factory(train, valid):
    """A small conv net — the engine must vmap conv/pool/dense/softmax,
    not just MLPs."""
    def factory():
        return StandardWorkflow(
            loader_factory=lambda wf: ArrayLoader(
                wf, train=train, valid=valid, minibatch_size=40,
                name="loader"),
            layers=[
                {"type": "conv_relu",
                 "->": {"n_kernels": 6, "kx": 3, "ky": 3,
                        "padding": 1},
                 "<-": {"learning_rate": 0.05,
                        "gradient_moment": 0.9}},
                {"type": "max_pooling",
                 "->": {"kx": 2, "ky": 2, "sliding": 2}, "<-": {}},
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 24},
                 "<-": {"learning_rate": 0.1}},
                {"type": "softmax", "->": {"output_sample_shape": 4},
                 "<-": {"learning_rate": 0.1}},
            ],
            decision_config={"max_epochs": 2}, name="member")
    return factory


@pytest.fixture(scope="module")
def trained_ensemble():
    prng.seed_all(77)
    train, valid, _ = synthetic_classification(
        240, 80, (10, 10, 1), n_classes=4, seed=42)
    factory = conv_member_factory(train, valid)
    trainer = EnsembleTrainer(factory,
                              lambda: JaxDevice(platform="cpu"),
                              n_members=3, base_seed=999)
    members = trainer.train()
    return factory, members, valid


class TestEngineParity:
    def test_streaming_matches_host_oracle(self, trained_ensemble):
        """One vmapped dispatch == members x layers of host calls, to
        f32 tolerance (XLA:CPU computes in f32 like the oracle)."""
        factory, members, (x, y) = trained_ensemble
        pred = EnsemblePredictor(factory,
                                 lambda: JaxDevice(platform="cpu"),
                                 members)                # auto -> engine
        assert pred.engine is not None
        p_dev = pred.predict_proba(x[:40])
        p_host = pred.predict_proba_host(x[:40])
        np.testing.assert_allclose(p_dev, p_host, rtol=2e-4,
                                   atol=2e-6)
        np.testing.assert_allclose(p_dev.sum(-1), 1.0, atol=1e-5)
        # error accounting rides the same donated-carry scoring path
        assert pred.error_pct(x, y, chunk=32) == pytest.approx(
            _host_error(pred, x, y), abs=1e-6)

    def test_resident_matches_host_oracle(self, trained_ensemble):
        """The HBM-resident gather variant: the split uploads once,
        every call gathers by index on device."""
        factory, members, (x, y) = trained_ensemble
        pred = EnsemblePredictor(factory,
                                 lambda: JaxDevice(platform="cpu"),
                                 members)
        eng = pred.engine
        eng.attach_dataset(x, y)
        idx = np.arange(40)
        np.testing.assert_allclose(
            eng.predict_proba_resident(idx),
            pred.predict_proba_host(x[:40]), rtol=2e-4, atol=2e-6)
        assert eng.error_pct_resident(chunk=32) == pytest.approx(
            _host_error(pred, x, y), abs=1e-6)
        # ragged tail: a chunk that does not divide the split must be
        # mask-padded, not retraced or miscounted
        assert eng.error_pct_resident(chunk=33) == pytest.approx(
            _host_error(pred, x, y), abs=1e-6)

    def test_ragged_streaming_chunk(self, trained_ensemble):
        factory, members, (x, y) = trained_ensemble
        pred = EnsemblePredictor(factory,
                                 lambda: JaxDevice(platform="cpu"),
                                 members)
        assert pred.error_pct(x, y, chunk=37) == pytest.approx(
            _host_error(pred, x, y), abs=1e-6)


def _host_error(pred, x, y) -> float:
    wrong = int((np.argmax(pred.predict_proba_host(x), -1)
                 != y).sum())
    return 100.0 * wrong / len(x)


class TestDeviceKnob:
    def test_host_mode_has_no_engine(self, trained_ensemble):
        factory, members, _ = trained_ensemble
        pred = EnsemblePredictor(factory,
                                 lambda: JaxDevice(platform="cpu"),
                                 members, device="host")
        assert pred.engine is None

    def test_numpy_backend_auto_stays_host(self, trained_ensemble):
        factory, members, _ = trained_ensemble
        pred = EnsemblePredictor(factory, NumpyDevice, members)
        assert pred.engine is None   # no jax device -> oracle path

    def test_bad_knob_rejected(self, trained_ensemble):
        factory, members, _ = trained_ensemble
        with pytest.raises(ValueError, match="device"):
            EnsemblePredictor(factory, NumpyDevice, members,
                              device="gpu")

    def test_engine_rejects_numpy_device(self, trained_ensemble):
        factory, members, _ = trained_ensemble
        pred = EnsemblePredictor(factory, NumpyDevice, members)
        with pytest.raises(ValueError, match="jax device"):
            EnsembleEvalEngine(pred._forwards,
                               [m["params"] for m in members],
                               NumpyDevice())

    def test_single_dispatch_counter(self, trained_ensemble):
        """The tentpole property itself: ONE device computation per
        predict_proba batch, not members x layers.  Counted via the
        engine's jitted callable."""
        factory, members, (x, _) = trained_ensemble
        pred = EnsemblePredictor(factory,
                                 lambda: JaxDevice(platform="cpu"),
                                 members)
        eng = pred.engine
        calls = {"n": 0}
        inner = eng._predict

        def counting(params, xb):
            calls["n"] += 1
            return inner(params, xb)

        eng._predict = counting
        pred.predict_proba(x[:24])
        assert calls["n"] == 1
