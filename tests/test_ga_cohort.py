"""Population-batched GA training (ISSUE 4 tentpole): same-signature
genome cohorts train as ONE vmapped fused dispatch chain
(ops/fused.py PopulationTrainEngine), bucketed by shape signature in
GeneticOptimizer._fitness_many and dispatched through the chip-owning
evaluator's cohort jobs (genetics/worker.py --serve + pool.py
evaluate_cohort).  The per-genome path is the parity ORACLE: batched
fitnesses must match it to f32 tolerance."""

import json
import sys
import textwrap

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.genetics import (GeneticOptimizer, Tune, liftable_tune,
                                shape_signature)

LR = "wine.layers[0]['<-']['learning_rate']"
WIDTH = "wine.layers[0]['->']['output_sample_shape']"


class TestLiftableSignature:
    def test_float_lr_and_wd_are_liftable(self):
        assert liftable_tune("m.layers[0]['<-']['learning_rate']",
                             Tune(0.1, 0.01, 1.0))
        assert liftable_tune("m.layers[2]['<-']['weight_decay']",
                             Tune(0.001, 0.0, 0.1))
        assert liftable_tune("m.layers[1]['<-']['learning_rate_bias']",
                             Tune(0.1, 0.01, 1.0))

    def test_int_and_structural_tunes_are_not(self):
        # an integer gene always changes shapes — never liftable, even
        # on a learning_rate-looking path
        assert not liftable_tune("m.layers[0]['<-']['learning_rate']",
                                 Tune(1, 1, 8))
        assert not liftable_tune(
            "m.layers[0]['->']['output_sample_shape']", Tune(16, 8, 32))
        assert not liftable_tune("m.loader['minibatch_size']",
                                 Tune(32.0, 8.0, 64.0))

    def test_signature_keys_only_non_liftable(self):
        tunes = {WIDTH: Tune(16, 8, 32), LR: Tune(0.1, 0.01, 1.0)}
        a = shape_signature({WIDTH: 16, LR: 0.3}, tunes)
        b = shape_signature({WIDTH: 16, LR: 0.9}, tunes)
        c = shape_signature({WIDTH: 24, LR: 0.3}, tunes)
        assert a == b           # lr does not split cohorts
        assert a != c           # width does


class TestCohortBucketing:
    """_fitness_many buckets by signature and dispatches one cohort
    per bucket; decode failures score inf without poisoning their
    bucket; a failing bucket falls back to the per-genome oracle."""

    # lr range < 10x so the gene stays linear (log-scale genes would
    # decode these hand-written genomes through exp)
    TUNES = {WIDTH: Tune(16, 8, 32), LR: Tune(0.5, 0.2, 1.0)}

    def fitness_of(self, values):
        return values[WIDTH] + values[LR]

    def test_buckets_by_signature_singletons_included(self):
        calls = []

        def cohort(values_list):
            calls.append([v[WIDTH] for v in values_list])
            return [self.fitness_of(v) for v in values_list]

        opt = GeneticOptimizer(self.fitness_of, self.TUNES,
                               population=4, generations=1,
                               evaluate_cohort=cohort)
        genomes = np.asarray([
            [16.0, 0.3], [16.0, 0.9], [24.0, 0.5], [16.0, 0.25]])
        # "['->']" sorts before "['<-']" -> gene order (width, lr)
        assert opt.paths == [WIDTH, LR]
        fits = opt._fitness_many(genomes)
        expect = [16.3, 16.9, 24.5, 16.25]
        assert np.allclose(fits, expect)
        sizes = sorted(len(c) for c in calls)
        assert sizes == [1, 3]          # one cohort + one singleton
        assert sorted(opt.last_cohort_sizes) == [1, 3]

    def test_decode_failure_scores_inf_without_poisoning(self):
        class BoomTune(Tune):
            def clip(self, x):
                if x > 20:
                    raise ValueError("boom")
                return super().clip(x)

        tunes = {WIDTH: BoomTune(16, 8, 32), LR: Tune(0.5, 0.2, 1.0)}
        seen = []

        def cohort(values_list):
            seen.extend(v[WIDTH] for v in values_list)
            return [1.0 for _ in values_list]

        opt = GeneticOptimizer(self.fitness_of, tunes, population=3,
                               generations=1, evaluate_cohort=cohort)
        fits = opt._fitness_many(np.asarray(
            [[16.0, 0.3], [28.0, 0.3], [16.0, 0.5]]))
        assert fits[1] == float("inf")      # decode raised
        assert fits[0] == 1.0 and fits[2] == 1.0
        assert seen == [16, 16]             # bad genome never shipped

    def test_failed_bucket_falls_back_to_oracle(self):
        def cohort(values_list):
            raise RuntimeError("cohort path down")

        opt = GeneticOptimizer(self.fitness_of, self.TUNES,
                               population=2, generations=1,
                               evaluate_cohort=cohort)
        fits = opt._fitness_many(np.asarray([[16.0, 0.3], [16.0, 0.9]]))
        assert np.allclose(fits, [16.3, 16.9])  # oracle answered


class TestEngineParity:
    """The vmapped engine against per-genome full workflow runs,
    in-process — the core parity pin (float-tune cohort, shared init,
    per-member lr/wd, early-stop bookkeeping)."""

    def build(self, lr, wd=0.001, epochs=5, fail=100):
        from veles_tpu.backends import JaxDevice
        from veles_tpu.models import wine

        class FL:
            workflow = None

        prng._streams.clear()
        prng.seed_all(1234)
        layers = [
            {"type": "all2all_tanh", "->": {"output_sample_shape": 8},
             "<-": {"learning_rate": lr, "weight_decay": wd,
                    "gradient_moment": 0.9}},
            {"type": "softmax", "->": {"output_sample_shape": 3},
             "<-": {"learning_rate": lr, "gradient_moment": 0.9}},
        ]
        w = wine.create_workflow(
            FL(), layers=layers,
            decision={"max_epochs": epochs, "fail_iterations": fail})
        w.initialize(device=JaxDevice(platform="cpu"))
        return w

    def test_cohort_matches_per_genome_oracle(self):
        from veles_tpu.launcher import workflow_fitness
        from veles_tpu.ops.fused import PopulationTrainEngine

        lrs = [0.3, 0.05, 0.8]
        oracle = []
        for lr in lrs:
            w = self.build(lr, fail=1)   # small fail_iterations: some
            w.run()                      # members stop early
            oracle.append(workflow_fitness(w))
            w.stop()

        w = self.build(lrs[0], fail=1)
        rates = np.asarray([[[lr, lr], [lr, lr]] for lr in lrs],
                           np.float32)
        decays = np.asarray([[[0.001, 0.0], [0.0, 0.0]]] * len(lrs),
                            np.float32)
        engine = PopulationTrainEngine(w, rates, decays)
        fits = engine.run()
        engine.release()
        w.stop()
        assert np.allclose(fits, oracle, atol=1e-3), (fits, oracle)

    def test_streaming_cohort_matches_resident(self):
        """Streaming cohorts (host-assembled superstep batches, zero
        dataset residency — the PR 18 lift of the dataset-must-fit
        constraint) train bit-identically to resident ones: the Keel
        stream scan consumes the same rows the resident scan gathers
        on device."""
        from veles_tpu.ops.fused import PopulationTrainEngine

        lrs = [0.3, 0.05]
        rates = np.asarray([[[lr, lr], [lr, lr]] for lr in lrs],
                           np.float32)
        decays = np.asarray([[[0.001, 0.0], [0.0, 0.0]]] * len(lrs),
                            np.float32)

        w = self.build(lrs[0], fail=1)
        engine = PopulationTrainEngine(w, rates, decays)
        assert not engine.streaming
        resident = engine.run()
        engine.release()
        w.stop()

        w = self.build(lrs[0], fail=1)
        w.loader.device_resident = False    # force the streaming path
        engine = PopulationTrainEngine(w, rates, decays)
        assert engine.streaming
        stream = engine.run()
        engine.release()
        w.stop()
        assert np.array_equal(stream, resident), (stream, resident)


@pytest.fixture
def cohort_workflow(tmp_path):
    wf = tmp_path / "wf.py"
    wf.write_text(textwrap.dedent("""
        from veles_tpu.models import wine

        def create_workflow(launcher):
            return wine.create_workflow(launcher)

        def run(launcher):
            launcher.create_workflow(create_workflow)
            launcher.initialize()
            launcher.run()
    """))
    cfg = tmp_path / "cfg.py"
    cfg.write_text(textwrap.dedent("""
        from veles_tpu.config import root
        from veles_tpu.genetics import Tune

        root.wine.decision = {"max_epochs": 3}
        root.wine.layers = [
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": Tune(8, 4, 16)},
             "<-": {"learning_rate": Tune(0.3, 0.01, 1.0)}},
            {"type": "softmax", "->": {"output_sample_shape": 3},
             "<-": {"learning_rate": 0.3}},
        ]
    """))
    return str(wf), str(cfg)


class TestPoolCohortParity:
    """End to end through the serve-mode evaluator: batched-cohort
    fitnesses == the per-genome oracle (mixed signatures, a singleton
    bucket, and a structurally-bad member that scores inf without
    poisoning its cohort)."""

    def serve_cmd(self, wf, cfg):
        return [sys.executable, "-m", "veles_tpu.genetics.worker",
                "--serve", wf, cfg, "-b", "cpu", "-s", "1234"]

    def test_cohort_matches_oracle_and_isolates_bad_member(
            self, cohort_workflow):
        from veles_tpu.genetics.pool import ChipEvaluatorPool
        wf, cfg = cohort_workflow
        cohort = [{WIDTH: 8, LR: 0.3}, {WIDTH: 8, LR: 0.05}]
        singleton = [{WIDTH: 12, LR: 0.3}]
        with ChipEvaluatorPool(self.serve_cmd(wf, cfg), workers=2,
                               timeout=300) as pool:
            oracle = pool.evaluate_many(cohort + singleton)
            batched = pool.evaluate_cohort(cohort)
            batched += pool.evaluate_cohort(singleton)
            assert all(np.isfinite(f) for f in oracle), oracle
            assert np.allclose(batched, oracle, atol=1e-3), \
                (batched, oracle)
            # a member whose decode produces a DIFFERENT structure
            # scores inf; the rest of the cohort still matches the
            # oracle (no poisoning, evaluator survives)
            mixed = pool.evaluate_cohort(
                [cohort[0], {WIDTH: -5, LR: 0.1}, cohort[1]])
            assert mixed[1] == float("inf")
            assert np.allclose([mixed[0], mixed[2]], oracle[:2],
                               atol=1e-3)

    def test_cli_ga_cohort_end_to_end(self, cohort_workflow):
        """`python -m veles_tpu -b tpu-evaluator --optimize` with
        cohort batching on: mixed-signature generations bucket and
        complete with finite best fitness."""
        import subprocess

        import os
        wf, cfg = cohort_workflow
        repo = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        res = subprocess.run(
            [sys.executable, "-m", "veles_tpu", "-b", "tpu-evaluator",
             "--optimize", "4:1", "--ga-workers", "2", wf, cfg],
            capture_output=True, text=True, cwd=repo, timeout=600)
        assert res.returncode == 0, res.stderr[-2000:]
        assert "cohorts:" in res.stderr      # the batched path ran
        out = json.loads(res.stdout.strip().splitlines()[-1])
        assert np.isfinite(out["fitness"])


class TestRbmCohortParity:
    """The zoo's long tail through the SAME engine (Menagerie): a CD-k
    RBM learning-rate cohort trains as ONE vmapped
    PopulationTrainEngine dispatch chain, and every member's trained
    params match a per-genome fused oracle run — the CD sampling
    draws ride the shared (seed, step) PRNG contract, so stochastic
    layers batch without drifting.  On a single-device backend the
    match is f32-bitwise; under the suite's 8-virtual-device XLA
    config vmap picks different matmul fusions, so the pin here is
    ulp-tight allclose (the SAME tolerance story as the SOM cohort,
    tests/test_zoo_fused.py)."""

    LCFG = {"minibatch_size": 50, "n_train": 200, "n_valid": 50}
    LRS = [0.3, 0.05, 0.8]

    def build(self, lr, cd_k):
        from veles_tpu.backends import JaxDevice
        from veles_tpu.loader.synthetic import MnistLoader
        from veles_tpu.ops.standard_workflow import StandardWorkflow

        prng._streams.clear()
        prng.seed_all(1234)
        w = StandardWorkflow(
            loader_factory=lambda wf: MnistLoader(
                wf, name="loader", targets_from_data=True,
                **self.LCFG),
            layers=[
                {"type": "binarization", "->": {}, "<-": {}},
                {"type": "rbm", "->": {"n_hidden": 16},
                 "<-": {"learning_rate": lr, "gradient_moment": 0.5,
                        "cd_k": cd_k}},
            ],
            loss_function="mse",
            decision_config={"max_epochs": 2},
            name="RbmCohortWf")
        w.initialize(device=JaxDevice(platform="cpu"))
        return w

    @pytest.mark.parametrize("cd_k", [1, 2])
    def test_member_params_bitwise_vs_per_genome_oracle(self, cd_k):
        from veles_tpu.ops.fused import PopulationTrainEngine

        oracle = []
        for lr in self.LRS:
            w = self.build(lr, cd_k)
            w.run()
            oracle.append({k: np.array(v.map_read()) for k, v in
                           w.forwards[1].param_vectors().items()})
            w.stop()

        w = self.build(self.LRS[0], cd_k)
        n_gds = len(w.gds)
        rates = np.asarray([[[lr, lr]] * n_gds for lr in self.LRS],
                           np.float32)
        decays = np.zeros_like(rates)
        engine = PopulationTrainEngine(w, rates, decays)
        engine.run()
        stacked = engine._params[w.forwards[1].name]
        for i, want in enumerate(oracle):
            for pn, arr in want.items():
                got = np.asarray(stacked[pn][i])
                assert np.allclose(got, arr, rtol=1e-4, atol=5e-6), \
                    (cd_k, i, pn,
                     float(np.max(np.abs(got - arr))))
        engine.release()
        w.stop()
