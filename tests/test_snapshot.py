"""Whole-workflow snapshot/resume equivalence (SURVEY.md §4.4): a run
snapshotted mid-way and resumed must land where an uninterrupted run
lands."""

import glob
import os

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import JaxDevice, NumpyDevice
from veles_tpu.models import mnist
from veles_tpu.snapshotter import load_workflow, save_workflow


class FL:
    workflow = None


LOADER = {"minibatch_size": 50, "n_train": 300, "n_valid": 100}


def build(max_epochs, snap_cfg=None):
    prng.seed_all(4242)
    return mnist.create_workflow(
        FL(), loader=dict(LOADER),
        decision={"max_epochs": max_epochs},
        snapshotter=snap_cfg)


class TestSnapshotResume:
    @pytest.mark.parametrize("device_factory", [
        NumpyDevice, lambda: JaxDevice(platform="cpu")])
    def test_resume_matches_straight_run(self, tmp_path, device_factory):
        # straight run: 4 epochs
        w_ref = build(4)
        w_ref.initialize(device=device_factory())
        w_ref.run()
        ref_weights = w_ref.forwards[0].weights.map_read().copy()
        ref_hist = [h["n_err"] for h in w_ref.decision.history]

        # interrupted run: 2 epochs -> snapshot -> resume to 4
        w1 = build(2)
        w1.initialize(device=device_factory())
        w1.run()
        path = str(tmp_path / "snap.pickle.gz")
        save_workflow(w1, path)

        w2 = load_workflow(path)
        w2.decision.max_epochs = 4
        w2.decision.complete.set(False)
        w2.initialize(device=device_factory())
        w2.run()
        got_weights = w2.forwards[0].weights.map_read()
        got_hist = [h["n_err"] for h in w2.decision.history]

        assert got_hist == ref_hist
        np.testing.assert_allclose(got_weights, ref_weights,
                                   rtol=2e-4, atol=2e-5)

    def test_snapshotter_unit_writes_on_improvement(self, tmp_path):
        w = build(3, snap_cfg={"directory": str(tmp_path),
                               "prefix": "t"})
        w.initialize(device=JaxDevice(platform="cpu"))
        w.run()
        files = glob.glob(os.path.join(str(tmp_path), "t_epoch*"))
        assert files, "no snapshots written"
        # best snapshot resumable
        w2 = load_workflow(sorted(files)[-1])
        w2.initialize(device=NumpyDevice())  # cross-backend resume
        assert w2.forwards[0].weights.mem is not None

    def test_synthetic_loader_snapshot_is_small(self, tmp_path):
        w = build(1)
        w.initialize(device=NumpyDevice())
        w.run()
        path = str(tmp_path / "s.pickle")
        save_workflow(w, path)
        # dataset (400*784*4 ≈ 1.25 MB) must NOT be inside; weights +
        # minibatch scratch vectors alone are ~0.7 MB
        assert os.path.getsize(path) < 900_000, os.path.getsize(path)
        w2 = load_workflow(path)
        w2.initialize(device=NumpyDevice())
        assert w2.loader.original_data.mem is not None
