"""Per-op correctness: numpy-vs-jax forward agreement, finite-difference
gradient checks against the hand-written backward, numpy-vs-jax backward
agreement (SURVEY.md §7 phase 4 test strategy)."""

import numpy as np
import pytest

import jax.numpy as jnp

from veles_tpu import prng
from veles_tpu.ops import activation as act_mod
from veles_tpu.ops import conv as conv_mod
from veles_tpu.ops import dropout as dropout_mod
from veles_tpu.ops import lrn as lrn_mod
from veles_tpu.ops import pooling as pool_mod
from veles_tpu.ops import deconv as deconv_mod
from veles_tpu.ops import depooling as depool_mod
from veles_tpu.ops import all2all as a2a_mod

RNG = np.random.default_rng(3)


def make_params(unit, in_shape):
    params = {}
    for name, shape in unit.param_shapes(in_shape).items():
        params[name] = RNG.standard_normal(shape).astype(np.float32) * 0.3
    return params


def fd_grad(f, x, eps=1e-3, probes=8):
    """Central finite differences of scalar f at a few coordinates."""
    g = np.zeros_like(x, dtype=np.float64)
    flat = x.reshape(-1)
    idxs = RNG.choice(flat.size, size=min(probes, flat.size),
                      replace=False)
    for i in idxs:
        old = flat[i]
        flat[i] = old + eps
        fp = f(x)
        flat[i] = old - eps
        fm = f(x)
        flat[i] = old
        g.reshape(-1)[i] = (fp - fm) / (2 * eps)
    return g, idxs


def check_unit(fwd_unit, gd_cls, in_shape, rtol=1e-4, atol=1e-4,
               fd_rtol=5e-2):
    """Run the full battery on one forward/gd pair."""
    x = RNG.standard_normal(in_shape).astype(np.float32)
    params = make_params(fwd_unit, in_shape)
    gd = gd_cls(forward=fwd_unit)

    # 1. forward numpy vs jax
    y_np, res_np = fwd_unit.apply_fwd(params, x, train=True)
    jparams = {k: jnp.asarray(v) for k, v in params.items()}
    y_jx, res_jx = fwd_unit.apply_fwd(jparams, jnp.asarray(x), train=True)
    np.testing.assert_allclose(np.asarray(y_jx), y_np,
                               rtol=1e-4, atol=1e-4)

    # 2. backward numpy vs jax (same upstream error)
    err = RNG.standard_normal(y_np.shape).astype(np.float32)
    ein_np, g_np = gd.backward_from_saved(params, res_np, err)
    ein_jx, g_jx = gd.backward_from_saved(jparams, res_jx,
                                          jnp.asarray(err))
    np.testing.assert_allclose(np.asarray(ein_jx), ein_np,
                               rtol=rtol, atol=atol)
    for k in g_np:
        np.testing.assert_allclose(np.asarray(g_jx[k]), g_np[k],
                                   rtol=rtol, atol=atol, err_msg=k)

    # 3. finite differences vs numpy backward: L = sum(output * err)
    def loss_x(xx):
        yy, _ = fwd_unit.apply_fwd(params, xx.astype(np.float32),
                                   train=True)
        return float((yy * err).sum())

    fd, idxs = fd_grad(loss_x, x.copy().astype(np.float64))
    got = ein_np.reshape(-1)[idxs]
    want = fd.reshape(-1)[idxs]
    np.testing.assert_allclose(got, want, rtol=fd_rtol, atol=1e-2)

    for pname in g_np:
        def loss_p(pp, pname=pname):
            p2 = dict(params)
            p2[pname] = pp.astype(np.float32)
            yy, _ = fwd_unit.apply_fwd(p2, x, train=True)
            return float((yy * err).sum())

        fd, idxs = fd_grad(loss_p, params[pname].copy().astype(np.float64))
        np.testing.assert_allclose(g_np[pname].reshape(-1)[idxs],
                                   fd.reshape(-1)[idxs],
                                   rtol=fd_rtol, atol=1e-2,
                                   err_msg=pname)


class TestAll2All:
    def test_linear(self):
        u = a2a_mod.All2All(output_sample_shape=7)
        check_unit(u, a2a_mod.GradientDescent, (4, 5))

    def test_tanh(self):
        u = a2a_mod.All2AllTanh(output_sample_shape=6)
        check_unit(u, a2a_mod.GDTanh, (3, 8))

    def test_relu(self):
        u = a2a_mod.All2AllRELU(output_sample_shape=6)
        check_unit(u, a2a_mod.GDRELU, (3, 8))

    def test_flattens_images(self):
        u = a2a_mod.All2All(output_sample_shape=5)
        check_unit(u, a2a_mod.GradientDescent, (2, 4, 4, 3))


class TestConv:
    def test_basic(self):
        u = conv_mod.Conv(n_kernels=4, kx=3, ky=3)
        check_unit(u, conv_mod.GradientDescentConv, (2, 6, 6, 3))

    def test_stride_pad(self):
        u = conv_mod.Conv(n_kernels=3, kx=3, ky=3, padding=1, sliding=2)
        check_unit(u, conv_mod.GradientDescentConv, (2, 7, 7, 2))

    def test_tanh(self):
        u = conv_mod.ConvTanh(n_kernels=2, kx=2, ky=2)
        check_unit(u, conv_mod.GradientDescentConv, (2, 5, 5, 2))

    def test_relu(self):
        u = conv_mod.ConvRELU(n_kernels=2, kx=2, ky=2)
        check_unit(u, conv_mod.GradientDescentConv, (2, 5, 5, 2))

    def test_rect_kernel(self):
        u = conv_mod.Conv(n_kernels=3, kx=2, ky=4, padding=(2, 1),
                          sliding=(2, 1))
        check_unit(u, conv_mod.GradientDescentConv, (2, 9, 8, 2))

    def test_output_shape(self):
        u = conv_mod.Conv(n_kernels=8, kx=11, ky=11, sliding=4)
        assert u.output_shape_for((1, 227, 227, 3)) == (1, 55, 55, 8)

    @pytest.mark.parametrize("geom", [
        # (kx, ky, pad, stride, in_shape) — AlexNet conv1 miniature,
        # stride not dividing kernel, rectangular stride, with padding
        (11, 11, 0, 4, (2, 31, 31, 3)),
        (5, 5, 0, 3, (2, 17, 17, 2)),
        (3, 4, (1, 2), (2, 3), (2, 11, 13, 3)),
        (2, 2, 0, 2, (1, 8, 8, 4)),
    ])
    def test_space_to_depth_exact(self, geom, monkeypatch):
        """The s2d rewrite must match lax.conv bit-for-bit-ish (f32
        reassociation only) in forward AND in both vjp cotangents."""
        import jax
        kx, ky, pad, stride, shp = geom
        u = conv_mod.Conv(n_kernels=5, kx=kx, ky=ky, padding=pad,
                          sliding=stride)
        assert u._s2d_eligible(shp[-1])
        wshape = u.param_shapes(shp)["weights"]
        w = RNG.standard_normal(wshape).astype(np.float32)
        x = RNG.standard_normal(shp).astype(np.float32)

        def run(s2d):
            monkeypatch.setenv("VELES_TPU_CONV_S2D", "1" if s2d else "0")
            y, vjp = jax.vjp(
                lambda ww, xx: u.pre_activation({"weights": ww}, xx),
                jnp.asarray(w), jnp.asarray(x))
            ct = jnp.asarray(
                RNG2.standard_normal(y.shape).astype(np.float32))
            dw, dx = vjp(ct)
            return np.asarray(y), np.asarray(dw), np.asarray(dx)

        RNG2 = np.random.default_rng(0)
        ref = run(False)
        RNG2 = np.random.default_rng(0)
        got = run(True)
        for a, b in zip(ref, got):
            assert a.shape == b.shape
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)

    def test_s2d_ineligible_for_unit_stride_or_many_channels(self):
        assert not conv_mod.Conv(n_kernels=4, kx=3, ky=3,
                                 sliding=1)._s2d_eligible(3)
        assert not conv_mod.Conv(n_kernels=4, kx=5, ky=5,
                                 sliding=4)._s2d_eligible(64)


class TestPooling:
    def test_max(self):
        u = pool_mod.MaxPooling(kx=2, ky=2)
        check_unit(u, pool_mod.GDMaxPooling, (2, 6, 6, 3))

    def test_max_overlapping(self):
        u = pool_mod.MaxPooling(kx=3, ky=3, sliding=2)
        check_unit(u, pool_mod.GDMaxPooling, (2, 7, 7, 2))

    def test_avg(self):
        u = pool_mod.AvgPooling(kx=2, ky=2)
        check_unit(u, pool_mod.GDAvgPooling, (2, 6, 6, 3))

    def test_stochastic_eval_mode_deterministic(self):
        u = pool_mod.StochasticPooling(kx=2, ky=2)
        x = RNG.standard_normal((2, 4, 4, 3)).astype(np.float32)
        y1 = u.apply({}, {"input": x})["output"]
        y2 = np.asarray(u.apply({}, {"input": jnp.asarray(x)})["output"])
        np.testing.assert_allclose(y1, y2, rtol=1e-5, atol=1e-5)

    def test_stochastic_train_samples_window_members(self):
        import jax
        u = pool_mod.StochasticPooling(kx=2, ky=2)
        x = np.abs(RNG.standard_normal((1, 4, 4, 1))).astype(np.float32)
        y, (xx, idx) = u.apply_fwd({}, jnp.asarray(x),
                                   rng=jax.random.key(0), train=True)
        y = np.asarray(y)
        w = u._windows(x)
        # each sampled value must be a member of its window
        for i in range(2):
            for j in range(2):
                assert y[0, i, j, 0] in w[0, i, j, :, 0]


class TestActivations:
    @pytest.mark.parametrize("cls", [
        act_mod.ActivationTanh, act_mod.ActivationSigmoid,
        act_mod.ActivationStrictRELU, act_mod.ActivationRELU,
        act_mod.ActivationLog])
    def test_all(self, cls):
        u = cls()
        check_unit(u, act_mod.GDActivation, (3, 7))


class TestLRN:
    def test_forward_reference_formula(self):
        u = lrn_mod.LRNormalizer(alpha=1e-4, beta=0.75, n=5, k=2.0)
        x = RNG.standard_normal((2, 3, 3, 8)).astype(np.float32)
        y = u.apply({}, {"input": x})["output"]
        # brute-force windowed sum
        c = x.shape[-1]
        want = np.empty_like(x)
        for i in range(c):
            lo, hi = max(0, i - 2), min(c, i + 3)
            s = (x[..., lo:hi] ** 2).sum(-1)
            want[..., i] = x[..., i] / (2.0 + 1e-4 * s) ** 0.75
        np.testing.assert_allclose(y, want, rtol=1e-5, atol=1e-6)

    def test_grads(self):
        u = lrn_mod.LRNormalizer(n=5)
        check_unit(u, lrn_mod.GDLRNormalizer, (2, 3, 3, 8))

    def test_grads_even_window(self):
        """Even n: the backward must use the ADJOINT window, which is
        NOT the forward window (fd check caught a 'symmetric window'
        shortcut that was wrong for n=4)."""
        u = lrn_mod.LRNormalizer(n=4, alpha=3e-2)
        check_unit(u, lrn_mod.GDLRNormalizer, (2, 3, 3, 8))

    def test_band_matrix_is_window_adjoint(self):
        """band_matrix(transpose=True) must be the exact matrix
        transpose (the adjoint of the window operator) for both
        parities — the backward pass depends on it."""
        for n in (3, 4, 5, 6):
            b = lrn_mod.band_matrix(12, n)
            bt = lrn_mod.band_matrix(12, n, transpose=True)
            np.testing.assert_array_equal(bt, b.T)
            assert b.sum(axis=0).max() == n  # interior taps

    def test_pallas_kernels_match_numpy_oracle(self):
        """The single-pass TPU kernels (interpret mode on CPU) vs the
        numpy shifted-adds oracle, forward and backward, both real
        channel widths (96 aligns to no lane boundary; 256 to two)."""
        from veles_tpu.ops import lrn_pallas
        if not lrn_pallas.available():
            pytest.skip("no pallas in this jax build")
        for c, n in ((96, 5), (256, 5), (96, 4)):
            u = lrn_mod.LRNormalizer(alpha=3e-2, beta=0.75, n=n, k=2.0)
            x = RNG.standard_normal((16, 3, 3, c)).astype(np.float32)
            err = RNG.standard_normal(x.shape).astype(np.float32)
            assert lrn_pallas.usable(x.shape, u.n, u.beta)

            y_np, res_np = u.apply_fwd({}, x)
            y_pl = np.asarray(lrn_pallas.lrn_fwd(
                x, u.n, u.k, u.alpha, interpret=True))
            np.testing.assert_allclose(y_pl, y_np, rtol=2e-5,
                                       atol=1e-6)

            gd = lrn_mod.GDLRNormalizer(forward=u)
            ein_np, _ = gd.backward_from_saved({}, res_np, err)
            ein_pl = np.asarray(lrn_pallas.lrn_bwd(
                x, err, u.n, u.k, u.alpha, interpret=True))
            np.testing.assert_allclose(ein_pl, ein_np, rtol=2e-4,
                                       atol=1e-5)

    def test_jax_banded_matmul_matches_numpy_oracle_both_parities(self):
        """The jax path's banded-matmul window sum must agree with the
        independent numpy shifted-adds oracle for ODD and EVEN window
        sizes (an n+1-tap symmetric band would pass only odd n)."""
        import jax.numpy as jnp
        for n in (4, 5):
            u = lrn_mod.LRNormalizer(alpha=3e-2, beta=0.75, n=n, k=2.0)
            x = RNG.standard_normal((2, 3, 3, 8)).astype(np.float32)
            err = RNG.standard_normal(x.shape).astype(np.float32)

            y_np, res_np = u.apply_fwd({}, x)
            y_jx, res_jx = u.apply_fwd({}, jnp.asarray(x))
            np.testing.assert_allclose(np.asarray(y_jx), y_np,
                                       rtol=2e-5, atol=1e-6)

            gd = lrn_mod.GDLRNormalizer(forward=u)
            ein_np, _ = gd.backward_from_saved({}, res_np, err)
            ein_jx, _ = gd.backward_from_saved({}, res_jx,
                                               jnp.asarray(err))
            np.testing.assert_allclose(np.asarray(ein_jx), ein_np,
                                       rtol=2e-4, atol=1e-5)


class TestDropout:
    def test_eval_identity(self):
        u = dropout_mod.Dropout(dropout_ratio=0.4)
        x = RNG.standard_normal((4, 5)).astype(np.float32)
        y, _ = u.apply_fwd({}, x, train=False)
        np.testing.assert_array_equal(y, x)

    def test_train_mask_and_backward(self):
        prng.seed_all(5)
        u = dropout_mod.Dropout(dropout_ratio=0.5)
        x = np.ones((64, 64), np.float32)
        y, (xx, mask) = u.apply_fwd({}, x, train=True)
        kept = (np.asarray(y) != 0)
        assert 0.3 < kept.mean() < 0.7
        np.testing.assert_allclose(np.asarray(y)[kept], 2.0)  # 1/keep
        gd = dropout_mod.GDDropout(forward=u)
        err = np.ones_like(x)
        ein, _ = gd.backward_from_saved({}, (xx, mask), err)
        np.testing.assert_array_equal(np.asarray(ein), np.asarray(mask))

    def test_jax_train_deterministic_per_key(self):
        import jax
        u = dropout_mod.Dropout(dropout_ratio=0.5)
        x = jnp.ones((8, 8))
        y1, _ = u.apply_fwd({}, x, rng=jax.random.key(7), train=True)
        y2, _ = u.apply_fwd({}, x, rng=jax.random.key(7), train=True)
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


class TestDeconv:
    def test_basic(self):
        u = deconv_mod.Deconv(n_kernels=3, kx=2, ky=2, sliding=2)
        check_unit(u, deconv_mod.GradientDescentDeconv, (2, 3, 3, 4))

    def test_stride1_pad(self):
        u = deconv_mod.Deconv(n_kernels=2, kx=3, ky=3, padding=1)
        check_unit(u, deconv_mod.GradientDescentDeconv, (2, 5, 5, 3))

    def test_inverts_conv_geometry(self):
        c = conv_mod.Conv(n_kernels=5, kx=4, ky=4, padding=1, sliding=2)
        out = c.output_shape_for((1, 10, 10, 3))
        d = deconv_mod.Deconv(n_kernels=3, kx=4, ky=4, padding=1,
                              sliding=2)
        assert d.output_shape_for(out) == (1, 10, 10, 3)


class TestDepooling:
    def test_forward_and_grads(self):
        u = depool_mod.Depooling(kx=2, ky=2)
        check_unit(u, depool_mod.GDDepooling, (2, 3, 3, 2))

    def test_upsamples(self):
        u = depool_mod.Depooling(kx=2, ky=2)
        x = np.arange(4, dtype=np.float32).reshape(1, 2, 2, 1)
        y = u.apply({}, {"input": x})["output"]
        assert y.shape == (1, 4, 4, 1)
        assert (y[0, :2, :2, 0] == 0).all()
