"""Vector coherence protocol + device backends + AcceleratedUnit seam
(SURVEY.md §7 phase 2)."""

import numpy as np
import pytest

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.backends import JaxDevice, NumpyDevice
from veles_tpu.memory import Vector


@pytest.fixture(scope="module")
def jaxdev():
    return JaxDevice(platform="cpu")


class TestVector:
    def test_host_only(self):
        v = Vector(np.arange(6, dtype=np.float32).reshape(2, 3), name="v")
        assert v.shape == (2, 3) and v.sample_size == 3 and len(v) == 2
        np.testing.assert_array_equal(v.map_read(), v.mem)

    def test_roundtrip_through_device(self, jaxdev):
        host = np.arange(4, dtype=np.float32)
        v = Vector(host, name="v")
        v.initialize(jaxdev)
        dev = v.unmap()
        assert dev is v.devmem
        # simulate a device-side write: rebind devmem
        v.devmem = dev * 2
        np.testing.assert_array_equal(v.map_read(), host * 2)

    def test_map_write_invalidates_device(self, jaxdev):
        v = Vector(np.ones(3, np.float32), name="v")
        v.initialize(jaxdev)
        first_dev = v.unmap()
        m = v.map_write()
        m[:] = 5
        dev = v.unmap()  # must re-upload
        assert dev is not first_dev
        np.testing.assert_array_equal(np.asarray(dev), [5, 5, 5])

    def test_map_invalidate_no_copy_down(self, jaxdev):
        v = Vector(np.zeros(3, np.float32), name="v")
        v.initialize(jaxdev)
        v.devmem = v.unmap() + 100  # device ahead of host
        m = v.map_invalidate()      # host declares full overwrite
        m[:] = 7
        np.testing.assert_array_equal(np.asarray(v.unmap()), [7, 7, 7])

    def test_unallocated_raises(self):
        v = Vector(name="v")
        with pytest.raises((RuntimeError, AttributeError)):
            v.map_read()

    def test_pickle_syncs_host(self, jaxdev):
        import pickle
        v = Vector(np.ones(2, np.float32), name="v")
        v.initialize(jaxdev)
        v.devmem = v.unmap() * 3
        v2 = pickle.loads(pickle.dumps(v))
        np.testing.assert_array_equal(v2.mem, [3, 3])
        assert v2.devmem is None


class Doubler(AcceleratedUnit):
    """Minimal accelerated unit: out = in * 2 + p."""

    def __init__(self, workflow=None, **kw):
        super().__init__(workflow, **kw)
        self.input = Vector(name="input")
        self.output = Vector(name="output")
        self.p = Vector(np.float32([10.0]), name="p")
        self.declare_input("x", self.input)
        self.declare_output("y", self.output)

    def gather_params(self):
        return {"p": self.p.unmap()}

    def apply(self, params, inputs, rng=None):
        return {"y": inputs["x"] * 2 + params["p"]}


class TestAcceleratedUnit:
    def _run(self, device):
        u = Doubler(name="d")
        u.input.mem = np.arange(3, dtype=np.float32)
        u.initialize(device=device)
        u.run()
        return u.output.map_read()

    def test_numpy_and_jax_agree(self, jaxdev):
        out_np = self._run(NumpyDevice())
        out_jax = self._run(jaxdev)
        np.testing.assert_allclose(out_np, [10, 12, 14])
        np.testing.assert_allclose(out_jax, out_np, rtol=1e-6)

    def test_jax_output_stays_on_device(self, jaxdev):
        u = Doubler(name="d")
        u.input.mem = np.arange(3, dtype=np.float32)
        u.initialize(device=jaxdev)
        u.run()
        assert u.output.devmem is not None
        assert u.output._valid == 2  # device-only until map_read
