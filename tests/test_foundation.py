"""Foundation-layer tests: config tree, Bool gates, PRNG determinism,
unit graph wiring and the workflow scheduler (SURVEY.md §7 phase 1)."""

import numpy as np
import pytest

from veles_tpu.config import Config, root, parse_overrides
from veles_tpu.mutable import Bool
from veles_tpu import prng
from veles_tpu.units import Unit, TrivialUnit
from veles_tpu.workflow import Workflow, Repeater


# -- config ------------------------------------------------------------

class TestConfig:
    def test_autovivify_and_set(self):
        c = Config("t")
        c.loader.minibatch_size = 60
        assert c.loader.minibatch_size == 60

    def test_update_nested(self):
        c = Config("t")
        c.update({"a": {"b": 1, "c": 2}, "d": 3})
        c.update({"a": {"b": 10}})
        assert c.a.b == 10 and c.a.c == 2 and c.d == 3

    def test_override_literal_parsing(self):
        c = Config("t")
        c.apply_override("x.lr", "0.01")
        c.apply_override("x.name", "hello")
        c.apply_override("x.layers", "[1, 2]")
        assert c.x.lr == 0.01 and c.x.name == "hello" and c.x.layers == [1, 2]

    def test_parse_overrides_mutates_root(self):
        rest = parse_overrides(["w.py", "root.loader.mb=99", "-v"])
        assert rest == ["w.py", "-v"]
        assert root.loader.mb == 99

    def test_todict(self):
        c = Config("t")
        c.a.b = 1
        assert c.todict() == {"a": {"b": 1}}


# -- Bool gates --------------------------------------------------------

class TestBool:
    def test_value_and_assign(self):
        b = Bool(False)
        assert not b
        b.set(True)
        assert b
        b << False
        assert not b

    def test_expression_lazy(self):
        a, b = Bool(False), Bool(False)
        c = a | b
        d = ~c
        assert not c and d
        b.set(True)
        assert c and not d

    def test_and(self):
        a, b = Bool(True), Bool(False)
        assert not (a & b)
        b.set(True)
        assert a & b

    def test_derived_not_assignable(self):
        with pytest.raises(ValueError):
            (~Bool()).set(True)

    def test_pickle_flattens_expr(self):
        import pickle
        a = Bool(True)
        c = pickle.loads(pickle.dumps(~a))
        assert not c  # captured value at pickle time


# -- PRNG --------------------------------------------------------------

class TestPrng:
    def test_streams_deterministic(self):
        a1 = prng.get("weights").numpy.standard_normal(5)
        prng.seed_all(1234)
        a2 = prng.get("weights").numpy.standard_normal(5)
        np.testing.assert_array_equal(a1, a2)

    def test_streams_independent(self):
        a = prng.get("a").numpy.standard_normal(5)
        b = prng.get("b").numpy.standard_normal(5)
        assert not np.allclose(a, b)

    def test_jax_keys_deterministic(self):
        import jax
        s = prng.get("drop")
        k1 = s.next_key()
        k2 = s.next_key()
        prng.seed_all(1234)
        s2 = prng.get("drop")
        assert jax.random.uniform(k1) == jax.random.uniform(s2.next_key())
        assert jax.random.uniform(k2) == jax.random.uniform(s2.next_key())

    def test_snapshot_roundtrip(self):
        s = prng.get("x")
        s.numpy.standard_normal(3)
        s.next_key()
        state = prng.snapshot_state()
        after = s.numpy.standard_normal(3)
        prng.restore_state(state)
        np.testing.assert_array_equal(
            prng.get("x").numpy.standard_normal(3), after)
        assert prng.get("x")._key_counter == 1


# -- unit graph + scheduler -------------------------------------------

class Recorder(Unit):
    """Appends its name to a shared trace on each run."""

    def __init__(self, workflow, name, trace):
        super().__init__(workflow, name=name)
        self.trace = trace

    def run(self):
        self.trace.append(self.name)


class TestWorkflowEngine:
    def test_linear_chain(self):
        trace = []
        w = Workflow(name="w")
        a = Recorder(w, "a", trace)
        b = Recorder(w, "b", trace)
        a.link_from(w.start_point)
        b.link_from(a)
        w.end_point.link_from(b)
        w.initialize()
        w.run()
        assert trace == ["a", "b"]

    def test_and_join(self):
        """A unit with two predecessors fires once, after both."""
        trace = []
        w = Workflow(name="w")
        a = Recorder(w, "a", trace)
        b = Recorder(w, "b", trace)
        c = Recorder(w, "c", trace)
        a.link_from(w.start_point)
        b.link_from(w.start_point)
        c.link_from(a, b)
        w.end_point.link_from(c)
        w.initialize()
        w.run()
        assert trace[-1] == "c" and trace.count("c") == 1

    def test_gate_skip_propagates(self):
        trace = []
        w = Workflow(name="w")
        a = Recorder(w, "a", trace)
        b = Recorder(w, "b", trace)
        a.link_from(w.start_point)
        b.link_from(a)
        w.end_point.link_from(b)
        a.gate_skip = Bool(True)
        w.initialize()
        w.run()
        assert trace == ["b"]

    def test_gate_block_stops(self):
        trace = []
        w = Workflow(name="w")
        a = Recorder(w, "a", trace)
        b = Recorder(w, "b", trace)
        a.link_from(w.start_point)
        b.link_from(a)
        w.end_point.link_from(a)  # workflow still terminates
        a_b = Bool(True)
        b.gate_block = a_b
        w.initialize()
        w.run()
        assert trace == ["a"]

    def test_training_loop_shape(self):
        """The canonical VELES loop: repeater -> body -> decision, with
        the back edge gated by decision.complete (SURVEY.md §4.1)."""
        trace = []
        w = Workflow(name="w")
        rpt = Repeater(w, name="repeater")
        body = Recorder(w, "body", trace)

        class Decision(Recorder):
            def __init__(self, workflow, trace):
                super().__init__(workflow, "decision", trace)
                self.complete = Bool(False)

            def run(self):
                super().run()
                if len([t for t in self.trace if t == "decision"]) >= 3:
                    self.complete.set(True)

        dec = Decision(w, trace)
        rpt.link_from(w.start_point)
        body.link_from(rpt)
        dec.link_from(body)
        rpt.link_from(dec)           # back edge (Repeater = OR join)
        rpt.gate_block = dec.complete
        w.end_point.link_from(dec)
        w.end_point.gate_block = ~dec.complete
        w.initialize()
        w.run()
        assert trace == ["body", "decision"] * 3

    def test_link_attrs(self):
        w = Workflow(name="w")
        src = TrivialUnit(w, name="src")
        dst = TrivialUnit(w, name="dst")
        src.output = 42
        dst.link_attrs(src, ("input", "output"))
        assert dst.input == 42
        src.output = 7
        assert dst.input == 7
        dst.input = 9  # two-way write-through
        assert src.output == 9

    def test_initialize_retry_on_attribute_error(self):
        """Unit B's initialize needs A's attribute created in A's
        initialize -> ordering resolved by the retry loop."""
        w = Workflow(name="w")

        class A(Unit):
            def initialize(self, **kw):
                self.out_size = 5

        class B(Unit):
            def initialize(self, **kw):
                self.n = self.__dict__["_src"].out_size

        a, b = A(w, name="a"), B(w, name="b")
        b._src = a
        a.link_from(w.start_point)
        b.link_from(a)
        w.end_point.link_from(b)
        w.initialize()
        assert b.n == 5
