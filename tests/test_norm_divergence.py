"""Pin the streaming normalizer's bounded-sample divergence (round-2
VERDICT weak #6 / next #8).

The streaming image path fits its normalizer on at most ``norm_sample``
TRAIN files (loader/image.py post_load_data) — the full set cannot be
materialized by definition.  The resident path fits on the whole TRAIN
split.  These tests pin (a) the statistic gap itself and (b) that the
end-to-end error trajectory of a streaming run stays within tolerance
of the resident run when the sample is bounded."""

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import JaxDevice
from veles_tpu.loader.base import TRAIN
from veles_tpu.loader.image import ImageDirectoryLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow


def write_png(path, arr):
    from PIL import Image
    Image.fromarray(arr.astype(np.uint8)).save(path)


@pytest.fixture(scope="module")
def big_tree(tmp_path_factory):
    """2 classes x 60 train files with DRIFTING brightness — a
    worst-ish case for subsample fitting: file order correlates with
    the statistic being estimated."""
    base = tmp_path_factory.mktemp("imgs")
    rng = np.random.default_rng(17)
    for split, n in (("train", 60), ("validation", 20)):
        for ci, cls in enumerate(["a", "b"]):
            d = base / split / cls
            d.mkdir(parents=True)
            for i in range(n):
                level = 30 + 120 * ci + (i % 7) * 10  # drift
                img = np.full((8, 8, 3), level, np.uint8)
                img += rng.integers(0, 30, img.shape, dtype=np.uint8)
                write_png(d / f"img{i:03d}.png", img)
    return base


def build(tree, streaming, norm_sample, mb=20, epochs=4):
    prng.seed_all(31)
    kw = {"max_resident_bytes": 0, "streaming": True} if streaming \
        else {"streaming": False}
    gd = {"learning_rate": 0.05, "gradient_moment": 0.9}
    return StandardWorkflow(
        loader_factory=lambda w: ImageDirectoryLoader(
            w, name="loader", data_dir=str(tree),
            target_shape=(8, 8, 3), minibatch_size=mb,
            normalization_type="mean_disp", norm_sample=norm_sample,
            **kw),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 12},
             "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 2},
             "<-": gd}],
        decision_config={"max_epochs": epochs},
        name="norm_div")


def val_losses(w):
    return [h["loss"] for h in w.decision.history
            if h["class"] == "validation"]


class TestNormalizerDivergence:
    def test_statistic_gap_is_bounded(self, big_tree):
        """mean/disp fitted on a 32-file prefix vs all 120 train files:
        the relative gap must stay small for this pinned dataset."""
        stats = {}
        for name, streaming, sample in (("full", False, 10 ** 9),
                                        ("sub", True, 32)):
            w = build(big_tree, streaming, sample)
            ld = w.loader
            ld.workflow = w
            ld.initialize(device=JaxDevice(platform="cpu"))
            assert ld.normalizer is not None
            stats[name] = ld.normalizer.state()
            w.stop()
        mean_gap = np.abs(stats["full"]["mean"] -
                          stats["sub"]["mean"]).max()
        disp_full = np.asarray(stats["full"]["std"])
        disp_gap = np.abs(disp_full - stats["sub"]["std"]) / disp_full
        assert mean_gap < 0.05, mean_gap       # pixels live in [0, 1]
        assert disp_gap.max() < 0.35, disp_gap.max()

    def test_trajectory_delta_within_tolerance(self, big_tree):
        """Streaming (bounded 32-file fit) vs resident (full fit):
        same seeds, same net — the validation-loss trajectories must
        track within 15% relative at every epoch and converge to the
        same decision."""
        wr = build(big_tree, streaming=False, norm_sample=10 ** 9)
        wr.initialize(device=JaxDevice(platform="cpu"))
        assert not wr.fused.streaming
        wr.run()
        ws = build(big_tree, streaming=True, norm_sample=32)
        ws.initialize(device=JaxDevice(platform="cpu"))
        assert ws.fused.streaming
        ws.run()
        lr, ls = val_losses(wr), val_losses(ws)
        assert len(lr) == len(ls) and lr
        for a, b in zip(lr, ls):
            assert abs(a - b) / max(abs(a), 1e-9) < 0.15, (lr, ls)
        # both runs learn the (easy) task
        assert lr[-1] < lr[0] and ls[-1] < ls[0]
