"""GA hyperparameter tuner (veles_tpu/genetics/) and ensemble
(veles_tpu/ensemble/) — SURVEY.md §3.1 Genetics / Ensemble."""

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import JaxDevice, NumpyDevice
from veles_tpu.config import Config
from veles_tpu.datasets import synthetic_classification
from veles_tpu.ensemble import EnsemblePredictor, EnsembleTrainer
from veles_tpu.genetics import (GeneticOptimizer, Tune, find_tunes,
                                substitute_tunes)
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow


class TestTune:
    def test_int_vs_float(self):
        assert Tune(16, 4, 64).is_int
        assert not Tune(0.1, 0.01, 1.0).is_int
        assert Tune(0.1, 0.001, 1.0).log_scale
        assert not Tune(0.5, 0.0, 1.0).log_scale

    def test_clip(self):
        t = Tune(16, 4, 64)
        assert t.clip(999) == 64
        assert t.clip(5.4) == 5
        assert Tune(0.1, 0.01, 1.0).clip(2.0) == 1.0

    def test_bad_range(self):
        with pytest.raises(ValueError):
            Tune(1, 5, 2)


class TestTreeWalking:
    def make_tree(self):
        cfg = Config("root")
        cfg.model.lr = Tune(0.1, 0.01, 1.0)
        cfg.model.layers = [
            {"type": "all2all", "->": {"out": Tune(32, 8, 128)}}]
        cfg.plain = 5
        return cfg

    def test_find(self):
        tunes = find_tunes(self.make_tree())
        assert set(tunes) == {"model.lr",
                              "model.layers[0]['->']['out']"}

    def test_substitute(self):
        cfg = self.make_tree()
        tunes = find_tunes(cfg)
        substitute_tunes(cfg, {p: t.value for p, t in tunes.items()})
        assert cfg.model.lr == 0.1
        assert cfg.model.layers[0]["->"]["out"] == 32
        assert not find_tunes(cfg)


class TestGeneticOptimizer:
    def test_optimizes_quadratic(self):
        """GA must find the minimum of a smooth 2-var function."""
        prng.seed_all(99)
        tunes = {"x": Tune(5.0, -10.0, 10.0),
                 "y": Tune(-3.0, -10.0, 10.0)}
        calls = []

        def f(v):
            calls.append(v)
            return (v["x"] - 2.0) ** 2 + (v["y"] + 1.0) ** 2

        opt = GeneticOptimizer(f, tunes, population=12, generations=10)
        best, fit = opt.run()
        assert fit < 0.5, (best, fit)
        assert abs(best["x"] - 2.0) < 1.0
        assert abs(best["y"] + 1.0) < 1.0

    def test_int_genes_stay_int(self):
        prng.seed_all(99)
        tunes = {"n": Tune(16, 4, 64)}
        opt = GeneticOptimizer(lambda v: abs(v["n"] - 32), tunes,
                               population=8, generations=8)
        best, fit = opt.run()
        assert isinstance(best["n"], int)
        # must improve on the default individual's fitness (|16-32|=16)
        assert fit < 16

    def test_failed_evaluations_survive(self):
        prng.seed_all(99)
        tunes = {"x": Tune(0.5, 0.0, 1.0)}

        def f(v):
            if v["x"] > 0.5:
                raise RuntimeError("boom")
            return v["x"]

        opt = GeneticOptimizer(f, tunes, population=6, generations=3)
        best, fit = opt.run()
        assert np.isfinite(fit)
        assert best["x"] <= 0.5

    def test_requires_tunes(self):
        with pytest.raises(ValueError, match="no Tune"):
            GeneticOptimizer(lambda v: 0.0, {})

    def test_history_len_and_double_run_no_duplicates(self):
        """history holds exactly generations+1 entries (per-generation
        rankings + the final evaluated population), and a second run()
        on the same optimizer starts fresh instead of appending a
        duplicate final-generation entry."""
        prng.seed_all(99)
        tunes = {"x": Tune(0.5, 0.0, 1.0)}
        opt = GeneticOptimizer(lambda v: v["x"], tunes,
                               population=6, generations=3)
        opt.run()
        assert len(opt.history) == 3 + 1
        opt.run()
        assert len(opt.history) == 3 + 1

    def test_resumed_complete_run_no_duplicates(self, tmp_path):
        """Resuming a COMPLETED run re-records only the final entry
        the checkpoint never held — length stays generations+1."""
        prng.seed_all(99)
        tunes = {"x": Tune(0.5, 0.0, 1.0)}
        state = str(tmp_path / "ga.json")
        opt = GeneticOptimizer(lambda v: v["x"], tunes, population=6,
                               generations=3, state_path=state)
        opt.run()
        assert len(opt.history) == 4
        opt2 = GeneticOptimizer(lambda v: v["x"], tunes, population=6,
                                generations=3, state_path=state)
        opt2.run()
        assert len(opt2.history) == 4

    def test_tunes_lr_of_real_workflow(self):
        """End-to-end: GA over the learning rate of a tiny workflow —
        the best LR must beat a pathologically small default."""
        prng.seed_all(99)
        train, valid, _ = synthetic_classification(
            200, 80, (8, 8, 1), n_classes=4, seed=42)

        def evaluate(values):
            prng.seed_all(1234)
            w = StandardWorkflow(
                loader_factory=lambda wf: ArrayLoader(
                    wf, train=train, valid=valid, minibatch_size=40,
                    name="loader"),
                layers=[{"type": "softmax",
                         "->": {"output_sample_shape": 4},
                         "<-": {"learning_rate": values["lr"]}}],
                decision_config={"max_epochs": 3}, name="ga_wf")
            w.initialize(device=JaxDevice(platform="cpu"))
            w.run()
            return w.decision.min_valid_error

        tunes = {"lr": Tune(1e-4, 1e-4, 2.0)}
        baseline = evaluate({"lr": 1e-4})
        opt = GeneticOptimizer(evaluate, tunes, population=8,
                               generations=3)
        best, fit = opt.run()
        assert fit < baseline, (fit, baseline)
        assert best["lr"] > 1e-3


def _member_factory(train, valid):
    def factory():
        return StandardWorkflow(
            loader_factory=lambda wf: ArrayLoader(
                wf, train=train, valid=valid, minibatch_size=40,
                name="loader"),
            layers=[
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 16},
                 "<-": {"learning_rate": 0.1}},
                {"type": "softmax", "->": {"output_sample_shape": 4},
                 "<-": {"learning_rate": 0.1}},
            ],
            decision_config={"max_epochs": 4}, name="member")
    return factory


class TestEnsemble:
    def test_train_and_aggregate(self):
        train, valid, _ = synthetic_classification(
            300, 100, (8, 8, 1), n_classes=4, seed=42)
        factory = _member_factory(train, valid)
        trainer = EnsembleTrainer(factory,
                                  lambda: JaxDevice(platform="cpu"),
                                  n_members=3, base_seed=555)
        members = trainer.train()
        assert len(members) == 3
        # seeds differ -> members differ
        w0 = members[0]["params"]["fwd0_all2all_tanh"]["weights"]
        w1 = members[1]["params"]["fwd0_all2all_tanh"]["weights"]
        assert not np.allclose(w0, w1)

        pred = EnsemblePredictor(factory,
                                 lambda: JaxDevice(platform="cpu"),
                                 members)
        x_valid, y_valid = valid
        ens_err = pred.error_pct(x_valid, y_valid)
        worst = max(m["valid_error"] for m in members)
        # the ensemble must at least not be worse than the worst member
        assert ens_err <= worst + 1e-9, (ens_err, worst)
        proba = pred.predict_proba(x_valid[:5])
        np.testing.assert_allclose(proba.sum(-1), 1.0, atol=1e-5)

    def test_empty_ensemble_rejected(self):
        with pytest.raises(ValueError, match="empty"):
            EnsemblePredictor(lambda: None, lambda: None, [])


class TestGaEnsembleForge:
    """The GA -> ensemble -> Forge coupling (round-4 VERDICT next #8 /
    weak #5: ensemble was a subsystem island)."""

    def test_save_load_members_roundtrip(self, tmp_path):
        from veles_tpu.ensemble import load_members, save_members
        members = [{
            "seed": 5, "valid_error": 1.5, "values": {"lr": 0.2},
            "forward_names": ["fwd0_softmax", "fwd1_max_pooling"],
            "params": {"fwd0_softmax": {
                "weights": np.arange(6, dtype=np.float32).reshape(2, 3),
                "bias": np.zeros(3, np.float32)}},
        }]
        path = str(tmp_path / "m.npz")
        save_members(path, members)
        loaded = load_members(path)
        assert loaded[0]["seed"] == 5
        # weightless forwards (pooling/LRN/dropout) serialize no
        # arrays but must come back as empty param dicts — the
        # predictor indexes params[f.name] for EVERY forward
        assert loaded[0]["params"]["fwd1_max_pooling"] == {}
        assert loaded[0]["values"] == {"lr": 0.2}
        np.testing.assert_array_equal(
            loaded[0]["params"]["fwd0_softmax"]["weights"],
            members[0]["params"]["fwd0_softmax"]["weights"])

    def test_separator_in_names_fails_at_save_time(self, tmp_path):
        """A '|' in a forward OR param name must fail when the artifact
        is written, not when a consumer later loads it."""
        from veles_tpu.ensemble import save_members
        base = {"seed": 1, "valid_error": 1.0, "values": None,
                "forward_names": ["ok"]}
        bad_fwd = [dict(base, params={"a|b": {"w": np.zeros(2)}})]
        with pytest.raises(ValueError, match="forward name"):
            save_members(str(tmp_path / "f.npz"), bad_fwd)
        bad_param = [dict(base, params={"ok": {"w|v": np.zeros(2)}})]
        with pytest.raises(ValueError, match="param name"):
            save_members(str(tmp_path / "p.npz"), bad_param)
        assert not (tmp_path / "p.npz").exists()

    def test_normalize_npz_path(self, tmp_path):
        from veles_tpu.ensemble import (load_members,
                                        normalize_npz_path,
                                        save_members)
        assert normalize_npz_path("a/b") == "a/b.npz"
        assert normalize_npz_path("a/b.npz") == "a/b.npz"
        members = [{"seed": 1, "valid_error": 1.0, "values": None,
                    "forward_names": ["f"],
                    "params": {"f": {"w": np.zeros(2, np.float32)}}}]
        # suffix-less save reports the REAL on-disk path, and the same
        # normalization makes the identical flag value load again
        suffixless = str(tmp_path / "ens")
        real = save_members(suffixless, members)
        assert real == suffixless + ".npz"
        assert load_members(normalize_npz_path(suffixless))[0][
            "seed"] == 1

    def test_from_ga_requires_history(self):
        class Opt:
            history = []
        with pytest.raises(ValueError, match="history"):
            EnsembleTrainer.from_ga(Opt(), lambda v: None, lambda: None)

    def test_ga_to_ensemble_to_forge_roundtrip(self, tmp_path):
        """End to end: GA tunes the lr -> its top-K genomes seed the
        ensemble -> trained members ship as a Forge package ->
        publish -> fetch -> install -> aggregate prediction."""
        import threading

        from veles_tpu import forge
        from veles_tpu.ensemble import (load_packed_ensemble,
                                        pack_ensemble)

        prng.seed_all(99)
        train, valid, _ = synthetic_classification(
            200, 80, (8, 8, 1), n_classes=4, seed=42)

        def factory(values=None):
            lr = values["lr"] if values else 0.1
            return StandardWorkflow(
                loader_factory=lambda wf: ArrayLoader(
                    wf, train=train, valid=valid, minibatch_size=40,
                    name="loader"),
                layers=[{"type": "softmax",
                         "->": {"output_sample_shape": 4},
                         "<-": {"learning_rate": lr}}],
                decision_config={"max_epochs": 2}, name="ga_member")

        def evaluate(values):
            prng.seed_all(1234)
            w = factory(values)
            w.initialize(device=JaxDevice(platform="cpu"))
            w.run()
            err = w.decision.min_valid_error
            w.stop()
            return err

        opt = GeneticOptimizer(evaluate, {"lr": Tune(0.05, 1e-3, 1.0)},
                               population=4, generations=2)
        opt.run()

        trainer = EnsembleTrainer.from_ga(
            opt, factory, lambda: JaxDevice(platform="cpu"), k=2,
            base_seed=321)
        members = trainer.train()
        assert len(members) == 2
        assert members[0]["values"] is not None  # genomes rode along

        wf_file = tmp_path / "ens_wf.py"
        wf_file.write_text("def run(launcher):\n    pass\n")
        pkg = str(tmp_path / "ens.vpkg")
        pack_ensemble(pkg, "ens", members, str(wf_file), author="t")

        server = forge.make_forge_server(str(tmp_path / "store"),
                                         port=0)
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        url = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            m = forge.publish(pkg, url)
            assert m["name"] == "ens"
            got = forge.fetch("ens", url, str(tmp_path / "dl"))
        finally:
            server.shutdown()
            t.join(timeout=5)

        loaded = load_packed_ensemble(got, str(tmp_path / "inst"))
        assert [mm["seed"] for mm in loaded] == \
            [mm["seed"] for mm in members]
        np.testing.assert_array_equal(
            loaded[0]["params"]["fwd0_softmax"]["weights"],
            members[0]["params"]["fwd0_softmax"]["weights"])
        pred = EnsemblePredictor(
            lambda: factory(members[0]["values"]),
            lambda: JaxDevice(platform="cpu"), loaded)
        x_valid, y_valid = valid
        err = pred.error_pct(x_valid, y_valid)
        worst = max(mm["valid_error"] for mm in loaded)
        assert err <= worst + 1e-9, (err, worst)
