"""Veleslint (veles_tpu/analysis): every rule catches its seeded
fixture violations and passes its clean twin, waivers and the
baseline behave, the knob/event registries are wired, the generated
docs table is in sync — and the FULL-REPO scan reports zero
non-baselined findings, which is the tier-1 gate that makes the
PR 6-8 hardening invariants bite on every future change."""

import json
import os

import pytest

from veles_tpu import events, knobs
from veles_tpu.analysis import (
    Config,
    check_knob_table,
    load_baseline,
    load_config,
    new_findings,
    repo_root,
    repo_scan,
    rule_names,
    run_lint,
    scan_source,
    write_baseline,
)
from veles_tpu.analysis.engine import _mini_toml_table

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "veleslint")


def fixture(name: str) -> str:
    with open(os.path.join(FIXTURES, name), encoding="utf-8") as f:
        return f.read()


def scan_fixture(name: str, rule: str, path: str = None,
                 config: Config = None):
    """Scan one fixture under a fake in-scope path, returning only
    the findings of the rule under test."""
    path = path or f"veles_tpu/_fixture_{name}"
    found = scan_source(path, fixture(name), config or Config())
    assert not any(f.rule == "parse-error" for f in found), found
    return [f for f in found if f.rule == rule]


# -- one positive + one clean fixture per rule -------------------------

def test_atomic_write_catches_seeded():
    got = scan_fixture("atomic_bad.py", "atomic-write")
    assert len(got) == 3, got
    assert {f.detail for f in got} == {"open-w", "open-wb", "open-w+"}


def test_atomic_write_clean():
    assert scan_fixture("atomic_clean.py", "atomic-write") == []


def test_atomic_write_out_of_scope():
    # the rule bites package code only; scripts write scratch freely
    found = scan_source("scripts/_fixture.py",
                        fixture("atomic_bad.py"), Config())
    assert [f for f in found if f.rule == "atomic-write"] == []


def test_env_registry_catches_seeded():
    got = scan_fixture("env_bad.py", "env-registry")
    assert {f.detail for f in got} == {
        "VELES_NOT_A_KNOB", "VELES_PREEMPT_GRAEC",
        "VELES_ALSO_UNDECLARED", "VELES_MYSTERY_FLAG"}, got


def test_env_registry_clean():
    # declared literals, module consts, class consts, non-VELES
    # names, and unresolvable dynamics all pass
    assert scan_fixture("env_clean.py", "env-registry") == []


def test_event_registry_catches_seeded():
    got = scan_fixture("event_bad.py", "event-registry")
    assert {f.detail for f in got} == {
        "ga.hang_detected", "ga.hangs_detcted", "ga.last_hang_wait",
        "ga.genome_seconds", "ga.cohort_train"}, got
    typo = [f for f in got if f.detail == "ga.hangs_detcted"]
    assert "NOT declared" in typo[0].message


def test_event_registry_clean():
    assert scan_fixture("event_clean.py", "event-registry") == []


def test_tracer_hygiene_catches_seeded():
    got = scan_fixture("tracer_bad.py", "tracer-hygiene")
    whats = {f.detail.split(":", 1)[1] for f in got}
    assert ".item()" in whats
    assert "print()" in whats
    assert "np.asarray()" in whats
    assert "float(lr)" in whats
    assert ".block_until_ready()" in whats
    assert "python branch on jnp value" in whats
    # every seeded traced function was detected, decorator and
    # passed-to-jit/vmap forms alike
    fns = {f.detail.split(":", 1)[0] for f in got}
    assert fns == {"decorated_sync", "partial_decorated",
                   "passed_to_jit", "vmapped"}, fns


def test_tracer_hygiene_clean():
    assert scan_fixture("tracer_clean.py", "tracer-hygiene") == []


def test_exit_code_catches_seeded():
    cfg = Config(exit_code_modules=["fx/exit_bad.py"])
    got = scan_fixture("exit_bad.py", "exit-code-literals",
                       path="fx/exit_bad.py", config=cfg)
    # os._exit(13), sys.exit(14), rc == 14, rc in (13, 14)
    assert len(got) == 5, got
    assert {f.detail for f in got} == {
        "exit-call-13", "exit-call-14", "comparison-13",
        "comparison-14"}


def test_exit_code_clean_and_scoped():
    cfg = Config(exit_code_modules=["fx/exit_clean.py"])
    assert scan_fixture("exit_clean.py", "exit-code-literals",
                        path="fx/exit_clean.py", config=cfg) == []
    # out of the configured module list, nothing fires at all
    found = scan_source("fx/elsewhere.py", fixture("exit_bad.py"),
                        Config())
    assert [f for f in found if f.rule == "exit-code-literals"] == []


def test_lock_discipline_catches_seeded():
    cfg = Config(lock_modules=["fx/lock_bad.py"])
    got = scan_fixture("lock_bad.py", "lock-discipline",
                       path="fx/lock_bad.py", config=cfg)
    assert {f.detail for f in got} == {
        "_jobs.setitem", "_jobs.clear", "_queue.append",
        "_queue.popleft", "_seen.append"}, got
    # the import-time mutation stayed exempt
    assert all(f.line > 11 for f in got)


def test_lock_discipline_clean():
    cfg = Config(lock_modules=["fx/lock_clean.py"])
    assert scan_fixture("lock_clean.py", "lock-discipline",
                        path="fx/lock_clean.py", config=cfg) == []


def test_waivers_suppress_findings():
    found = scan_source("veles_tpu/_fixture_waiver.py",
                        fixture("waiver.py"), Config())
    assert found == [], found


# -- engine mechanics --------------------------------------------------

def test_finding_key_is_line_stable():
    a = scan_fixture("env_bad.py", "env-registry")
    # shift the whole module down: lines move, keys must not
    b = scan_source("veles_tpu/_fixture_env_bad.py",
                    "# pad\n# pad\n" + fixture("env_bad.py"),
                    Config())
    b = [f for f in b if f.rule == "env-registry"]
    assert {f.key for f in a} == {f.key for f in b}
    assert {f.line for f in a} != {f.line for f in b}


def test_baseline_roundtrip(tmp_path):
    findings = scan_fixture("env_bad.py", "env-registry")
    path = str(tmp_path / "baseline.json")
    write_baseline(path, findings)
    # a freshly grandfathered baseline carries TODO justifications —
    # the loader must refuse it until a human writes the reasons
    with pytest.raises(ValueError, match="justification"):
        load_baseline(path)
    with open(path) as f:
        data = json.load(f)
    for entry in data["findings"]:
        entry["justification"] = "fixture: deliberately seeded"
    with open(path, "w") as f:
        json.dump(data, f)
    baseline = load_baseline(path)
    assert len(baseline) == len({f.key for f in findings})
    assert new_findings(findings, baseline) == []


def test_mini_toml_fallback_parses_pyproject():
    # python 3.10 has no tomllib; the fallback must read the real
    # [tool.veleslint] section (multi-line string arrays included)
    with open(os.path.join(repo_root(), "pyproject.toml")) as f:
        table = _mini_toml_table(f.read(), "tool.veleslint")
    assert table["baseline"] == "veles_tpu/analysis/baseline.json"
    assert "veles_tpu" in table["paths"]
    assert "veles_tpu/telemetry.py" in table["lock_modules"]
    assert "scripts/chaos_drill.py" in table["exit_code_modules"]


def test_config_rejects_unknown_keys():
    with pytest.raises(ValueError, match="unknown key"):
        Config(not_a_real_option=True)


# -- registry wiring ---------------------------------------------------

def test_knob_registry_defaults():
    from veles_tpu import supervisor
    assert knobs.get("VELES_SUPERVISE_MAX_CRASHES") == 5
    assert knobs.get("VELES_PREEMPT_GRACE") == 25.0
    assert knobs.get("VELES_FAULTS") == ""
    # the supervisor's env-default strings agree with the registry
    assert int(os.environ.get(supervisor.MAX_CRASHES_ENV, "5")) == \
        knobs.get(supervisor.MAX_CRASHES_ENV)
    # parsing: flags are set-and-not-"0"; malformed values fall back
    assert knobs.get("VELES_PREEMPT_DISABLE",
                     {"VELES_PREEMPT_DISABLE": "1"}) is True
    assert knobs.get("VELES_PREEMPT_DISABLE",
                     {"VELES_PREEMPT_DISABLE": "0"}) is False
    assert knobs.get("VELES_PREEMPT_GRACE",
                     {"VELES_PREEMPT_GRACE": "banana"}) == 25.0
    with pytest.raises(KeyError):
        knobs.get("VELES_NOT_A_KNOB")


def test_event_registry_covers_drill_names():
    # the names chaos_drill asserts on must stay declared — renaming
    # an event now breaks HERE, not mid-drill
    for name in ("ga.hang_detected", "ga.evaluator_restart",
                 "snapshot.fallback", "ga.checkpoint_fallback",
                 "loader.corrupt_file", "device.oom_retry",
                 "device.oom_degraded", "multihost.emergency_snapshot",
                 "preempt.requested", "preempt.final_snapshot",
                 "supervisor.resumed", "supervisor.done"):
        assert events.known(name), name
    assert not events.known("ga.hangs_detcted")
    assert events.all_names()


def test_rule_catalog_is_stable():
    assert rule_names() == [
        "atomic-write", "env-registry", "event-registry",
        "tracer-hygiene", "exit-code-literals", "lock-discipline",
        "engine-residency-seam", "thread-lifecycle", "wire-protocol",
        "trace-wire-key", "lock-order", "blocking-under-lock",
        "waiter-discipline"]


# -- docs + full-repo gate ---------------------------------------------

def test_guide_knob_table_in_sync():
    root = repo_root()
    finding = check_knob_table(root, load_config(root))
    assert finding is None, finding and finding.message


def test_full_repo_scan_zero_new_findings():
    """THE gate: the whole repo, scanned with the checked-in config
    and baseline, reports nothing new.  If this fails you either fix
    the finding, waive it inline with a reason, or baseline it with a
    written justification (docs/guide.md section 10)."""
    new, baseline = repo_scan()
    assert baseline, "baseline.json should load non-empty"
    msg = "\n".join(f.format() for f in new)
    assert not new, f"new veleslint findings:\n{msg}"


def test_cli_json_smoke(capsys):
    from veles_tpu.analysis import cli
    rc = cli.main(["--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["new"] == []
    assert out["baseline_total"] == 2


def test_cli_single_rule_and_exit_code(tmp_path, capsys):
    # a scratch repo with one seeded violation: rc must be 1 and the
    # finding printed — the CI contract
    from veles_tpu.analysis import cli
    root = tmp_path / "repo"
    (root / "veles_tpu").mkdir(parents=True)
    (root / "veles_tpu" / "bad.py").write_text(
        'def w(p):\n    with open(p, "w") as f:\n        f.write("x")\n')
    (root / "docs").mkdir()
    (root / "docs" / "guide.md").write_text("stub\n")
    rc = cli.main(["--root", str(root), "--rule", "atomic-write",
                   "--no-docs-check"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "atomic-write" in out and "bad.py" in out


def test_run_lint_changed_only_scoping(tmp_path):
    """--changed-only semantics: per-file findings report only for
    the given paths, while law-level findings (the lock-order json)
    always report — the graph is meaningless piecemeal."""
    root = tmp_path / "repo"
    (root / "veles_tpu").mkdir(parents=True)
    bad = 'def w(p):\n    with open(p, "w") as f:\n        f.write("x")\n'
    (root / "veles_tpu" / "a.py").write_text(bad)
    (root / "veles_tpu" / "b.py").write_text(bad)
    found = run_lint(str(root), Config(), check_docs=False,
                     only_paths=["veles_tpu/a.py"])
    per_file = [f for f in found if f.path.endswith(".py")]
    assert {f.path for f in per_file} == {"veles_tpu/a.py"}
    assert any(f.rule == "lock-order" and f.detail == "missing"
               for f in found)
    full = run_lint(str(root), Config(), check_docs=False)
    assert {f.path for f in full if f.path.endswith(".py")} == \
        {"veles_tpu/a.py", "veles_tpu/b.py"}


def test_scan_is_fast_enough_for_tier1():
    import time
    t0 = time.perf_counter()
    run_lint(repo_root())
    assert time.perf_counter() - t0 < 10.0
