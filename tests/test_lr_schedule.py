"""Per-iteration LR schedules must advance per MINIBATCH in fused mode
(superstep scan), not per loader firing — the fused trajectory must
match the eager one exactly (round-1 VERDICT weak #8 / next #10)."""

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import JaxDevice
from veles_tpu.datasets import synthetic_classification
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow


def build(policy_by):
    prng.seed_all(4242)
    train, valid, _ = synthetic_classification(
        160, 40, (8, 8, 1), n_classes=4, seed=99)
    gd = {"learning_rate": 0.1, "gradient_moment": 0.0}
    return StandardWorkflow(
        loader_factory=lambda w: ArrayLoader(
            w, train=train, valid=valid, minibatch_size=20,
            name="loader"),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
             "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": gd},
        ],
        decision_config={"max_epochs": 3},
        lr_adjust_config={"policy_name": "inv",
                          "policy_kwargs": {"gamma": 0.3, "power": 1.0},
                          "by": policy_by},
        superstep=8,
        name="lr_test")


class TestPerIterationSchedule:
    @pytest.mark.parametrize("by", ["iteration", "epoch"])
    def test_fused_superstep_matches_eager(self, by):
        """8 train minibatches/epoch -> fused mode runs the whole epoch
        as ONE scan; with by='iteration' each scanned minibatch must see
        its own lr, or the trajectories diverge."""
        w_eager = build(by)
        w_eager.initialize(device=JaxDevice(platform="cpu"),
                           fused=False)
        w_eager.run()

        w_fused = build(by)
        w_fused.initialize(device=JaxDevice(platform="cpu"))
        assert w_fused.loader.superstep == 8
        w_fused.run()

        # the schedule consumed the same number of iterations
        assert w_eager.lr_adjust._iteration == \
            w_fused.lr_adjust._iteration == 24  # 3 epochs x 8
        he = [h for h in w_eager.decision.history
              if h["class"] == "validation"]
        hf = [h for h in w_fused.decision.history
              if h["class"] == "validation"]
        assert len(he) == len(hf) == 3
        for a, b in zip(he, hf):
            assert abs(a["loss"] - b["loss"]) < 1e-5, (by, a, b)
        for f_e, f_f in zip(w_eager.forwards, w_fused.forwards):
            np.testing.assert_allclose(
                np.asarray(f_e.weights.map_read()),
                np.asarray(w_fused.fused._params[f_f.name]["weights"]),
                atol=1e-5)

    def test_lr_rates_row_mismatch_raises(self):
        from veles_tpu.loader.base import TRAIN
        w = build("iteration")
        w.initialize(device=JaxDevice(platform="cpu"))
        while True:  # the first loader firings are validation
            w.loader.run()
            if w.loader.minibatch_class == TRAIN:
                break
        w.fused.lr_rates = [[[0.1, 0.1]] * 2] * 3  # wrong row count
        with pytest.raises(ValueError, match="superstep"):
            w.fused.run()
