"""Offline ImageNet preparation tooling (SURVEY.md §3.2 samples row:
resizing, label json, mean image) — round-2 VERDICT next #7."""

import json
import os
import tarfile

import numpy as np
import pytest

from veles_tpu.datasets import prepare_imagenet


def write_png(path, arr):
    from PIL import Image
    Image.fromarray(arr.astype(np.uint8)).save(path)


def make_flat_tree(base, n_classes=3, per_class=10, size=40):
    rng = np.random.default_rng(11)
    for c in range(n_classes):
        d = os.path.join(base, f"class_{c}")
        os.makedirs(d)
        for i in range(per_class):
            arr = rng.integers(0, 255, (size, size + 8, 3))
            write_png(os.path.join(d, f"im{i:03d}.png"), arr)


class TestPrepareImagenet:
    def test_flat_tree(self, tmp_path):
        src = tmp_path / "src"
        out = tmp_path / "out"
        os.makedirs(src)
        make_flat_tree(str(src))
        manifest = prepare_imagenet(str(src), str(out), image_size=32,
                                    valid_frac=0.2, progress_every=0)
        assert manifest["n_classes"] == 3
        counts = manifest["counts"]
        assert counts["train"] + counts["validation"] == 30
        assert counts["validation"] == 6  # 0.2 of 10 per class
        labels = json.loads((out / "labels.json").read_text())
        assert labels == {"class_0": 0, "class_1": 1, "class_2": 2}
        mean = np.load(out / "mean_image.npy")
        assert mean.shape == (32, 32, 3)
        assert 0.2 < mean.mean() < 0.8  # uniform-noise pixels
        # every output image is the target size
        from PIL import Image
        some = next((out / "train" / "class_0").glob("*.jpg"))
        with Image.open(some) as im:
            assert im.size == (32, 32)

    def test_presplit_tree_and_archive(self, tmp_path):
        src = tmp_path / "src"
        for split in ("train", "validation"):
            for c in ("a", "b"):
                d = src / split / c
                os.makedirs(d)
                n = 4 if split == "train" else 2
                for i in range(n):
                    write_png(str(d / f"x{i}.png"),
                              np.full((8, 8, 3), 100 + i))
        tar = tmp_path / "data.tar.gz"
        with tarfile.open(tar, "w:gz") as t:
            t.add(src, arcname=".")
        out = tmp_path / "out"
        manifest = prepare_imagenet(str(tar), str(out), image_size=8,
                                    progress_every=0)
        assert manifest["counts"] == {"train": 8, "validation": 4,
                                      "test": 0}
        assert not (out / "_extracted").exists()

    def test_loader_trains_on_prepared_tree(self, tmp_path):
        """End-to-end: prepared output feeds ImageDirectoryLoader via
        the alexnet config's data_dir hook."""
        from veles_tpu.backends import JaxDevice
        from veles_tpu.models import alexnet

        src = tmp_path / "src"
        os.makedirs(src)
        make_flat_tree(str(src), n_classes=2, per_class=12, size=24)
        out = tmp_path / "prepared"
        prepare_imagenet(str(src), str(out), image_size=20,
                         valid_frac=0.25, progress_every=0)

        class FL:
            workflow = None

        w = alexnet.create_workflow(
            FL(),
            loader={"data_dir": str(out), "image_size": 20,
                    "minibatch_size": 6},
            n_classes=2,
            layers=[  # tiny stand-in net; the loader is under test
                {"type": "conv_relu",
                 "->": {"n_kernels": 4, "kx": 5, "ky": 5, "sliding": 2},
                 "<-": {"learning_rate": 0.02}},
                {"type": "max_pooling",
                 "->": {"kx": 2, "ky": 2}, "<-": {}},
                {"type": "softmax", "->": {"output_sample_shape": 2},
                 "<-": {"learning_rate": 0.02}}],
            decision={"max_epochs": 2}, lr_adjust=None)
        w.initialize(device=JaxDevice(platform="cpu"))
        w.run()
        assert len(w.decision.history) == 4
        for h in w.decision.history:
            assert np.isfinite(h["loss"])

    def test_wrapper_dir_archive(self, tmp_path):
        """`tar czf x.tgz ILSVRC/` layouts (one top-level wrapper dir)
        must descend to the real tree, not treat the wrapper as a
        class."""
        src = tmp_path / "ILSVRC"
        for c in ("a", "b"):
            d = src / "train" / c
            os.makedirs(d)
            for i in range(3):
                write_png(str(d / f"x{i}.png"), np.full((8, 8, 3), 90))
        tar = tmp_path / "wrapped.tar.gz"
        with tarfile.open(tar, "w:gz") as t:
            t.add(src, arcname="ILSVRC")
        out = tmp_path / "out"
        manifest = prepare_imagenet(str(tar), str(out), image_size=8,
                                    progress_every=0)
        assert manifest["n_classes"] == 2
        assert manifest["counts"]["train"] == 6

    def test_extension_collision_not_overwritten(self, tmp_path):
        src = tmp_path / "src" / "cls"
        os.makedirs(src)
        write_png(str(src / "im.png"), np.full((8, 8, 3), 10))
        from PIL import Image
        Image.fromarray(np.full((8, 8, 3), 200, np.uint8)).save(
            src / "im.jpeg")
        out = tmp_path / "out"
        manifest = prepare_imagenet(str(tmp_path / "src"), str(out),
                                    image_size=8, valid_frac=0.0,
                                    progress_every=0)
        assert manifest["counts"]["train"] == 2
        produced = sorted(p.name for p in
                          (out / "train" / "cls").glob("*.jpg"))
        assert len(produced) == 2, produced  # no silent overwrite

    def test_images_only_under_wrapper_raises(self, tmp_path):
        """Class dirs that hold only subdirectories (no images at the
        scanned depth) must fail loudly, not emit an empty dataset."""
        for cls in ("cls_a", "cls_b"):
            deep = tmp_path / "src" / cls / "too_deep"
            os.makedirs(deep)
            write_png(str(deep / "x.png"), np.full((8, 8, 3), 10))
        with pytest.raises(ValueError, match="zero images"):
            prepare_imagenet(str(tmp_path / "src"),
                             str(tmp_path / "out"), progress_every=0)

    def test_bad_source_raises(self, tmp_path):
        with pytest.raises(ValueError):
            prepare_imagenet(str(tmp_path / "nope"),
                             str(tmp_path / "out"))
        empty = tmp_path / "empty"
        os.makedirs(empty)
        with pytest.raises(ValueError):
            prepare_imagenet(str(empty), str(tmp_path / "out2"))
