"""Forge packaging (veles_tpu/forge.py) and image-file loaders
(veles_tpu/loader/image.py) — SURVEY.md §3.1 Forge client / Image
loaders."""

import io
import json
import os
import tarfile

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import NumpyDevice
from veles_tpu.forge import ForgePackage
from veles_tpu.loader.base import TRAIN, VALID
from veles_tpu.loader.image import (FileListImageLoader,
                                    ImageDirectoryLoader, decode_image)
from veles_tpu.ops.standard_workflow import StandardWorkflow


def write_png(path, arr):
    from PIL import Image

    Image.fromarray(arr.astype(np.uint8)).save(path)


@pytest.fixture
def image_tree(tmp_path):
    """train/validation trees with 2 classes of tiny distinct images."""
    rng = np.random.default_rng(7)
    for split, n in (("train", 12), ("validation", 6)):
        for ci, cls in enumerate(["circles", "squares"]):
            d = tmp_path / split / cls
            d.mkdir(parents=True)
            for i in range(n):
                img = np.full((10, 12, 3), 40 + 150 * ci, np.uint8)
                img += rng.integers(0, 40, img.shape, dtype=np.uint8)
                write_png(d / f"img{i}.png", img)
    return tmp_path


class TestDecodeImage:
    def test_resize_and_gray(self, tmp_path):
        p = tmp_path / "x.png"
        write_png(p, np.full((8, 8, 3), 128, np.uint8))
        a = decode_image(str(p), (4, 6, 1))
        assert a.shape == (4, 6, 1)
        assert a.dtype == np.float32
        assert 0.45 < a.mean() < 0.55  # normalized

    def test_rgb(self, tmp_path):
        p = tmp_path / "x.png"
        write_png(p, np.full((5, 5, 3), 255, np.uint8))
        a = decode_image(str(p), (5, 5, 3))
        assert a.shape == (5, 5, 3)
        np.testing.assert_allclose(a, 1.0)


class TestImageDirectoryLoader:
    def test_loads_tree(self, image_tree):
        ld = ImageDirectoryLoader(
            data_dir=str(image_tree), target_shape=(10, 12, 3),
            minibatch_size=8, name="imgloader")
        ld.initialize(device=None)
        assert ld.class_names == ["circles", "squares"]
        assert ld.class_lengths == [0, 12, 24]
        assert ld.original_data.mem.shape == (36, 10, 12, 3)
        # labels match pixel intensity classes
        labels = ld.original_labels.mem
        dark = ld.original_data.mem[labels == 0].mean()
        bright = ld.original_data.mem[labels == 1].mean()
        assert dark < bright

    def test_empty_tree_raises(self, tmp_path):
        ld = ImageDirectoryLoader(data_dir=str(tmp_path),
                                  name="imgloader")
        with pytest.raises(ValueError, match="no class directories"):
            ld.load_data()

    def test_trains_workflow(self, image_tree):
        prng.seed_all(777)
        w = StandardWorkflow(
            loader_factory=lambda wf: ImageDirectoryLoader(
                wf, data_dir=str(image_tree), target_shape=(10, 12, 3),
                minibatch_size=12, name="loader",
                normalization_type="mean_disp"),
            layers=[{"type": "softmax",
                     "->": {"output_sample_shape": 2},
                     "<-": {"learning_rate": 0.05}}],
            decision_config={"max_epochs": 10}, name="img_wf")
        w.initialize(device=NumpyDevice())
        w.run()
        # trivial brightness classes must be fully separable
        assert w.decision.epoch_error_pct[1] == 0.0, \
            w.decision.epoch_error_pct

    def test_snapshot_drops_pixels(self, image_tree):
        import pickle
        ld = ImageDirectoryLoader(
            data_dir=str(image_tree), target_shape=(10, 12, 3),
            minibatch_size=8, name="imgloader")
        ld.initialize(device=None)
        blob = pickle.dumps(ld)
        assert len(blob) < 20000, len(blob)
        ld2 = pickle.loads(blob)
        ld2.initialize(device=None)  # re-decodes from disk
        np.testing.assert_array_equal(ld2.original_labels.mem,
                                      ld.original_labels.mem)


class TestFileListLoader:
    def test_explicit_lists(self, image_tree):
        paths0 = sorted((image_tree / "train" / "circles").iterdir())
        paths1 = sorted((image_tree / "train" / "squares").iterdir())
        train = [(str(p), 0) for p in paths0[:8]] + \
                [(str(p), 1) for p in paths1[:8]]
        valid = [(str(p), 0) for p in paths0[8:]] + \
                [(str(p), 1) for p in paths1[8:]]
        ld = FileListImageLoader(train=train, valid=valid,
                                 target_shape=(10, 12, 3),
                                 minibatch_size=8, name="fl")
        ld.initialize(device=None)
        assert ld.class_lengths == [0, 8, 16]


class TestLoaderNormalization:
    def test_mean_disp_fit_on_train_only(self):
        from veles_tpu.loader import ArrayLoader
        x_tr = np.random.default_rng(0).normal(5.0, 2.0,
                                               (100, 4)).astype(np.float32)
        x_va = np.random.default_rng(1).normal(9.0, 2.0,
                                               (40, 4)).astype(np.float32)
        y_tr = np.zeros(100, np.int64)
        y_va = np.zeros(40, np.int64)
        ld = ArrayLoader(train=(x_tr, y_tr), valid=(x_va, y_va),
                         minibatch_size=20, name="n",
                         normalization_type="mean_disp")
        ld.initialize(device=None)
        data = ld.original_data.mem
        train_rows = data[ld.class_offset(2):]
        valid_rows = data[:40]
        # train standardized exactly; valid shifted by the TRAIN stats
        np.testing.assert_allclose(train_rows.mean(0), 0.0, atol=1e-4)
        assert valid_rows.mean() > 1.0  # (9-5)/2 = 2-ish

    def test_normalizer_state_survives_snapshot(self):
        import pickle
        from veles_tpu.loader.synthetic import \
            SyntheticClassificationLoader
        ld = SyntheticClassificationLoader(
            n_train=50, n_valid=20, shape=(4, 4, 1), n_classes=2,
            minibatch_size=10, name="n",
            normalization_type="mean_disp")
        ld.initialize(device=None)
        normed = ld.original_data.mem.copy()
        mean0 = ld.normalizer.mean.copy()
        ld2 = pickle.loads(pickle.dumps(ld))
        ld2.initialize(device=None)  # regenerates + re-applies stats
        np.testing.assert_array_equal(ld2.normalizer.mean, mean0)
        np.testing.assert_allclose(ld2.original_data.mem, normed,
                                   atol=1e-6)


class TestForge:
    @pytest.fixture
    def pkg(self, tmp_path):
        wf = tmp_path / "wf.py"
        wf.write_text("def run(launcher):\n    pass\n")
        cfg = tmp_path / "cfg.py"
        cfg.write_text("root.x = 1\n")
        snap = tmp_path / "snap.pkl.gz"
        snap.write_bytes(b"\x1f\x8b" + b"0" * 100)
        out = str(tmp_path / "model.vpkg")
        ForgePackage.pack(out, "mnist-demo", str(wf), [str(cfg)],
                          snapshot=str(snap), version="2.1.0",
                          author="me", description="demo net")
        return out, tmp_path

    def test_pack_and_manifest(self, pkg):
        out, _ = pkg
        m = ForgePackage.read_manifest(out)
        assert m["name"] == "mnist-demo"
        assert m["entry"] == "wf.py"
        assert m["configs"] == ["cfg.py"]
        assert m["snapshot"] == "snap.pkl.gz"
        assert set(m["sha256"]) == {"wf.py", "cfg.py", "snap.pkl.gz"}

    def test_install_verifies_and_extracts(self, pkg, tmp_path):
        out, _ = pkg
        dest = tmp_path / "store"
        m = ForgePackage.install(out, str(dest))
        root = m["root"]
        assert root.endswith("mnist-demo-2.1.0")
        assert os.path.isfile(os.path.join(root, "wf.py"))
        assert os.path.isfile(os.path.join(root, "snap.pkl.gz"))

    def test_install_detects_corruption(self, pkg, tmp_path):
        out, src = pkg
        # corrupt a member but keep the manifest hashes
        with tarfile.open(out, "r:gz") as tar:
            members = {m.name: tar.extractfile(m).read()
                       if m.isfile() else None
                       for m in tar.getmembers()}
        members["cfg.py"] = b"root.x = 666  # tampered\n"
        bad = str(src / "bad.vpkg")
        with tarfile.open(bad, "w:gz") as tar:
            for name, data in members.items():
                info = tarfile.TarInfo(name)
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))
        with pytest.raises(ValueError, match="checksum mismatch"):
            ForgePackage.install(bad, str(tmp_path / "store2"))

    def test_install_rejects_traversal(self, tmp_path):
        evil = str(tmp_path / "evil.vpkg")
        manifest = json.dumps({"format_version": 1, "name": "e",
                               "version": "1", "sha256": {}}).encode()
        with tarfile.open(evil, "w:gz") as tar:
            info = tarfile.TarInfo("manifest.json")
            info.size = len(manifest)
            tar.addfile(info, io.BytesIO(manifest))
            info = tarfile.TarInfo("../../escape.txt")
            info.size = 3
            tar.addfile(info, io.BytesIO(b"pwn"))
        with pytest.raises(ValueError, match="unsafe member"):
            ForgePackage.install(evil, str(tmp_path / "store3"))

    def test_list_store(self, pkg, tmp_path):
        out, _ = pkg
        store = tmp_path / "thestore"
        store.mkdir()
        import shutil
        shutil.copy(out, store / "model.vpkg")
        (store / "junk.vpkg").write_bytes(b"not a tar")
        items = ForgePackage.list_store(str(store))
        assert len(items) == 1
        assert items[0]["name"] == "mnist-demo"

    def test_rejects_future_format(self, tmp_path):
        fut = str(tmp_path / "fut.vpkg")
        manifest = json.dumps({"format_version": 99, "name": "f",
                               "version": "1", "sha256": {}}).encode()
        with tarfile.open(fut, "w:gz") as tar:
            info = tarfile.TarInfo("manifest.json")
            info.size = len(manifest)
            tar.addfile(info, io.BytesIO(manifest))
        with pytest.raises(ValueError, match="newer"):
            ForgePackage.read_manifest(fut)
