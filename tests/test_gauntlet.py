"""Gauntlet unit tests: traffic determinism, the scale controller's
hysteresis/cooldown/bounds, and the degradation ladder's strict
ordering — all pure (no subprocesses, scripted clocks) — plus ONE
real-fleet pin: scale-down under live load drains the victim, re-homes
its exclusively-placed tail model BEFORE the SIGTERM, and loses zero
requests (never a 404)."""

import filecmp
import os
import textwrap
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from veles_tpu.serve.autoscale import (ACT_DOWN, ACT_RELAX,
                                       ACT_SATURATED, ACT_UP, RUNGS,
                                       DegradationLadder,
                                       ScaleController)
from veles_tpu.serve.traffic import (Arrival, OpenLoopDriver,
                                     TrafficSpec, generate,
                                     read_trace, write_trace)


def _spec(**kw):
    base = dict(seed=7, duration_s=30.0, peak_rps=40.0, swing=10.0,
                burst_every_s=8.0, burst_len_s=2.0, burst_mult=2.0,
                models=["hot", "warm", "tail"], zipf_s=1.1)
    base.update(kw)
    return TrafficSpec(**base)


class TestTrafficGenerator:
    def test_deterministic_bitwise_trace(self, tmp_path):
        """The acceptance pin: two generations of the same seeded
        spec write BYTE-identical trace files."""
        p1, p2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
        write_trace(p1, _spec(), generate(_spec()))
        write_trace(p2, _spec(), generate(_spec()))
        assert os.path.getsize(p1) > 0
        assert filecmp.cmp(p1, p2, shallow=False), \
            "same spec+seed must replay bit-identically"

    def test_seed_changes_schedule(self):
        a = generate(_spec(seed=1))
        b = generate(_spec(seed=2))
        assert [x.t for x in a] != [x.t for x in b]

    def test_trace_roundtrip(self, tmp_path):
        spec, arrivals = _spec(), generate(_spec())
        path = str(tmp_path / "day.jsonl")
        write_trace(path, spec, arrivals)
        spec2, back = read_trace(path)
        assert spec2.to_dict() == spec.to_dict()
        assert len(back) == len(arrivals)
        assert all(a.t == b.t and a.model == b.model
                   and a.row_seed == b.row_seed
                   for a, b in zip(arrivals, back))

    def test_torn_trace_fails_loudly(self, tmp_path):
        path = str(tmp_path / "torn.jsonl")
        write_trace(path, _spec(), generate(_spec()))
        lines = open(path).readlines()
        open(path, "w").writelines(lines[:len(lines) // 2])
        with pytest.raises(ValueError, match="torn"):
            read_trace(path)

    def test_diurnal_swing(self):
        """Arrivals concentrate at mid-day: the peak-half of the day
        must carry several times the trough-half's traffic (the
        schedule really sweeps a >=10x rate swing)."""
        spec = _spec(duration_s=60.0, burst_mult=1.0,
                     peak_rps=50.0, swing=10.0)
        arrivals = generate(spec)
        # quarters 2+3 straddle the raised-cosine peak at t=30
        mid = sum(1 for a in arrivals if 15.0 <= a.t < 45.0)
        edge = len(arrivals) - mid
        assert mid > 2.5 * max(1, edge)
        # sanity: total volume is in the right ballpark (mean rate
        # integrates to ~0.55 * peak over one full period)
        assert 0.25 * 50 * 60 < len(arrivals) < 0.9 * 50 * 60

    def test_zipf_skew(self):
        spec = _spec(duration_s=60.0, peak_rps=60.0, zipf_s=1.5)
        counts = {m: 0 for m in spec.models}
        for a in generate(spec):
            counts[a.model] += 1
        assert counts["hot"] > counts["warm"] > counts["tail"] > 0

    def test_burst_windows_raise_rate(self):
        spec = _spec(duration_s=40.0, peak_rps=40.0, swing=1.0,
                     burst_every_s=6.0, burst_len_s=3.0,
                     burst_mult=3.0)
        arrivals = generate(spec)
        burst = [a for a in arrivals if a.burst]
        plain = [a for a in arrivals if not a.burst]
        assert burst and plain
        # the window layout is reproducible: generate() draws it
        # FIRST from the seeded rng, before any thinning draws
        from veles_tpu.serve.traffic import _burst_windows
        wins = _burst_windows(spec, np.random.default_rng(spec.seed))
        span = sum(b - a for a, b in wins)
        assert 0 < span < spec.duration_s
        # swing=1 flattens the diurnal curve, so rate density inside
        # burst windows must be ~burst_mult x the outside density
        dens_b = len(burst) / span
        dens_p = len(plain) / (spec.duration_s - span)
        assert dens_b > 1.5 * dens_p


class TestOpenLoopDriver:
    def _arrivals(self, n=50, gap=0.002):
        return [Arrival(i, i * gap, "m", 123 + i, False)
                for i in range(n)]

    def test_every_arrival_gets_one_outcome(self):
        drv = OpenLoopDriver(lambda a: {"probs": [0.5]}, workers=8)
        res = drv.run(self._arrivals())
        assert [r["i"] for r in res] == list(range(50))
        assert all(r["status"] == "ok" for r in res)

    def test_outcome_classification(self):
        def fn(a):
            if a.i % 3 == 0:
                return {"error": "overloaded", "overloaded": True}
            if a.i % 3 == 1:
                raise RuntimeError("boom")
            return {"probs": [0.1], "pred": [0]}
        res = OpenLoopDriver(fn, workers=4).run(self._arrivals(30))
        by = {r["i"]: r["status"] for r in res}
        assert by[0] == "shed" and by[1] == "error" and by[2] == "ok"

    def test_latency_counts_from_scheduled_time(self):
        """Open-loop honesty: a slow answer's latency includes the
        schedule-relative delay, never less than the server time."""
        import time as _t

        def slow(a):
            _t.sleep(0.05)
            return {"probs": [1.0]}
        res = OpenLoopDriver(slow, workers=4).run(
            self._arrivals(n=4, gap=0.001))
        assert all(r["latency_s"] >= 0.05 for r in res)


class TestScaleController:
    def _ctl(self, **kw):
        base = dict(min_replicas=1, max_replicas=4, up_ms=200.0,
                    down_ms=25.0, up_sustain_s=1.0,
                    down_sustain_s=2.0, cooldown_s=5.0)
        base.update(kw)
        return ScaleController(**base)

    def test_sustained_pressure_scales_up(self):
        c = self._ctl()
        assert c.observe(500.0, 2, 0.0) is None     # window opens
        assert c.observe(500.0, 2, 0.5) is None     # not sustained yet
        assert c.observe(500.0, 2, 1.0) == ACT_UP   # sustained

    def test_blip_does_not_scale(self):
        """Hysteresis: pressure that dips back into the band resets
        the sustain window — one burst never spawns."""
        c = self._ctl()
        assert c.observe(500.0, 2, 0.0) is None
        assert c.observe(100.0, 2, 0.5) is None     # back in band
        assert c.observe(500.0, 2, 0.9) is None     # window restarts
        assert c.observe(500.0, 2, 1.8) is None
        assert c.observe(500.0, 2, 1.95) == ACT_UP

    def test_cooldown_spaces_actions(self):
        c = self._ctl()
        assert c.observe(500.0, 2, 1.0) is None
        assert c.observe(500.0, 2, 2.0) == ACT_UP   # t=2: action
        assert c.observe(500.0, 3, 3.5) is None     # sustained again
        assert c.observe(500.0, 3, 6.9) is None     # but in cooldown
        assert c.observe(500.0, 3, 7.1) == ACT_UP   # cooldown passed

    def test_max_clamp_saturates(self):
        c = self._ctl(max_replicas=2)
        c.observe(500.0, 2, 0.0)
        assert c.observe(500.0, 2, 1.0) == ACT_SATURATED

    def test_sustained_idle_scales_down(self):
        c = self._ctl()
        assert c.observe(5.0, 3, 0.0) is None
        assert c.observe(5.0, 3, 1.0) is None
        assert c.observe(5.0, 3, 2.0) == ACT_DOWN

    def test_min_clamp_relaxes(self):
        c = self._ctl(min_replicas=2)
        c.observe(5.0, 2, 0.0)
        assert c.observe(5.0, 2, 2.0) == ACT_RELAX

    def test_band_resets_both_windows(self):
        c = self._ctl()
        c.observe(5.0, 3, 0.0)           # idle window opens
        c.observe(100.0, 3, 1.0)         # in band: resets
        assert c.observe(5.0, 3, 2.5) is None  # idle restarts at 2.5
        assert c.observe(5.0, 3, 4.6) == ACT_DOWN

    def test_up_and_down_share_the_cooldown(self):
        c = self._ctl()
        c.observe(500.0, 2, 0.0)
        assert c.observe(500.0, 2, 1.0) == ACT_UP
        c.observe(5.0, 3, 1.5)
        # idle sustained by t=3.5 but cooldown runs to t=6
        assert c.observe(5.0, 3, 3.5) is None
        assert c.observe(5.0, 3, 6.5) == ACT_DOWN

    def test_validates_band(self):
        with pytest.raises(ValueError):
            self._ctl(down_ms=300.0)     # inverted band
        with pytest.raises(ValueError):
            self._ctl(min_replicas=0)
        with pytest.raises(ValueError):
            ScaleController(min_replicas=3, max_replicas=2)

    def test_from_knobs(self):
        env = {"VELES_FLEET_SCALE_MIN": "2",
               "VELES_FLEET_SCALE_MAX": "8",
               "VELES_FLEET_SCALE_UP_MS": "150",
               "VELES_FLEET_SCALE_COOLDOWN": "9"}
        c = ScaleController.from_knobs(environ=env)
        assert (c.min_replicas, c.max_replicas) == (2, 8)
        assert c.up_ms == 150.0 and c.cooldown_s == 9.0


class TestDegradationLadder:
    def test_strict_engage_release_order(self):
        lad = DegradationLadder()
        engaged = [lad.engage() for _ in range(3)]
        assert engaged == list(RUNGS)
        assert lad.engage() is None          # exhausted
        released = [lad.release() for _ in range(3)]
        assert released == list(reversed(RUNGS))
        assert lad.release() is None         # fully recovered
        assert lad.depth == 0

    def test_partial_recovery_re_engages_in_order(self):
        lad = DegradationLadder()
        lad.engage()                          # learner
        lad.engage()                          # hedge
        assert lad.release() == "hedge"       # LIFO
        assert lad.engage() == "hedge"        # pressure returns
        assert lad.engage() == "shed_tail"
        assert lad.depth == 3


class TestControllerLadderComposition:
    """The autoscaler's decision table, driven through a scripted
    signal sequence — the full production-day state machine without a
    single subprocess."""

    def test_full_day_script(self):
        c = ScaleController(min_replicas=1, max_replicas=2,
                            up_ms=200.0, down_ms=25.0,
                            up_sustain_s=1.0, down_sustain_s=1.0,
                            cooldown_s=2.0)
        lad = DegradationLadder()
        n = 1
        log = []
        # (t, pressure) — morning ramp, saturated noon, evening fall
        script = [(0.0, 500.0), (1.0, 500.0),        # -> up (n=2)
                  (3.0, 500.0), (4.0, 500.0),        # -> saturated
                  (6.0, 500.0), (7.0, 500.0),        # -> saturated
                  (9.0, 10.0), (10.0, 10.0),         # -> down...
                  (12.0, 10.0), (13.0, 10.0),
                  (15.0, 10.0), (16.0, 10.0),
                  (18.0, 10.0), (19.0, 10.0)]
        for t, p in script:
            act = c.observe(p, n, t)
            if act == ACT_UP:
                n += 1
                log.append("up")
            elif act == ACT_SATURATED:
                r = lad.engage()
                if r:
                    log.append(f"engage:{r}")
            elif act == ACT_DOWN:
                if lad.depth:
                    log.append(f"release:{lad.release()}")
                else:
                    n -= 1
                    log.append("down")
            elif act == ACT_RELAX:
                if lad.depth:
                    log.append(f"release:{lad.release()}")
        assert log == ["up", "engage:learner", "engage:hedge",
                       "release:hedge", "release:learner", "down"]
        assert n == 1 and lad.depth == 0


# -- the real-fleet pin (satellite: retire ordering) -------------------

WF_TEXT = textwrap.dedent("""
    from veles_tpu import prng
    from veles_tpu.datasets import synthetic_classification
    from veles_tpu.loader import ArrayLoader
    from veles_tpu.ops.standard_workflow import StandardWorkflow

    def create_workflow(launcher):
        prng.seed_all(4242)
        train, valid, _ = synthetic_classification(
            64, 16, (6, 6, 1), n_classes=3, seed=5)
        return StandardWorkflow(
            loader_factory=lambda w: ArrayLoader(
                w, train=train, valid=valid, minibatch_size=16,
                name="loader"),
            layers=[
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 12},
                 "<-": {"learning_rate": 0.1}},
                {"type": "softmax", "->": {"output_sample_shape": 3},
                 "<-": {"learning_rate": 0.1}},
            ],
            decision_config={"max_epochs": 2}, name="gauntlet_wf")
""")


@pytest.fixture(scope="module")
def pkg(tmp_path_factory):
    """One small ensemble package (the test_fleet recipe)."""
    from veles_tpu import prng
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.ensemble.packaging import pack_ensemble
    from veles_tpu.launcher import load_workflow_module

    d = str(tmp_path_factory.mktemp("gauntlet_pkg"))
    wf_path = os.path.join(d, "wf_gauntlet.py")
    with open(wf_path, "w") as f:
        f.write(WF_TEXT)
    mod = load_workflow_module(wf_path)

    class FL:
        workflow = None

    prng.seed_all(77)
    w = mod.create_workflow(FL())
    w.initialize(device=NumpyDevice())
    base = {fw.name: {k: np.asarray(v) for k, v in
                      fw.gather_params().items()}
            for fw in w.forwards}
    rng = np.random.default_rng(77)
    members = []
    for _ in range(2):
        params = {fn: {pn: (a + 0.05 * rng.standard_normal(a.shape)
                            .astype(np.float32))
                       for pn, a in p.items()}
                  for fn, p in base.items()}
        members.append({"params": params, "valid_error": 0.0,
                        "seed": 77,
                        "forward_names": [fw.name
                                          for fw in w.forwards],
                        "values": None})
    path = os.path.join(d, "gauntlet.vpkg")
    pack_ensemble(path, "gauntlet", members, wf_path)
    return path


class TestScaleDownUnderLoad:
    """The retire-ordering pin: ``retire_replica`` must (1) mark the
    victim so routing stops picking it, (2) RE-HOME its exclusively
    placed tail model onto a survivor, (3) drain its in-flight queue —
    all BEFORE the SIGTERM — so a scale-down in the middle of live
    traffic loses zero requests and never 404s a tail model.  The
    freed install dir must land in the warm pool and be reused by the
    next scale-up."""

    @pytest.fixture(scope="class")
    def router(self, pkg, tmp_path_factory):
        from veles_tpu.serve.fleet import PlacementPolicy
        from veles_tpu.serve.router import FleetRouter
        mdir = str(tmp_path_factory.mktemp("gauntlet_metrics"))
        # hot={"core"}: core replicates everywhere, the two tail
        # models partition one-per-replica — so whichever replica
        # retires holds one of them EXCLUSIVELY
        r = FleetRouter(
            {"core": pkg, "tail_a": pkg, "tail_b": pkg},
            n_replicas=2, backend="cpu", max_batch=16, max_wait_ms=5,
            placement=PlacementPolicy(budget_bytes=1 << 30,
                                      hot={"core"}),
            metrics_dir=mdir, cwd=REPO)
        yield r
        r.close(kill=True)

    def test_placement_splits_the_tail(self, router):
        assert sorted(router.placement["core"]) == [0, 1]
        tails = {m: router.placement[m] for m in ("tail_a", "tail_b")}
        assert all(len(p) == 1 for p in tails.values()), tails
        assert {p[0] for p in tails.values()} == {0, 1}, tails

    def test_retire_under_load_loses_nothing(self, router):
        from veles_tpu import events, telemetry
        x = np.ones((1, 6, 6, 1), np.float32)
        models = ["core", "tail_a", "tail_b"]
        # warm every replica directly (compile the one dispatch shape
        # + LRU-load every model) so the loaded window is steady
        for r in router.replicas:
            for m in models:
                assert "probs" in r.client.request(m, x, timeout=120)

        errors = []
        ok = [0]
        stop = threading.Event()

        def loop(i):
            while not stop.is_set():
                m = models[i % len(models)]
                res = router.request(m, x, timeout=60)
                if "probs" in res:
                    ok[0] += 1
                elif not res.get("overloaded"):
                    errors.append((m, res))
                time.sleep(0.002)

        threads = [threading.Thread(target=loop, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        try:
            time.sleep(1.0)
            # the youngest replica retires while traffic flows; its
            # exclusive tail model must be re-homed BEFORE the SIGTERM
            victim_idx = router.retire_replica(cause="test",
                                               drain_timeout=60.0)
            assert victim_idx == 1
            time.sleep(1.5)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)

        assert not errors, f"lost/404'd {len(errors)}: {errors[:3]}"
        assert ok[0] > 0
        # placement no longer references the corpse — every model
        # (incl. the victim's exclusive tail) routes to the survivor
        for m, placed in router.placement.items():
            assert victim_idx not in placed, (m, placed)
        for m in models:
            assert "probs" in router.request(m, x, timeout=60)
        live = [r for r in router.replicas if not r.retiring]
        assert len(live) == 1 and live[0].idx == 0
        retired = telemetry.recent_events(
            events.EV_FLEET_REPLICA_RETIRED)
        assert retired and retired[-1]["replica"] == 1
        assert retired[-1]["drained"] is True
        # the victim's install dir joined the warm pool
        assert router._warm_dirs

    def test_scale_up_reuses_the_warm_dir(self, router):
        from veles_tpu import events, telemetry
        warm = list(router._warm_dirs)
        newbie = router.add_replica(cause="test")
        # indices are never reused: the corpse stays 1, the new
        # member mints 2 and inherits the retired install dir
        assert newbie is not None and newbie.idx == 2
        assert newbie.install_dir == warm[-1]
        assert not router._warm_dirs
        spawned = telemetry.recent_events(events.EV_FLEET_SCALE_UP)
        assert spawned and spawned[-1]["replica"] == 2
        assert spawned[-1]["warm_dir"] is True
        x = np.ones((1, 6, 6, 1), np.float32)
        for m in ("core", "tail_a", "tail_b"):
            assert "probs" in router.request(m, x, timeout=120)


# -- the production day itself (slow soak) -----------------------------

@pytest.mark.slow
def test_gauntlet_production_day_slow():
    """The full accountable soak: a long diurnal day with bursts, the
    gray fault armed, a coordinated mid-burst preemption, an elastic
    fleet riding the curve — and the post-run books must balance
    (zero lost/corrupt answers, every scale/degrade/eject event
    traced to a recorded cause).  ~10 min wall."""
    import json
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               GAUNTLET_DURATION=os.environ.get(
                   "GAUNTLET_DURATION", "600"),
               GAUNTLET_PREEMPTIONS="2")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "gauntlet.py"),
         "--json"],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=1800)
    assert p.returncode == 0, p.stderr[-4000:]
    rec = json.loads(p.stdout.strip().splitlines()[-1])
    assert rec["gauntlet_ok"] is True
    assert rec["lost"] == 0 and rec["corrupt"] == 0
    assert rec["scale_ups"] >= 2 and rec["scale_downs"] >= 2
    assert rec["accountability"]["unexplained"] == []
