"""CLI front end: python -m veles_tpu workflow.py config.py root.k=v
(reference: veles/__main__.py contract)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(args, cwd=REPO, timeout=300):
    env = dict(os.environ)
    return subprocess.run(
        [sys.executable, "-m", "veles_tpu"] + args,
        capture_output=True, text=True, cwd=cwd, env=env,
        timeout=timeout)


@pytest.fixture
def workflow_file(tmp_path):
    p = tmp_path / "wf.py"
    p.write_text(textwrap.dedent("""
        import json
        from veles_tpu.config import root
        from veles_tpu.models import mnist

        def run(launcher):
            launcher.create_workflow(
                mnist.create_workflow,
                loader={"minibatch_size": 25,
                        "n_train": int(root.test.n_train),
                        "n_valid": 50},
                decision={"max_epochs": 2})
            launcher.initialize()
            launcher.run()
            d = launcher.workflow.decision
            tr = [h["loss"] for h in d.history if h["class"] == "train"]
            print("RESULT " + json.dumps({
                "train_losses": tr,
                "epochs": launcher.workflow.loader.epoch_number}))
    """))
    return str(p)


@pytest.fixture
def config_file(tmp_path):
    p = tmp_path / "cfg.py"
    p.write_text("root.test.n_train = 100\n")
    return str(p)


class TestCLI:
    def test_workflow_with_config_and_override(self, workflow_file,
                                               config_file):
        r = run_cli([workflow_file, config_file, "root.test.n_train=150",
                     "-b", "cpu"])
        assert r.returncode == 0, r.stderr[-2000:]
        line = [ln for ln in r.stdout.splitlines()
                if ln.startswith("RESULT ")][0]
        data = json.loads(line[len("RESULT "):])
        assert data["epochs"] == 2
        assert data["train_losses"][-1] < data["train_losses"][0]

    def test_numpy_backend_flag(self, workflow_file, config_file):
        r = run_cli([workflow_file, config_file, "-b", "numpy"])
        assert r.returncode == 0, r.stderr[-2000:]

    def test_log_events_jsonl_sink(self, workflow_file, config_file,
                                   tmp_path):
        """--log-events FILE appends every run event as one JSON line
        (the reference's MongoDB event-sink parity, file-shaped)."""
        events = tmp_path / "events.jsonl"
        r = run_cli([workflow_file, config_file, "-b", "numpy",
                     "--log-events", str(events)])
        assert r.returncode == 0, r.stderr[-2000:]
        lines = [json.loads(ln) for ln in
                 events.read_text().splitlines()]
        assert lines, "no events recorded"
        assert all({"ts", "level", "unit", "message"} <= set(e)
                   for e in lines)
        # the run's lifecycle is in the durable record
        assert any("epoch" in e["message"].lower() or
                   "workflow" in e["unit"].lower() for e in lines)

    def test_dump_config(self, workflow_file, config_file):
        r = run_cli([workflow_file, config_file, "--dump-config"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "n_train = 100" in r.stdout

    def test_bad_workflow_file(self, tmp_path):
        p = tmp_path / "empty.py"
        p.write_text("x = 1\n")
        r = run_cli([str(p)])
        assert r.returncode == 2
        assert "defines neither" in r.stderr


class TestEnsembleCli:
    def test_ensemble_train_then_test(self, tmp_path):
        """--ensemble-train N persists members; --ensemble-test
        aggregates them (reference CLI ensemble surface)."""
        ens = str(tmp_path / "ens.npz")
        r = run_cli(["veles_tpu/models/mnist.py", "-b", "cpu",
                     "--ensemble-train", "2", "--ensemble-test",
                     "--ensemble-file", ens,
                     "root.mnist.loader.minibatch_size=25",
                     "root.mnist.loader.n_train=500",
                     "root.mnist.loader.n_valid=100",
                     "root.mnist.decision.max_epochs=5"])
        assert r.returncode == 0, r.stderr[-2000:]
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["members"] == 2
        assert len(out["member_valid_errors_pct"]) == 2
        # mean-probability aggregation has no worst-member guarantee
        # in general — assert the number is a sane percentage and the
        # members actually trained (not chance-level 90% on 10 classes)
        assert 0.0 <= out["ensemble_valid_error_pct"] <= 100.0
        assert max(out["member_valid_errors_pct"]) < 60.0
        assert os.path.exists(ens)

    def test_ensemble_needs_create_workflow(self, tmp_path):
        p = tmp_path / "wf.py"
        p.write_text("def run(launcher):\n    pass\n")
        r = run_cli([str(p), "--ensemble-train", "2", "-b", "numpy"])
        assert r.returncode == 2
        assert "create_workflow" in r.stderr

    def test_ensemble_edge_cases(self, tmp_path):
        p = tmp_path / "wf.py"
        p.write_text("def create_workflow(launcher):\n    pass\n")
        # N < 1 rejected cleanly
        r = run_cli([str(p), "--ensemble-train", "0", "-b", "numpy"])
        assert r.returncode == 2 and "N >= 1" in r.stderr
        # test-only with no member file: clean message, not traceback
        r = run_cli([str(p), "--ensemble-test", "-b", "numpy",
                     "--ensemble-file", str(tmp_path / "none.npz")])
        assert r.returncode == 2
        assert "does not exist" in r.stderr
        assert "Traceback" not in r.stderr


class TestServeModelsCli:
    """The --serve-models entry on the smoke-tested CLI surface (the
    full subprocess round trip lives in tests/test_serve.py, which
    drives this same entry through serve.client.HiveClient)."""

    def test_bad_model_spec_is_usage_error(self):
        r = run_cli(["--serve-models", "not-a-pair"])
        assert r.returncode == 2
        assert "NAME=PACKAGE" in r.stderr
        assert "Traceback" not in r.stderr

    def test_missing_package_is_usage_error(self, tmp_path):
        r = run_cli(["--serve-models",
                     f"m={tmp_path}/nope.vpkg"])
        assert r.returncode == 2
        assert "no such package" in r.stderr


class TestServeFleetCli:
    """The --serve-fleet entry on the smoke-tested CLI surface (the
    full 2-replica protocol round trip lives in tests/test_fleet.py
    TestFleetCliProtocol)."""

    def test_bad_model_spec_is_usage_error(self, tmp_path):
        r = run_cli(["--serve-fleet", "2", "not-a-pair"])
        assert r.returncode == 2
        assert "NAME=PACKAGE" in r.stderr
        assert "Traceback" not in r.stderr

    def test_missing_package_is_usage_error(self, tmp_path):
        r = run_cli(["--serve-fleet", "2",
                     f"m={tmp_path}/nope.vpkg"])
        assert r.returncode == 2
        assert "no such package" in r.stderr

    def test_zero_replicas_is_usage_error(self, tmp_path):
        pkg = tmp_path / "m.vpkg"
        pkg.write_bytes(b"x")
        r = run_cli(["--serve-fleet", "0", f"m={pkg}"])
        assert r.returncode == 2
        assert ">= 1" in r.stderr

    def test_bad_canary_spec_is_usage_error(self, tmp_path):
        pkg = tmp_path / "m.vpkg"
        pkg.write_bytes(b"x")
        # a canary naming an unregistered model must die at parse
        # time, before any replica spawns
        r = run_cli(["--serve-fleet", "1", f"m={pkg}",
                     "--canary", "ghost=m:0.5"])
        assert r.returncode == 2
        assert "ghost" in r.stderr
        assert "Traceback" not in r.stderr


class TestBenchFleetCli:
    """bench.py --fleet-only rides the smoke-tested CLI surface like
    --serve-only: the skip knob must short-circuit the phase cleanly
    (the measured run lands in BENCH_r07.json)."""

    def test_fleet_only_skip_short_circuits(self):
        env = dict(os.environ)
        env["BENCH_SKIP_FLEET"] = "1"
        r = subprocess.run(
            [sys.executable, "bench.py", "--fleet-only"],
            capture_output=True, text=True, cwd=REPO, env=env,
            timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        assert json.loads(r.stdout.strip().splitlines()[-1]) is None


class TestBenchZooCli:
    """bench.py --zoo-only rides the same smoke-tested CLI surface as
    the other fast paths: the skip knob must short-circuit the phase
    cleanly (the measured run lands in BENCH_r14.json)."""

    def test_zoo_only_skip_short_circuits(self):
        env = dict(os.environ)
        env["BENCH_SKIP_ZOO"] = "1"
        r = subprocess.run(
            [sys.executable, "bench.py", "--zoo-only"],
            capture_output=True, text=True, cwd=REPO, env=env,
            timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        assert json.loads(r.stdout.strip().splitlines()[-1]) is None
