"""CLI front end: python -m veles_tpu workflow.py config.py root.k=v
(reference: veles/__main__.py contract)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(args, cwd=REPO, timeout=300):
    env = dict(os.environ)
    return subprocess.run(
        [sys.executable, "-m", "veles_tpu"] + args,
        capture_output=True, text=True, cwd=cwd, env=env,
        timeout=timeout)


@pytest.fixture
def workflow_file(tmp_path):
    p = tmp_path / "wf.py"
    p.write_text(textwrap.dedent("""
        import json
        from veles_tpu.config import root
        from veles_tpu.models import mnist

        def run(launcher):
            launcher.create_workflow(
                mnist.create_workflow,
                loader={"minibatch_size": 25,
                        "n_train": int(root.test.n_train),
                        "n_valid": 50},
                decision={"max_epochs": 2})
            launcher.initialize()
            launcher.run()
            d = launcher.workflow.decision
            tr = [h["loss"] for h in d.history if h["class"] == "train"]
            print("RESULT " + json.dumps({
                "train_losses": tr,
                "epochs": launcher.workflow.loader.epoch_number}))
    """))
    return str(p)


@pytest.fixture
def config_file(tmp_path):
    p = tmp_path / "cfg.py"
    p.write_text("root.test.n_train = 100\n")
    return str(p)


class TestCLI:
    def test_workflow_with_config_and_override(self, workflow_file,
                                               config_file):
        r = run_cli([workflow_file, config_file, "root.test.n_train=150",
                     "-b", "cpu"])
        assert r.returncode == 0, r.stderr[-2000:]
        line = [ln for ln in r.stdout.splitlines()
                if ln.startswith("RESULT ")][0]
        data = json.loads(line[len("RESULT "):])
        assert data["epochs"] == 2
        assert data["train_losses"][-1] < data["train_losses"][0]

    def test_numpy_backend_flag(self, workflow_file, config_file):
        r = run_cli([workflow_file, config_file, "-b", "numpy"])
        assert r.returncode == 0, r.stderr[-2000:]

    def test_log_events_jsonl_sink(self, workflow_file, config_file,
                                   tmp_path):
        """--log-events FILE appends every run event as one JSON line
        (the reference's MongoDB event-sink parity, file-shaped)."""
        events = tmp_path / "events.jsonl"
        r = run_cli([workflow_file, config_file, "-b", "numpy",
                     "--log-events", str(events)])
        assert r.returncode == 0, r.stderr[-2000:]
        lines = [json.loads(ln) for ln in
                 events.read_text().splitlines()]
        assert lines, "no events recorded"
        assert all({"ts", "level", "unit", "message"} <= set(e)
                   for e in lines)
        # the run's lifecycle is in the durable record
        assert any("epoch" in e["message"].lower() or
                   "workflow" in e["unit"].lower() for e in lines)

    def test_dump_config(self, workflow_file, config_file):
        r = run_cli([workflow_file, config_file, "--dump-config"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "n_train = 100" in r.stdout

    def test_bad_workflow_file(self, tmp_path):
        p = tmp_path / "empty.py"
        p.write_text("x = 1\n")
        r = run_cli([str(p)])
        assert r.returncode == 2
        assert "defines neither" in r.stderr
