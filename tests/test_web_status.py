"""Web status dashboard (SURVEY.md §3.1 Web status): HTTP API,
dashboard rendering, per-epoch reporting from a live workflow."""

import json
import urllib.request

import pytest

from veles_tpu import prng
from veles_tpu.backends import NumpyDevice
from veles_tpu.datasets import synthetic_classification
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow
from veles_tpu.web_status import WebStatusServer


@pytest.fixture
def server():
    s = WebStatusServer(port=0, host="127.0.0.1")
    s.start_background()
    yield s
    s.shutdown()


def url(server, path):
    return f"http://127.0.0.1:{server.port}{path}"


def get_json(server, path):
    with urllib.request.urlopen(url(server, path), timeout=5) as r:
        return json.loads(r.read())


class TestApi:
    def test_empty_status(self, server):
        assert get_json(server, "/api/status") == {}

    def test_update_roundtrip(self, server):
        body = json.dumps({"id": "r1", "name": "w", "epoch": 3,
                           "train_error_pct": 12.5}).encode()
        req = urllib.request.Request(
            url(server, "/api/update"), data=body,
            headers={"Content-Type": "application/json"})
        assert json.loads(urllib.request.urlopen(
            req, timeout=5).read()) == {"ok": True}
        runs = get_json(server, "/api/status")
        assert runs["r1"]["epoch"] == 3

    def test_bad_update_is_400(self, server):
        req = urllib.request.Request(
            url(server, "/api/update"), data=b"{not json",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 400

    def test_nondict_update_is_400(self, server):
        req = urllib.request.Request(
            url(server, "/api/update"), data=b"[1, 2]",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 400

    def test_dashboard_escapes_html(self, server):
        """Names come from unauthenticated POSTs — they must never
        reach the page as markup."""
        evil = "<script>alert(1)</script>"
        body = json.dumps({"id": "r9", "name": evil}).encode()
        urllib.request.urlopen(urllib.request.Request(
            url(server, "/api/update"), data=body), timeout=5)
        with urllib.request.urlopen(url(server, "/"), timeout=5) as r:
            html_page = r.read().decode()
        assert evil not in html_page
        assert "&lt;script&gt;" in html_page

    def test_dashboard_html(self, server):
        body = json.dumps({"id": "r2", "name": "MyNet",
                           "epoch": 7}).encode()
        urllib.request.urlopen(urllib.request.Request(
            url(server, "/api/update"), data=body), timeout=5)
        with urllib.request.urlopen(url(server, "/"), timeout=5) as r:
            html = r.read().decode()
        assert "MyNet" in html and "<table>" in html


class TestWorkflowReporting:
    def test_workflow_posts_per_epoch(self, server):
        prng.seed_all(777)
        train, valid, _ = synthetic_classification(
            200, 80, (8, 8, 1), n_classes=4, seed=42)
        w = StandardWorkflow(
            loader_factory=lambda wf: ArrayLoader(
                wf, train=train, valid=valid, minibatch_size=40,
                name="loader"),
            layers=[{"type": "softmax",
                     "->": {"output_sample_shape": 4},
                     "<-": {"learning_rate": 0.1}}],
            decision_config={"max_epochs": 3}, name="status_wf")
        w.link_status_reporter(url(server, ""), mode="standalone")
        w.initialize(device=NumpyDevice())
        w.run()
        runs = get_json(server, "/api/status")
        assert len(runs) == 1
        (row,) = runs.values()
        assert row["name"] == "status_wf"
        assert row["epoch"] == 3
        assert row["complete"] is True
        assert row["valid_error_pct"] < 100.0


class TestMetricsDashboard:
    """Sightline mode: --metrics-dir renders LIVE telemetry through
    the obs_report internals instead of the legacy push feed."""

    @pytest.fixture
    def metrics_dir(self, tmp_path):
        from veles_tpu import events, telemetry
        telemetry.configure(str(tmp_path))
        telemetry.counter(events.CTR_SERVE_REQUESTS).inc(7)
        telemetry.gauge(events.GAUGE_SERVE_MODELS_RESIDENT).set(2)
        telemetry.histogram(events.HIST_SERVE_REQUEST_SECONDS) \
            .record(0.004)
        telemetry.event(events.EV_SERVE_READY, pid=123,
                        platform="cpu")
        telemetry.flush()
        yield str(tmp_path)
        telemetry.configure(None)

    @pytest.fixture
    def mserver(self, metrics_dir):
        s = WebStatusServer(port=0, host="127.0.0.1",
                            metrics_dir=metrics_dir)
        s.start_background()
        yield s
        s.shutdown()

    def test_dashboard_renders_live_telemetry(self, mserver):
        with urllib.request.urlopen(url(mserver, "/"), timeout=5) as r:
            page = r.read().decode()
        assert "serve.requests" in page
        assert "serve.request_seconds" in page
        assert "serve.ready" in page          # journal timeline
        assert "live telemetry" in page

    def test_api_metrics_returns_merged_snapshot(self, mserver):
        snap = get_json(mserver, "/api/metrics")
        assert snap["counters"]["serve.requests"] == 7
        assert snap["gauges"]["serve.models_resident"] == 2
        assert snap["histograms"]["serve.request_seconds"]["count"] \
            == 1
        assert snap["snapshots"] >= 1

    def test_legacy_push_feed_still_reachable(self, mserver):
        # /api/status and /api/update keep working in Sightline mode
        body = json.dumps({"id": "r1", "name": "w",
                           "epoch": 1}).encode()
        urllib.request.urlopen(urllib.request.Request(
            url(mserver, "/api/update"), data=body), timeout=5)
        assert get_json(mserver, "/api/status")["r1"]["epoch"] == 1
