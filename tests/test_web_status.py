"""Web status dashboard (SURVEY.md §3.1 Web status): HTTP API,
dashboard rendering, per-epoch reporting from a live workflow."""

import json
import urllib.request

import pytest

from veles_tpu import prng
from veles_tpu.backends import NumpyDevice
from veles_tpu.datasets import synthetic_classification
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow
from veles_tpu.web_status import WebStatusServer


@pytest.fixture
def server():
    s = WebStatusServer(port=0, host="127.0.0.1")
    s.start_background()
    yield s
    s.shutdown()


def url(server, path):
    return f"http://127.0.0.1:{server.port}{path}"


def get_json(server, path):
    with urllib.request.urlopen(url(server, path), timeout=5) as r:
        return json.loads(r.read())


class TestApi:
    def test_empty_status(self, server):
        assert get_json(server, "/api/status") == {}

    def test_update_roundtrip(self, server):
        body = json.dumps({"id": "r1", "name": "w", "epoch": 3,
                           "train_error_pct": 12.5}).encode()
        req = urllib.request.Request(
            url(server, "/api/update"), data=body,
            headers={"Content-Type": "application/json"})
        assert json.loads(urllib.request.urlopen(
            req, timeout=5).read()) == {"ok": True}
        runs = get_json(server, "/api/status")
        assert runs["r1"]["epoch"] == 3

    def test_bad_update_is_400(self, server):
        req = urllib.request.Request(
            url(server, "/api/update"), data=b"{not json",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 400

    def test_nondict_update_is_400(self, server):
        req = urllib.request.Request(
            url(server, "/api/update"), data=b"[1, 2]",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 400

    def test_dashboard_escapes_html(self, server):
        """Names come from unauthenticated POSTs — they must never
        reach the page as markup."""
        evil = "<script>alert(1)</script>"
        body = json.dumps({"id": "r9", "name": evil}).encode()
        urllib.request.urlopen(urllib.request.Request(
            url(server, "/api/update"), data=body), timeout=5)
        with urllib.request.urlopen(url(server, "/"), timeout=5) as r:
            html_page = r.read().decode()
        assert evil not in html_page
        assert "&lt;script&gt;" in html_page

    def test_dashboard_html(self, server):
        body = json.dumps({"id": "r2", "name": "MyNet",
                           "epoch": 7}).encode()
        urllib.request.urlopen(urllib.request.Request(
            url(server, "/api/update"), data=body), timeout=5)
        with urllib.request.urlopen(url(server, "/"), timeout=5) as r:
            html = r.read().decode()
        assert "MyNet" in html and "<table>" in html


class TestWorkflowReporting:
    def test_workflow_posts_per_epoch(self, server):
        prng.seed_all(777)
        train, valid, _ = synthetic_classification(
            200, 80, (8, 8, 1), n_classes=4, seed=42)
        w = StandardWorkflow(
            loader_factory=lambda wf: ArrayLoader(
                wf, train=train, valid=valid, minibatch_size=40,
                name="loader"),
            layers=[{"type": "softmax",
                     "->": {"output_sample_shape": 4},
                     "<-": {"learning_rate": 0.1}}],
            decision_config={"max_epochs": 3}, name="status_wf")
        w.link_status_reporter(url(server, ""), mode="standalone")
        w.initialize(device=NumpyDevice())
        w.run()
        runs = get_json(server, "/api/status")
        assert len(runs) == 1
        (row,) = runs.values()
        assert row["name"] == "status_wf"
        assert row["epoch"] == 3
        assert row["complete"] is True
        assert row["valid_error_pct"] < 100.0


class TestMetricsDashboard:
    """Sightline mode: --metrics-dir renders LIVE telemetry through
    the obs_report internals instead of the legacy push feed."""

    @pytest.fixture
    def metrics_dir(self, tmp_path):
        from veles_tpu import events, telemetry
        telemetry.configure(str(tmp_path))
        telemetry.counter(events.CTR_SERVE_REQUESTS).inc(7)
        telemetry.gauge(events.GAUGE_SERVE_MODELS_RESIDENT).set(2)
        telemetry.histogram(events.HIST_SERVE_REQUEST_SECONDS) \
            .record(0.004)
        telemetry.event(events.EV_SERVE_READY, pid=123,
                        platform="cpu")
        telemetry.flush()
        yield str(tmp_path)
        telemetry.configure(None)

    @pytest.fixture
    def mserver(self, metrics_dir):
        s = WebStatusServer(port=0, host="127.0.0.1",
                            metrics_dir=metrics_dir)
        s.start_background()
        yield s
        s.shutdown()

    def test_dashboard_renders_live_telemetry(self, mserver):
        with urllib.request.urlopen(url(mserver, "/"), timeout=5) as r:
            page = r.read().decode()
        assert "serve.requests" in page
        assert "serve.request_seconds" in page
        assert "serve.ready" in page          # journal timeline
        assert "live telemetry" in page

    def test_api_metrics_returns_merged_snapshot(self, mserver):
        snap = get_json(mserver, "/api/metrics")
        assert snap["counters"]["serve.requests"] == 7
        assert snap["gauges"]["serve.models_resident"] == 2
        assert snap["histograms"]["serve.request_seconds"]["count"] \
            == 1
        assert snap["snapshots"] >= 1

    def test_legacy_push_feed_still_reachable(self, mserver):
        # /api/status and /api/update keep working in Sightline mode
        body = json.dumps({"id": "r1", "name": "w",
                           "epoch": 1}).encode()
        urllib.request.urlopen(urllib.request.Request(
            url(mserver, "/api/update"), data=body), timeout=5)
        assert get_json(mserver, "/api/status")["r1"]["epoch"] == 1


class TestFleetDashboard:
    """Fleet mode (ISSUE 11 satellite): a metrics dir with replica-*
    child dirs renders per-replica rows (pid, resident models, queue
    depth, qps, p99) and the per-model canary split on the dashboard,
    in /api/metrics, and through obs_report --fleet."""

    @pytest.fixture
    def fleet_dir(self, tmp_path):
        import time as _t

        from veles_tpu.telemetry import Registry
        now = round(_t.time(), 3)
        for i, (pid, reqs) in enumerate(((111, 500), (222, 400))):
            d = tmp_path / f"replica-{i}"
            d.mkdir()
            reg = Registry()
            reg.counter("serve.requests").inc(reqs)
            reg.gauge("serve.models_resident").set(2)
            reg.gauge("serve.queue_depth").set(1)
            for _ in range(10):
                reg.histogram("serve.request_seconds").record(0.005)
            snap = reg.snapshot()
            snap["pid"], snap["ts"] = pid, now
            (d / f"metrics-{pid}.json").write_text(json.dumps(snap))
            (d / f"journal-{pid}.jsonl").write_text(json.dumps(
                {"ts": now - 10.0, "event": "serve.ready",
                 "pid": pid}) + "\n")
        # the router process's own registry: per-model traffic split
        reg = Registry()
        reg.counter("fleet.requests").inc(900)
        reg.counter("fleet.model.primary.requests").inc(900)
        reg.counter("fleet.model.shadow.requests").inc(90)
        reg.counter("fleet.model.shadow.mirrored").inc(90)
        for _ in range(5):
            reg.histogram(
                "fleet.model.primary.request_seconds").record(0.006)
        snap = reg.snapshot()
        snap["pid"], snap["ts"] = 99, now
        (tmp_path / "metrics-99.json").write_text(json.dumps(snap))
        (tmp_path / "journal-99.jsonl").write_text(json.dumps(
            {"ts": now - 11.0, "event": "fleet.ready",
             "canaries": {"shadow": {"of": "primary",
                                     "fraction": 0.1}}}) + "\n")
        return str(tmp_path)

    @pytest.fixture
    def fserver(self, fleet_dir):
        s = WebStatusServer(port=0, host="127.0.0.1",
                            metrics_dir=fleet_dir)
        s.start_background()
        yield s
        s.shutdown()

    def test_fleet_rows_read_child_snapshots(self, fleet_dir):
        from veles_tpu.obs import fleet_rows
        rows = fleet_rows(fleet_dir)
        assert [r["replica"] for r in rows] == [0, 1]
        assert rows[0]["pid"] == 111 and rows[1]["pid"] == 222
        assert rows[0]["models_resident"] == 2
        assert rows[0]["queue_depth"] == 1
        # 500 requests over the 10s ready->flush wall
        assert rows[0]["qps"] == pytest.approx(50.0, abs=0.5)
        assert rows[0]["p99_ms"] == pytest.approx(5.0, rel=0.2)

    def test_dashboard_renders_fleet_view(self, fserver):
        with urllib.request.urlopen(url(fserver, "/"),
                                    timeout=5) as r:
            page = r.read().decode()
        assert "fleet replicas" in page
        assert "111" in page and "222" in page
        assert "fleet per-model split" in page
        assert "canary-of:primary" in page

    def test_api_metrics_carries_fleet_block(self, fserver):
        snap = get_json(fserver, "/api/metrics")
        assert len(snap["fleet"]["replicas"]) == 2
        models = {m["model"]: m for m in snap["fleet"]["models"]}
        assert models["shadow"]["canary_of"] == "primary"
        assert models["shadow"]["mirrored"] == 90
        # the A/B split: shadow sees ~10% of primary's traffic
        assert models["shadow"]["share"] == pytest.approx(
            90 / 990, abs=0.01)

    def test_obs_report_fleet_flag(self, fleet_dir, capsys):
        import os
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "scripts"))
        try:
            import obs_report
        finally:
            sys.path.pop(0)
        assert obs_report.main([fleet_dir, "--fleet"]) == 0
        out = capsys.readouterr().out
        assert "fleet replicas" in out
        assert "canary-of:primary" in out
        # a non-fleet dir declines the flag loudly
        assert obs_report.main(
            [os.path.join(fleet_dir, "replica-0"), "--fleet"]) == 1
