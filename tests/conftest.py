"""Test configuration.

Tests run on XLA:CPU with 8 virtual devices so sharding/mesh code paths
are exercised without TPU hardware (the driver's dryrun does the same).
Must run before the first `import jax` anywhere in the test process.
"""

import os

# Force CPU: the driver environment presets JAX_PLATFORMS=axon (the
# real TPU chip) and its sitecustomize sets jax_platforms
# programmatically, so the env var alone is not enough — update the
# jax config before any backend initializes.  Unit tests must be fast,
# f32-exact, and see 8 virtual devices for sharding coverage.  TPU
# smoke tests opt back in explicitly.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", jax.default_backend()

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _isolated_data_dir(tmp_path_factory):
    """Point root.common.data_dir at a fresh temp dir for the whole
    session: real datasets materialized on this machine (e.g. bench.py's
    secondary metric writes MNIST IDX files under ~/.veles_tpu/data)
    must not leak into the suite — MnistLoader would silently switch
    from the tiny synthetic sets to 60k real samples and the suite's
    runtime would triple."""
    from veles_tpu.config import root
    root.common.data_dir = str(tmp_path_factory.mktemp("data"))
    yield


@pytest.fixture(autouse=True)
def _reset_global_state():
    """Each test gets a clean config tree, PRNG registry, and
    telemetry registry (zeroed in place; no metrics dir armed)."""
    from veles_tpu import config, prng, telemetry
    saved = dict(config.root.__dict__)
    saved_mdir = os.environ.pop(telemetry.ENV_DIR, None)
    telemetry.reset()
    telemetry.set_enabled(True)
    prng._streams.clear()
    prng.seed_all(1234)
    yield
    config.root.__dict__.clear()
    config.root.__dict__.update(saved)
    prng._streams.clear()
    if saved_mdir is not None:
        os.environ[telemetry.ENV_DIR] = saved_mdir
    else:
        os.environ.pop(telemetry.ENV_DIR, None)
    telemetry.reset()
    telemetry.set_enabled(True)
