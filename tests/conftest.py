"""Test configuration.

Tests run on XLA:CPU with 8 virtual devices so sharding/mesh code paths
are exercised without TPU hardware (the driver's dryrun does the same).
Must run before the first `import jax` anywhere in the test process.
"""

import os

# Force CPU: the driver environment presets JAX_PLATFORMS=axon (the
# real TPU chip) and its sitecustomize sets jax_platforms
# programmatically, so the env var alone is not enough — update the
# jax config before any backend initializes.  Unit tests must be fast,
# f32-exact, and see 8 virtual devices for sharding coverage.  TPU
# smoke tests opt back in explicitly.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", jax.default_backend()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_global_state():
    """Each test gets a clean config tree and PRNG registry."""
    from veles_tpu import config, prng
    saved = dict(config.root.__dict__)
    prng._streams.clear()
    prng.seed_all(1234)
    yield
    config.root.__dict__.clear()
    config.root.__dict__.update(saved)
    prng._streams.clear()
