"""Seeded atomic-write violations (veleslint fixture)."""
import json


def save_state(path, payload):
    with open(path, "w") as f:          # finding: bare text write
        json.dump(payload, f)


def save_blob(path, blob):
    f = open(path, "wb")                # finding: bare binary write
    f.write(blob)
    f.close()


def save_kw(path, blob):
    with open(path, mode="w+") as f:    # finding: mode keyword
        f.write(blob)
