"""Seeded lock-order cycle: two locks acquired in opposite orders on
two paths — the classic AB/BA deadlock, one of them through a direct
call."""
import threading

from veles_tpu.analysis import witness

_alpha = witness.lock("fx.alpha")
_beta = threading.Lock()


def forward():
    with _alpha:
        with _beta:
            return 1


def _grab_alpha():
    with _alpha:
        return 2


def backward():
    with _beta:
        return _grab_alpha()   # beta -> alpha: closes the cycle
