"""Clean counterpart of lock_bad (veleslint fixture)."""
import threading
from collections import deque

_lock = threading.Lock()
_jobs = {}
_queue = deque()


class Pool:
    def __init__(self):
        self._mu = threading.Lock()
        self.jobs = {}

    def submit(self, job_id, payload):
        # instance state is the owner's concern, not this rule's
        self.jobs[job_id] = payload


def submit(job_id, payload):
    with _lock:
        _jobs[job_id] = payload
        _queue.append(job_id)


def drain():
    with _lock:
        while _queue:
            _queue.popleft()
        _jobs.clear()


def worker():
    threading.Thread(target=drain).start()
