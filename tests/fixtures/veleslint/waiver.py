"""Inline-waiver fixture: every violation here carries a disable
comment, so a scan must come back clean."""
import json
import os

from veles_tpu import telemetry


def save_state(path, payload):
    # scratch file rewritten every run; a tear is self-healing
    with open(path, "w") as f:  # veleslint: disable=atomic-write
        json.dump(payload, f)


def read_knob():
    # experiment-local override, deliberately unregistered
    return os.environ.get(
        "VELES_SCRATCH_ONLY")  # veleslint: disable=env-registry


def emit():
    telemetry.event("ga.hang_detected")  # veleslint: disable
