"""Seeded wire-protocol violations: typo'd and ad-hoc keys in dicts
flowing to the wire (emit arg, assigned-then-sent, returned
response)."""
import json


def emit(obj):
    print(json.dumps(obj))


def answer(jid):
    emit({"id": jid, "modle": "x"})            # finding: typo'd key
    hello = {"ready": True, "bogus_field": 1}  # finding: ad-hoc key
    emit(hello)
    return {"error": "y", "why_not": 2}        # finding: ad-hoc key
