"""Clean counterpart of atomic_bad (veleslint fixture)."""
import json
import os
import tempfile


def load_state(path):
    with open(path) as f:               # reads are fine
        return json.load(f)


def load_blob(path):
    with open(path, "rb") as f:
        return f.read()


def save_state(path, payload):
    from veles_tpu.snapshotter import atomic_write
    with atomic_write(path, "w") as f:  # the hardened helper
        json.dump(payload, f)


def save_raw(path, blob):
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
    with os.fdopen(fd, "wb") as f:      # tempfile dance inline
        f.write(blob)
    os.replace(tmp, path)
