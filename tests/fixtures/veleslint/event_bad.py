"""Seeded event-registry violations (veleslint fixture)."""
from veles_tpu import telemetry


def hang(kind):
    # finding: declared name, but as an ad-hoc literal
    telemetry.event("ga.hang_detected", kind=kind)
    # finding: a TYPO no registry entry matches — the class of bug
    # chaos_drill assertions could previously only catch at runtime
    telemetry.counter("ga.hangs_detcted").inc()
    telemetry.gauge("ga.last_hang_wait").set(1.0)       # finding
    telemetry.histogram("ga.genome_seconds").record(2)  # finding
    with telemetry.span("ga.cohort_train"):             # finding
        pass
    return telemetry.recent_events("ga.hang_detected")  # finding
