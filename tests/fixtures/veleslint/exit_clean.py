"""Clean counterpart of exit_bad (veleslint fixture)."""
import os
import sys

EXIT_MULTIHOST_ABORT = 13   # constant definitions are the source
EXIT_PREEMPTED = 14
RESUME_CODES = frozenset((EXIT_MULTIHOST_ABORT, EXIT_PREEMPTED))


def abort():
    os._exit(EXIT_MULTIHOST_ABORT)


def preempt():
    sys.exit(EXIT_PREEMPTED)


def classify(rc):
    if rc == EXIT_PREEMPTED:
        return "preempted"
    if rc in RESUME_CODES:
        return "resume"
    if rc == 17:                # a non-contract code stays a number
        return "drill"
    return "crash"
