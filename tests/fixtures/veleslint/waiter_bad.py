"""Seeded waiter-discipline violations: the PR-12 timeout leak (an
exception edge abandons the wire id), a normal-path abandon, and a
dropped submit handle."""
from concurrent.futures import Future


class Router:
    def timeout_leak(self, client, model, rows):
        jid = client.submit(model, rows)       # finding: exc path
        try:
            return client.wait_for(jid, timeout=1.0)
        except TimeoutError:
            return None                        # jid never cancelled

    def branch_leak(self, client, model, rows, fast):
        jid = client.submit(model, rows)       # finding: normal path
        if fast:
            return client.wait_for(jid, timeout=1.0)
        return None                            # jid abandoned

    def dropped(self, pool, fn):
        pool.submit(fn)                        # finding: dropped

    def future_leak(self, ok):
        fut = Future()                         # finding: normal path
        if ok:
            fut.set_result(1)
            return fut
        return None                            # fut abandoned
