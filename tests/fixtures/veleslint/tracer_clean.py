"""Clean counterpart of tracer_bad (veleslint fixture)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def pure_step(params, grads, lr):
    # jnp control flow stays in-graph; shapes are static python
    upd = jnp.where(jnp.isnan(grads), 0.0, grads)
    k = int(params.shape[0])            # static shape: fine
    return params - lr * upd / k


def traced_scan(carry, x):
    return carry + x, carry


_step = jax.jit(pure_step)


def host_side(arr):
    # host code may sync freely — the rule only bites inside traced
    # functions
    v = arr.sum().item()
    print("host", v)
    return np.asarray(arr)
