"""Seeded thread-lifecycle violation: a non-daemon thread in a
module that never joins anything."""
import threading


def spawn(worker):
    t = threading.Thread(target=worker, name="straggler")  # finding
    t.start()
    return t
