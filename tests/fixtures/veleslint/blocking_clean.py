"""Clean twin: waits on the held condition (which releases it),
bounded timeouts, and blocking work moved outside the critical
section."""
import queue
import threading
import time

_q = queue.Queue()


class Worker:
    def __init__(self):
        self._cond = threading.Condition()
        self._lock = threading.Lock()

    def cond_wait_is_fine(self):
        with self._cond:
            self._cond.wait(0.1)       # releases the held lock

    def timeout_bounded(self):
        with self._lock:
            pass
        return _q.get(timeout=1.0)     # outside the lock anyway

    def future_with_timeout(self, fut):
        with self._lock:
            snapshot = 1
        time.sleep(0.01)               # outside the lock
        return fut.result(timeout=2.0), snapshot

    def work_outside(self):
        with self._lock:
            payload = list(range(3))
        time.sleep(0.01)
        return payload
