"""Seeded exit-code-literals violations (veleslint fixture)."""
import os
import sys


def abort():
    os._exit(13)                        # finding: exit-call literal


def preempt():
    sys.exit(14)                        # finding: exit-call literal


def classify(rc):
    if rc == 14:                        # finding: comparison literal
        return "preempted"
    if rc in (13, 14):                  # findings: both comparators
        return "resume"
    return "crash"
