"""Clean counterpart of event_bad (veleslint fixture)."""
from veles_tpu import events, telemetry


def hang(kind):
    telemetry.event(events.EV_GA_HANG_DETECTED, kind=kind)
    telemetry.counter(events.CTR_GA_HANGS_DETECTED).inc()
    telemetry.gauge(events.GAUGE_GA_LAST_HANG_WAIT).set(1.0)
    telemetry.histogram(events.HIST_GA_GENOME_SECONDS).record(2)
    with telemetry.span(events.SPAN_GA_COHORT_TRAIN):
        pass
    return telemetry.recent_events(events.EV_GA_HANG_DETECTED)


def dynamic(kind):
    # f-strings and variables are the documented dynamic families
    telemetry.counter(f"fused.{kind}_seconds").inc(1.0)
    name = events.EV_GA_GENERATION
    telemetry.event(name, gen=1)
