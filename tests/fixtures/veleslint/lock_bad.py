"""Seeded lock-discipline violations (veleslint fixture)."""
import threading
from collections import deque

_lock = threading.Lock()
_jobs = {}
_queue = deque()
_seen: list = []

_jobs["boot"] = 1      # import-time mutation: exempt (no threads yet)


def submit(job_id, payload):
    _jobs[job_id] = payload             # finding: setitem, no lock
    _queue.append(job_id)               # finding: append, no lock


def drain():
    while _queue:
        _seen.append(_queue.popleft())  # findings: append + popleft
    _jobs.clear()                       # finding: clear, no lock


def worker():
    threading.Thread(target=drain).start()
