"""Seeded tracer-hygiene violations (veleslint fixture)."""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_sync(x):
    v = x.sum().item()                  # finding: .item()
    print("loss", v)                    # finding: print
    return x * v


@partial(jax.jit, static_argnums=(1,))
def partial_decorated(x, n):
    host = np.asarray(x)                # finding: np.asarray
    return x + host.shape[0] + n


def passed_to_jit(params, lr):
    if jnp.any(jnp.isnan(params)):      # finding: branch on jnp value
        return params
    step = float(lr)                    # finding: float(param)
    return params - step * params


_step = jax.jit(passed_to_jit, donate_argnums=(0,))


def vmapped(row):
    row.block_until_ready()             # finding: device sync
    return row * 2


_v = jax.vmap(vmapped)
