"""Seeded blocking-under-lock violations: sleeps, untimed queue
ops, Future.result, and a transitive sleep through a helper — all
while a lock is held."""
import queue
import subprocess
import threading
import time

_q = queue.Queue()


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._proc = subprocess.Popen(["true"])

    def sleeps_under_lock(self):
        with self._lock:
            time.sleep(1.0)            # finding: time.sleep

    def untimed_queue_get(self):
        with self._lock:
            return _q.get()            # finding: Queue.get no timeout

    def untimed_future(self, fut):
        with self._lock:
            return fut.result()        # finding: .result() no timeout

    def waits_process(self):
        with self._lock:
            self._proc.wait()          # finding: Popen.wait no timeout

    def indirect(self):
        with self._lock:
            self._helper()             # finding: sleeps via _helper

    def _helper(self):
        time.sleep(0.5)
