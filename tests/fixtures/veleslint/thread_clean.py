"""Clean twin: daemon threads, and a non-daemon thread the module
provably joins on its shutdown path."""
import threading


def spawn_daemon(worker):
    t = threading.Thread(target=worker, daemon=True,
                         name="background")
    t.start()
    return t


def spawn_and_join(worker):
    ts = [threading.Thread(target=worker) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
