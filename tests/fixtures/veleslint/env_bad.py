"""Seeded env-registry violations (veleslint fixture)."""
import os

_TYPO_ENV = "VELES_PREEMPT_GRAEC"


def read_undeclared():
    return os.environ.get("VELES_NOT_A_KNOB")       # finding


def read_typo():
    return os.environ.get(_TYPO_ENV, "25")          # finding (const)


def write_undeclared():
    os.environ["VELES_ALSO_UNDECLARED"] = "1"       # finding


def getenv_undeclared():
    return os.getenv("VELES_MYSTERY_FLAG")          # finding
