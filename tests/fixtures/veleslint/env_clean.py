"""Clean counterpart of env_bad (veleslint fixture)."""
import os

GRACE_ENV = "VELES_PREEMPT_GRACE"


class Runner:
    FAULTS_ENV = "VELES_FAULTS"

    def grace(self):
        return float(os.environ.get(GRACE_ENV, "25"))   # declared

    def faults(self):
        return os.environ.get(self.FAULTS_ENV, "")      # class const


def metrics_dir():
    return os.environ.get("VELES_METRICS_DIR")          # declared


def non_veles():
    return os.environ.get("JAX_PLATFORMS")              # out of scope


def dynamic(name):
    return os.environ.get(name)                         # unresolvable
