"""Clean twin: declared keys only, dynamic keys skipped, dicts
handed to constructors are not wire payloads."""
import json


def emit(obj):
    print(json.dumps(obj))


class Meta:
    def __init__(self, meta):
        self.meta = meta


def answer(jid, model, key):
    emit({"id": jid, "model": model, "probs": [], "rows_n": 0,
          "crc": 0})
    resp = {"error": "overloaded", "overloaded": True}
    emit(resp)
    emit({key: 1})                       # dynamic key: skipped
    Meta({"workflow": None, "package": "p"})   # ctor arg: not wire
    return {"id": jid, "expired": True}
