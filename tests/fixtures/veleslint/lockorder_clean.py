"""Clean twin: both paths acquire in the same global order — nested
acquisition makes edges, but never a cycle."""
import threading

from veles_tpu.analysis import witness

_alpha = witness.lock("fx.alpha")
_beta = threading.Lock()


def forward():
    with _alpha:
        with _beta:
            return 1


def also_forward():
    with _alpha:
        return _grab_beta()


def _grab_beta():
    with _beta:
        return 2
