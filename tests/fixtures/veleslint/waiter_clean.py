"""Clean twin: every waiter is resolved, cancelled, or handed off on
every path — exception edges included."""
from concurrent.futures import Future


class Router:
    def cancel_on_timeout(self, client, model, rows):
        jid = client.submit(model, rows)
        try:
            return client.wait_for(jid, timeout=1.0)
        except TimeoutError:
            client.cancel(jid)
            return None

    def handoff_to_container(self, client, model, rows, pending):
        jid = client.submit(model, rows)
        pending[jid] = model                   # stored = handed off
        return jid

    def callback_resolves(self, pool, fn, done):
        fut = pool.submit(fn)
        fut.add_done_callback(done)

    def closure_handoff(self, pool, fn):
        fut = pool.submit(fn)

        def reaper():
            return fut.result(timeout=5.0)     # captured = handoff
        return reaper

    def always_resolves(self, ok):
        fut = Future()
        if ok:
            fut.set_result(1)
        else:
            fut.cancel()
        return fut
