"""Keel (ISSUE 18): ONE execution core under every engine loop.

The four engine loops — ``FusedStepRunner``, ``EnsembleEvalEngine``,
``PopulationTrainEngine`` (ops/fused.py) and the online scavenger's
``ShadowTrainer`` (online/trainer.py) — are thin adapters over
``veles_tpu.engine.core``: shared trace builders + one placement /
donation / arbiter surface.  Pins:

- the core primitives: the ``put`` / ``donating_jit`` seam, pytree
  byte accounting, and the process-arbiter charge/discharge ledger;
- the **engine-equivalence matrix**: for each loop, every combination
  of the orthogonal execution flags (streaming vs resident data,
  row-sharded vs replicated residency, member-sharded vs unsharded
  cohorts, on-mesh vs off) trains/scores **f32-BITWISE** identically —
  the flags select placement, never math;
- ``ShadowTrainer`` == a raw Keel-builder composition, bitwise — the
  adapter adds plumbing, not arithmetic;
- the GA→serving handoff (genetics/handoff.py): the final cohort's
  top-K members become a served ensemble with ZERO host round trips —
  no npz is ever written, the served stacked params are bitwise-equal
  to the trained cohort rows, and the ledger shows the serve charge.
"""

import glob
import os

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import JaxDevice
from veles_tpu.datasets import synthetic_classification
from veles_tpu.engine import core as engine_core
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow
from veles_tpu.parallel import (DataParallel, MeshJaxDevice,
                                make_mesh)
from veles_tpu.serve import residency


@pytest.fixture(autouse=True)
def _fresh_process_arbiter():
    """Each test sees a clean process-arbiter singleton (charges from
    one test's cores must not leak into another's ledger reads)."""
    saved = residency._process_arbiter
    residency._process_arbiter = None
    yield
    residency._process_arbiter = saved


# -- shared builders -----------------------------------------------------

N_TRAIN, N_VALID = 240, 57            # not divisible by the 8-mesh
SAMPLE = (10, 10, 1)


def build_workflow(mb=24, max_epochs=2, **loader_kw):
    prng._streams.clear()
    prng.seed_all(4242)
    train, valid, _ = synthetic_classification(
        N_TRAIN, N_VALID, SAMPLE, n_classes=7, seed=99)
    gd = {"learning_rate": 0.1, "weight_decay": 0.0001,
          "gradient_moment": 0.9}
    return StandardWorkflow(
        loader_factory=lambda w: ArrayLoader(
            w, train=train, valid=valid, minibatch_size=mb,
            name="loader", **loader_kw),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
             "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 7},
             "<-": gd},
        ],
        decision_config={"max_epochs": max_epochs},
        name="keel_matrix")


def build_wine(lr, epochs=4, fail=1):
    from veles_tpu.models import wine

    class FL:
        workflow = None

    prng._streams.clear()
    prng.seed_all(1234)
    layers = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 8},
         "<-": {"learning_rate": lr, "weight_decay": 0.001,
                "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 3},
         "<-": {"learning_rate": lr, "gradient_moment": 0.9}},
    ]
    w = wine.create_workflow(
        FL(), layers=layers,
        decision={"max_epochs": epochs, "fail_iterations": fail})
    w.initialize(device=JaxDevice(platform="cpu"))
    return w


def wine_cohort(lrs):
    rates = np.asarray([[[lr, lr], [lr, lr]] for lr in lrs],
                       np.float32)
    decays = np.asarray([[[0.001, 0.0], [0.0, 0.0]]] * len(lrs),
                        np.float32)
    return rates, decays


def host_params(w):
    return {f.name: {k: np.asarray(v)
                     for k, v in w.fused._params[f.name].items()}
            for f in w.forwards}


# -- core primitives -----------------------------------------------------

class TestCorePrimitives:
    def test_put_roundtrips_values_and_dtype(self):
        dev = JaxDevice(platform="cpu")
        core = engine_core.ExecutionCore(dev, None)
        x = np.arange(24, dtype=np.uint8).reshape(4, 6)
        buf = core.put(x)
        assert np.asarray(buf).dtype == np.uint8      # wire-preserving
        assert np.array_equal(np.asarray(buf), x)

    def test_donate_flag_is_droppable(self):
        """A core built with donate=False compiles the SAME adapter
        code without donation: the input buffer stays readable after
        the call (the debugging escape hatch)."""
        dev = JaxDevice(platform="cpu")
        core = engine_core.ExecutionCore(dev, None, donate=False)
        step = core.jit(lambda a: a + 1.0, donate=(0,))
        buf = core.put(np.float32([1.0, 2.0]))
        out = step(buf)
        assert np.array_equal(np.asarray(buf), [1.0, 2.0])  # not donated
        assert np.array_equal(np.asarray(out), [2.0, 3.0])

    def test_tree_nbytes_counts_nested_leaves(self):
        tree = {"a": {"w": np.zeros((3, 4), np.float32)},
                "b": {"w": np.zeros(8, np.float32),
                      "v": np.zeros(2, np.uint8)}}
        assert engine_core.tree_nbytes(tree) == 3 * 4 * 4 + 8 * 4 + 2

    def test_charge_lands_on_the_process_ledger(self):
        mgr = residency.install_process_arbiter(
            residency.ResidencyManager(None, budget_bytes=1 << 30))
        core = engine_core.ExecutionCore(None, None, pool="cohort",
                                         name="matrix-test")
        core.charge(12345)
        assert mgr.ledger()["cohort"] == 12345
        core.charge(777)                    # re-charge replaces
        assert mgr.ledger()["cohort"] == 777
        core.release()
        assert mgr.ledger()["cohort"] == 0

    def test_unknown_pool_is_rejected(self):
        mgr = residency.ResidencyManager(None, budget_bytes=1)
        with pytest.raises(ValueError):
            mgr.reserve("x", 1, pool="hbm2")


# -- the engine-equivalence matrix ---------------------------------------

class TestFusedMatrix:
    """FusedStepRunner: streaming / resident / row-sharded / mesh are
    pure placement flags — every combination yields the bitwise-same
    parameter trajectory."""

    def run_single(self, **loader_kw):
        w = build_workflow(**loader_kw)
        w.initialize(device=JaxDevice(platform="cpu"))
        w.run()
        params = host_params(w)
        hist = list(w.decision.history)
        streaming = bool(w.fused.streaming)
        w.stop()
        return params, hist, streaming

    def run_mesh(self, n=8, **loader_kw):
        w = build_workflow(**loader_kw)
        dp = DataParallel(w, n)
        w.initialize(device=dp.install())
        w.run()
        params = host_params(w)
        hist = list(w.decision.history)
        shard = bool(w.loader.shard_resident)
        stream = bool(w.fused.streaming)
        w.stop()
        return params, hist, shard, stream

    @staticmethod
    def assert_bitwise(pa, pb):
        for fn in pa:
            for k in pa[fn]:
                assert np.array_equal(pa[fn][k], pb[fn][k]), \
                    (fn, k)

    def test_streaming_matches_resident_single_device(self):
        p_res, h_res, s_res = self.run_single()
        p_str, h_str, s_str = self.run_single(max_resident_bytes=0)
        assert not s_res and s_str
        assert h_res == h_str
        self.assert_bitwise(p_res, p_str)

    def test_row_sharded_matches_replicated_on_mesh(self):
        p_rep, h_rep, sh_rep, _ = self.run_mesh()
        p_sh, h_sh, sh_sh, stream = self.run_mesh(
            max_resident_bytes=(N_TRAIN + N_VALID) * 4
            * int(np.prod(SAMPLE)) // 4)
        assert not sh_rep and sh_sh and not stream
        assert h_rep == h_sh
        self.assert_bitwise(p_rep, p_sh)

    def test_mesh_streaming_matches_mesh_resident(self):
        p_rep, h_rep, _, stream_rep = self.run_mesh()
        p_str, h_str, _, stream = self.run_mesh(max_resident_bytes=0)
        assert not stream_rep and stream
        assert h_rep == h_str
        self.assert_bitwise(p_rep, p_str)


class TestCohortMatrix:
    """PopulationTrainEngine: the full streaming x member-sharded
    grid returns bitwise-identical fitness vectors — the PR 18 lift
    of dataset-must-fit composes with the Lattice mesh placement."""

    LRS = [0.3, 0.05, 0.8]

    def run_cohort(self, streaming=False, mesh_n=0):
        from veles_tpu.ops.fused import PopulationTrainEngine
        w = build_wine(self.LRS[0])
        if streaming:
            w.loader.device_resident = False
        rates, decays = wine_cohort(self.LRS)
        engine = PopulationTrainEngine(
            w, rates, decays, mesh=make_mesh(mesh_n) if mesh_n
            else None)
        assert engine.streaming == streaming
        assert engine.member_sharded == bool(mesh_n)
        fits = np.asarray(engine.run())
        engine.release()
        w.stop()
        return fits

    def test_full_flag_grid_is_bitwise_identical(self):
        oracle = self.run_cohort()
        for streaming in (False, True):
            for mesh_n in (0, 8):
                if not streaming and not mesh_n:
                    continue
                got = self.run_cohort(streaming, mesh_n)
                assert np.array_equal(got, oracle), \
                    (streaming, mesh_n, got, oracle)


class TestEnsembleMatrix:
    """EnsembleEvalEngine: member-sharded serving scores bitwise like
    unsharded — the fixed left-to-right add chain in
    ``build_mean_probs`` is placement-independent by construction."""

    def predictions(self, member_sharded):
        from veles_tpu.ops.fused import EnsembleEvalEngine
        w = build_wine(0.3, epochs=2, fail=100)
        w.run()
        members = [host_params(w) for _ in range(3)]
        rng = np.random.default_rng(7)
        for i, mp in enumerate(members):
            for fn, d in mp.items():
                for k in d:
                    d[k] = d[k] + np.float32(0.01 * (i + 1)) \
                        * rng.standard_normal(d[k].shape) \
                        .astype(np.float32)
        device = MeshJaxDevice(make_mesh(8)) if member_sharded \
            else JaxDevice(platform="cpu")
        engine = EnsembleEvalEngine(
            w.forwards, members, device,
            shard_members=member_sharded)
        x = np.asarray(w.loader.original_data.map_read()[:16],
                       np.float32)
        probs = np.asarray(engine.predict_proba(x))
        engine.release()
        w.stop()
        return probs

    def test_member_sharded_predict_is_bitwise(self):
        p_un = self.predictions(member_sharded=False)
        p_sh = self.predictions(member_sharded=True)
        assert np.array_equal(p_un, p_sh)


class TestShadowTrainerIsKeelComposition:
    """One ShadowTrainer micro-step == the raw Keel-builder
    composition (build_forward + build_backward vmapped over members),
    bitwise — the online adapter adds plumbing, not arithmetic."""

    def test_step_matches_raw_builders(self):
        import jax
        import jax.numpy as jnp

        from veles_tpu.online.trainer import ShadowTrainer
        from veles_tpu.ops import batching

        w = build_wine(0.1, epochs=2, fail=100)
        w.run()
        base = host_params(w)
        rng = np.random.default_rng(5)
        members = [{fn: {k: v + np.float32(0.02)
                         * rng.standard_normal(v.shape)
                         .astype(np.float32)
                         for k, v in d.items()}
                    for fn, d in base.items()} for _ in range(2)]
        device = w.fused.device
        stacked = batching.stack_member_params(w.forwards, members,
                                               device)
        B = 8
        x = np.asarray(w.loader.original_data.map_read()[:B],
                       np.float32)
        labels = np.asarray(
            w.loader.original_labels.map_read()[:B], np.int32)

        tr = ShadowTrainer(w.forwards, w.gds, w.evaluator, device,
                           stacked, seed=33, lr_scale=0.1,
                           micro_batch=B)
        tr.step(x, labels, version=0)
        got = {fn: {k: np.asarray(v) for k, v in d.items()}
               for fn, d in tr._params.items()}

        # the oracle: the same Keel bodies composed by hand
        cd = batching.resolve_compute_dtype(None, device)
        cast = batching.make_caster(cd)
        fwd = engine_core.build_forward(w.forwards, 33, cd)
        bwd = engine_core.build_backward(w.forwards, w.gds, cd)
        evaluator = w.evaluator

        def member_step(params, opt, lr, xb, lb, mask, rc):
            cparams = cast(params)
            out, residuals = fwd(cparams, xb, rc, True)
            m = evaluator.metrics_fn(out.astype(jnp.float32), lb,
                                     mask)
            new_params, new_opt = bwd(cparams, params, opt,
                                      residuals, m["err_output"], lr)
            return new_params, new_opt

        stacked2 = batching.stack_member_params(w.forwards, members,
                                                device)
        opt2 = {gd.name: {k: device.zeros((2,) + tuple(v.shape),
                                          np.float32)
                          for k, v in gd.accumulated_grads.items()}
                for gd in w.gds
                if gd is not None and gd.accumulated_grads}
        step = jax.jit(jax.vmap(member_step,
                                in_axes=(0, 0, None, None, None,
                                         None, None)))
        lr = np.asarray([[gd.learning_rate * 0.1,
                          gd.learning_rate_bias * 0.1]
                         if gd is not None else [0.0, 0.0]
                         for gd in w.gds], np.float32)
        want, _ = step(stacked2, opt2, lr, x, labels,
                       np.ones(B, np.float32), 0)
        for fn, d in got.items():
            for k, v in d.items():
                assert np.array_equal(v, np.asarray(want[fn][k])), \
                    (fn, k)
        w.stop()


# -- the GA -> serving handoff -------------------------------------------

class TestGAHandoff:
    """The zero-host-round-trip handoff: the trained cohort's top-K
    members become a served ensemble through one jitted device gather
    + ``swap_params`` — no snapshot, no npz, no Forge package, no
    host copy of the params on the critical path."""

    LRS = [0.3, 0.05, 0.8]
    K = 2

    def _handoff(self, tmp_path, monkeypatch, mesh_n=0):
        from veles_tpu.genetics.handoff import GAServingHandoff
        from veles_tpu.ops.fused import PopulationTrainEngine

        monkeypatch.chdir(tmp_path)
        # any host-side snapshot write on the handoff path is a bug —
        # np.savez/save tripping proves a host round trip sneaked in
        for fname in ("savez", "savez_compressed", "save"):
            monkeypatch.setattr(
                np, fname,
                lambda *a, **k: (_ for _ in ()).throw(AssertionError(
                    "handoff touched the host: np.%s called" % fname)))

        w = build_wine(self.LRS[0])
        mesh = None
        if mesh_n:
            # cohort and serving tier share ONE device set (the mesh):
            # the adopt gather is a single jitted program over both
            mesh = make_mesh(mesh_n)
            serve_device = MeshJaxDevice(mesh)
            monkeypatch.setenv("VELES_SERVE_MESH_SHARD", "always")
        else:
            serve_device = w.fused.device
        sample_shape = tuple(np.asarray(
            w.loader.original_data.map_read()).shape[1:])
        rates, decays = wine_cohort(self.LRS)
        engine = PopulationTrainEngine(w, rates, decays, mesh=mesh)

        # the scaffold pre-builds (register + compile + warm) from the
        # cohort's INIT params — this overlaps training in production
        init_members = [
            {fn: {k: np.asarray(arr[i]) for k, arr in d.items()}
             for fn, d in engine._params.items()}
            for i in range(self.K)]
        mgr = residency.ResidencyManager(serve_device,
                                         budget_bytes=1 << 30)
        ho = GAServingHandoff(mgr, "winner", w.fused.forwards,
                              init_members,
                              sample_shape=sample_shape)
        fits = np.asarray(engine.run())
        serve_engine = ho.adopt_cohort(engine, fits)
        idx = ho.top_k(fits)

        # bitwise: every served member row equals the trained cohort's
        # (a member-sharded stack carries mesh-padding rows past K —
        # never read by the fixed-order mean, so only K rows matter)
        for fn, d in serve_engine.stacked_params.items():
            for k, arr in d.items():
                want = np.asarray(engine._params[fn][k])[idx]
                got = np.asarray(arr)[:self.K]
                assert np.array_equal(got, want), (fn, k)

        # the engine is LIVE: a request flows through the batcher
        x = np.asarray(w.loader.original_data.map_read()[:4],
                       np.float32)
        out = np.asarray(serve_engine.submit(x).result())
        assert out.shape == (4, 3)
        assert np.all(np.isfinite(out))

        # refresh_host is the OFF-critical-path host copy; the ledger
        # carries the serve charge for the adopted stack
        ho.refresh_host()
        assert mgr.ledger()["serve"] > 0
        engine.release()
        w.stop()
        assert glob.glob(os.path.join(str(tmp_path), "**", "*.npz"),
                         recursive=True) == []
        return fits, idx

    def test_handoff_serves_trained_members_without_npz(
            self, tmp_path, monkeypatch):
        fits, idx = self._handoff(tmp_path, monkeypatch)
        # top_k is the stable best-first order of min-is-best fitness
        order = np.argsort(fits, kind="stable")[:self.K]
        assert np.array_equal(idx, order.astype(np.int32))

    def test_handoff_onto_member_sharded_serving(
            self, tmp_path, monkeypatch):
        """The adopt gather lands member-sharded when the serving
        replica shards its member axis (the Prism placement)."""
        self._handoff(tmp_path, monkeypatch, mesh_n=8)

    def test_handoff_event_journaled(self, tmp_path, monkeypatch):
        from veles_tpu import events, telemetry
        self._handoff(tmp_path, monkeypatch)
        evs = telemetry.recent_events(events.EV_GA_HANDOFF)
        assert evs and evs[-1]["members"] == self.K

    def test_adopt_after_release_is_refused(self):
        from veles_tpu.genetics.handoff import GAServingHandoff
        from veles_tpu.ops.fused import PopulationTrainEngine

        w = build_wine(self.LRS[0])
        sample_shape = tuple(np.asarray(
            w.loader.original_data.map_read()).shape[1:])
        rates, decays = wine_cohort(self.LRS)
        engine = PopulationTrainEngine(w, rates, decays)
        members = [
            {fn: {k: np.asarray(arr[i]) for k, arr in d.items()}
             for fn, d in engine._params.items()}
            for i in range(self.K)]
        mgr = residency.ResidencyManager(w.fused.device,
                                         budget_bytes=1 << 30)
        ho = GAServingHandoff(mgr, "late", w.fused.forwards, members,
                              sample_shape=sample_shape, warm_rows=0)
        fits = np.asarray(engine.run())
        engine.release()
        with pytest.raises(RuntimeError):
            ho.adopt_cohort(engine, fits)
        w.stop()
