"""Real-file CIFAR-10 path end-to-end (round-3 VERDICT next #3):
binary/pickle batch files written offline -> loader picks them over
the synthetic stand-in -> the BASELINE config #3 workflow trains."""

import os
import pickle

import numpy as np
import pytest

from veles_tpu import datasets, prng
from veles_tpu.backends import JaxDevice
from veles_tpu.config import root


@pytest.fixture
def cifar_dir(tmp_path):
    base = datasets.generate_cifar10_batches(
        str(tmp_path / "cifar10" / "cifar-10-batches-bin"),
        n_train=500, n_test=100)
    old = root.common.get("data_dir") if "common" in root else None
    root.common.data_dir = str(tmp_path)
    yield base
    root.common.data_dir = old


class TestBinaryRoundtrip:
    def test_write_read(self, cifar_dir):
        real = datasets.try_load_real_cifar10()
        assert real is not None
        (tx, ty), (vx, vy) = real
        assert tx.shape == (500, 32, 32, 3) and vx.shape == \
            (100, 32, 32, 3)
        assert tx.dtype == np.float32 and 0.0 <= tx.min() \
            and tx.max() <= 1.0
        assert set(np.unique(ty)) <= set(range(10))
        # byte-exact vs the generator's source arrays (quantized)
        (sx, sy), (qx, qy), _ = datasets.synthetic_classification(
            500, 100, (32, 32, 3), n_classes=10, noise=0.5, seed=32323)
        np.testing.assert_array_equal(ty, sy)
        np.testing.assert_allclose(
            tx, np.round(sx * 255.0).astype(np.uint8) / 255.0,
            atol=1e-7)

    def test_generator_idempotent(self, tmp_path):
        base = datasets.generate_cifar10_batches(str(tmp_path),
                                                 n_train=50, n_test=10)
        mtimes = {f: os.path.getmtime(os.path.join(base, f))
                  for f in os.listdir(base)}
        base2 = datasets.generate_cifar10_batches(str(tmp_path),
                                                  n_train=99,
                                                  n_test=10)
        assert base2 == base
        for f, t in mtimes.items():
            assert os.path.getmtime(os.path.join(base, f)) == t

    def test_partial_genuine_set_never_overwritten(self, tmp_path):
        genuine = np.zeros((3, 3073), np.uint8) + 7
        genuine.tofile(str(tmp_path / "data_batch_1.bin"))
        with pytest.raises(FileExistsError, match="partial"):
            datasets.generate_cifar10_batches(str(tmp_path),
                                              n_train=50, n_test=10)
        back = np.fromfile(str(tmp_path / "data_batch_1.bin"),
                           np.uint8)
        np.testing.assert_array_equal(back, genuine.reshape(-1))
        assert not os.path.exists(tmp_path / "test_batch.bin")

    def test_corrupt_batch_rejected_not_crashed(self, tmp_path):
        """A truncated .bin batch must make the real-file probe return
        None (fall back to synthetic), not raise."""
        d = tmp_path / "cifar10" / "cifar-10-batches-bin"
        d.mkdir(parents=True)
        for name in ("data_batch_1 data_batch_2 data_batch_3 "
                     "data_batch_4 data_batch_5 test_batch").split():
            (d / f"{name}.bin").write_bytes(b"\x01" * 100)  # not 3073k
        old = root.common.get("data_dir") if "common" in root else None
        root.common.data_dir = str(tmp_path)
        try:
            assert datasets.try_load_real_cifar10() is None
        finally:
            root.common.data_dir = old


class TestPickleLayout:
    def test_python_pickle_batches_load(self, tmp_path):
        """The upstream python-version layout (pickle dicts with
        b'data' / b'labels') parses identically to binary."""
        d = tmp_path / "cifar10" / "cifar-10-batches-py"
        d.mkdir(parents=True)
        rng = np.random.default_rng(5)
        want_x, want_y = [], []
        names = [f"data_batch_{i}" for i in range(1, 6)] + \
            ["test_batch"]
        for name in names:
            x = rng.integers(0, 256, (20, 3072)).astype(np.uint8)
            y = rng.integers(0, 10, 20).astype(np.int64)
            with open(d / name, "wb") as f:
                # py2-era upstream pickles have bytes keys
                pickle.dump({b"data": x, b"labels": list(y)}, f)
            want_x.append(x)
            want_y.append(y)
        old = root.common.get("data_dir") if "common" in root else None
        root.common.data_dir = str(tmp_path)
        try:
            real = datasets.try_load_real_cifar10()
        finally:
            root.common.data_dir = old
        assert real is not None
        (tx, ty), (vx, vy) = real
        assert tx.shape == (100, 32, 32, 3) and vx.shape[0] == 20
        np.testing.assert_array_equal(
            ty, np.concatenate(want_y[:-1]).astype(np.int32))
        # channel deinterleave: plane layout R|G|B -> HWC
        np.testing.assert_allclose(
            tx[0], want_x[0][0].reshape(3, 32, 32)
            .transpose(1, 2, 0) / 255.0, atol=1e-7)


class TestRealFileTraining:
    def test_loader_prefers_real_files(self, cifar_dir):
        from veles_tpu.loader.synthetic import Cifar10Loader
        from veles_tpu.workflow import Workflow
        w = Workflow(name="t")
        ld = Cifar10Loader(w, name="loader", minibatch_size=50)
        ld.initialize(device=None)
        assert ld.class_lengths == [0, 100, 500]

    def test_baseline_config_trains_from_real_files(self, cifar_dir):
        """BASELINE config #3 (CIFAR-10 conv + LR policy + weight
        decay) end-to-end from real-format batch files."""
        prng.seed_all(4321)
        from veles_tpu.models import cifar10

        class FL:
            workflow = None
        w = cifar10.create_workflow(
            FL(), loader={"minibatch_size": 50},
            decision={"max_epochs": 2})
        w.initialize(device=JaxDevice(platform="cpu"))
        assert w.loader.class_lengths == [0, 100, 500]
        w.run()
        hist = [h for h in w.decision.history
                if h["class"] == "validation"]
        assert len(hist) == 2
        assert all(np.isfinite(h["loss"]) for h in w.decision.history)
