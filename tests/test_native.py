"""Native C++ inference runtime (native/ + veles_tpu/export.py +
veles_tpu/native.py) — the libVeles equivalent (SURVEY.md §3.3).
The python numpy forward path is the oracle; the C++ runtime must
match it to float tolerance on every exported op."""

import shutil

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import NumpyDevice
from veles_tpu.datasets import synthetic_classification
from veles_tpu.export import export_model
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


def build_and_train(layers, shape=(12, 12, 1), n_classes=4,
                    max_epochs=1, loss="softmax", mb=20):
    prng.seed_all(777)
    train, valid, _ = synthetic_classification(
        80, 40, shape, n_classes=n_classes, seed=42)
    if loss == "mse":  # autoencoder: target = the input itself
        train = (train[0], train[1], train[0])
        valid = (valid[0], valid[1], valid[0])
    w = StandardWorkflow(
        loader_factory=lambda wf: ArrayLoader(
            wf, train=train, valid=valid, minibatch_size=mb,
            name="loader"),
        layers=layers, loss_function=loss,
        decision_config={"max_epochs": max_epochs}, name="native_wf")
    w.initialize(device=NumpyDevice())
    w.run()
    return w


def python_forward(w, x):
    out = np.asarray(x, np.float32)
    for f in w.forwards:
        params = {k: np.asarray(v) for k, v in f.gather_params().items()}
        out, _ = f.apply_fwd(params, out, rng=None, train=False)
        out = np.asarray(out)
    return out


def roundtrip(w, tmp_path, batch=8):
    from veles_tpu.native import NativeModel

    path = str(tmp_path / "model.vtpn")
    export_model(w, path)
    model = NativeModel(path)
    x = w.loader.original_data.mem[:batch]
    want = python_forward(w, x).reshape(batch, -1)
    got = model.run(x)
    model.close()
    return want, got


class TestNativeRuntime:
    def test_dense_net(self, tmp_path):
        w = build_and_train([
            {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
             "<-": {"learning_rate": 0.1}},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": {"learning_rate": 0.1}},
        ])
        want, got = roundtrip(w, tmp_path)
        np.testing.assert_allclose(got, want, atol=1e-5)
        np.testing.assert_allclose(got.sum(-1), 1.0, atol=1e-5)

    def test_conv_net_with_everything(self, tmp_path):
        """conv+relu, LRN, maxpool, dropout(identity), FC tanh,
        softmax — the AlexNet op family end to end."""
        w = build_and_train([
            {"type": "conv_relu",
             "->": {"n_kernels": 6, "kx": 3, "ky": 3, "padding": 1,
                    "sliding": 2}, "<-": {"learning_rate": 0.05}},
            {"type": "norm", "->": {"n": 3}, "<-": {}},
            {"type": "max_pooling", "->": {"kx": 2, "ky": 2,
                                           "sliding": 2}, "<-": {}},
            {"type": "dropout", "->": {"dropout_ratio": 0.4}, "<-": {}},
            {"type": "all2all_tanh", "->": {"output_sample_shape": 12},
             "<-": {"learning_rate": 0.05}},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": {"learning_rate": 0.05}},
        ])
        want, got = roundtrip(w, tmp_path)
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_avgpool_and_stochpool(self, tmp_path):
        w = build_and_train([
            {"type": "avg_pooling", "->": {"kx": 2, "ky": 2,
                                           "sliding": 2}, "<-": {}},
            {"type": "stochastic_pooling",
             "->": {"kx": 2, "ky": 2, "sliding": 2}, "<-": {}},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": {"learning_rate": 0.1}},
        ])
        want, got = roundtrip(w, tmp_path)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_autoencoder_with_deconv(self, tmp_path):
        w = build_and_train([
            {"type": "conv_tanh",
             "->": {"n_kernels": 4, "kx": 4, "ky": 4, "sliding": 2,
                    "padding": 1}, "<-": {"learning_rate": 0.02}},
            {"type": "deconv",
             "->": {"n_kernels": 1, "kx": 4, "ky": 4, "sliding": 2,
                    "padding": 1}, "<-": {"learning_rate": 0.02}},
        ], loss="mse")
        want, got = roundtrip(w, tmp_path)
        np.testing.assert_allclose(got, want.reshape(got.shape),
                                   atol=1e-4)

    def test_model_metadata(self, tmp_path):
        from veles_tpu.native import NativeModel

        w = build_and_train([
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": {"learning_rate": 0.1}}])
        path = str(tmp_path / "m.vtpn")
        export_model(w, path)
        m = NativeModel(path)
        assert m.input_shape == (12, 12, 1)
        assert m.output_size == 4
        assert m.num_ops == 1
        m.close()

    def test_bad_file_rejected(self, tmp_path):
        from veles_tpu.native import NativeModel

        bad = tmp_path / "junk.vtpn"
        bad.write_bytes(b"NOPE" + b"\x00" * 64)
        with pytest.raises(ValueError, match="magic"):
            NativeModel(str(bad))

    def test_truncated_file_rejected(self, tmp_path):
        from veles_tpu.native import NativeModel

        w = build_and_train([
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": {"learning_rate": 0.1}}])
        path = tmp_path / "m.vtpn"
        export_model(w, str(path))
        data = path.read_bytes()
        (tmp_path / "trunc.vtpn").write_bytes(data[:len(data) // 2])
        with pytest.raises(ValueError, match="truncated|corrupt"):
            NativeModel(str(tmp_path / "trunc.vtpn"))

    def test_wrong_input_shape_rejected(self, tmp_path):
        from veles_tpu.native import NativeModel

        w = build_and_train([
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": {"learning_rate": 0.1}}])
        path = str(tmp_path / "m.vtpn")
        export_model(w, path)
        m = NativeModel(path)
        with pytest.raises(ValueError, match="sample shape"):
            m.run(np.zeros((2, 5, 5, 1), np.float32))
        m.close()


class TestDbnExport:
    def test_dbn_mlp_roundtrip(self, tmp_path):
        """The fine-tuned DBN stack (binarization -> sigmoid dense ->
        softmax) must deploy through the native runtime — OP_BINARIZE
        carries the eval-mode threshold (models/mnist_dbn.py)."""
        w = build_and_train([
            {"type": "binarization", "->": {}, "<-": {}},
            {"type": "all2all_sigmoid",
             "->": {"output_sample_shape": 12},
             "<-": {"learning_rate": 0.1}},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": {"learning_rate": 0.1}},
        ])
        want, got = roundtrip(w, tmp_path)
        np.testing.assert_allclose(got, want, atol=1e-5)
        np.testing.assert_allclose(got.sum(-1), 1.0, atol=1e-5)
