"""DBN: greedy RBM pretraining feeding the fine-tuned MLP
(models/mnist_dbn.py — the consumer of RBM.hidden_of's stacking
surface; SURVEY.md §3.2 "RBM / other")."""

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import JaxDevice
from veles_tpu.models import mnist_dbn

LOADER = {"minibatch_size": 25, "n_train": 400, "n_valid": 100}
HIDDEN = [32, 16]


@pytest.fixture(scope="module")
def dev():
    return JaxDevice(platform="cpu")


class FakeLauncher:
    workflow = None


def _finetune_val_errors(pretrained, epochs, dev):
    prng.seed_all(99)
    fl = FakeLauncher()
    w = mnist_dbn.create_workflow(
        fl, loader=dict(LOADER), hidden=list(HIDDEN),
        decision={"max_epochs": epochs})
    w.initialize(device=dev)
    if pretrained is not None:
        mnist_dbn.apply_pretrained(w, pretrained)
    w.run()
    errs = [h["error_pct"] for h in w.decision.history
            if h["class"] == "validation"]
    w.stop()
    return errs


@pytest.fixture(scope="module")
def pretrained(dev):
    prng.seed_all(7)
    return mnist_dbn.pretrain(device=dev, loader_cfg=dict(LOADER),
                              hidden=HIDDEN, epochs=3)


class TestDbn:
    def test_pretrain_shapes(self, pretrained):
        assert len(pretrained) == 2
        assert pretrained[0]["weights"].shape == (28 * 28, 32)
        assert pretrained[0]["bias"].shape == (32,)
        # stage 2 stacks on stage 1's hidden width
        assert pretrained[1]["weights"].shape == (32, 16)
        assert pretrained[1]["bias"].shape == (16,)
        for p in pretrained:
            assert np.isfinite(p["weights"]).all()
            assert p["weights"].std() > 0  # actually trained

    def test_pretraining_beats_cold_start(self, pretrained, dev):
        """The DBN's reason to exist: at a fixed small fine-tune
        budget and fixed seed, RBM-initialized layers reach lower
        validation error than cold-start backprop."""
        cold = _finetune_val_errors(None, epochs=2, dev=dev)
        warm = _finetune_val_errors(pretrained, epochs=2, dev=dev)
        assert warm[-1] < cold[-1], (warm, cold)

    def test_transplant_rejects_mismatched_stack(self, pretrained, dev):
        prng.seed_all(5)
        fl = FakeLauncher()
        w = mnist_dbn.create_workflow(
            fl, loader=dict(LOADER), hidden=[32],  # one layer only
            decision={"max_epochs": 1})
        w.initialize(device=dev)
        with pytest.raises(ValueError):
            mnist_dbn.apply_pretrained(w, pretrained)
        w.stop()

    def test_transplanted_weights_are_live(self, pretrained, dev):
        """The transplanted parameters must be what the first fused
        firing actually consumes (not clobbered by fill_params)."""
        prng.seed_all(11)
        fl = FakeLauncher()
        w = mnist_dbn.create_workflow(
            fl, loader=dict(LOADER), hidden=list(HIDDEN),
            decision={"max_epochs": 1})
        w.initialize(device=dev)
        mnist_dbn.apply_pretrained(w, pretrained)
        from veles_tpu.ops.all2all import All2AllSigmoid
        sig = [f for f in w.forwards if isinstance(f, All2AllSigmoid)]
        got = np.asarray(sig[0].gather_params()["weights"])
        np.testing.assert_array_equal(got, pretrained[0]["weights"])
        w.stop()
