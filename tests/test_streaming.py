"""Streaming data path: datasets that do NOT live in HBM.

Round-1 VERDICT missing #1 / next #2: the fused TPU path previously
required the whole dataset resident in HBM; ImageNet (~150 GB) cannot
fit in 16 GB.  These tests force ``device_resident=False`` (residency
budget 0) and verify the host-assembled, prefetch-overlapped superstep
path reproduces the resident path exactly — including across epoch
shuffles — for array loaders, image-directory loaders, MSE targets,
and the sharded mesh."""

import os

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import JaxDevice
from veles_tpu.datasets import synthetic_classification
from veles_tpu.loader import ArrayLoader
from veles_tpu.loader.image import ImageDirectoryLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow


def build_mlp(max_epochs=3, streaming=False, mb=20):
    prng.seed_all(1357)
    train, valid, _ = synthetic_classification(
        160, 40, (8, 8, 1), n_classes=4, seed=7)
    kw = {"max_resident_bytes": 0} if streaming else {}
    gd = {"learning_rate": 0.1, "gradient_moment": 0.9}
    return StandardWorkflow(
        loader_factory=lambda w: ArrayLoader(
            w, train=train, valid=valid, minibatch_size=mb,
            name="loader", **kw),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
             "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": gd},
        ],
        decision_config={"max_epochs": max_epochs},
        name="stream_test")


def final_weights(w):
    return {f.name: np.asarray(w.fused._params[f.name]["weights"])
            for f in w.forwards}


def valid_history(w):
    return [h for h in w.decision.history if h["class"] == "validation"]


class TestStreamingArrays:
    def test_streaming_matches_resident_trajectory(self):
        wr = build_mlp()
        wr.initialize(device=JaxDevice(platform="cpu"))
        assert not wr.fused.streaming
        wr.run()

        ws = build_mlp(streaming=True)
        ws.initialize(device=JaxDevice(platform="cpu"))
        assert ws.fused.streaming
        assert not ws.loader.device_resident
        ws.run()

        hr, hs = valid_history(wr), valid_history(ws)
        assert len(hr) == len(hs) == 3
        for a, b in zip(hr, hs):
            assert abs(a["loss"] - b["loss"]) < 1e-6, (a, b)
            assert a["n_err"] == b["n_err"], (a, b)
        fr, fs = final_weights(wr), final_weights(ws)
        for n in fr:
            np.testing.assert_allclose(fr[n], fs[n], atol=1e-6)

    def test_prefetched_batches_are_the_right_rows(self):
        """Across 2 epochs (reshuffle between them) every streaming
        superstep batch must equal the resident gather of its own
        indices — proves the peek/prefetch never desyncs."""
        w = build_mlp(streaming=True)
        w.initialize(device=JaxDevice(platform="cpu"))
        ld = w.loader
        data = ld.original_data.mem
        seen_groups = 0
        for _ in range(2 * 12):  # 2 epochs x (2 valid + 8 train)/8 ...
            ld.run()
            if ld.superstep_data is None:
                continue
            k, mb = ld.superstep_indices.shape
            want = data[ld.superstep_indices.reshape(-1)].reshape(
                ld.superstep_data.shape)
            np.testing.assert_array_equal(ld.superstep_data, want)
            seen_groups += 1
            if ld.epoch_number >= 2:
                break
        assert seen_groups >= 4

    def test_streaming_mse_targets(self):
        """Autoencoder-style: targets stream alongside the data."""
        prng.seed_all(2468)
        train, valid, _ = synthetic_classification(
            80, 20, (6, 6, 1), n_classes=3, seed=11)
        x, y = train
        w = StandardWorkflow(
            loader_factory=lambda wf: ArrayLoader(
                wf, train=(x, y, x.reshape(len(x), -1)),
                valid=(valid[0], valid[1],
                       valid[0].reshape(len(valid[0]), -1)),
                minibatch_size=10, name="loader",
                max_resident_bytes=0),
            layers=[
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 12},
                 "<-": {"learning_rate": 0.05}},
                {"type": "all2all",
                 "->": {"output_sample_shape": 36},
                 "<-": {"learning_rate": 0.05}},
            ],
            loss_function="mse",
            decision_config={"max_epochs": 3},
            name="stream_mse")
        w.initialize(device=JaxDevice(platform="cpu"))
        assert w.fused.streaming
        w.run()
        losses = [h["loss"] for h in valid_history(w)]
        assert len(losses) == 3
        assert losses[-1] < losses[0]
        assert all(np.isfinite(l) for l in losses)

    def test_streaming_with_mesh(self):
        """Sharded streaming: batch rows device_put over the data axis;
        trajectory matches the unsharded streaming run."""
        from veles_tpu.parallel import DataParallel
        w1 = build_mlp(streaming=True)
        w1.initialize(device=JaxDevice(platform="cpu"))
        w1.run()

        w4 = build_mlp(streaming=True)
        dp = DataParallel(w4, 4)
        w4.initialize(device=dp.install())
        assert w4.fused.streaming
        w4.run()

        h1, h4 = valid_history(w1), valid_history(w4)
        for a, b in zip(h1, h4):
            assert abs(a["loss"] - b["loss"]) < 5e-3, (a, b)
            assert abs(a["n_err"] - b["n_err"]) <= 2, (a, b)


def make_image_tree(root, n_classes=3, per_class=20, size=(12, 12)):
    from PIL import Image
    rng = np.random.RandomState(33)
    for split, n in (("train", per_class), ("validation", 5)):
        for c in range(n_classes):
            d = os.path.join(root, split, f"class{c}")
            os.makedirs(d, exist_ok=True)
            for i in range(n):
                # class-dependent base intensity + noise: learnable
                base = int(200 * c / max(n_classes - 1, 1)) + 20
                arr = np.clip(rng.normal(base, 30, size),
                              0, 255).astype(np.uint8)
                Image.fromarray(arr, "L").save(
                    os.path.join(d, f"im{i}.png"))


class TestStreamingImages:
    def test_image_directory_streaming_matches_resident(self, tmp_path):
        make_image_tree(str(tmp_path))

        def build(streaming):
            prng.seed_all(9753)
            return StandardWorkflow(
                loader_factory=lambda wf: ImageDirectoryLoader(
                    wf, data_dir=str(tmp_path),
                    target_shape=(12, 12, 1), minibatch_size=15,
                    streaming=streaming, name="loader"),
                layers=[
                    {"type": "all2all_tanh",
                     "->": {"output_sample_shape": 16},
                     "<-": {"learning_rate": 0.1}},
                    {"type": "softmax",
                     "->": {"output_sample_shape": 3},
                     "<-": {"learning_rate": 0.1}},
                ],
                decision_config={"max_epochs": 4},
                name="img_stream")

        wr = build(False)
        wr.initialize(device=JaxDevice(platform="cpu"))
        assert not wr.fused.streaming
        wr.run()

        ws = build(True)
        ws.initialize(device=JaxDevice(platform="cpu"))
        assert ws.fused.streaming
        ws.run()

        hr, hs = valid_history(wr), valid_history(ws)
        assert len(hr) == len(hs) == 4
        for a, b in zip(hr, hs):
            assert abs(a["loss"] - b["loss"]) < 1e-6, (a, b)
        # and it actually learns on this separable toy set
        assert hs[-1]["error_pct"] < hs[0]["error_pct"] or \
            hs[-1]["error_pct"] <= 10.0

    def test_auto_streaming_threshold(self, tmp_path):
        make_image_tree(str(tmp_path), per_class=4)
        ld_kwargs = dict(data_dir=str(tmp_path),
                         target_shape=(12, 12, 1), minibatch_size=6)

        from veles_tpu.workflow import Workflow
        w = Workflow(name="t")
        small = ImageDirectoryLoader(w, name="l1",
                                     max_resident_bytes=10 ** 9,
                                     **ld_kwargs)
        small.initialize(device=None)
        assert small.device_resident
        w2 = Workflow(name="t2")
        big = ImageDirectoryLoader(w2, name="l2",
                                   max_resident_bytes=100,
                                   **ld_kwargs)
        big.initialize(device=None)
        assert not big.device_resident
        # streaming loader decodes per minibatch instead of upfront
        assert big.original_data.mem is None
        big.run()
        assert float(np.abs(big.minibatch_data.map_read()).sum()) > 0

    def test_forced_resident_over_budget_does_not_redecode(
            self, tmp_path):
        """Round-2 advisor low: streaming=False + dataset over the HBM
        budget flips device_resident off; assemble_rows must then slice
        the already-decoded host pixels, not hit the disk again."""
        make_image_tree(str(tmp_path), per_class=4)
        from veles_tpu.workflow import Workflow
        w = Workflow(name="t")
        ld = ImageDirectoryLoader(w, name="l",
                                  data_dir=str(tmp_path),
                                  target_shape=(12, 12, 1),
                                  minibatch_size=6,
                                  streaming=False,
                                  max_resident_bytes=100)
        ld.initialize(device=None)
        assert not ld.device_resident       # over budget
        assert ld.original_data.mem is not None  # but decoded upfront
        decodes = []
        orig = ld._decode_one
        ld._decode_one = lambda i: decodes.append(i) or orig(i)
        rows, labels, _ = ld.assemble_rows(np.arange(4))
        assert decodes == []                # sliced, not re-decoded
        np.testing.assert_array_equal(rows, ld.original_data.mem[:4])


class TestStreamDtypeAndRelease:
    def test_synth_cache_opt_in_only(self, monkeypatch, tmp_path):
        """The large-dataset memo must stay OFF for ordinary runs (it
        retains a duplicate multi-GB copy) and ON under the bench's
        env opt-in."""
        from veles_tpu import datasets
        monkeypatch.setattr(datasets, "_SYNTH_CACHE_MIN_BYTES", 1024)
        datasets._synth_cache.clear()
        args = dict(n_train=64, n_valid=0, shape=(4, 4, 3), seed=5)

        monkeypatch.delenv("VELES_TPU_SYNTH_CACHE", raising=False)
        a, _, _ = datasets.synthetic_classification(**args)
        b, _, _ = datasets.synthetic_classification(**args)
        assert a[0] is not b[0] and not datasets._synth_cache

        monkeypatch.setenv("VELES_TPU_SYNTH_CACHE", "1")
        c, _, _ = datasets.synthetic_classification(**args)
        d, _, _ = datasets.synthetic_classification(**args)
        assert d[0] is c[0]
        np.testing.assert_array_equal(np.asarray(a[0]),
                                      np.asarray(c[0]))
        datasets._synth_cache.clear()

    def test_release_device_state_drops_buffers(self):
        """bench.py relies on this to fit two workflows' HBM on one
        chip: after release, the runner and its units hold no device
        arrays and a later run() rebuilds them."""
        w = build_mlp(streaming=True)
        w.initialize(device=JaxDevice(platform="cpu"))
        w.loader.run()
        w.fused.run()
        assert w.fused._params is not None
        w.fused.release_device_state(sync=True)
        assert w.fused._params is None and w.fused._acc is None
        assert not w.fused._inflight
        for f in w.forwards:
            assert f.output.devmem is None
        # the runner recovers: next firing re-uploads the synced host
        # params and keeps training from where it stopped
        before = {f.name: np.asarray(
            f.param_vectors()["weights"].mem).copy()
            for f in w.forwards}
        w.loader.run()
        w.fused.run()
        assert w.fused._params is not None
        after = {n: np.asarray(w.fused._params[n]["weights"])
                 for n in before}
        for n in before:  # params moved (training continued) ...
            assert np.abs(after[n] - before[n]).max() > 0
            # ... from the SYNCED values, not a re-init (SGD step is
            # small; re-init would differ by O(weight scale))
            assert np.abs(after[n] - before[n]).max() < 0.2


class TestTransferAccounting:
    def test_stream_transfer_seconds_accumulates_and_pickles(self):
        """bench.py's primary streaming-efficiency metric depends on
        FusedStepRunner.stream_transfer_seconds — it must accumulate
        only in streaming mode and default to 0.0 across snapshots."""
        ws = build_mlp(streaming=True)
        ws.initialize(device=JaxDevice(platform="cpu"))
        assert ws.fused.stream_transfer_seconds == 0.0
        ws.run()
        assert ws.fused.stream_transfer_seconds > 0.0

        wr = build_mlp()
        wr.initialize(device=JaxDevice(platform="cpu"))
        wr.run()
        assert wr.fused.stream_transfer_seconds == 0.0  # resident path

        # snapshot round-trip: the counter is plain state; pre-field
        # snapshots default it (fused.__setstate__)
        import pickle
        state = pickle.loads(pickle.dumps(ws.fused.__getstate__()))
        state.pop("stream_transfer_seconds", None)
        ws.fused.__dict__.pop("stream_transfer_seconds", None)
        ws.fused.__setstate__(state)
        assert ws.fused.stream_transfer_seconds == 0.0
