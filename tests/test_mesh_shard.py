"""Lattice (ISSUE 15): mesh-sharded residency and population
execution — capacity scales with the mesh instead of replicating it.

Pins, on the suite's 8-virtual-device CPU mesh:

- the accounting fix: ``MeshJaxDevice.put`` (replicated) charges N x
  bytes against ``h2d_bytes``; ``put_sharded`` charges the padded
  total once (= total/N per device), and the device store really
  holds 1/N rows per device;
- **f32-EXACT parity** of sharded vs unsharded (replicated) residency
  for resident fused training — the shard_map local-gather + psum
  assembly sums each row with N-1 exact zeros, so the placement
  cannot change a single bit of the trajectory;
- the residency decision: a dataset over ONE device's budget goes
  row-sharded RESIDENT on a mesh (it used to degrade to host
  streaming), still streams when even total/N does not fit, and
  non-divisible row counts ride the padded tile tail;
- **f32-EXACT parity** of member-sharded vs unsharded GA cohorts
  (members are embarrassingly parallel — P/N-per-device placement
  must not change per-member math), including a cohort smaller than
  the mesh (pure padding) and the ``_hbm_cohort_cap`` x N unlock;
- the EnsembleEvalEngine row-sharded ``attach_dataset`` variant
  scoring bit-identically to the replicated attach.
"""

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import JaxDevice
from veles_tpu.datasets import synthetic_classification
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow
from veles_tpu.parallel import (DataParallel, MeshJaxDevice, make_mesh,
                                padded_rows)

N_TRAIN, N_VALID = 480, 101          # 581 total — NOT divisible by 8
SAMPLE = (12, 12, 1)
TOTAL_BYTES = (N_TRAIN + N_VALID) * int(np.prod(SAMPLE)) * 4


def build_workflow(mb=48, max_epochs=2, momentum=0.9, **loader_kw):
    prng.seed_all(777)
    train, valid, _ = synthetic_classification(
        N_TRAIN, N_VALID, SAMPLE, n_classes=10, seed=42)
    gd = {"learning_rate": 0.1, "weight_decay": 0.0001,
          "gradient_moment": momentum}
    return StandardWorkflow(
        loader_factory=lambda w: ArrayLoader(
            w, train=train, valid=valid, minibatch_size=mb,
            name="loader", **loader_kw),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 32},
             "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 10},
             "<-": gd},
        ],
        decision_config={"max_epochs": max_epochs},
        name="mesh_shard_test")


def run_mesh(n=8, **loader_kw):
    """One mesh training run -> (history, final host params)."""
    w = build_workflow(**loader_kw)
    dp = DataParallel(w, n)
    w.initialize(device=dp.install())
    w.run()
    params = {f.name: {k: np.asarray(v)
                       for k, v in w.fused._params[f.name].items()}
              for f in w.forwards}
    hist = list(w.decision.history)
    shard = bool(w.loader.shard_resident)
    stream = bool(w.fused.streaming)
    devmem = w.loader.original_data.devmem
    w.stop()
    return hist, params, shard, stream, devmem


class TestMeshAccounting:
    def test_replicated_put_charges_n_copies(self):
        """The PR-15 accounting fix: an 8-device replicated upload
        physically lands 8 copies and must charge 8x (it charged 1x
        while burning N x HBM)."""
        dev = MeshJaxDevice(make_mesh(8))
        base = dev.h2d_bytes
        dev.put(np.zeros((10, 10), np.float32))
        assert dev.h2d_bytes - base == 400 * 8

    def test_sharded_put_charges_total_over_n_per_device(self):
        dev = MeshJaxDevice(make_mesh(8))
        base = dev.h2d_bytes
        buf = dev.put_sharded(np.zeros((10, 7), np.float32))
        # 10 rows pad to 16 (2 per device); charge = padded total once
        assert buf.shape[0] == 16
        assert dev.h2d_bytes - base == 16 * 7 * 4
        per_dev = {s.data.nbytes for s in buf.addressable_shards}
        assert per_dev == {2 * 7 * 4}
        assert not buf.is_fully_replicated

    def test_sharded_put_preserves_dtype(self):
        """uint8 quantized datasets must shard at 1 byte/element."""
        dev = MeshJaxDevice(make_mesh(8))
        buf = dev.put_sharded(np.zeros((16, 4), np.uint8))
        assert np.dtype(buf.dtype) == np.uint8
        assert {s.data.nbytes for s in buf.addressable_shards} == {8}

    def test_padded_rows(self):
        assert padded_rows(581, 8) == 584
        assert padded_rows(16, 8) == 16
        assert padded_rows(1, 8) == 8


class TestShardedResidencyParity:
    def test_sharded_training_is_f32_exact_vs_replicated(self):
        """THE Lattice pin: row-sharded residency must reproduce the
        replicated-residency mesh trajectory BITWISE — same batch
        sharding, same gradient psum, the gather assembles each row
        as value + (N-1) exact zeros.  Non-divisible row count (581)
        exercises the padded tile tail throughout."""
        h_rep, p_rep, shard_rep, _, dev_rep = run_mesh(
            mesh_shard="never")
        h_sh, p_sh, shard_sh, stream_sh, dev_sh = run_mesh(
            mesh_shard="always")
        assert not shard_rep and shard_sh and not stream_sh
        assert dev_rep.is_fully_replicated
        assert not dev_sh.is_fully_replicated
        assert len(h_rep) == len(h_sh) == 4
        for a, b in zip(h_rep, h_sh):
            assert a["n_err"] == b["n_err"], (a, b)
            assert a["loss"] == b["loss"], (a, b)
        for fn in p_rep:
            for k in p_rep[fn]:
                assert np.array_equal(p_rep[fn][k], p_sh[fn][k]), \
                    (fn, k)

    def test_per_device_bytes_shrink_by_n(self):
        _, _, _, _, dev_sh = run_mesh(mesh_shard="always")
        per_dev = max(s.data.nbytes for s in dev_sh.addressable_shards)
        # <= total/8 + one padded row tile
        tile = padded_rows(N_TRAIN + N_VALID, 8) // 8
        assert per_dev == tile * int(np.prod(SAMPLE)) * 4
        assert per_dev <= TOTAL_BYTES // 8 + \
            int(np.prod(SAMPLE)) * 4


class TestResidencyDecision:
    BUDGET = TOTAL_BYTES // 2      # over ONE device, fits at /8

    def test_over_one_device_budget_goes_sharded_resident(self):
        """The capacity unlock: this dataset/budget pair DEGRADED TO
        STREAMING before Lattice; on the mesh it now goes resident
        row-sharded, f32-exact."""
        w = build_workflow(max_resident_bytes=self.BUDGET)
        dp = DataParallel(w, 8)
        w.initialize(device=dp.install())
        assert w.loader.shard_resident
        assert w.loader.device_resident
        assert not w.fused.streaming and w.fused.data_sharded
        w.stop()

    def test_same_budget_single_device_still_streams(self):
        w = build_workflow(max_resident_bytes=self.BUDGET)
        w.initialize(device=JaxDevice(platform="cpu"))
        assert not w.loader.device_resident and w.fused.streaming
        w.stop()

    def test_over_budget_sharded_run_matches_unsharded_oracle(self):
        """Acceptance: the over-one-device-budget dataset trains
        resident on the mesh with f32-exact parity to the unsharded
        (replicated-residency) oracle."""
        h_rep, p_rep, _, _, _ = run_mesh(mesh_shard="never")
        h_sh, p_sh, shard, stream, _ = run_mesh(
            max_resident_bytes=self.BUDGET)   # auto mode decides
        assert shard and not stream
        for a, b in zip(h_rep, h_sh):
            assert a["n_err"] == b["n_err"] and a["loss"] == b["loss"]
        for fn in p_rep:
            for k in p_rep[fn]:
                assert np.array_equal(p_rep[fn][k], p_sh[fn][k])

    def test_under_sharded_budget_still_streams(self):
        w = build_workflow(max_resident_bytes=TOTAL_BYTES // 64)
        dp = DataParallel(w, 8)
        w.initialize(device=dp.install())
        assert not w.loader.shard_resident
        assert not w.loader.device_resident and w.fused.streaming
        w.stop()

    def test_never_mode_keeps_replication(self):
        w = build_workflow(mesh_shard="never")
        dp = DataParallel(w, 8)
        w.initialize(device=dp.install())
        assert not w.loader.shard_resident
        assert w.loader.original_data.devmem.is_fully_replicated
        w.stop()


class TestMemberShardedCohort:
    """Member-sharded PopulationTrainEngine: P/N members per device,
    f32-exact vs the unsharded engine (the existing engine is itself
    parity-pinned against per-genome oracles in test_ga_cohort)."""

    def build(self, lr, epochs=4, fail=1):
        from veles_tpu.models import wine

        class FL:
            workflow = None

        prng._streams.clear()
        prng.seed_all(1234)
        layers = [
            {"type": "all2all_tanh", "->": {"output_sample_shape": 8},
             "<-": {"learning_rate": lr, "weight_decay": 0.001,
                    "gradient_moment": 0.9}},
            {"type": "softmax", "->": {"output_sample_shape": 3},
             "<-": {"learning_rate": lr, "gradient_moment": 0.9}},
        ]
        w = wine.create_workflow(
            FL(), layers=layers,
            decision={"max_epochs": epochs, "fail_iterations": fail})
        w.initialize(device=JaxDevice(platform="cpu"))
        return w

    def cohort(self, lrs):
        rates = np.asarray([[[lr, lr], [lr, lr]] for lr in lrs],
                           np.float32)
        decays = np.asarray([[[0.001, 0.0], [0.0, 0.0]]] * len(lrs),
                            np.float32)
        return rates, decays

    def run_cohort(self, lrs, mesh=None):
        from veles_tpu.ops.fused import PopulationTrainEngine
        w = self.build(lrs[0])
        rates, decays = self.cohort(lrs)
        engine = PopulationTrainEngine(w, rates, decays, mesh=mesh)
        fits = np.asarray(engine.run())
        sharded = engine.member_sharded
        stacked = engine._n_stacked
        engine.release()
        w.stop()
        return fits, sharded, stacked

    def test_member_sharded_is_f32_exact_non_divisible(self):
        """3 members over 8 devices: pure padding cohort — fitness
        must match the unsharded engine bitwise."""
        lrs = [0.3, 0.05, 0.8]
        f_un, sh_un, _ = self.run_cohort(lrs)
        f_sh, sh_sh, stacked = self.run_cohort(lrs, mesh=make_mesh(8))
        assert not sh_un and sh_sh
        assert stacked == 8                 # padded to one full tile
        assert f_sh.shape == (3,)
        assert np.array_equal(f_un, f_sh), (f_un, f_sh)

    def test_member_sharded_wide_cohort_f32_exact(self):
        """P > N with a remainder (11 over 8 -> 16 stacked)."""
        lrs = [0.05 + 0.06 * i for i in range(11)]
        f_un, _, _ = self.run_cohort(lrs)
        f_sh, sharded, stacked = self.run_cohort(lrs, mesh=make_mesh(8))
        assert sharded and stacked == 16
        assert np.array_equal(f_un, f_sh), (f_un, f_sh)

    def test_knob_never_disables_member_sharding(self, monkeypatch):
        from veles_tpu.ops.fused import PopulationTrainEngine
        monkeypatch.setenv("VELES_MESH_SHARD_MEMBERS", "never")
        w = self.build(0.3)
        rates, decays = self.cohort([0.3, 0.5])
        engine = PopulationTrainEngine(w, rates, decays,
                                       mesh=make_mesh(8))
        assert not engine.member_sharded
        engine.release()
        w.stop()

    def test_hbm_cohort_cap_scales_with_mesh(self, monkeypatch):
        """Acceptance: >=4x the members admitted at the same
        per-device budget."""
        from veles_tpu.genetics.worker import _hbm_cohort_cap
        monkeypatch.setenv("VELES_TPU_GA_HBM_BUDGET", str(1 << 20))
        w = self.build(0.3)
        cap1 = _hbm_cohort_cap(w, 0, n_devices=1)
        cap8 = _hbm_cohort_cap(w, 0, n_devices=8)
        assert cap8 >= 4 * cap1, (cap1, cap8)
        monkeypatch.setenv("VELES_MESH_SHARD_MEMBERS", "never")
        assert _hbm_cohort_cap(w, 0, n_devices=8) == cap1
        w.stop()


class TestEnsembleShardedAttach:
    def test_sharded_attach_scores_exactly_like_replicated(self):
        from veles_tpu.ops.fused import EnsembleEvalEngine

        prng.seed_all(7)
        train, valid, _ = synthetic_classification(
            200, 77, (6, 6, 1), n_classes=5, seed=3)
        w = StandardWorkflow(
            loader_factory=lambda wf: ArrayLoader(
                wf, train=train, valid=valid, minibatch_size=20,
                name="loader"),
            layers=[
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 16},
                 "<-": {"learning_rate": 0.1}},
                {"type": "softmax", "->": {"output_sample_shape": 5},
                 "<-": {"learning_rate": 0.1}},
            ],
            decision_config={"max_epochs": 1}, name="ens")
        w.initialize(device=JaxDevice(platform="cpu"))
        w.run()
        params = {f.name: {k: np.asarray(v)
                           for k, v in f.gather_params().items()}
                  for f in w.forwards}
        x, y = valid
        dev = MeshJaxDevice(make_mesh(8))
        eng = EnsembleEvalEngine(list(w.forwards), [params, params],
                                 dev)
        eng.attach_dataset(x, y, shard=False)
        e_rep = eng.error_pct_resident()
        p_rep = eng.predict_proba_resident(np.arange(10))
        eng.attach_dataset(x, y, shard=True)
        assert eng._dataset_sharded
        assert not eng._dataset.is_fully_replicated
        # 77 rows -> 80 padded, 10 per device
        assert eng._dataset.shape[0] == 80
        e_sh = eng.error_pct_resident()
        p_sh = eng.predict_proba_resident(np.arange(10))
        assert e_rep == e_sh
        assert np.array_equal(p_rep, p_sh)
        eng.release()
        w.stop()

    def test_oversize_split_attaches_sharded_under_auto(self,
                                                        monkeypatch):
        """attach_dataset's auto mode mirrors the loader decision: a
        split over one device's budget shards instead of failing the
        budget."""
        from veles_tpu.ops.fused import EnsembleEvalEngine

        prng.seed_all(7)
        train, valid, _ = synthetic_classification(
            64, 40, (6, 6, 1), n_classes=5, seed=3)
        w = StandardWorkflow(
            loader_factory=lambda wf: ArrayLoader(
                wf, train=train, valid=valid, minibatch_size=16,
                name="loader"),
            layers=[{"type": "softmax",
                     "->": {"output_sample_shape": 5},
                     "<-": {"learning_rate": 0.1}}],
            decision_config={"max_epochs": 1}, name="ens2")
        w.initialize(device=JaxDevice(platform="cpu"))
        w.run()
        params = {f.name: {k: np.asarray(v)
                           for k, v in f.gather_params().items()}
                  for f in w.forwards}
        x, y = valid
        monkeypatch.setenv("VELES_MAX_RESIDENT_BYTES",
                           str(x.nbytes // 2))
        dev = MeshJaxDevice(make_mesh(8))
        eng = EnsembleEvalEngine(list(w.forwards), [params], dev)
        eng.attach_dataset(x, y)    # auto
        assert eng._dataset_sharded
        assert eng.error_pct_resident() >= 0.0
        eng.release()
        w.stop()
