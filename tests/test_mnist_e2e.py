"""End-to-end MNIST All2All slice (SURVEY.md §7 phase 3, BASELINE
config #1): loader -> All2AllTanh -> All2AllSoftmax -> evaluator -> GD
chain -> decision loop, on both backends, numpy (eager graph) vs jax
(fused single-step) agreement."""

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import JaxDevice, NumpyDevice
from veles_tpu.datasets import synthetic_classification
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow


def build_workflow(max_epochs=3, mb=50, n_train=500, n_valid=200,
                   momentum=0.0):
    prng.seed_all(777)
    train, valid, _ = synthetic_classification(
        n_train, n_valid, (28, 28, 1), n_classes=10, seed=42)
    loader_factory = lambda w: ArrayLoader(  # noqa: E731
        w, train=train, valid=valid, minibatch_size=mb, name="loader")
    gd = {"learning_rate": 0.1, "weight_decay": 0.0001,
          "gradient_moment": momentum}
    w = StandardWorkflow(
        loader_factory=loader_factory,
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 64},
             "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 10},
             "<-": gd},
        ],
        decision_config={"max_epochs": max_epochs},
        name="mnist_test")
    return w


def run_backend(device, **kwargs):
    w = build_workflow(**kwargs)
    w.initialize(device=device)
    w.run()
    return w


class TestMnistEndToEnd:
    def test_numpy_learns(self):
        w = run_backend(NumpyDevice(), max_epochs=8)
        # error must drop well below chance (90%)
        assert w.decision.epoch_error_pct[1] < 30.0, \
            w.decision.epoch_error_pct
        assert w.decision.min_valid_epoch >= 0

    def test_fused_jax_learns(self):
        w = run_backend(JaxDevice(platform="cpu"), max_epochs=8)
        assert w.decision.epoch_error_pct[1] < 30.0, \
            w.decision.epoch_error_pct

    def test_backends_agree(self):
        """Same seed => identical init; trajectories must match
        closely (fp reassociation differences only)."""
        w_np = run_backend(NumpyDevice(), max_epochs=2)
        w_jx = run_backend(JaxDevice(platform="cpu"), max_epochs=2)
        hist_np = [h for h in w_np.decision.history
                   if h["class"] == "validation"]
        hist_jx = [h for h in w_jx.decision.history
                   if h["class"] == "validation"]
        assert len(hist_np) == len(hist_jx)
        for a, b in zip(hist_np, hist_jx):
            assert abs(a["loss"] - b["loss"]) < 5e-3, (a, b)
            assert abs(a["n_err"] - b["n_err"]) <= 3, (a, b)

    def test_momentum_backends_agree(self):
        w_np = run_backend(NumpyDevice(), max_epochs=2, momentum=0.9)
        w_jx = run_backend(JaxDevice(platform="cpu"), max_epochs=2,
                           momentum=0.9)
        a = w_np.decision.epoch_loss[1]
        b = w_jx.decision.epoch_loss[1]
        assert abs(a - b) < 1e-2, (a, b)

    def test_eager_jax_matches_fused(self):
        """Per-unit jax graph (fused=False) equals the fused step."""
        dev = JaxDevice(platform="cpu")
        w1 = build_workflow(max_epochs=1)
        w1.initialize(device=dev, fused=False)
        w1.run()
        w2 = build_workflow(max_epochs=1)
        w2.initialize(device=dev, fused=True)
        w2.run()
        a = w1.decision.epoch_loss[1]
        b = w2.decision.epoch_loss[1]
        assert abs(a - b) < 1e-4, (a, b)

    def test_weights_update_and_readable(self):
        w = run_backend(JaxDevice(platform="cpu"), max_epochs=1)
        wts = w.forwards[0].weights.map_read()
        assert np.isfinite(wts).all()
        # initial weights came from the 'weights' stream; after one
        # epoch they must have moved
        prng.seed_all(777)
        w2 = build_workflow()
        w2.initialize(device=NumpyDevice())
        assert not np.allclose(wts, w2.forwards[0].weights.mem)

    def test_decision_history_structure(self):
        w = run_backend(NumpyDevice(), max_epochs=2)
        classes = [h["class"] for h in w.decision.history]
        assert classes == ["validation", "train"] * 2
