"""The five BASELINE.json benchmark configs, built small and run for a
couple of epochs on the jax-CPU backend (+ numpy spot check)."""

import numpy as np
import pytest

from veles_tpu.backends import JaxDevice, NumpyDevice
from veles_tpu.launcher import Launcher
from veles_tpu.models import (alexnet, cifar10, kohonen, mnist, mnist7,
                              mnist_ae, wine)


class FakeLauncher:
    """Just enough of Launcher for create_workflow()."""
    workflow = None


@pytest.fixture(scope="module")
def dev():
    return JaxDevice(platform="cpu")


def small(cfg_overrides):
    fl = FakeLauncher()
    return fl, cfg_overrides


class TestMnist:
    def test_runs_and_learns_jax(self, dev):
        fl = FakeLauncher()
        w = mnist.create_workflow(
            fl, loader={"minibatch_size": 50, "n_train": 400,
                        "n_valid": 120},
            decision={"max_epochs": 6})
        w.initialize(device=dev)
        w.run()
        assert w.decision.epoch_error_pct[1] < 50.0, \
            w.decision.epoch_error_pct

    def test_runs_numpy(self):
        fl = FakeLauncher()
        w = mnist.create_workflow(
            fl, loader={"minibatch_size": 50, "n_train": 200,
                        "n_valid": 60},
            decision={"max_epochs": 2})
        w.initialize(device=NumpyDevice())
        w.run()
        assert len(w.decision.history) == 4


class TestMnist7:
    def test_conv_net_learns(self, dev):
        fl = FakeLauncher()
        w = mnist7.create_workflow(
            fl, loader={"minibatch_size": 25, "n_train": 200,
                        "n_valid": 50},
            decision={"max_epochs": 4})
        w.initialize(device=dev)
        w.run()
        first = w.decision.history[0]["loss"]
        last = [h for h in w.decision.history
                if h["class"] == "validation"][-1]["loss"]
        assert last < first, (first, last)


class TestCifar10:
    def test_runs_with_lr_policy(self, dev):
        fl = FakeLauncher()
        w = cifar10.create_workflow(
            fl, loader={"minibatch_size": 25, "n_train": 150,
                        "n_valid": 50},
            decision={"max_epochs": 3})
        w.initialize(device=dev)
        w.run()
        assert w.lr_adjust is not None
        # inverse policy must have decayed the lr below base
        assert w.gds[0].learning_rate < 0.02
        assert all(np.isfinite(h["loss"]) for h in w.decision.history)


class TestAlexNet:
    def test_tiny_alexnet_steps(self, dev):
        """Full 15-layer AlexNet topology at 227x227 is too slow for a
        unit test on 1 CPU core; run the real layer stack with a
        reduced input (99x99) and few samples to prove the topology
        compiles and trains end-to-end."""
        fl = FakeLauncher()
        w = alexnet.create_workflow(
            fl,
            loader={"minibatch_size": 8, "n_train": 16, "n_valid": 8,
                    "shape": (99, 99, 3), "n_classes": 10,
                    "noise": 0.5, "max_shift": 4, "seed": 1},
            n_classes=10,
            decision={"max_epochs": 1})
        w.initialize(device=dev)
        w.run()
        assert all(np.isfinite(h["loss"]) for h in w.decision.history)
        # 15 layers: 5 conv + 2 LRN + 3 pool + 3 fc + 2 dropout
        assert len(w.forwards) == 15


class TestMnistAE:
    def test_autoencoder_reconstruction_improves(self, dev):
        fl = FakeLauncher()
        w = mnist_ae.create_workflow(
            fl, loader={"minibatch_size": 25, "n_train": 200,
                        "n_valid": 50},
            decision={"max_epochs": 4})
        w.initialize(device=dev)
        w.run()
        val = [h["loss"] for h in w.decision.history
               if h["class"] == "validation"]
        assert val[-1] < val[0], val


class TestKohonen:
    def test_som_quantization_error_drops(self, dev):
        fl = FakeLauncher()
        w = kohonen.create_workflow(
            fl, loader={"minibatch_size": 50, "n_train": 500,
                        "n_valid": 0, "shape": (8, 8, 1),
                        "n_classes": 10, "seed": 888},
            decision={"max_epochs": 8})
        w.initialize(device=dev)
        w.run()
        tr = [h["loss"] for h in w.decision.history
              if h["class"] == "train"]
        assert tr[-1] < tr[0] * 0.9, tr

    def test_som_numpy_matches_jax(self, dev):
        from veles_tpu import prng
        results = []
        for device in (NumpyDevice(), dev):
            prng.seed_all(99)
            fl = FakeLauncher()
            w = kohonen.create_workflow(
                fl, loader={"minibatch_size": 50, "n_train": 200,
                            "n_valid": 0, "shape": (8, 8, 1),
                            "n_classes": 10, "seed": 888},
                decision={"max_epochs": 2})
            w.initialize(device=device)
            w.run()
            results.append(w.forward.weights.map_read().copy())
        np.testing.assert_allclose(results[0], results[1],
                                   rtol=1e-4, atol=1e-5)


class TestWine:
    def test_runs_and_learns_jax(self, dev):
        fl = FakeLauncher()
        w = wine.create_workflow(fl)
        w.initialize(device=dev)
        w.run()
        assert w.decision.epoch_error_pct[1] < 30.0, \
            w.decision.epoch_error_pct

    def test_runs_numpy(self):
        fl = FakeLauncher()
        w = wine.create_workflow(fl, decision={"max_epochs": 2})
        w.initialize(device=NumpyDevice())
        w.run()
        assert len(w.decision.history) == 4
