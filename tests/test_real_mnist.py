"""Real-file MNIST path end-to-end (round-1 VERDICT missing #3): IDX
files written offline -> loader picks them over the synthetic
stand-in -> training runs."""

import numpy as np
import pytest

from veles_tpu import datasets, prng
from veles_tpu.backends import JaxDevice
from veles_tpu.config import root


@pytest.fixture
def idx_dir(tmp_path):
    base = datasets.generate_mnist_idx(str(tmp_path / "mnist"),
                                       n_train=512, n_test=128)
    # point the data dir at tmp (try_load_real_mnist reads
    # <data_dir>/mnist)
    old = root.common.get("data_dir") if "common" in root else None
    root.common.data_dir = str(tmp_path)
    yield base
    root.common.data_dir = old


class TestIdxRoundtrip:
    def test_write_read(self, tmp_path):
        arr = (np.random.default_rng(1).random((7, 5, 4)) * 255) \
            .astype(np.uint8)
        p = str(tmp_path / "a.idx")
        datasets.write_idx(p, arr)
        back = datasets._read_idx(p)
        np.testing.assert_array_equal(arr, back)

    def test_generator_idempotent(self, tmp_path):
        base = datasets.generate_mnist_idx(str(tmp_path), n_train=16,
                                           n_test=8)
        import os
        mtimes = {f: os.path.getmtime(os.path.join(base, f))
                  for f in os.listdir(base)}
        base2 = datasets.generate_mnist_idx(str(tmp_path), n_train=32,
                                            n_test=8)
        assert base2 == base
        for f, t in mtimes.items():
            assert os.path.getmtime(os.path.join(base, f)) == t

    def test_partial_genuine_set_never_overwritten(self, tmp_path):
        """A partial pre-placed IDX set must raise, not be silently
        replaced with synthetic data (code-review finding)."""
        import os
        genuine = (np.zeros((4, 28, 28), np.uint8) + 7)
        datasets.write_idx(
            str(tmp_path / "train-images-idx3-ubyte"), genuine)
        with pytest.raises(FileExistsError, match="partial"):
            datasets.generate_mnist_idx(str(tmp_path), n_train=16,
                                        n_test=8)
        # the genuine file survived untouched
        back = datasets._read_idx(
            str(tmp_path / "train-images-idx3-ubyte"))
        np.testing.assert_array_equal(back, genuine)
        assert not os.path.exists(tmp_path / "t10k-labels-idx1-ubyte")


class TestRealFileLoading:
    def test_loader_prefers_real_files(self, idx_dir):
        real = datasets.try_load_real_mnist()
        assert real is not None
        (tx, ty), (vx, vy) = real
        assert tx.shape == (512, 28, 28, 1) and vx.shape[0] == 128
        assert tx.dtype == np.float32 and 0.0 <= tx.min() \
            and tx.max() <= 1.0

        from veles_tpu.loader.synthetic import MnistLoader
        from veles_tpu.workflow import Workflow
        w = Workflow(name="t")
        ld = MnistLoader(w, name="loader", minibatch_size=64)
        ld.initialize(device=None)
        # real sizes, not the requested synthetic defaults
        assert ld.class_lengths == [0, 128, 512]

    def test_trains_on_real_files(self, idx_dir):
        prng.seed_all(4321)
        from veles_tpu.models import mnist

        class FL:
            workflow = None
        w = mnist.create_workflow(
            FL(), loader={"minibatch_size": 64},
            decision={"max_epochs": 4})
        w.initialize(device=JaxDevice(platform="cpu"))
        assert w.loader.class_lengths == [0, 128, 512]
        w.run()
        hist = [h for h in w.decision.history
                if h["class"] == "validation"]
        assert len(hist) == 4
        assert hist[-1]["error_pct"] < hist[0]["error_pct"] or \
            hist[-1]["error_pct"] < 30.0
