"""Sightline telemetry core (ISSUE 7 acceptance).

- registry semantics: get-or-create identity, counters/gauges, the
  enable switch, in-place reset;
- histogram quantile accuracy against numpy on known distributions
  (the log-bucket + geometric-interpolation estimator), merge
  equivalence across snapshots;
- span nesting (thread-local stack, histogram feed, journal lineage);
- atomic snapshot writes under a concurrent-writer torture loop — a
  reader must never parse a torn file (the PR-6 tempfile+rename
  discipline, applied to metrics);
- parent merge of an evaluator child's snapshot in a REAL
  ``worker.py --serve`` round-trip, rendered by scripts/obs_report.py;
- the per-generation hang-descriptor reset in ChipEvaluatorPool
  (stale ``last_hang_*`` must not leak into the next generation);
- the fused runner's per-dispatch telemetry: first-call compile split,
  steady-state histograms, wire-byte property backed by the registry.
"""

import json
import glob
import os
import sys
import threading
import time

import numpy as np
import pytest

from veles_tpu import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- registry semantics ------------------------------------------------

class TestRegistry:
    def test_get_or_create_identity(self):
        c = telemetry.counter("t.c")
        assert telemetry.counter("t.c") is c
        h = telemetry.histogram("t.h")
        assert telemetry.histogram("t.h") is h
        g = telemetry.gauge("t.g")
        assert telemetry.gauge("t.g") is g

    def test_counter_and_gauge(self):
        c = telemetry.counter("t.c2")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        g = telemetry.gauge("t.g2")
        assert g.value is None
        g.set(7)
        g.set(3)
        assert g.value == 3

    def test_disabled_is_noop(self):
        telemetry.set_enabled(False)
        try:
            telemetry.counter("t.off").inc()
            telemetry.gauge("t.off").set(1)
            telemetry.histogram("t.off").record(1.0)
            telemetry.event("t.off_event")
            with telemetry.span("t.off_span"):
                assert telemetry.span_stack() == []
        finally:
            telemetry.set_enabled(True)
        assert telemetry.counter("t.off").value == 0
        assert telemetry.gauge("t.off").value is None
        assert telemetry.histogram("t.off").count == 0
        assert telemetry.recent_events("t.off_event") == []
        assert telemetry.histogram("t.off_span").count == 0

    def test_reset_zeroes_in_place(self):
        c = telemetry.counter("t.r")
        h = telemetry.histogram("t.rh")
        c.inc(9)
        h.record(1.0)
        telemetry.reset()
        # object identity survives: call sites holding a reference
        # stay wired to the registry after a reset
        assert telemetry.counter("t.r") is c
        assert c.value == 0
        assert h.count == 0
        c.inc()
        assert telemetry.counter("t.r").value == 1

    def test_snapshot_shape_and_merge(self):
        telemetry.counter("t.s").inc(4)
        telemetry.gauge("t.sg").set(2.0)
        telemetry.histogram("t.sh").record(0.5)
        snap = telemetry.snapshot()
        assert snap["counters"]["t.s"] == 4
        assert snap["gauges"]["t.sg"] == 2.0
        assert snap["histograms"]["t.sh"]["count"] == 1
        assert "p50" in snap["histograms"]["t.sh"]
        # merging the snapshot back in: counters add, histograms add,
        # gauges only fill where absent
        telemetry.gauge("t.sg").set(9.0)
        telemetry.merge_snapshot(snap)
        assert telemetry.counter("t.s").value == 8
        assert telemetry.histogram("t.sh").count == 2
        assert telemetry.gauge("t.sg").value == 9.0    # kept local
        assert telemetry.gauge("t.only_in_snap").value is None


# -- histogram quantiles ----------------------------------------------

class TestHistogramQuantiles:
    @pytest.mark.parametrize("dist,kw", [
        ("lognormal", {"mean": 0.0, "sigma": 1.0}),
        ("uniform", {"low": 0.001, "high": 10.0}),
        ("exponential", {"scale": 0.05}),
    ])
    def test_quantiles_match_numpy(self, dist, kw):
        rng = np.random.default_rng(7)
        xs = getattr(rng, dist)(size=20000, **kw)
        h = telemetry.Histogram(dist)
        for x in xs:
            h.record(x)
        for q in (0.5, 0.9, 0.99):
            got = h.quantile(q)
            want = float(np.quantile(xs, q))
            assert abs(got - want) / want < 0.08, (q, got, want)
        assert h.count == len(xs)
        assert h.min == xs.min() and h.max == xs.max()
        assert abs(h.sum - xs.sum()) < 1e-6 * abs(xs.sum())

    def test_merge_equals_combined_distribution(self):
        rng = np.random.default_rng(3)
        a = rng.lognormal(0, 0.5, 5000)
        b = rng.lognormal(1.0, 0.5, 5000)
        ha, hb, hall = (telemetry.Histogram(n) for n in "ab3")
        for x in a:
            ha.record(x)
        for x in b:
            hb.record(x)
        for x in np.concatenate([a, b]):
            hall.record(x)
        merged = telemetry.Histogram("m")
        merged.merge_dict(ha.to_dict())
        merged.merge_dict(hb.to_dict())
        assert merged.count == hall.count
        for q in (0.5, 0.9, 0.99):
            assert merged.quantile(q) == pytest.approx(
                hall.quantile(q), rel=1e-12)

    def test_edge_cases(self):
        h = telemetry.Histogram("e")
        assert h.quantile(0.5) is None
        h.record(0.0)       # underflow bucket; min stays exact
        h.record(1e12)      # overflow bucket; max stays exact
        assert h.quantile(0.0) == 0.0
        assert h.quantile(1.0) == 1e12
        assert h.count == 2

    def test_delta_quantile_tracks_the_window_not_history(self):
        """The sentinel's windowed read: the quantile of ONLY the
        samples since the snapshot — a load shift must show up
        immediately even against a long contrary history."""
        rng = np.random.default_rng(11)
        h = telemetry.Histogram("w")
        fast = rng.lognormal(np.log(0.005), 0.3, 10000)   # ~5ms era
        for x in fast:
            h.record(x)
        base = h.snapshot_buckets()
        slow = rng.lognormal(np.log(0.050), 0.3, 2000)    # ~50ms era
        for x in slow:
            h.record(x)
        got = h.delta_quantile(base, 0.95, min_count=20)
        want = float(np.quantile(slow, 0.95))
        # the windowed p95 reads the NEW era...
        assert abs(got - want) / want < 0.08, (got, want)
        # ...while the cumulative p95 is still dragged down by the
        # 10k-sample fast history (the lag the window exists to fix)
        assert h.quantile(0.95) < 0.8 * want
        # an empty/thin window reports None instead of a stale number
        base2 = h.snapshot_buckets()
        assert h.delta_quantile(base2, 0.95, min_count=20) is None
        for _ in range(5):
            h.record(0.01)
        assert h.delta_quantile(base2, 0.95, min_count=20) is None
        # one log bucket is ~7.5% wide and the windowed path has no
        # observed-min/max clamp to tighten it
        assert h.delta_quantile(base2, 0.95, min_count=5) \
            == pytest.approx(0.01, rel=0.1)


# -- spans -------------------------------------------------------------

class TestSpans:
    def test_nesting_and_histogram_feed(self):
        with telemetry.span("t.outer", journal=True):
            assert telemetry.span_stack() == ["t.outer"]
            with telemetry.span("t.inner", journal=True):
                assert telemetry.span_stack() == ["t.outer", "t.inner"]
                time.sleep(0.01)
            assert telemetry.span_stack() == ["t.outer"]
        assert telemetry.span_stack() == []
        assert telemetry.histogram("t.inner").count == 1
        assert telemetry.histogram("t.outer").count == 1
        assert telemetry.histogram("t.inner").min >= 0.01
        # outer wholly contains inner
        assert telemetry.histogram("t.outer").min >= \
            telemetry.histogram("t.inner").min

    def test_journal_lineage(self):
        with telemetry.span("t.a", journal=True, tag="x"):
            with telemetry.span("t.b", journal=True):
                pass
        ev_b = telemetry.recent_events("t.b")[-1]
        ev_a = telemetry.recent_events("t.a")[-1]
        assert ev_b["parent"] == "t.a" and ev_b["depth"] == 1
        assert ev_a["parent"] is None and ev_a["depth"] == 0
        assert ev_a["tag"] == "x"
        assert ev_a["seconds"] >= ev_b["seconds"]


# -- snapshot persistence ---------------------------------------------

class TestSnapshotFiles:
    def test_flush_writes_parseable_snapshot(self, tmp_path):
        telemetry.configure(str(tmp_path))
        telemetry.counter("t.f").inc(3)
        telemetry.event("t.flush_probe")
        path = telemetry.flush()
        assert path and os.path.basename(path) == \
            f"metrics-{os.getpid()}.json"
        snap = json.load(open(path))
        assert snap["counters"]["t.f"] == 3
        # the journal carries the event, one JSON object per line
        jf = os.path.join(str(tmp_path),
                          f"journal-{os.getpid()}.jsonl")
        lines = [json.loads(ln) for ln in open(jf)]
        assert any(ev["event"] == "t.flush_probe" for ev in lines)

    def test_concurrent_writer_torture(self, tmp_path):
        """Writers flushing in a loop while readers parse: every read
        of the snapshot file must yield complete JSON (the atomic
        tempfile+rename contract), and the metric values must be
        internally consistent."""
        telemetry.configure(str(tmp_path))
        c = telemetry.counter("t.torture")
        path = os.path.join(str(tmp_path),
                            f"metrics-{os.getpid()}.json")
        stop = threading.Event()
        errors = []

        def writer():
            while not stop.is_set():
                c.inc()
                telemetry.histogram("t.torture_h").record(0.01)
                telemetry.flush()

        def reader():
            seen = 0
            while not stop.is_set() or seen == 0:
                if not os.path.exists(path):
                    continue
                try:
                    with open(path) as f:
                        snap = json.load(f)
                except ValueError as e:      # a torn file
                    errors.append(repr(e))
                    return
                assert "counters" in snap
                seen += 1

        threads = [threading.Thread(target=writer) for _ in range(3)] \
            + [threading.Thread(target=reader) for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors
        snap = json.load(open(path))
        assert snap["counters"]["t.torture"] > 0
        # no stray temp files survive the storm
        assert not glob.glob(os.path.join(str(tmp_path), "*.tmp"))

    def test_adopt_child_snapshot(self, tmp_path):
        telemetry.configure(str(tmp_path))
        child = {"pid": 99999, "counters": {"t.child": 7},
                 "histograms": {"t.ch": {
                     "count": 1, "sum": 0.5, "min": 0.5, "max": 0.5,
                     "buckets": {"1": 1}}}}
        cpath = os.path.join(str(tmp_path), "metrics-99999.json")
        json.dump(child, open(cpath, "w"))
        assert telemetry.adopt_child_snapshot(99999)
        assert telemetry.counter("t.child").value == 7
        assert telemetry.histogram("t.ch").count == 1
        # renamed so offline merging cannot double count it ...
        assert not os.path.exists(cpath)
        assert os.path.exists(cpath + ".merged")
        # ... and a second adopt is a no-op
        assert not telemetry.adopt_child_snapshot(99999)
        assert telemetry.counter("t.child").value == 7


# -- the per-generation hang reset (satellite) -------------------------

class _FakeProc:
    def poll(self):
        return None


class TestPoolGenerationReset:
    def test_last_hang_fields_reset_per_generation(self):
        """last_hang_kind/last_hang_wait described a hang from
        generations ago forever; evaluate_many must reset them so
        drill telemetry attributes hangs to the RIGHT generation
        (cumulative counts stay in the registry)."""
        from veles_tpu.genetics.pool import ChipEvaluatorPool
        pool = ChipEvaluatorPool(["true"], workers=1)
        pool._note_hang("heartbeat", 12.0)    # generation N's hang
        assert pool.last_hang_kind == "heartbeat"
        assert pool.hangs_detected == 1
        pool._proc = _FakeProc()              # no real evaluator

        def fake_run_jobs(jobs, fits):
            for j in jobs:
                fits[j["id"]] = 1.0
            return {j["id"] for j in jobs}

        pool._run_jobs = fake_run_jobs
        fits = pool.evaluate_many([{"x": 1.0}])
        assert fits == [1.0]
        # generation N+1 saw no hang: the descriptors are fresh ...
        assert pool.last_hang_kind is None
        assert pool.last_hang_wait is None
        # ... while the cumulative registry count is untouched
        assert pool.hangs_detected == 1

    def test_registry_carries_hang_counters(self):
        from veles_tpu.genetics.pool import ChipEvaluatorPool
        pool = ChipEvaluatorPool(["true"], workers=1)
        pool._note_hang("genome_deadline", 4.5)
        assert telemetry.counter("ga.hangs_detected").value == 1
        assert telemetry.gauge("ga.last_hang_wait").value == 4.5
        assert telemetry.recent_events("ga.hang_detected")
        # a second pool in the same process reports only its own share
        pool2 = ChipEvaluatorPool(["true"], workers=1)
        assert pool2.hangs_detected == 0
        assert pool.hangs_detected == 1


# -- fused runner telemetry -------------------------------------------

def _tiny_workflow(n_train=160, max_epochs=2):
    from veles_tpu import prng
    from veles_tpu.datasets import synthetic_classification
    from veles_tpu.loader import ArrayLoader
    from veles_tpu.ops.standard_workflow import StandardWorkflow
    prng.seed_all(1357)
    train, valid, _ = synthetic_classification(
        n_train, 40, (8, 8, 1), n_classes=4, seed=7)
    gd = {"learning_rate": 0.1}
    return StandardWorkflow(
        loader_factory=lambda w: ArrayLoader(
            w, train=train, valid=valid, minibatch_size=20,
            name="loader"),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
             "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": gd},
        ],
        decision_config={"max_epochs": max_epochs}, name="tm_wf")


class TestFusedTelemetry:
    def test_dispatch_metrics_and_compile_split(self, tmp_path):
        from veles_tpu.backends import JaxDevice
        telemetry.configure(str(tmp_path))
        w = _tiny_workflow()
        w.initialize(device=JaxDevice(platform="cpu"))
        w.run()
        w.stop()
        snap = telemetry.snapshot()
        c = snap["counters"]
        assert c["fused.dispatches"] > 0
        assert c["fused.train_images"] == w.fused.processed_images
        assert c["fused.eval_images"] == w.fused.processed_eval_images
        assert c["loader.epochs"] == 2
        # compile/execute split: the first dispatch of each kind is a
        # gauge; the steady-state histogram holds the REST and its
        # p50/p99 are finite and ordered
        g = snap["gauges"]
        assert g["fused.first_train_dispatch_seconds"] > 0
        h = snap["histograms"]["fused.train_dispatch_seconds"]
        assert h["count"] > 0
        assert 0 < h["p50"] <= h["p99"] <= h["max"]
        # the first (compile) sample is far above the steady p99 on
        # any jitted backend
        assert g["fused.first_train_dispatch_seconds"] > h["p99"]
        assert telemetry.recent_events("fused.summary")
        # the flushed snapshot renders through obs_report
        telemetry.flush()
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import obs_report
        finally:
            sys.path.pop(0)
        reg, snaps, journals, events = obs_report.load_dir(
            str(tmp_path))
        assert snaps and events
        text = obs_report.render(str(tmp_path), reg, snaps, journals,
                                 events)
        assert "fused.train_dispatch_seconds" in text
        assert "p99" in text and "fused train" in text

    def test_stream_bytes_property_backed_by_registry(self):
        from veles_tpu.backends import JaxDevice
        from veles_tpu.loader import ArrayLoader
        from veles_tpu import prng
        from veles_tpu.datasets import synthetic_classification
        from veles_tpu.ops.standard_workflow import StandardWorkflow
        prng.seed_all(1357)
        train, valid, _ = synthetic_classification(
            160, 40, (8, 8, 1), n_classes=4, seed=7)
        gd = {"learning_rate": 0.1}
        w = StandardWorkflow(
            loader_factory=lambda wf: ArrayLoader(
                wf, train=train, valid=valid, minibatch_size=20,
                name="loader", max_resident_bytes=0),
            layers=[
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 16}, "<-": gd},
                {"type": "softmax", "->": {"output_sample_shape": 4},
                 "<-": gd},
            ],
            decision_config={"max_epochs": 1}, name="tm_stream")
        w.initialize(device=JaxDevice(platform="cpu"))
        w.run()
        w.stop()
        assert w.fused.streaming
        # property and registry agree (single write site feeds both)
        assert w.fused.stream_transfer_bytes > 0
        assert telemetry.counter(
            "fused.stream_transfer_bytes").value == \
            w.fused.stream_transfer_bytes
        assert telemetry.counter(
            "fused.stream_transfer_seconds").value > 0
        # the property is read-only: the old mutation path is gone
        with pytest.raises(AttributeError):
            w.fused.stream_transfer_bytes = 0


# -- the real --serve round-trip merge --------------------------------

class TestServeChildMerge:
    def test_parent_merges_evaluator_child_snapshot(self, tmp_path,
                                                    monkeypatch):
        """A REAL chip-owning evaluator child (worker.py --serve)
        trains two genomes; its per-job telemetry (span histogram +
        the fused engine's own counters) flushes to the shared metrics
        dir and the pool folds it into the parent registry at close —
        one aggregate view for the whole GA process tree."""
        import textwrap

        from veles_tpu.genetics.pool import ChipEvaluatorPool
        mdir = tmp_path / "metrics"
        telemetry.configure(str(mdir))
        wf = tmp_path / "wf.py"
        wf.write_text(textwrap.dedent("""
            from veles_tpu.models import wine

            def run(launcher):
                launcher.create_workflow(wine.create_workflow)
                launcher.initialize()
                launcher.run()
        """))
        cfg = tmp_path / "cfg.py"
        cfg.write_text(textwrap.dedent("""
            from veles_tpu.config import root
            from veles_tpu.genetics import Tune

            root.wine.decision = {"max_epochs": 2}
            root.wine.layers = [
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 8},
                 "<-": {"learning_rate": Tune(0.3, 0.01, 1.0)}},
                {"type": "softmax", "->": {"output_sample_shape": 3},
                 "<-": {"learning_rate": 0.3}},
            ]
        """))
        lr = "wine.layers[0]['<-']['learning_rate']"
        pool = ChipEvaluatorPool(
            [sys.executable, "-m", "veles_tpu.genetics.worker",
             "--serve", str(wf), str(cfg), "-b", "cpu", "-s", "1234"],
            workers=2, timeout=600)
        with pool:
            child_pid = pool.hello["pid"]
            fits = pool.evaluate_many([{lr: 0.1}, {lr: 0.5}])
        assert all(np.isfinite(f) for f in fits), fits
        # the child's snapshot was merged and retired
        merged = os.path.join(str(mdir),
                              f"metrics-{child_pid}.json.merged")
        assert os.path.exists(merged), os.listdir(str(mdir))
        # parent registry now carries the child-side per-job record
        # AND the child's own fused-engine counters
        assert telemetry.counter("evaluator.jobs").value == 2
        assert telemetry.histogram(
            "evaluator.job_seconds").count == 2
        assert telemetry.counter("fused.dispatches").value > 0
        # per-genome distribution came from the parent's own clocking
        assert telemetry.histogram("ga.genome_seconds").count == 2
        assert telemetry.histogram(
            "ga.genome_seconds").quantile(0.99) > 0
        # the aggregate renders: per-genome p50/p99 + the child events
        telemetry.flush()
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import obs_report
        finally:
            sys.path.pop(0)
        reg, snaps, journals, events = obs_report.load_dir(str(mdir))
        text = obs_report.render(str(mdir), reg, snaps, journals,
                                 events)
        assert "ga.genome_seconds" in text
        assert "evaluator.job_seconds" in text
        assert reg.counters["evaluator.jobs"].value == 2
