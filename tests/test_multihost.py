"""Multi-host SPMD actually executed (round-5 VERDICT missing #2): a
2-process ``jax.distributed`` run on the CPU backend through the CLI's
``--multihost`` flag / ``init_multihost()``, asserting
``jax.process_count() == 2`` and a cross-process psum.

Each child is a real ``python -m veles_tpu --multihost`` invocation —
the exact launch recipe docs/guide.md documents (same command on every
host, coordinator/process id/count from the JAX_* env vars) — so this
pins the whole path: env parsing in init_multihost, the gloo CPU
collectives transport, and the collective itself."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def multihost_workflow(tmp_path):
    wf = tmp_path / "mh_wf.py"
    wf.write_text(textwrap.dedent("""
        def run(launcher):
            import jax
            import jax.numpy as jnp
            assert jax.process_count() == 2, jax.process_count()
            # one local device per process -> the psum axis spans BOTH
            # processes; summing ones across it must yield the global
            # device count
            out = jax.pmap(lambda v: jax.lax.psum(v, "i"),
                           axis_name="i")(
                jnp.ones(jax.local_device_count()))
            print("MULTIHOST_OK", jax.process_count(),
                  float(out[0]), flush=True)
    """))
    return str(wf)


def test_two_process_cpu_psum(multihost_workflow):
    port = free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(pid),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "veles_tpu", "--multihost",
             "-b", "cpu", multihost_workflow],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO, env=env))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, err[-2000:]
        # process_count 2, psum of ones over both processes = 2.0
        assert "MULTIHOST_OK 2 2.0" in out, (out, err[-1000:])
