"""Multi-host SPMD actually executed (round-5 VERDICT missing #2): a
2-process ``jax.distributed`` run on the CPU backend through the CLI's
``--multihost`` flag / ``init_multihost()``, asserting
``jax.process_count() == 2`` and a cross-process psum.

Each child is a real ``python -m veles_tpu --multihost`` invocation —
the exact launch recipe docs/guide.md documents (same command on every
host, coordinator/process id/count from the JAX_* env vars) — so this
pins the whole path: env parsing in init_multihost, the gloo CPU
collectives transport, and the collective itself."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def multihost_workflow(tmp_path):
    wf = tmp_path / "mh_wf.py"
    wf.write_text(textwrap.dedent("""
        def run(launcher):
            import jax
            import jax.numpy as jnp
            assert jax.process_count() == 2, jax.process_count()
            # one local device per process -> the psum axis spans BOTH
            # processes; summing ones across it must yield the global
            # device count
            out = jax.pmap(lambda v: jax.lax.psum(v, "i"),
                           axis_name="i")(
                jnp.ones(jax.local_device_count()))
            print("MULTIHOST_OK", jax.process_count(),
                  float(out[0]), flush=True)
    """))
    return str(wf)


@pytest.fixture
def cohort_multihost_workflow(tmp_path):
    """A member-sharded GA cohort over a 2-PROCESS mesh (Lattice):
    each process owns one CPU device, the PopulationTrainEngine
    shards its stacked member axis across both, and every process
    must read back the same finite fitness vector."""
    wf = tmp_path / "mh_cohort_wf.py"
    wf.write_text(textwrap.dedent("""
        def run(launcher):
            import jax
            import numpy as np
            from veles_tpu import prng
            from veles_tpu.backends import JaxDevice
            from veles_tpu.models import wine
            from veles_tpu.ops.fused import PopulationTrainEngine
            from veles_tpu.parallel import make_mesh

            assert jax.process_count() == 2, jax.process_count()

            class FL:
                workflow = None

            prng._streams.clear()
            prng.seed_all(1234)
            lrs = [0.3, 0.05, 0.8]
            layers = [
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 8},
                 "<-": {"learning_rate": lrs[0],
                        "weight_decay": 0.001,
                        "gradient_moment": 0.9}},
                {"type": "softmax", "->": {"output_sample_shape": 3},
                 "<-": {"learning_rate": lrs[0],
                        "gradient_moment": 0.9}},
            ]
            w = wine.create_workflow(
                FL(), layers=layers,
                decision={"max_epochs": 2, "fail_iterations": 1})
            w.initialize(device=JaxDevice(platform="cpu"))
            # one local device per process -> the 2-device mesh spans
            # BOTH processes; members shard P/N across them
            mesh = make_mesh(2, devices=jax.devices())
            rates = np.asarray(
                [[[lr, lr], [lr, lr]] for lr in lrs], np.float32)
            decays = np.asarray(
                [[[0.001, 0.0], [0.0, 0.0]]] * 3, np.float32)
            engine = PopulationTrainEngine(w, rates, decays,
                                           mesh=mesh)
            assert engine.member_sharded
            assert engine._n_stacked == 4   # 3 members pad to 2x2
            fits = np.asarray(engine.run())
            assert fits.shape == (3,), fits.shape
            assert np.isfinite(fits).all(), fits
            engine.release()
            w.stop()
            print("COHORT_MULTIHOST_OK",
                  " ".join(f"{v:.6f}" for v in fits), flush=True)
    """))
    return str(wf)


def _run_two_process(workflow_path):
    port = free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(pid),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "veles_tpu", "--multihost",
             "-b", "cpu", workflow_path],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO, env=env))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def test_two_process_member_sharded_cohort(cohort_multihost_workflow):
    """The Lattice multihost pin: a member-sharded cohort trains over
    a mesh spanning two real processes, and both read back the SAME
    fitness vector (the replicated re-layout before the host fetch is
    what makes a sharded accumulator globally readable)."""
    outs = _run_two_process(cohort_multihost_workflow)
    fits_lines = []
    for rc, out, err in outs:
        assert rc == 0, err[-2000:]
        line = [ln for ln in out.splitlines()
                if ln.startswith("COHORT_MULTIHOST_OK")]
        assert line, (out, err[-1000:])
        fits_lines.append(line[0])
    assert fits_lines[0] == fits_lines[1], fits_lines


def test_two_process_cpu_psum(multihost_workflow):
    port = free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(pid),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "veles_tpu", "--multihost",
             "-b", "cpu", multihost_workflow],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO, env=env))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, err[-2000:]
        # process_count 2, psum of ones over both processes = 2.0
        assert "MULTIHOST_OK 2 2.0" in out, (out, err[-1000:])
