"""Menagerie (ISSUE 19): the zoo's long tail on the Keel core.

The SOM epoch is ONE donated ``lax.scan`` built through the
engine-core trace builders; the eager per-minibatch dispatch loop is
the parity ORACLE (same masked step body, so fused-vs-eager pins
f32-BITWISE, ragged final minibatch included).  SOM hyperparameter
cohorts train population-batched (``SOMPopulationEngine``) against
per-member fused oracle runs; the DBN's greedy stages chain ON DEVICE
with the inter-stage host-transfer byte count pinned at zero (and
bitwise-equal weights against an explicit host-round-trip oracle);
the SOM serves through the unchanged Forge -> Hive surface and adopts
GA cohort winners HBM-to-HBM through ``GAServingHandoff``.
"""

import copy
import os
import textwrap

import numpy as np
import pytest

from veles_tpu import events, prng, telemetry
from veles_tpu.backends import JaxDevice
from veles_tpu.models import kohonen as kmod
from veles_tpu.models import mnist_dbn
from veles_tpu.ops.kohonen import SOMPopulationEngine
from veles_tpu.parallel import make_mesh

# mb=50 over n_train=230: the final train minibatch is RAGGED (30
# rows) — the scan pads it to the fixed shape and masks the padding
# out of the update, so every parity pin below covers the ragged tail
LCFG = {"minibatch_size": 50, "n_train": 230, "n_valid": 60,
        "shape": (6, 6, 1), "n_classes": 5, "seed": 888}
SOM_SHAPE = (5, 5)
TCFG = {"alpha0": 0.3, "alpha_min": 0.01, "decay_epochs": 4}
DCFG = {"max_epochs": 3}

HP = np.array([
    [0.3, 0.01, 2.5, 0.5],
    [0.5, 0.05, 3.0, 0.8],
    [0.1, 0.02, 1.5, 0.4],
], np.float32)


def build_som(fused=True, trainer_cfg=None, decision_cfg=None,
              name="ZooSom"):
    prng._streams.clear()
    prng.seed_all(4242)
    w = kmod.KohonenWorkflow(
        loader_cfg=dict(LCFG), som_shape=SOM_SHAPE,
        trainer_cfg=dict(trainer_cfg or TCFG),
        decision_cfg=dict(decision_cfg or DCFG), name=name)
    w.initialize(device=JaxDevice(platform="cpu"), fused=fused)
    return w


def _valid_losses(w):
    return [r["loss"] for r in w.decision.history
            if r["class"] == "validation"]


class TestSomFusedParity:
    """The fused epoch scan against the eager per-minibatch loop:
    same masked step body, same per-step schedule, so the trained
    prototypes are f32-BITWISE equal."""

    def test_fused_matches_eager_f32_exact(self):
        we = build_som(fused=False)
        assert not we.trainer.fused
        we.run()
        eager_w = np.asarray(we.forward.weights.map_read())
        eager_losses = _valid_losses(we)
        we.stop()

        wf = build_som(fused=True)
        assert wf.trainer.fused
        wf.run()
        fused_w = np.asarray(wf.forward.weights.map_read())
        fused_losses = _valid_losses(wf)
        wf.stop()

        assert np.array_equal(fused_w, eager_w)
        # the per-epoch validation QE rides the same pin (the eval
        # class runs through build_som_eval in the fused path)
        assert len(fused_losses) == len(eager_losses) > 0
        assert np.array_equal(np.float32(fused_losses),
                              np.float32(eager_losses))

    def test_fused_dispatch_count_is_per_class(self):
        """One fused dispatch per (epoch, class) — the whole point:
        the eager loop pays one dispatch per minibatch."""
        c = telemetry.counter(events.CTR_SOM_FUSED_DISPATCHES)
        before = c.value
        w = build_som(fused=True)
        w.run()
        w.stop()
        # max_epochs train firings + the interleaved validation
        # firings (one each per epoch, plus the initial valid pass)
        fired = c.value - before
        n_batches = -(-LCFG["n_train"] // LCFG["minibatch_size"]) \
            + -(-LCFG["n_valid"] // LCFG["minibatch_size"])
        assert 0 < fired <= 2 * DCFG["max_epochs"] + 2
        assert fired < DCFG["max_epochs"] * n_batches

    def test_streaming_matches_resident_f32_exact(self):
        """device_resident=False: the epoch scans host-assembled
        superstep batches instead of gathering in-trace from the
        resident store — same rows, same math, bitwise weights."""
        wr = build_som(fused=True)
        wr.run()
        resident_w = np.asarray(wr.forward.weights.map_read())
        wr.stop()

        ws = build_som(fused=True)
        ws.loader.device_resident = False
        ws.run()
        assert not ws.trainer._fused_resident
        stream_w = np.asarray(ws.forward.weights.map_read())
        ws.stop()
        assert np.array_equal(stream_w, resident_w)

    def test_no_post_warmup_recompiles(self):
        """Epoch 1 compiles the train and eval scans once each; every
        later epoch reuses the executables (ragged tails ride the
        mask, the schedule rides the scan xs — neither retraces)."""
        w = build_som(fused=True,
                      decision_cfg={"max_epochs": 6})
        w.run()
        tr = w.trainer
        assert tr._train_epoch._cache_size() == 1
        assert tr._eval_epoch._cache_size() == 1
        w.stop()


class TestSomCohortParity:
    """P hyperparameter genomes as ONE vmapped cohort vs P per-member
    fused oracle runs (each member's fitness = its min per-epoch mean
    validation QE, read off the oracle's decision history)."""

    def _oracle(self):
        fits = []
        for a0, amin, s0, smin in HP:
            w = build_som(
                fused=True,
                trainer_cfg={"alpha0": float(a0),
                             "alpha_min": float(amin),
                             "sigma0": float(s0),
                             "sigma_min": float(smin),
                             "decay_epochs": TCFG["decay_epochs"]},
                name="ZooSomOracle")
            w.run()
            fits.append(min(_valid_losses(w)))
            w.stop()
        return np.asarray(fits)

    def test_cohort_matches_per_member_oracle(self):
        w = build_som(fused=True)
        engine = SOMPopulationEngine(w, HP)
        fits = engine.run()
        engine.release()
        w.stop()
        oracle = self._oracle()
        # vmap batching may refuse the oracle's exact matmul fusion
        # on CPU XLA (observed: one f32 ulp on one member) — tight
        # allclose, not bitwise
        assert np.allclose(fits, oracle, rtol=1e-5, atol=0.0), \
            (fits, oracle)

    def test_padded_cohort_on_mesh_matches_unsharded(self):
        """P=3 members on a 2-device mesh pad to 4 (member 0
        repeated); per-member math never reduces across members, so
        the REAL members' fitness is bitwise-independent of the
        sharding."""
        w = build_som(fused=True)
        flat = SOMPopulationEngine(w, HP)
        base = flat.run()
        flat.release()
        w.stop()

        w = build_som(fused=True)
        engine = SOMPopulationEngine(w, HP, mesh=make_mesh(2))
        assert engine.member_sharded
        assert engine._n_stacked == 4 and engine.n_members == 3
        fits = engine.run()
        assert fits.shape == (3,)
        engine.release()
        w.stop()
        assert np.array_equal(fits, base), (fits, base)


DBN_LOADER = {"minibatch_size": 25, "n_train": 200, "n_valid": 40}
DBN_HIDDEN = [24, 12]


class HostRoundTripLoader(mnist_dbn.DeviceArrayLoader):
    """The oracle loader: same stage arrays, but forced through a
    host d2h + h2d round trip (f32-lossless), so the byte counter
    sees what the classic handoff pays while the MATH stays
    identical to the device chain."""

    def load_data(self):
        before = int(self.device.h2d_bytes)
        self._splits = {
            k: (self.device.put(np.asarray(v)) if v is not None
                else None)
            for k, v in self._splits.items()}
        super().load_data()
        self.ingest_h2d_bytes = int(self.device.h2d_bytes) - before


class TestDbnDeviceChain:
    """Greedy DBN stages chain on device: stage k+1's hidden reps are
    computed, sliced, and ingested without the dataset ever visiting
    the host."""

    def _pretrain(self, dev):
        prng.seed_all(7)
        stats = {}
        out = mnist_dbn.pretrain(device=dev,
                                 loader_cfg=dict(DBN_LOADER),
                                 hidden=list(DBN_HIDDEN), epochs=2,
                                 stats=stats)
        return out, stats

    def test_zero_interstage_host_bytes(self):
        dev = JaxDevice(platform="cpu")
        out, stats = self._pretrain(dev)
        assert stats["device_chain"] is True
        assert stats["interstage_h2d_bytes"] == 0
        assert len(stats["stages"]) == len(DBN_HIDDEN) - 1
        for st in stats["stages"]:
            assert st["h2d_bytes"] == 0
            # the stage dataset exists ONLY on device — the loader
            # never materialized a host copy to upload from
            assert st["host_free"] is True
        assert out[1]["weights"].shape == tuple(DBN_HIDDEN)

    def test_handoff_event_journaled(self):
        dev = JaxDevice(platform="cpu")
        self._pretrain(dev)
        evs = telemetry.recent_events(events.EV_DBN_STAGE_HANDOFF)
        assert evs and evs[-1]["h2d_bytes"] == 0
        assert evs[-1]["rows"] == (DBN_LOADER["n_train"]
                                   + DBN_LOADER["n_valid"])

    def test_chain_matches_host_round_trip_oracle(self, monkeypatch):
        """Routing the SAME stage arrays through an explicit host
        round trip changes where the bytes flow — h2d goes positive —
        and NOTHING else: every stage's weights stay f32-bitwise
        equal.  The device chain is a pure byte-routing win."""
        dev = JaxDevice(platform="cpu")
        chained, _ = self._pretrain(dev)

        monkeypatch.setattr(mnist_dbn, "DeviceArrayLoader",
                            HostRoundTripLoader)
        dev2 = JaxDevice(platform="cpu")
        roundtrip, stats = self._pretrain(dev2)
        assert stats["interstage_h2d_bytes"] > 0
        for a, b in zip(chained, roundtrip):
            assert np.array_equal(a["weights"], b["weights"])
            assert np.array_equal(a["bias"], b["bias"])


SOM_WF_TEXT = textwrap.dedent("""
    from veles_tpu.models import kohonen

    def create_workflow(launcher):
        return kohonen.create_workflow(
            launcher,
            loader={"minibatch_size": 50, "n_train": 230,
                    "n_valid": 60, "shape": (6, 6, 1),
                    "n_classes": 5, "seed": 888},
            som_shape=(5, 5),
            trainer={"alpha0": 0.3, "alpha_min": 0.01,
                     "decay_epochs": 4},
            decision={"max_epochs": 1})
""")


def _som_package(d, name="zoo_som", n_members=2, seed=4242):
    """One Forge SOM ensemble package + its host oracle members."""
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.ensemble.packaging import pack_ensemble
    from veles_tpu.launcher import load_workflow_module

    wf_path = os.path.join(d, "wf_som.py")
    with open(wf_path, "w") as f:
        f.write(SOM_WF_TEXT)
    mod = load_workflow_module(wf_path)

    class FL:
        workflow = None

    prng.seed_all(seed)
    w = mod.create_workflow(FL())
    w.initialize(device=NumpyDevice())
    base = {w.forward.name: {
        k: np.asarray(v) for k, v in w.forward.gather_params().items()}}
    rng = np.random.default_rng(seed)
    members = []
    for _ in range(n_members):
        params = {fn: {pn: (a + 0.05 * rng.standard_normal(a.shape)
                            .astype(np.float32))
                       for pn, a in p.items()}
                  for fn, p in base.items()}
        members.append({"params": params, "valid_error": 0.0,
                        "seed": seed,
                        "forward_names": [w.forward.name],
                        "values": None})
    pkg = os.path.join(d, f"{name}.vpkg")
    pack_ensemble(pkg, name, members, wf_path)
    return pkg, members, w


class TestSomServing:
    """The SOM through the unchanged Forge -> Hive surface: its
    apply_fwd IS the serving op (the (B, N) squared-distance map;
    clients read argmin winners and sqrt quantization errors), so
    pack_ensemble / load_model_package / the batched engine need no
    SOM-specific code."""

    def test_forge_package_serves_winner_and_qe(self, tmp_path):
        from veles_tpu.config import root
        from veles_tpu.serve.hive import load_model_package
        from veles_tpu.serve.residency import ResidencyManager

        pkg, members, w0 = _som_package(str(tmp_path))
        pristine = copy.deepcopy(root.__dict__)
        dev = JaxDevice(platform="cpu")
        model = load_model_package(
            "zoo_som", pkg, dev,
            str(tmp_path / "install"), pristine)
        assert model.sample_shape == (6, 6, 1)
        mgr = ResidencyManager(dev, budget_bytes=1 << 30)
        mgr.register(model)
        engine = mgr.ensure("zoo_som")
        engine.attach_batcher(mgr.max_batch, mgr.max_wait_s,
                              label="zoo_som",
                              sample_shape=model.sample_shape)

        rng = np.random.default_rng(99)
        x = rng.random((4, 6, 6, 1)).astype(np.float32)
        served = np.asarray(engine.submit(x).result())

        # host oracle: the member-loop mean of apply_fwd d2 maps
        acc = None
        for m in members:
            p = {k: np.asarray(v)
                 for k, v in m["params"][w0.forward.name].items()}
            d2, _ = w0.forward.apply_fwd(p, x)
            acc = d2 if acc is None else acc + d2
        oracle = acc / len(members)
        assert served.shape == (4, 25)
        assert np.allclose(served, oracle, rtol=1e-5, atol=1e-6)
        # the decisions a client actually reads off the map
        assert np.array_equal(served.argmin(1), oracle.argmin(1))
        qe = np.sqrt(np.maximum(served.min(1), 0.0))
        assert np.allclose(
            qe, np.sqrt(np.maximum(oracle.min(1), 0.0)),
            rtol=1e-4, atol=1e-5)


class TestSomHandoff:
    """A just-trained SOM cohort adopts into serving HBM-to-HBM:
    GAServingHandoff is generic over any engine with a member-stacked
    ``_params`` tree, and SOMPopulationEngine is one."""

    K = 2

    def test_adopt_cohort_serves_trained_maps(self):
        from veles_tpu.genetics.handoff import GAServingHandoff
        from veles_tpu.serve.residency import ResidencyManager

        w = build_som(fused=True)
        engine = SOMPopulationEngine(w, HP)
        init_members = [
            {fn: {k: np.asarray(arr[i]) for k, arr in d.items()}
             for fn, d in engine._params.items()}
            for i in range(self.K)]
        sample_shape = tuple(
            np.asarray(w.loader.original_data.map_read()).shape[1:])
        mgr = ResidencyManager(w.trainer.device,
                               budget_bytes=1 << 30)
        ho = GAServingHandoff(mgr, "som_winner", [w.forward],
                              init_members,
                              sample_shape=sample_shape)
        fits = np.asarray(engine.run())
        serve_engine = ho.adopt_cohort(engine, fits)
        idx = ho.top_k(fits)
        assert np.array_equal(
            idx, np.argsort(fits, kind="stable")[:self.K]
            .astype(np.int32))
        for fn, d in serve_engine.stacked_params.items():
            for k, arr in d.items():
                want = np.asarray(engine._params[fn][k])[idx]
                assert np.array_equal(np.asarray(arr)[:self.K], want)
        x = np.asarray(w.loader.original_data.map_read()[:4],
                       np.float32)
        out = np.asarray(serve_engine.submit(x).result())
        assert out.shape == (4, 25)
        assert np.all(np.isfinite(out))
        engine.release()
        w.stop()
