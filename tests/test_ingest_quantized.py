"""Quantized uint8 ingest: the wire/HBM codec for byte-ranged datasets.

The streaming path is hard link-bound (BENCH_r05: pipeline efficiency
0.9988 against a ~115 img/s h2d floor at 2 bytes/pixel), so the codec
ships byte-ranged datasets as uint8 — 1 byte/pixel on the wire, 4x
less HBM when resident — and the fused step dequantizes on device
(``x = q * scale + bias`` with the affine folded from the fitted
Normalizer).  These tests pin the three contracts:

- numerics: quantized and float ingest produce the same training
  trajectory (within bf16 rounding) in BOTH streaming and resident
  modes, including an MNIST-style conv workflow and a sharded mesh;
- wire accounting: the streaming path moves <= half the bytes per
  image of the bf16 wire (pixel payload exactly half), certified by
  the ``stream_transfer_bytes`` hook;
- residency: a byte-ranged dataset 4x over the float budget stays
  HBM-resident as uint8 instead of falling off the streaming cliff.
"""

import pickle

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import JaxDevice, NumpyDevice
from veles_tpu.datasets import synthetic_classification
from veles_tpu.loader import ArrayLoader
from veles_tpu.loader.quantize import (AffineDequant, derive_dequant,
                                       quantizable_source, to_uint8)
from veles_tpu.normalization import make_normalizer
from veles_tpu.ops.standard_workflow import StandardWorkflow


def byte_dataset(n_train=160, n_valid=40, shape=(8, 8, 1), n_classes=4,
                 seed=7):
    """A byte-ranged dataset pair: uint8 pixels + labels."""
    rng = np.random.RandomState(seed)
    total = n_train + n_valid
    x = rng.randint(0, 256, (total,) + shape).astype(np.uint8)
    y = rng.randint(0, n_classes, total).astype(np.int32)
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])


def build_mlp(train, valid, quant, streaming=False, mb=20,
              norm="mean_disp", epochs=2, budget=None):
    prng.seed_all(1357)
    kw = {}
    if streaming:
        kw["max_resident_bytes"] = 0
    elif budget is not None:
        kw["max_resident_bytes"] = budget
    gd = {"learning_rate": 0.05, "gradient_moment": 0.9}
    return StandardWorkflow(
        loader_factory=lambda w: ArrayLoader(
            w, train=train, valid=valid, minibatch_size=mb,
            name="loader", normalization_type=norm,
            quantized_ingest=quant, **kw),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
             "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": gd},
        ],
        decision_config={"max_epochs": epochs},
        name="quant_test")


def valid_history(w):
    return [h for h in w.decision.history if h["class"] == "validation"]


def assert_same_trajectory(wa, wb, loss_atol=5e-3, err_slack=0):
    ha, hb = valid_history(wa), valid_history(wb)
    assert len(ha) == len(hb) >= 2
    for a, b in zip(ha, hb):
        assert abs(a["loss"] - b["loss"]) < loss_atol, (a, b)
        assert abs(a["n_err"] - b["n_err"]) <= err_slack, (a, b)


class TestCodec:
    """The affine dequant reproduces the host normalizer bit-tight."""

    @pytest.mark.parametrize("kind,params", [
        ("none", {}),
        ("linear", {}),
        ("linear", {"lo": 0.0, "hi": 1.0}),
        ("mean_disp", {}),
        ("pointwise", {}),
        ("external_mean", {"scale": 1.0 / 255.0}),
    ])
    def test_dequant_matches_normalizer(self, kind, params):
        rng = np.random.RandomState(3)
        q = rng.randint(0, 256, (64, 6, 6, 2)).astype(np.uint8)
        norm = make_normalizer(kind, **params)
        norm.fit(q)
        want = norm.apply(q)          # the float-ingest pixels
        dq = derive_dequant(norm)
        assert dq is not None
        got = dq.apply_host(q)        # what the traced prologue does
        # one f32 ulp of composed-affine error, far inside bf16 ulp
        span = max(float(np.abs(want).max()), 1.0)
        np.testing.assert_allclose(got, want, atol=2e-5 * span)

    def test_pre_scale_composes(self):
        """decode-to-bytes loaders fold their /255 convention in."""
        q = np.arange(256, dtype=np.uint8).reshape(16, 16)
        dq = derive_dequant(None, pre_scale=1.0 / 255.0)
        np.testing.assert_allclose(dq.apply_host(q),
                                   q.astype(np.float32) / 255.0,
                                   atol=1e-7)

    def test_unfitted_normalizer_refused(self):
        assert make_normalizer("mean_disp").affine_params() is None
        assert derive_dequant(make_normalizer("linear")) is None

    def test_quantizable_source_rules(self):
        u8 = np.array([0, 255], np.uint8)
        assert quantizable_source(u8, strict=True)
        i64 = np.array([0, 255], np.int64)
        assert not quantizable_source(i64, strict=True)   # auto: no
        assert quantizable_source(i64, strict=False)      # True: yes
        f_int = np.array([0.0, 12.0, 255.0], np.float32)
        assert quantizable_source(f_int, strict=False)
        f_frac = np.array([0.5], np.float32)
        assert not quantizable_source(f_frac, strict=False)
        assert not quantizable_source(np.array([-1], np.int32),
                                      strict=False)

    def test_to_uint8_validates(self):
        np.testing.assert_array_equal(
            to_uint8(np.array([0.0, 7.0, 255.0])),
            np.array([0, 7, 255], np.uint8))
        with pytest.raises(ValueError):
            to_uint8(np.array([256.0]))

    def test_explicit_true_on_float_data_is_loud(self):
        train, valid = byte_dataset()
        fx = train[0].astype(np.float32) + 0.25   # not byte-ranged
        w = build_mlp((fx, train[1]), valid, quant=True)
        with pytest.raises(ValueError, match="byte-ranged"):
            w.initialize(device=JaxDevice(platform="cpu"))

    def test_aliased_targets_stay_float(self):
        """Autoencoder-style targets alias the input: auto-quantization
        must stand down (the f32 loss consumes targets undequantized)."""
        from veles_tpu.workflow import Workflow
        train, valid = byte_dataset()
        w = Workflow(name="t")
        ld = ArrayLoader(w, train=train, minibatch_size=20, name="l",
                         normalization_type="linear",
                         targets_from_labels=True)  # target = input
        ld.initialize(device=None)
        assert ld.dequant is None
        assert ld.original_data.mem.dtype == np.float32
        assert ld.original_targets.mem is ld.original_data.mem


class TestTrajectoryParity:
    """Quantized and float ingest train identically (CPU backend)."""

    def test_resident_matches_float(self):
        train, valid = byte_dataset()
        wq = build_mlp(train, valid, quant="auto")
        wq.initialize(device=JaxDevice(platform="cpu"))
        assert wq.loader.dequant is not None
        assert wq.loader.original_data.mem.dtype == np.uint8
        assert not wq.fused.streaming
        wq.run()

        wf = build_mlp(train, valid, quant=False)
        wf.initialize(device=JaxDevice(platform="cpu"))
        assert wf.loader.dequant is None
        assert wf.loader.original_data.mem.dtype == np.float32
        wf.run()
        assert_same_trajectory(wq, wf)

    def test_streaming_matches_float_and_resident(self):
        train, valid = byte_dataset()
        ws = build_mlp(train, valid, quant="auto", streaming=True)
        ws.initialize(device=JaxDevice(platform="cpu"))
        assert ws.fused.streaming
        assert ws.loader.dequant is not None
        # the wire must stay uint8 — no stream_dtype widening
        ws.run()
        assert ws.loader.superstep_data.dtype == np.uint8

        wf = build_mlp(train, valid, quant=False, streaming=True)
        wf.initialize(device=JaxDevice(platform="cpu"))
        wf.run()
        assert_same_trajectory(ws, wf)

        wr = build_mlp(train, valid, quant="auto")
        wr.initialize(device=JaxDevice(platform="cpu"))
        wr.run()
        assert_same_trajectory(ws, wr)

    def test_mnist_conv_parity_both_modes(self):
        """The acceptance workflow: an MNIST-style conv net over
        byte-ranged 28x28 digits — quantized vs bf16/float ingest,
        streaming AND resident, loss curves equal within bf16
        rounding."""
        prng.seed_all(2468)
        train, valid, _ = synthetic_classification(
            120, 40, (28, 28, 1), n_classes=10, seed=11)
        tx = np.round(np.asarray(train[0]) * 255.0).astype(np.uint8)
        vx = np.round(np.asarray(valid[0]) * 255.0).astype(np.uint8)
        ty, vy = train[1], valid[1]
        gd = {"learning_rate": 0.03, "gradient_moment": 0.9}
        layers = [
            {"type": "conv_tanh", "->": {"n_kernels": 4, "kx": 5,
                                         "ky": 5}, "<-": gd},
            {"type": "max_pooling", "->": {"kx": 2, "ky": 2}, "<-": {}},
            {"type": "all2all_tanh", "->": {"output_sample_shape": 32},
             "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 10},
             "<-": gd},
        ]

        def build(quant, streaming):
            prng.seed_all(1357)
            kw = {"max_resident_bytes": 0} if streaming else {}
            return StandardWorkflow(
                loader_factory=lambda w: ArrayLoader(
                    w, train=(tx, ty), valid=(vx, vy),
                    minibatch_size=20, name="loader",
                    normalization_type="linear",
                    normalization_parameters={"lo": 0.0, "hi": 1.0},
                    quantized_ingest=quant, **kw),
                layers=layers,
                decision_config={"max_epochs": 2},
                name="mnist_conv_quant")

        runs = {}
        for quant in ("auto", False):
            for streaming in (False, True):
                w = build(quant, streaming)
                w.initialize(device=JaxDevice(platform="cpu"))
                assert w.fused.streaming == streaming
                assert (w.loader.dequant is not None) == \
                    (quant == "auto")
                w.run()
                runs[(quant, streaming)] = w
        # bf16 rounding at these loss magnitudes (~2.3): |eps| ~ 2e-2;
        # the codec lands orders of magnitude inside it
        assert_same_trajectory(runs[("auto", False)],
                               runs[(False, False)])
        assert_same_trajectory(runs[("auto", True)],
                               runs[(False, True)])
        assert_same_trajectory(runs[("auto", True)],
                               runs[("auto", False)])

    def test_mesh_sharded_quantized_stream(self):
        """uint8 superstep batches shard over the data axis; the
        trajectory matches the unsharded quantized run."""
        from veles_tpu.parallel import DataParallel
        train, valid = byte_dataset()
        w1 = build_mlp(train, valid, quant="auto", streaming=True)
        w1.initialize(device=JaxDevice(platform="cpu"))
        w1.run()

        w4 = build_mlp(train, valid, quant="auto", streaming=True)
        dp = DataParallel(w4, 4)
        w4.initialize(device=dp.install())
        assert w4.fused.streaming
        assert w4.loader.dequant is not None
        w4.run()
        assert_same_trajectory(w1, w4, loss_atol=5e-3, err_slack=2)

    def test_numpy_backend_host_fill_dequantizes(self):
        """The eager/numpy golden path reads float minibatches: the
        host fill applies the same affine the traced prologue does."""
        train, valid = byte_dataset()
        w = build_mlp(train, valid, quant="auto")
        w.initialize(device=NumpyDevice())
        ld = w.loader
        assert ld.dequant is not None
        assert ld.minibatch_data.mem.dtype == np.float32
        w.loader.run()
        idx = ld.minibatch_indices.map_read()
        want = ld.dequant.apply_host(ld.original_data.mem[idx])
        np.testing.assert_allclose(ld.minibatch_data.map_read(), want,
                                   atol=1e-6)
        np.testing.assert_allclose(
            ld.normalized_host_rows(idx), want, atol=0)


class TestWireAccounting:
    """stream_transfer_bytes certifies what the codec actually moved."""

    def test_uint8_wire_halves_bf16_bytes_per_image(self):
        train, valid = byte_dataset(shape=(16, 16, 3))
        wq = build_mlp(train, valid, quant="auto", streaming=True)
        wq.initialize(device=JaxDevice(platform="cpu"))
        wq.run()
        wf = build_mlp(train, valid, quant=False, streaming=True)
        wf.initialize(device=JaxDevice(platform="cpu"))
        wf.run()
        images = wq.fused.processed_images + \
            wq.fused.processed_eval_images
        images_f = wf.fused.processed_images + \
            wf.fused.processed_eval_images
        assert images == images_f > 0
        bpi_q = wq.fused.stream_transfer_bytes / images
        bpi_f = wf.fused.stream_transfer_bytes / images_f
        px = 16 * 16 * 3
        # CPU assembles the float wire in f32 (the compute dtype); the
        # bf16 wire a TPU ships is exactly half of that
        bpi_bf16 = bpi_f / 2
        assert bpi_f >= px * 4            # f32 pixels + labels
        # acceptance: <= half the bytes per image vs the bf16 wire
        assert bpi_q <= 0.5 * bpi_f
        assert bpi_q <= bpi_bf16
        # and the pixel payload is EXACTLY 1 byte/px — half the bf16
        # wire's 2, a quarter of the f32 wire's 4
        assert wq.loader.superstep_data.dtype == np.uint8
        assert wq.loader.superstep_data.nbytes == \
            wq.loader.superstep_data.size
        assert bpi_q < px * 1.5           # ~1 byte/px + label overhead

    def test_resident_has_no_stream_bytes(self):
        train, valid = byte_dataset()
        w = build_mlp(train, valid, quant="auto")
        w.initialize(device=JaxDevice(platform="cpu"))
        w.run()
        assert w.fused.stream_transfer_bytes == 0

    def test_device_put_accounting_is_dtype_preserving(self):
        dev = JaxDevice(platform="cpu")
        base = dev.h2d_bytes
        buf = dev.put(np.zeros((10, 10), np.uint8))
        assert np.dtype(buf.dtype) == np.uint8     # no silent widening
        assert dev.h2d_bytes - base == 100          # 1 byte/element
        dev.put(np.zeros(4, np.float32))
        assert dev.h2d_bytes - base == 116

    def test_stream_transfer_bytes_pickles_with_default(self):
        train, valid = byte_dataset()
        w = build_mlp(train, valid, quant="auto", streaming=True)
        w.initialize(device=JaxDevice(platform="cpu"))
        w.run()
        assert w.fused.stream_transfer_bytes > 0
        state = pickle.loads(pickle.dumps(w.fused.__getstate__()))
        state.pop("stream_transfer_bytes", None)
        w.fused.__dict__.pop("stream_transfer_bytes", None)
        w.fused.__setstate__(state)
        assert w.fused.stream_transfer_bytes == 0


class TestResidencyBudget:
    """uint8 residency: 4x more dataset per byte of budget."""

    def test_byte_ranged_4x_over_float_budget_stays_resident(self):
        # 16384 uint8 elements: float ingest needs 64 KiB (4x OVER the
        # 16 KiB budget -> streaming cliff); quantized needs exactly
        # 16 KiB -> resident
        n_train, n_valid = 192, 64
        shape = (8, 8, 1)
        assert (n_train + n_valid) * int(np.prod(shape)) == 16384
        budget = 16384
        train, valid = byte_dataset(n_train, n_valid, shape)

        wf = build_mlp(train, valid, quant=False, budget=budget)
        wf.initialize(device=JaxDevice(platform="cpu"))
        assert not wf.loader.device_resident    # fell off the cliff
        assert wf.fused.streaming

        wq = build_mlp(train, valid, quant="auto", budget=budget)
        wq.initialize(device=JaxDevice(platform="cpu"))
        assert wq.loader.device_resident        # back on the chip
        assert not wq.fused.streaming
        assert wq.loader.original_data.mem.dtype == np.uint8
        assert wq.loader.original_data.nbytes == budget
        # and it trains
        wq.run()
        assert len(valid_history(wq)) == 2

    def test_hbm_copy_is_uint8(self):
        """The devmem the fused step gathers from is the 1-byte copy."""
        train, valid = byte_dataset()
        w = build_mlp(train, valid, quant="auto")
        w.initialize(device=JaxDevice(platform="cpu"))
        dataset = w.loader.original_data.unmap()
        assert np.dtype(dataset.dtype) == np.uint8


def make_image_tree(root, n_classes=3, per_class=12, size=(12, 12)):
    from PIL import Image
    rng = np.random.RandomState(33)
    for split, n in (("train", per_class), ("validation", 4)):
        for c in range(n_classes):
            d = root / split / f"class{c}"
            d.mkdir(parents=True, exist_ok=True)
            for i in range(n):
                base = int(200 * c / max(n_classes - 1, 1)) + 20
                arr = np.clip(rng.normal(base, 30, size),
                              0, 255).astype(np.uint8)
                Image.fromarray(arr, "L").save(str(d / f"im{i}.png"))


class TestImageLoaderQuantized:
    """File loaders decode straight to uint8 under quantized ingest:
    the /255 convention folds into the on-device dequant affine."""

    def _build(self, tmp_path, quant, streaming="auto", epochs=2,
               budget=None):
        from veles_tpu.loader.image import ImageDirectoryLoader
        prng.seed_all(9753)
        kw = {}
        if budget is not None:
            kw["max_resident_bytes"] = budget
        return StandardWorkflow(
            loader_factory=lambda wf: ImageDirectoryLoader(
                wf, data_dir=str(tmp_path), target_shape=(12, 12, 1),
                minibatch_size=9, streaming=streaming,
                quantized_ingest=quant, name="loader", **kw),
            layers=[
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 16},
                 "<-": {"learning_rate": 0.1}},
                {"type": "softmax", "->": {"output_sample_shape": 3},
                 "<-": {"learning_rate": 0.1}},
            ],
            decision_config={"max_epochs": epochs},
            name="img_quant")

    def test_resident_quantized_matches_float(self, tmp_path):
        make_image_tree(tmp_path)
        wq = self._build(tmp_path, quant=True)
        wq.initialize(device=JaxDevice(platform="cpu"))
        ld = wq.loader
        assert ld.dequant is not None
        assert ld.original_data.mem.dtype == np.uint8
        # decoded bytes dequantize to the float path's /255 pixels
        np.testing.assert_allclose(
            ld.normalized_host_rows(np.arange(4)),
            ld.original_data.mem[:4].astype(np.float32) / 255.0,
            atol=1e-7)
        wq.run()

        wf = self._build(tmp_path, quant="auto")   # auto = float here
        wf.initialize(device=JaxDevice(platform="cpu"))
        assert wf.loader.dequant is None
        wf.run()
        assert_same_trajectory(wq, wf)

    def test_streaming_decode_raw_wire(self, tmp_path):
        """streaming=True + quantized: files decode to uint8 on the
        prefetch path and ship 1 byte/pixel; trajectory matches the
        resident quantized run."""
        make_image_tree(tmp_path)
        ws = self._build(tmp_path, quant=True, streaming=True)
        ws.initialize(device=JaxDevice(platform="cpu"))
        ld = ws.loader
        assert not ld.device_resident and ld.dequant is not None
        assert ld.original_data.mem is None     # nothing pre-decoded
        ws.run()
        assert ws.loader.superstep_data.dtype == np.uint8
        assert ws.fused.stream_transfer_bytes > 0

        wr = self._build(tmp_path, quant=True)
        wr.initialize(device=JaxDevice(platform="cpu"))
        wr.run()
        assert_same_trajectory(ws, wr)

    def test_quantized_budget_estimate_is_1_byte(self, tmp_path):
        """streaming='auto' sizes the decoded set at 1 byte/element
        under quantized ingest — trees that stream at f32 stay
        resident."""
        make_image_tree(tmp_path, per_class=4)
        n_imgs = 3 * (4 + 4)
        budget = n_imgs * 12 * 12 * 2   # between 1x and 4x bytes
        wf = self._build(tmp_path, quant=False, budget=budget)
        wf.initialize(device=JaxDevice(platform="cpu"))
        assert not wf.loader.device_resident    # f32 estimate: over

        wq = self._build(tmp_path, quant=True, budget=budget)
        wq.initialize(device=JaxDevice(platform="cpu"))
        assert wq.loader.device_resident        # uint8 estimate: under
        assert wq.loader.original_data.mem.dtype == np.uint8


class TestSnapshotRoundtrip:
    def test_dequant_rides_loader_pickle(self):
        train, valid = byte_dataset()
        w = build_mlp(train, valid, quant="auto")
        w.initialize(device=JaxDevice(platform="cpu"))
        ld = w.loader
        assert ld.dequant is not None
        state = pickle.loads(pickle.dumps(ld.__getstate__()))
        ld2 = ArrayLoader.__new__(ArrayLoader)
        ld2.__setstate__(state)
        assert ld2.dequant is not None
        np.testing.assert_array_equal(ld2.dequant.scale,
                                      ld.dequant.scale)
        np.testing.assert_array_equal(ld2.dequant.bias, ld.dequant.bias)
        # pre-codec snapshots default the new attrs
        for k in ("dequant", "quantized_ingest", "_quant_pre_scale"):
            state.pop(k, None)
        ld3 = ArrayLoader.__new__(ArrayLoader)
        ld3.__setstate__(state)
        assert ld3.dequant is None
        assert ld3.quantized_ingest == "auto"
        assert ld3._quant_pre_scale == 1.0

    def test_affine_dequant_is_plain_state(self):
        dq = AffineDequant(np.float32(0.5), np.zeros(3, np.float32))
        dq2 = pickle.loads(pickle.dumps(dq))
        np.testing.assert_array_equal(dq2.scale, dq.scale)
        assert dq.nbytes == 16
