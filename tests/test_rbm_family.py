"""RBM / cutter / resizable_all2all family (SURVEY.md §3.2 "RBM /
other" — reconstructed from the survey description; the reference
mount is empty).  Standard battery: numpy-vs-jax agreement, fd grad
checks where a true gradient exists, an independent numpy CD-1 oracle
for the RBM, and workflow-level convergence."""

import numpy as np
import pytest

import jax.numpy as jnp

from test_ops import check_unit

from veles_tpu import prng
from veles_tpu.backends import JaxDevice, NumpyDevice
from veles_tpu.ops import all2all as a2a_mod
from veles_tpu.ops import cutter as cutter_mod
from veles_tpu.ops import rbm as rbm_mod
from veles_tpu.ops import resizable_all2all as ra2a_mod

RNG = np.random.default_rng(7)


@pytest.fixture(scope="module")
def dev():
    return JaxDevice(platform="cpu")


class FakeLauncher:
    workflow = None


class TestCutter:
    def test_battery(self):
        u = cutter_mod.Cutter(padding=(1, 2, 1, 1))
        check_unit(u, cutter_mod.GDCutter, (3, 8, 9, 2))

    def test_shapes_and_values(self):
        u = cutter_mod.Cutter(padding=(2, 1, 1, 3))
        x = RNG.standard_normal((2, 10, 12, 3)).astype(np.float32)
        y = u.apply({}, {"input": x})["output"]
        assert y.shape == (2, 6, 9, 3)
        np.testing.assert_array_equal(y, x[:, 1:7, 2:11])
        assert u.output_shape_for((2, 10, 12, 3)) == (2, 6, 9, 3)

    def test_overcut_rejected(self):
        u = cutter_mod.Cutter(padding=(5, 5, 5, 5))
        with pytest.raises(ValueError):
            u.output_shape_for((1, 8, 8, 1))


class TestAll2AllSigmoid:
    def test_battery(self):
        u = a2a_mod.All2AllSigmoid(output_sample_shape=6)
        check_unit(u, a2a_mod.GDSigmoid, (5, 9))


class TestResizableAll2All:
    def test_battery(self):
        u = ra2a_mod.ResizableAll2All(output_sample_shape=7)
        check_unit(u, ra2a_mod.GDResizableAll2All, (4, 5))

    def test_resize_preserves_learned_columns(self, dev):
        u = ra2a_mod.ResizableAll2All(output_sample_shape=6)
        u.input.mem = RNG.standard_normal((3, 4)).astype(np.float32)
        u.initialize(device=dev)
        w_before = np.array(u.weights.map_read())
        b_before = np.array(u.bias.map_read())
        u.resize(9)
        assert u.weights.shape == (4, 9)
        np.testing.assert_array_equal(u.weights.map_read()[:, :6],
                                      w_before)
        np.testing.assert_array_equal(u.bias.map_read()[:6], b_before)
        u.resize(4)  # shrink keeps the prefix
        np.testing.assert_array_equal(u.weights.map_read(),
                                      w_before[:, :4])

    def test_resize_mid_run_fused(self, dev):
        """A resize between epochs must invalidate the fused trace and
        keep training (explicit recompile, no stale-shape crash)."""
        from veles_tpu.loader.synthetic import \
            SyntheticClassificationLoader
        from veles_tpu.ops.standard_workflow import StandardWorkflow
        prng.seed_all(5)
        w = StandardWorkflow(
            loader_factory=lambda wf: SyntheticClassificationLoader(
                wf, name="loader", minibatch_size=20, n_train=80,
                n_valid=20, shape=(6, 6, 1), n_classes=4),
            layers=[{"type": "resizable_all2all",
                     "->": {"output_sample_shape": 8},
                     "<-": {"learning_rate": 0.05,
                            "gradient_moment": 0.9}},
                    {"type": "softmax",
                     "->": {"output_sample_shape": 4},
                     "<-": {"learning_rate": 0.05}}],
            loss_function="softmax",
            decision_config={"max_epochs": 2},
            name="ResizeWf")
        w.initialize(device=dev)
        w.run()
        hist1 = len(w.decision.history)
        # widen the hidden layer; the softmax's input width changes, so
        # its weights must be refilled too (fresh fine-tune phase)
        w.forwards[0].resize(12)
        sm = w.forwards[1]
        sm.weights.reset()
        sm.bias.reset()
        sm.fill_params((0, 12))
        sm.weights.initialize(dev)
        sm.bias.initialize(dev)
        w.decision.complete.set(False)
        w.decision.max_epochs = 4
        w.run()
        assert w.forwards[0].weights.shape == (36, 12)
        assert len(w.decision.history) > hist1
        for h in w.decision.history:
            assert np.isfinite(h["loss"])


def _rbm_params(n_vis, n_hid):
    return {
        "weights": (RNG.standard_normal((n_vis, n_hid)) * 0.1)
        .astype(np.float32),
        "bias": np.zeros(n_hid, np.float32),
        "vbias": np.zeros(n_vis, np.float32),
    }


class TestRBM:
    def test_forward_numpy_vs_jax(self):
        u = rbm_mod.RBM(n_hidden=5)
        params = _rbm_params(12, 5)
        x = RNG.random((4, 12)).astype(np.float32)
        out_np = u.apply(params, {"input": x})
        jp = {k: jnp.asarray(v) for k, v in params.items()}
        out_jx = u.apply(jp, {"input": jnp.asarray(x)})
        for k in ("output", "hidden"):
            np.testing.assert_allclose(np.asarray(out_jx[k]),
                                       out_np[k], rtol=1e-5, atol=1e-5)
        assert out_np["output"].shape == x.shape
        assert out_np["hidden"].shape == (4, 5)

    def test_cd1_matches_independent_oracle(self):
        """GDRBM's numpy path vs a from-scratch CD-1 transcription
        (identical Bernoulli draws via the same 'rbm' stream seed)."""
        u = rbm_mod.RBM(n_hidden=6)
        gd = rbm_mod.GDRBM(forward=u)
        params = _rbm_params(10, 6)
        x = RNG.random((8, 10)).astype(np.float32)
        h0_prob = u.hidden_of(params, x)

        prng.seed_all(77)
        _, grads = gd.backward_from_saved(params, (x, h0_prob, None),
                                          np.zeros_like(x))

        prng.seed_all(77)
        gen = prng.get("rbm").numpy
        sig = lambda v: 1.0 / (1.0 + np.exp(-v))
        h0 = (gen.random(h0_prob.shape) < h0_prob).astype(np.float32)
        v1 = sig(h0 @ params["weights"].T + params["vbias"])
        h1 = sig(v1 @ params["weights"] + params["bias"])
        n = x.shape[0]
        np.testing.assert_allclose(
            grads["weights"], -(x.T @ h0_prob - v1.T @ h1) / n,
            rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            grads["bias"], -(h0_prob - h1).sum(0) / n,
            rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            grads["vbias"], -(x - v1).sum(0) / n, rtol=1e-5, atol=1e-6)

    def test_binarization(self):
        u = rbm_mod.Binarization()
        x = RNG.random((200, 7)).astype(np.float32)
        prng.seed_all(3)
        y, _ = u.apply_fwd({}, x, train=True)
        assert set(np.unique(y)) <= {0.0, 1.0}
        # statistics follow the probabilities
        assert abs(y.mean() - x.mean()) < 0.05
        # eval mode: deterministic threshold
        y_eval, _ = u.apply_fwd({}, x, train=False)
        np.testing.assert_array_equal(y_eval, (x > 0.5).astype(np.float32))

    def test_workflow_reconstruction_improves_fused(self, dev):
        from veles_tpu.models import mnist_rbm
        fl = FakeLauncher()
        w = mnist_rbm.create_workflow(
            fl, loader={"minibatch_size": 25, "n_train": 300,
                        "n_valid": 50, "targets_from_data": True},
            decision={"max_epochs": 5})
        w.initialize(device=dev)
        w.run()
        val = [h["loss"] for h in w.decision.history
               if h["class"] == "validation"]
        assert val[-1] < val[0], val

    def test_workflow_numpy_eager(self):
        from veles_tpu.models import mnist_rbm
        fl = FakeLauncher()
        w = mnist_rbm.create_workflow(
            fl, loader={"minibatch_size": 25, "n_train": 100,
                        "n_valid": 25, "targets_from_data": True},
            decision={"max_epochs": 2})
        w.initialize(device=NumpyDevice())
        w.run()
        assert len(w.decision.history) == 4
        for h in w.decision.history:
            assert np.isfinite(h["loss"])

    def test_fused_determinism(self, dev):
        """Two identically-seeded fused runs produce identical metric
        histories (CD sampling keys are (seed, step)-deterministic)."""
        from veles_tpu.models import mnist_rbm
        hists = []
        for _ in range(2):
            prng.seed_all(42)
            fl = FakeLauncher()
            w = mnist_rbm.create_workflow(
                fl, loader={"minibatch_size": 20, "n_train": 100,
                            "n_valid": 20, "targets_from_data": True},
                decision={"max_epochs": 2})
            w.initialize(device=dev)
            w.run()
            hists.append([(h["class"], h["loss"])
                          for h in w.decision.history])
        assert hists[0] == hists[1]
