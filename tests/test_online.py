"""Evergreen online learning (ISSUE 14): the replay buffer's
determinism + uint8 codec, the online-vs-offline training oracle
(f32-exact replay), residency/swap atomicity (a busy model is never a
spill victim; promotion swaps under the residency lock are never
torn), and the REAL ``--serve-models --online`` hive: a drifted label
stream is learned and gated-promoted HBM-to-HBM while serving stays
correct, and a poisoned training stream never promotes.
"""

import os
import threading
import time

import numpy as np
import pytest

from tests.test_serve import (_build_package, _host_oracle,
                              _journal_events)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _drifted(label, n_classes=3):
    """The drift the online tests serve: the truth generator's labels
    rotate one class — a served model frozen at package time is
    suddenly (and consistently) wrong."""
    return (int(label) + 1) % n_classes


class TestReplayBuffer:
    def test_reservoir_bounded_and_deterministic(self):
        from veles_tpu.online.buffer import ReplayBuffer
        rng = np.random.default_rng(3)
        rows = rng.standard_normal((300, 4)).astype(np.float32)
        labels = rng.integers(0, 3, 300)

        def fill():
            b = ReplayBuffer(capacity=32, seed=9, holdout_every=10)
            for i in range(300):
                b.add(rows[i][None], labels[i])
            return b

        b1, b2 = fill(), fill()
        assert b1.train_rows == 32
        assert 0 < b1.holdout_rows <= b1.holdout_cap
        # same seed + same tap order -> identical retained sets (the
        # property the offline training oracle replays against)
        x1, l1 = b1.sample(16, np.random.default_rng(5))
        x2, l2 = b2.sample(16, np.random.default_rng(5))
        assert np.array_equal(x1, x2) and np.array_equal(l1, l2)
        assert b1.version == b2.version

    def test_uint8_codec_roundtrips_and_shrinks(self):
        from veles_tpu.loader.quantize import AffineDequant
        from veles_tpu.online.buffer import ReplayBuffer
        dq = AffineDequant(1.0 / 255.0, 0.0)
        src = np.random.default_rng(0).integers(
            0, 256, (40, 8), dtype=np.uint8)
        rows = dq.apply_host(src)   # what a client would send: f32
        bq = ReplayBuffer(64, seed=1, holdout_every=0, dequant=dq)
        bf = ReplayBuffer(64, seed=1, holdout_every=0, dequant=None)
        for i in range(40):
            bq.add(rows[i][None], 0)
            bf.add(rows[i][None], 0)
        assert bq.quantized and not bf.quantized
        # 4x against the residency charge, value-exact on decode
        assert bq.nbytes * 4 == bf.nbytes
        xq, _ = bq.sample(16, np.random.default_rng(2))
        xf, _ = bf.sample(16, np.random.default_rng(2))
        assert np.array_equal(xq, xf)

    def test_non_byte_ranged_rows_stay_float(self):
        from veles_tpu.loader.quantize import AffineDequant
        from veles_tpu.online.buffer import ReplayBuffer
        dq = AffineDequant(1.0 / 255.0, 0.0)
        b = ReplayBuffer(16, seed=1, holdout_every=0, dequant=dq)
        rows = np.random.default_rng(1).standard_normal(
            (4, 8)).astype(np.float32)
        b.add(rows, np.zeros(4))
        assert not b.quantized   # lossless or nothing
        x, _ = b.sample(4, np.random.default_rng(0))
        assert x.dtype == np.float32


def _tiny_served_model(seed=11, n_members=3):
    """A resident HostedModel + manager on XLA:CPU (in-process)."""
    from veles_tpu import prng
    from veles_tpu.backends import JaxDevice
    from veles_tpu.datasets import synthetic_classification
    from veles_tpu.loader import ArrayLoader
    from veles_tpu.ops.standard_workflow import StandardWorkflow
    from veles_tpu.serve.residency import HostedModel, ResidencyManager

    prng.seed_all(4242)
    train, valid, _ = synthetic_classification(
        64, 16, (6, 6, 1), n_classes=3, seed=5)
    w = StandardWorkflow(
        loader_factory=lambda w: ArrayLoader(
            w, train=train, valid=valid, minibatch_size=16,
            name="loader"),
        layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 12},
             "<-": {"learning_rate": 0.1}},
            {"type": "softmax", "->": {"output_sample_shape": 3},
             "<-": {"learning_rate": 0.1}},
        ],
        decision_config={"max_epochs": 2}, name="online_wf")
    device = JaxDevice(platform="cpu")
    w.initialize(device=device)
    base = {fw.name: {k: np.asarray(v) for k, v in
                      fw.gather_params().items()}
            for fw in w.forwards}
    rng = np.random.default_rng(seed)
    members = [{fn: {pn: a + 0.05 * rng.standard_normal(a.shape)
                     .astype(np.float32) for pn, a in p.items()}
                for fn, p in base.items()} for _ in range(n_members)]
    m = HostedModel("alpha", w.forwards, members,
                    meta={"workflow": w, "seed": seed},
                    sample_shape=(6, 6, 1))
    res = ResidencyManager(device, budget_bytes=1 << 30, max_batch=8,
                           max_wait_s=0.002)
    res.register(m)
    res.ensure("alpha")
    return res, m, w, train


class TestOnlineOfflineOracle:
    """The determinism contract: replaying the SAME tapped rows
    through the recorded (step, buffer version) history reproduces
    the online param trajectory f32-exactly — online learning is a
    pure function of the tap order."""

    def test_offline_replay_is_f32_exact(self):
        from veles_tpu.online.buffer import ReplayBuffer
        from veles_tpu.online.trainer import ShadowTrainer
        from veles_tpu.ops import batching
        res, m, w, (xs, ys) = _tiny_served_model()
        device = res.device
        B = 8
        adds = [(xs[i % len(xs)][None],
                 _drifted(ys[i % len(ys)])) for i in range(120)]

        def make(seed=77):
            buf = ReplayBuffer(64, seed=seed, holdout_every=8)
            tr = ShadowTrainer(
                m.forwards, w.gds, w.evaluator, device,
                batching.stack_member_params(m.forwards,
                                             m.member_params, device),
                seed=seed, lr_scale=1.0, micro_batch=B)
            return buf, tr

        # ONLINE: adds and steps interleaved (the live hive shape)
        buf1, t1 = make()
        k = 0
        for i, (rows, lab) in enumerate(adds):
            buf1.add(rows, lab)
            if buf1.train_rows >= B and i % 7 == 3:
                x, lb = buf1.sample(B, t1.sample_rng())
                t1.step(x, lb, buf1.version)
                k += 1
        assert k >= 10 and t1.history

        # OFFLINE: same tapped rows, steps replayed at the recorded
        # buffer versions
        buf2, t2 = make()
        it = iter(adds)
        for step, version in t1.history:
            while buf2.version < version:
                rows, lab = next(it)
                buf2.add(rows, lab)
            x, lb = buf2.sample(B, t2.sample_rng(step))
            t2.step(x, lb, version)

        for fn, d in t1._params.items():
            for pn, a in d.items():
                assert np.array_equal(np.asarray(a),
                                      np.asarray(t2._params[fn][pn])), \
                    f"param {fn}.{pn} diverged between online and " \
                    f"offline replay"


class _FakeEngine:
    def __init__(self, busy=False):
        self.busy = busy
        self.resident = True
        self.drained = 0
        self.spilled = 0

    def drain(self, timeout=30.0):
        self.drained += 1
        return True

    def spill_params(self):
        self.spilled += 1
        self.resident = False


class TestResidencySwapAtomicity:
    """ISSUE 14 satellite: a promotion-triggered (or any) LRU spill
    can never evict the model a dispatch is mid-flight on, and the
    promotion swap happens under the declared residency lock."""

    def _manager(self, budget):
        from veles_tpu.backends import JaxDevice
        from veles_tpu.serve.residency import ResidencyManager
        return ResidencyManager(JaxDevice(platform="cpu"),
                                budget_bytes=budget)

    def _hosted(self, name, nbytes, busy):
        from veles_tpu.serve.residency import HostedModel
        m = HostedModel.__new__(HostedModel)
        m.name = name
        m.forwards = []
        m.member_params = []
        m.meta = {}
        m.sample_shape = None
        m.engine = _FakeEngine(busy=busy)
        m.param_bytes = nbytes
        m.last_used = 0.0
        return m

    def test_busy_model_is_never_the_spill_victim(self):
        res = self._manager(budget=1000)
        a = self._hosted("a", 600, busy=True)    # LRU and mid-flight
        b = self._hosted("b", 600, busy=False)
        a.last_used, b.last_used = 1.0, 2.0
        res.models["a"] = a
        res.models["b"] = b
        incoming = self._hosted("c", 600, busy=False)
        incoming.engine = None
        res.models["c"] = incoming
        with res._lock:
            victim, blocked = res._pick_victim(incoming)
        # the idle model spills; the busy LRU one is untouchable
        assert victim is b and not blocked
        assert a.engine.spilled == 0
        # with ONLY busy candidates, nothing spills (the caller waits
        # for a quiet window rather than tearing params out from
        # under a dispatch)
        b.engine.busy = True
        with res._lock:
            victim, blocked = res._pick_victim(incoming)
        assert victim is None and blocked

    def test_swap_params_requires_residency(self):
        res = self._manager(budget=1 << 30)
        m = self._hosted("a", 100, busy=False)
        m.engine.adopted = None
        m.engine.adopt_stacked_params = \
            lambda p: setattr(m.engine, "adopted", p)
        res.models["a"] = m
        token = {"new": "params"}
        assert res.swap_params("a", token) is m.engine
        assert m.engine.adopted is token
        m.engine.resident = False
        with pytest.raises(RuntimeError):
            res.swap_params("a", token)

    def test_swap_mid_request_never_tears_answers(self):
        """``online.swap_mid_request``: promotion races live
        dispatches; every answer equals the OLD oracle or the NEW one
        — never a mix of the two param sets."""
        from veles_tpu import faults
        from veles_tpu.online.promote import PromotionGate
        from veles_tpu.online.trainer import ShadowTrainer
        from veles_tpu.ops import batching
        res, m, w, (xs, ys) = _tiny_served_model(seed=21)
        engine = m.engine
        x = xs[:4]
        old = np.asarray(engine.submit(x).result(timeout=30))
        tr = ShadowTrainer(
            m.forwards, w.gds, w.evaluator, res.device,
            batching.stack_member_params(m.forwards, m.member_params,
                                         res.device),
            seed=3, lr_scale=1.0, micro_batch=8)
        # make the shadow measurably different: a few real steps
        for k in range(6):
            rng = tr.sample_rng()
            idx = rng.integers(0, len(xs), 8)
            tr.step(xs[idx],
                    [(int(ys[i]) + 1) % 3 for i in idx], k)
        answers = []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                answers.append(
                    np.asarray(engine.submit(x).result(timeout=30)))

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        time.sleep(0.1)
        gate = PromotionGate("alpha", res, margin=0.0, min_steps=1)
        gate.last_step_ts = time.monotonic()
        faults.arm("online.swap_mid_request@model=alpha&seconds=0.3")
        try:
            gate.promote(tr.take_params(), tr.steps)
        finally:
            faults.arm("")
        time.sleep(0.2)
        stop.set()
        t.join(timeout=10)
        new = np.asarray(engine.submit(x).result(timeout=30))
        assert not np.allclose(old, new)   # the swap really landed
        assert len(answers) >= 2
        for a in answers:
            ok_old = np.allclose(a, old, atol=1e-6)
            ok_new = np.allclose(a, new, atol=1e-6)
            assert ok_old or ok_new, "torn answer: matches neither " \
                "the pre- nor the post-promotion oracle"


@pytest.fixture(scope="module")
def online_pkg(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("online_pkgs"))
    return _build_package(d, "alpha", 11)


def _learn_env(**extra):
    env = {
        "VELES_ONLINE_MICRO_BATCH": "8",
        "VELES_ONLINE_MIN_STEPS": "4",
        "VELES_ONLINE_LR_SCALE": "1.0",
        "VELES_ONLINE_PROMOTE_MARGIN": "5.0",
        "VELES_ONLINE_HOLDOUT_EVERY": "6",
        "VELES_ONLINE_IDLE_MS": "1",
        "VELES_FAULTS": "",
    }
    env.update(extra)
    return env


class TestHiveOnline:
    """The real ``--serve-models --online`` subprocess: drift is
    learned, the gate promotes HBM-to-HBM, serving stays correct and
    recompile-free, and time_to_serve is recorded."""

    @pytest.fixture(scope="class")
    def served(self, online_pkg, tmp_path_factory):
        from veles_tpu.serve.client import HiveClient
        mdir = str(tmp_path_factory.mktemp("online_metrics"))
        c = HiveClient({"alpha": online_pkg["pkg"]}, backend="cpu",
                       max_batch=8, max_wait_ms=2, online=True,
                       metrics_dir=mdir, env=_learn_env(), cwd=REPO)
        c.metrics_dir = mdir
        yield c
        c.close()

    def _payloads(self, pkg, n=96):
        """Labeled drifted traffic: rows from the packaged training
        distribution, labels = the live truth AFTER drift (what the
        frozen model is now consistently wrong about)."""
        w = pkg["workflow"]
        xs = np.asarray(w.loader.original_data.mem, np.float32)
        ys = np.asarray(w.loader.original_labels.mem)
        out = []
        for i in range(n):
            j = i % len(xs)
            out.append((xs[j][None], [_drifted(ys[j])]))
        return out

    def test_drift_is_learned_and_promoted(self, served, online_pkg):
        assert served.hello.get("online") is True
        payloads = self._payloads(online_pkg)
        deadline = time.monotonic() + 180
        i = 0
        row = None
        first_promote_row = None
        while time.monotonic() < deadline:
            for _ in range(8):
                x, lab = payloads[i % len(payloads)]
                i += 1
                jid = served.submit("alpha", x, label=lab)
                r = served.wait_for(jid, timeout=60)
                assert "error" not in r, r
            row = served.learn().get("alpha")
            if row and row["promotions"] >= 1:
                if first_promote_row is None:
                    first_promote_row = row
                # keep learning until the SERVING model (gate rounds
                # re-score it as the incumbent) is genuinely good on
                # the drifted truth, so the served-accuracy check
                # below is not judging a barely-over-the-margin
                # first promotion
                if row["incumbent_error_pct"] is not None and \
                        row["incumbent_error_pct"] <= 40.0:
                    break
            time.sleep(0.05)
        assert row, "no learner row from op=learn"
        assert row["promotions"] >= 1, row
        # the gated win was real: the journal's promotion record
        # carries the scores of the round that fired it — the
        # shadow's held-out error beat the then-incumbent by the
        # margin (the live op=learn row may already show a LATER
        # round's scores)
        promos = []
        wait_until = time.monotonic() + 30
        while time.monotonic() < wait_until and not promos:
            promos = _journal_events(served.metrics_dir,
                                     "online.promoted")
            if not promos:
                time.sleep(0.5)
        assert promos, "no online.promoted journal event"
        ev = promos[0]
        assert ev["shadow_error_pct"] \
            < ev["incumbent_error_pct"] - 4.9, ev
        # and the promoted model now answers the DRIFTED truth better
        # than the frozen oracle did
        right = wrong_frozen = 0
        for x, lab in payloads[:24]:
            r = served.request("alpha", x, timeout=60)
            assert "pred" in r, r
            frozen_pred = int(np.argmax(
                _host_oracle(online_pkg, x), axis=-1)[0])
            if r["pred"][0] == lab[0]:
                right += 1
            if frozen_pred != lab[0]:
                wrong_frozen += 1
        assert right > 24 - wrong_frozen, (right, wrong_frozen)
        # time_to_serve: last step -> first served request, recorded
        row = served.learn()["alpha"]
        assert row["time_to_serve_ms"] is not None
        assert row["time_to_serve_ms"] >= 0.0

    def test_zero_post_warmup_recompiles_with_learner(self, served):
        st0 = served.stats()
        before = st0["counters"].get("serve.compiles", 0)
        x = np.ones((2, 6, 6, 1), np.float32)
        for _ in range(6):
            assert "probs" in served.request("alpha", x, timeout=60)
        after = served.stats()["counters"].get("serve.compiles", 0)
        assert after == before, "the learner caused serving recompiles"

    def test_learner_journals_and_gauges(self, served):
        st = served.stats()
        assert st["counters"].get("online.steps", 0) > 0
        assert st["counters"].get("online.tapped_rows", 0) > 0
        gs = st["gauges"]
        assert gs.get("online.model.alpha.steps", 0) > 0
        served.stats()   # flush-adjacent poke
        evs = _journal_events(served.metrics_dir, "online.promoted")
        # the journal file may lag one flush; the op=learn row is the
        # live truth and was asserted above — only check consistency
        for ev in evs:
            assert ev["model"] == "alpha"


class TestHiveOnlinePoison:
    """``online.poison_batch``: a corrupted training label stream —
    with CLEAN traffic that matches the packaged model — must never
    promote."""

    def test_poisoned_stream_never_promotes(self, online_pkg,
                                            tmp_path_factory):
        from veles_tpu.serve.client import HiveClient
        mdir = str(tmp_path_factory.mktemp("online_poison"))
        env = _learn_env(
            VELES_FAULTS="online.poison_batch@slot=train&times=*")
        c = HiveClient({"alpha": online_pkg["pkg"]}, backend="cpu",
                       max_batch=8, max_wait_ms=2, online=True,
                       metrics_dir=mdir, env=env, cwd=REPO)
        try:
            w = online_pkg["workflow"]
            xs = np.asarray(w.loader.original_data.mem, np.float32)
            # CLEAN labels: what the packaged ensemble actually
            # predicts (so the un-poisoned incumbent is near-perfect
            # on the held-out slice and garbage cannot beat it)
            deadline = time.monotonic() + 60
            i = 0
            row = None
            while time.monotonic() < deadline:
                for _ in range(8):
                    j = i % len(xs)
                    i += 1
                    x = xs[j][None]
                    lab = [int(np.argmax(_host_oracle(online_pkg, x),
                                         axis=-1)[0])]
                    r = c.wait_for(
                        c.submit("alpha", x, label=lab), timeout=60)
                    assert "error" not in r, r
                row = c.learn().get("alpha")
                if row and row["steps"] >= 12 and \
                        row["shadow_error_pct"] is not None:
                    break
                time.sleep(0.05)
            assert row and row["steps"] >= 12, row
            assert row["shadow_error_pct"] is not None, row
            assert row["promotions"] == 0, \
                f"poisoned labels were promoted: {row}"
        finally:
            c.close()


class TestHiveOnlineLatency:
    """The scavenger must not own the chip: serving p99 with the
    learner active stays bounded vs learner-off on the same box (the
    strict 1.2x bar is the BENCH_r09 acceptance; the tier-1 bound is
    loose enough to survive a noisy CI box)."""

    @pytest.mark.slow
    def test_p99_bounded_vs_learner_off(self, online_pkg,
                                        tmp_path_factory):
        from veles_tpu.serve.client import HiveClient
        w = online_pkg["workflow"]
        xs = np.asarray(w.loader.original_data.mem, np.float32)
        ys = np.asarray(w.loader.original_labels.mem)

        def window(online):
            mdir = str(tmp_path_factory.mktemp(
                f"online_lat_{int(online)}"))
            c = HiveClient({"alpha": online_pkg["pkg"]},
                           backend="cpu", max_batch=8, max_wait_ms=2,
                           online=online, metrics_dir=mdir,
                           env=_learn_env(), cwd=REPO)
            try:
                x = xs[:1]
                for _ in range(8):   # warm the serving dispatch
                    c.request("alpha", x, timeout=60)
                if online:
                    # warm the LEARNER too: feed labeled traffic and
                    # wait for the first scavenged step, so the timed
                    # window never pays the one-time step compile
                    deadline = time.monotonic() + 60
                    i = 0
                    while time.monotonic() < deadline:
                        j = i % len(xs)
                        i += 1
                        c.wait_for(c.submit("alpha", xs[j][None],
                                            label=[_drifted(ys[j])]),
                                   timeout=60)
                        if i % 8 == 0:
                            if c.stats()["counters"].get(
                                    "online.steps", 0) > 0:
                                break
                            time.sleep(0.05)
                st0 = c.stats()
                steps0 = st0["counters"].get("online.steps", 0)
                # bursty closed loop: live traffic has gaps — that is
                # exactly the resource the scavenger exists to steal
                t_end = time.perf_counter() + 3.0
                i = 0
                while time.perf_counter() < t_end:
                    for _ in range(5):
                        j = i % len(xs)
                        i += 1
                        r = c.wait_for(c.submit(
                            "alpha", xs[j][None],
                            label=[_drifted(ys[j])] if online
                            else None), timeout=60)
                        assert "error" not in r, r
                    time.sleep(0.01)
                st1 = c.stats()
                steps = st1["counters"].get("online.steps", 0) - steps0
            finally:
                c.close()
            from bench import _serve_hist_window
            lat = _serve_hist_window(
                st1["histograms"].get("serve.request_seconds"),
                st0["histograms"].get("serve.request_seconds"))
            return (lat.quantile(0.99) or 0.0), steps

        p99_off, _ = window(False)
        p99_on, steps_on = window(True)
        assert steps_on > 0, "the learner never scavenged a step " \
                             "under bursty load"
        assert p99_on <= max(8.0 * p99_off, p99_off + 0.25), \
            (p99_on, p99_off)
