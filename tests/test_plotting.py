"""Plotting units + graphics bus (SURVEY.md §3.1 Graphics bus /
Plotting units): per-epoch events, file rendering, zmq PUB/SUB."""

import os
import threading

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import NumpyDevice
from veles_tpu.datasets import synthetic_classification
from veles_tpu.graphics_server import (FileRenderer, GraphicsServer,
                                       get_server, shutdown_server)
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow


@pytest.fixture(autouse=True)
def _fresh_server(tmp_path):
    shutdown_server()
    server = get_server()
    server.out_dir = str(tmp_path / "plots")
    yield server
    shutdown_server()


def build_workflow(max_epochs=2):
    prng.seed_all(777)
    train, valid, _ = synthetic_classification(
        200, 80, (8, 8, 1), n_classes=4, seed=42)
    w = StandardWorkflow(
        loader_factory=lambda wf: ArrayLoader(
            wf, train=train, valid=valid, minibatch_size=40,
            name="loader"),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
             "<-": {"learning_rate": 0.1}},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": {"learning_rate": 0.1}},
        ],
        decision_config={"max_epochs": max_epochs},
        name="plot_test")
    w.link_plotters()
    return w


class TestFileRenderer:
    def test_curves(self, tmp_path):
        r = FileRenderer(str(tmp_path))
        path = r.render({"kind": "curves", "plotter": "err",
                         "series": {"train": ([0, 1], [50.0, 20.0])}})
        assert path and os.path.exists(path)

    def test_matrix(self, tmp_path):
        r = FileRenderer(str(tmp_path))
        path = r.render({"kind": "matrix", "plotter": "conf",
                         "matrix": np.eye(4)})
        assert path and os.path.exists(path)

    def test_image_grid(self, tmp_path):
        r = FileRenderer(str(tmp_path))
        path = r.render({"kind": "image_grid", "plotter": "w",
                         "tiles": [np.random.rand(8, 8)
                                   for _ in range(5)]})
        assert path and os.path.exists(path)

    def test_unknown_kind_ignored(self, tmp_path):
        r = FileRenderer(str(tmp_path))
        assert r.render({"kind": "nope", "plotter": "x"}) is None


class TestPlottersInWorkflow:
    def test_workflow_emits_plots(self, _fresh_server):
        w = build_workflow()
        w.initialize(device=NumpyDevice())
        w.run()
        out = _fresh_server.out_dir
        made = sorted(os.listdir(out))
        assert "plt_error.png" in made, made
        assert "plt_loss.png" in made, made
        assert "plt_confusion.png" in made, made
        # 8x8 FC weights are square-able -> weight tiles render too
        assert "plt_weights.png" in made, made

    def test_plotters_fire_once_per_epoch(self, _fresh_server):
        events = []
        _fresh_server.enqueue = lambda e: events.append(e)
        w = build_workflow(max_epochs=3)
        w.initialize(device=NumpyDevice())
        w.run()
        per = {}
        for e in events:
            per[e["plotter"]] = per.get(e["plotter"], 0) + 1
        assert per["plt_error"] == 3, per


class TestSnapshotResume:
    def test_resumed_plotters_still_fire(self, _fresh_server):
        """Pickling flattens derived gate Bools to frozen values; the
        re-wiring at initialize must re-derive plotter gates or resumed
        runs plot never/always (regression for the frozen-gate bug)."""
        import pickle
        w = build_workflow(max_epochs=1)
        w.initialize(device=NumpyDevice())
        w.run()
        w2 = pickle.loads(pickle.dumps(w))
        events = []
        _fresh_server.enqueue = lambda e: events.append(e)
        w2.decision.max_epochs = 3
        w2.decision.complete.set(False)  # it finished; train 2 more
        w2.initialize(device=NumpyDevice())
        w2.run()
        n_err_events = sum(1 for e in events
                           if e["plotter"] == "plt_error")
        assert n_err_events == 2, (n_err_events, len(events))

    def test_old_snapshot_without_new_attrs_resumes(self):
        """Snapshots written before _extra_after_decision/plotters/
        confusion_per_class existed must still resume (the __setstate__
        defaults)."""
        import pickle
        w = build_workflow(max_epochs=1)
        w.initialize(device=NumpyDevice())
        w.run()
        # simulate an old snapshot: these attrs did not exist back then
        del w.__dict__["_extra_after_decision"]
        del w.__dict__["plotters"]
        del w.decision.__dict__["confusion_per_class"]
        w2 = pickle.loads(pickle.dumps(w))
        w2.decision.complete.set(False)
        w2.decision.max_epochs = 2
        w2.initialize(device=NumpyDevice())
        w2.run()
        assert len(w2.decision.history) >= 4

    def test_confusion_is_per_epoch(self, _fresh_server):
        """Decision snapshots + zeroes the evaluator's confusion at
        each class end — totals must equal ONE epoch's sample count,
        not the whole run's."""
        w = build_workflow(max_epochs=3)
        w.initialize(device=NumpyDevice())
        w.run()
        from veles_tpu.loader.base import VALID
        conf = w.decision.confusion_per_class[VALID]
        assert conf is not None
        assert conf.sum() == 80  # one validation epoch, not 3x


class TestPubSub:
    def test_zmq_roundtrip(self, tmp_path):
        import socket as pysocket

        from veles_tpu.graphics_client import GraphicsClient

        with pysocket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        endpoint = f"tcp://127.0.0.1:{port}"
        server = GraphicsServer(endpoint=endpoint,
                                out_dir=str(tmp_path / "srv"),
                                render=False)
        client = GraphicsClient(endpoint, str(tmp_path / "cli"))
        got = []
        t = threading.Thread(target=lambda: got.append(
            client.serve(max_events=1)), daemon=True)
        t.start()
        # PUB/SUB needs the subscription to land; retry until delivery
        import time
        for _ in range(100):
            server.enqueue({"kind": "curves", "plotter": "live",
                            "series": {"t": ([0], [1.0])}})
            if not t.is_alive():
                break
            time.sleep(0.05)
        t.join(timeout=5)
        assert got == [1]
        assert os.path.exists(tmp_path / "cli" / "live.png")
        server.close()
