"""One-pipeline real-data rehearsal (round-5 VERDICT missing #4): an
on-disk image tree -> the ``prepare-imagenet`` CLI -> the streaming
image loader -> the fused train step, end to end in one test.  Bounded
CPU-tier variant; tests_tpu/ carries the chip-tier twin.

What it pins that the per-piece tests cannot: the prepared tree's
layout is what ImageDirectoryLoader expects, streaming mode decodes on
the prefetch path, the fused step consumes host-assembled superstep
batches, and the wire accounting (``stream_transfer_bytes``) sees real
pixels move."""

import os

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.datasets import _main as datasets_cli


def write_png(path, arr):
    from PIL import Image
    Image.fromarray(arr.astype(np.uint8)).save(path)


def make_tree(base, n_classes=2, per_class=12, size=24):
    rng = np.random.default_rng(17)
    for c in range(n_classes):
        d = os.path.join(base, f"cls_{c}")
        os.makedirs(d)
        for i in range(per_class):
            # class-dependent mean so a couple of supersteps of
            # training have signal to reduce
            arr = rng.integers(0, 120, (size, size, 3)) + 100 * c
            write_png(os.path.join(d, f"im{i:02d}.png"),
                      np.clip(arr, 0, 255))


def build_streaming_workflow(prepared, image_size=20, mb=6):
    from veles_tpu.loader.image import ImageDirectoryLoader
    from veles_tpu.ops.standard_workflow import StandardWorkflow

    prng.seed_all(1234)
    return StandardWorkflow(
        loader_factory=lambda wf: ImageDirectoryLoader(
            wf, name="loader", data_dir=prepared,
            target_shape=(image_size, image_size, 3),
            minibatch_size=mb, streaming=True),
        layers=[
            {"type": "conv_relu",
             "->": {"n_kernels": 4, "kx": 5, "ky": 5, "sliding": 2},
             "<-": {"learning_rate": 0.02}},
            {"type": "max_pooling", "->": {"kx": 2, "ky": 2},
             "<-": {}},
            {"type": "softmax", "->": {"output_sample_shape": 2},
             "<-": {"learning_rate": 0.02}},
        ],
        loss_function="softmax",
        decision_config={"max_epochs": 2},
        superstep=2,
        name="RehearsalWorkflow")


def test_prepare_then_stream_train(tmp_path, capsys):
    from veles_tpu.backends import JaxDevice

    src = tmp_path / "src"
    os.makedirs(src)
    make_tree(str(src))
    prepared = tmp_path / "prepared"
    # the REAL CLI surface, not the library function
    rc = datasets_cli(["prepare-imagenet", str(src),
                       "--out", str(prepared), "--image-size", "20",
                       "--valid-frac", "0.25"])
    assert rc == 0
    assert (prepared / "labels.json").exists()

    w = build_streaming_workflow(str(prepared))
    w.initialize(device=JaxDevice(platform="cpu"))
    # the tiny tree must actually have fallen off the resident path —
    # otherwise this rehearses the wrong pipeline
    assert w.fused.streaming
    assert not w.loader.device_resident
    w.run()
    w.stop()

    # a few streaming supersteps trained: finite loss every epoch ...
    hist = w.decision.history
    assert len(hist) == 4          # 2 epochs x (validation + train)
    for h in hist:
        assert np.isfinite(h["loss"]), hist
    # ... and real bytes moved over the (virtual) wire, consistent
    # with >= the train split's pixels for the epochs run
    assert w.fused.stream_transfer_bytes > 0
    one_image = 20 * 20 * 3 * 4    # f32 pixels
    assert w.fused.stream_transfer_bytes >= one_image * 18  # 9/epoch


def test_streaming_equals_resident_first_epoch(tmp_path):
    """The rehearsal's streaming trajectory is not a new numerics
    path: the same prepared tree trained resident (streaming=False)
    produces the same first-epoch metrics."""
    from veles_tpu.backends import JaxDevice
    from veles_tpu.datasets import prepare_imagenet
    from veles_tpu.loader.image import ImageDirectoryLoader
    from veles_tpu.ops.standard_workflow import StandardWorkflow

    src = tmp_path / "src"
    os.makedirs(src)
    make_tree(str(src))
    prepared = str(tmp_path / "prepared")
    prepare_imagenet(str(src), prepared, image_size=20,
                     valid_frac=0.25, progress_every=0)

    def run_one(streaming):
        prng._streams.clear()
        prng.seed_all(1234)
        w = StandardWorkflow(
            loader_factory=lambda wf: ImageDirectoryLoader(
                wf, name="loader", data_dir=prepared,
                target_shape=(20, 20, 3), minibatch_size=6,
                streaming=streaming),
            layers=[
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 8},
                 "<-": {"learning_rate": 0.05}},
                {"type": "softmax", "->": {"output_sample_shape": 2},
                 "<-": {"learning_rate": 0.05}},
            ],
            loss_function="softmax",
            decision_config={"max_epochs": 1},
            superstep=2, name="RehearsalParity")
        w.initialize(device=JaxDevice(platform="cpu"))
        w.run()
        w.stop()
        return [(h["class"], h["n_err"], round(h["loss"], 5))
                for h in w.decision.history]

    assert run_one(True) == run_one(False)
