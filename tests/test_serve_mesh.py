"""Prism (ISSUE 17 tentpole): a hive replica that OWNS an N-device
mesh.  On the forced 8-virtual-device CPU backend (conftest pins
``--xla_force_host_platform_device_count=8`` before the first jax
import) these tests prove:

(a) a member-sharded serving engine answers BITWISE identically to the
    1-device engine — and so does a real ``--mesh 8`` subprocess
    against a plain 1-device subprocess serving the same package;
(b) a model over ONE device's budget goes member-sharded-RESIDENT
    (``serve.model_sharded_resident`` journaled, ZERO spill events)
    where the identical 1-device replica LRU-spills;
(c) PlacementPolicy places against real heterogeneous capacities
    (a --mesh replica advertises devices x per-device budget);
(d) a REAL 2-replica fleet with one ``--mesh 8`` replica comes up,
    reports the mixed topology, and answers at oracle parity;
(e) the adaptive coalescing window (Sentinel delta-quantile gap
    estimator) stretches while arrivals keep pace and collapses on a
    stall — cold start stays exactly static.
"""

import json
import os
import textwrap
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WF_TEXT = textwrap.dedent("""
    from veles_tpu import prng
    from veles_tpu.datasets import synthetic_classification
    from veles_tpu.loader import ArrayLoader
    from veles_tpu.ops.standard_workflow import StandardWorkflow

    def create_workflow(launcher):
        prng.seed_all(4242)
        train, valid, _ = synthetic_classification(
            64, 16, (6, 6, 1), n_classes=3, seed=5)
        return StandardWorkflow(
            loader_factory=lambda w: ArrayLoader(
                w, train=train, valid=valid, minibatch_size=16,
                name="loader"),
            layers=[
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 12},
                 "<-": {"learning_rate": 0.1}},
                {"type": "softmax", "->": {"output_sample_shape": 3},
                 "<-": {"learning_rate": 0.1}},
            ],
            decision_config={"max_epochs": 2}, name="prism_wf")
""")


def _build_package(d, name, seed, n_members=3):
    """One Forge ensemble package + its host oracle ingredients."""
    from veles_tpu import prng
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.ensemble.packaging import pack_ensemble
    from veles_tpu.launcher import load_workflow_module

    wf_path = os.path.join(d, f"wf_{name}.py")
    with open(wf_path, "w") as f:
        f.write(WF_TEXT)
    mod = load_workflow_module(wf_path)

    class FL:
        workflow = None

    prng.seed_all(seed)
    w = mod.create_workflow(FL())
    w.initialize(device=NumpyDevice())
    base = {fw.name: {k: np.asarray(v) for k, v in
                      fw.gather_params().items()}
            for fw in w.forwards}
    rng = np.random.default_rng(seed)
    members = []
    for _ in range(n_members):
        params = {fn: {pn: (a + 0.05 * rng.standard_normal(a.shape)
                            .astype(np.float32))
                       for pn, a in p.items()}
                  for fn, p in base.items()}
        members.append({"params": params, "valid_error": 0.0,
                        "seed": seed,
                        "forward_names": [fw.name
                                          for fw in w.forwards],
                        "values": None})
    pkg = os.path.join(d, f"{name}.vpkg")
    pack_ensemble(pkg, name, members, wf_path)
    return {"pkg": pkg, "members": members, "workflow": w}


def _host_oracle(model, x):
    acc = None
    for m in model["members"]:
        out = np.asarray(x, np.float32)
        for fw in model["workflow"].forwards:
            p = {k: np.asarray(v)
                 for k, v in m["params"][fw.name].items()}
            out, _ = fw.apply_fwd(p, out, rng=None, train=False)
        out = np.asarray(out)
        acc = out if acc is None else acc + out
    return acc / len(model["members"])


def _journal_events(metrics_dir, name):
    out = []
    if not os.path.isdir(metrics_dir):
        return out
    for fn in os.listdir(metrics_dir):
        if not fn.startswith("journal-"):
            continue
        with open(os.path.join(metrics_dir, fn)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("event") == name:
                    out.append(ev)
    return out


def _stacked_bytes(members):
    return sum(int(np.prod(a.shape)) * 4
               for m in members for p in m["params"].values()
               for a in p.values())


@pytest.fixture(scope="module")
def packages(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("prism_pkgs"))
    return {"alpha": _build_package(d, "alpha", 11),
            "beta": _build_package(d, "beta", 22)}


class TestShardedEngineParity:
    """(a) in-process: the member-sharded engine on an 8-device mesh
    is BITWISE the 1-device engine — same stable add chain, all_gather
    is exact, padded members are never read."""

    def test_mesh_engine_bitwise_vs_single_device(self, packages):
        from veles_tpu.backends import JaxDevice
        from veles_tpu.ops.fused import EnsembleEvalEngine
        from veles_tpu.parallel.data_parallel import MeshJaxDevice
        from veles_tpu.parallel.mesh import make_mesh

        w = packages["alpha"]["workflow"]
        mp = [m["params"] for m in packages["alpha"]["members"]]
        oracle = EnsembleEvalEngine(w.forwards, mp,
                                    JaxDevice(platform="cpu"))
        eng = EnsembleEvalEngine(w.forwards, mp,
                                 MeshJaxDevice(make_mesh(8)),
                                 shard_members=True)
        try:
            assert eng.member_sharded
            # 3 members pad to 8 (one per device); the answer only
            # reads the real 3
            assert eng._n_stacked == 8
            assert eng.n_members == 3
            assert eng.param_bytes == oracle.param_bytes
            assert eng.param_bytes_per_device * 8 > eng.param_bytes
            rng = np.random.default_rng(33)
            for n in (1, 5, 16):
                x = rng.standard_normal((n, 6, 6, 1)) \
                    .astype(np.float32)
                got = np.asarray(eng.predict_proba(x))
                want = np.asarray(oracle.predict_proba(x))
                assert got.dtype == want.dtype
                assert np.array_equal(got, want)
        finally:
            eng.release()
            oracle.release()

    def test_spill_restore_keeps_sharded_placement_bitwise(
            self, packages):
        from veles_tpu.backends import JaxDevice
        from veles_tpu.ops.fused import EnsembleEvalEngine
        from veles_tpu.parallel.data_parallel import MeshJaxDevice
        from veles_tpu.parallel.mesh import make_mesh

        w = packages["beta"]["workflow"]
        mp = [m["params"] for m in packages["beta"]["members"]]
        oracle = EnsembleEvalEngine(w.forwards, mp,
                                    JaxDevice(platform="cpu"))
        eng = EnsembleEvalEngine(w.forwards, mp,
                                 MeshJaxDevice(make_mesh(8)),
                                 shard_members=True)
        try:
            x = np.random.default_rng(7).standard_normal(
                (4, 6, 6, 1)).astype(np.float32)
            want = np.asarray(oracle.predict_proba(x))
            assert np.array_equal(
                np.asarray(eng.predict_proba(x)), want)
            eng.spill_params()
            assert not eng.resident
            eng.restore_params(mp)
            # restore re-pads and lands on the SAME sharding: the
            # compiled dispatcher answers without retracing
            assert eng.member_sharded and eng.resident
            assert np.array_equal(
                np.asarray(eng.predict_proba(x)), want)
        finally:
            eng.release()
            oracle.release()


class TestMeshServeSubprocess:
    """(a) over the wire: a real ``--mesh 8`` replica (forced to
    member-shard) answers bitwise vs a plain 1-device replica."""

    @pytest.fixture(scope="class")
    def pair(self, packages, tmp_path_factory):
        from veles_tpu.serve.client import HiveClient
        mdir = str(tmp_path_factory.mktemp("prism_mesh"))
        mesh_c = HiveClient(
            {"alpha": packages["alpha"]["pkg"]}, backend="cpu",
            max_batch=8, max_wait_ms=5, metrics_dir=mdir,
            env={"VELES_SERVE_MESH_SHARD": "always"},
            mesh=8, cwd=REPO)
        flat_c = HiveClient(
            {"alpha": packages["alpha"]["pkg"]}, backend="cpu",
            max_batch=8, max_wait_ms=5, cwd=REPO)
        yield {"mesh": mesh_c, "flat": flat_c, "mdir": mdir}
        mesh_c.close()
        flat_c.close()

    def test_hello_advertises_mesh_capacity(self, pair):
        h = pair["mesh"].hello
        assert h["ready"] and h["platform"] == "cpu"
        assert h["devices"] == 8
        assert h["device_budget"] > 0
        assert h["models"]["alpha"]["resident"]
        assert h["models"]["alpha"]["sharded"] is True
        flat = pair["flat"].hello
        assert flat["devices"] == 1
        assert flat["models"]["alpha"]["sharded"] is False

    def test_mesh_serve_bitwise_vs_flat_serve(self, pair, packages):
        rng = np.random.default_rng(99)
        for n in (1, 3, 8):
            x = rng.standard_normal((n, 6, 6, 1)).astype(np.float32)
            rm = pair["mesh"].request("alpha", x, timeout=60)
            rf = pair["flat"].request("alpha", x, timeout=60)
            assert "probs" in rm and "probs" in rf, (rm, rf)
            got = np.asarray(rm["probs"], np.float32)
            ref = np.asarray(rf["probs"], np.float32)
            assert np.array_equal(got, ref)
            want = _host_oracle(packages["alpha"], x)
            np.testing.assert_allclose(got, want, atol=1e-4)
            assert rm["pred"] == rf["pred"]

    def test_sharded_resident_journaled(self, pair):
        evs = _journal_events(pair["mdir"],
                              "serve.model_sharded_resident")
        assert evs, "no serve.model_sharded_resident journal event"
        ev = evs[-1]
        assert ev["model"] == "alpha" and ev["devices"] == 8
        assert 0 < ev["per_device"] < ev["param_bytes"]


class TestOverBudgetGoesShardedResident:
    """(b) the capacity win itself: with a per-device budget under ONE
    model's bytes, the 1-device replica thrashes the LRU spill path
    while the --mesh 8 replica holds BOTH models member-sharded
    resident — zero spills, journal-pinned."""

    def _budget(self, packages):
        # between the sharded per-device charge (2 models x
        # bytes_one/3 after padding 3->8) and one model's full bytes
        bytes_one = _stacked_bytes(packages["alpha"]["members"])
        return bytes_one * 3 // 4

    def test_mesh_replica_zero_spills(self, packages,
                                      tmp_path_factory):
        from veles_tpu.serve.client import HiveClient
        mdir = str(tmp_path_factory.mktemp("prism_overbudget"))
        c = HiveClient(
            {"alpha": packages["alpha"]["pkg"],
             "beta": packages["beta"]["pkg"]},
            backend="cpu", max_batch=8, max_wait_ms=5,
            hbm_budget=self._budget(packages), metrics_dir=mdir,
            env={"VELES_SERVE_MESH_SHARD": "auto"}, mesh=8, cwd=REPO)
        try:
            h = c.hello
            # BOTH over-one-device's-budget models are resident at
            # once — sharded, not spilled
            for name in ("alpha", "beta"):
                assert h["models"][name]["resident"], h
                assert h["models"][name]["sharded"] is True, h
            x = np.ones((2, 6, 6, 1), np.float32)
            for name in ("alpha", "beta", "alpha", "beta"):
                r = c.request(name, x, timeout=60)
                assert "probs" in r, (name, r)
                np.testing.assert_allclose(
                    np.asarray(r["probs"]),
                    _host_oracle(packages[name], x), atol=1e-4)
            st = c.stats()
            assert st["gauges"]["serve.models_resident"] == 2
            assert st["gauges"]["serve.mesh_devices"] == 8
            per_dev = st["gauges"]["serve.resident_bytes_per_device"]
            assert 0 < per_dev <= self._budget(packages)
            assert st["counters"].get("serve.spills", 0) == 0
        finally:
            c.close()
        sharded = _journal_events(mdir, "serve.model_sharded_resident")
        assert {e["model"] for e in sharded} == {"alpha", "beta"}
        assert not _journal_events(mdir, "serve.model_spilled")

    def test_single_device_replica_spills_same_budget(
            self, packages, tmp_path_factory):
        from veles_tpu.serve.client import HiveClient
        mdir = str(tmp_path_factory.mktemp("prism_flat_budget"))
        c = HiveClient(
            {"alpha": packages["alpha"]["pkg"],
             "beta": packages["beta"]["pkg"]},
            backend="cpu", max_batch=8, max_wait_ms=5,
            hbm_budget=self._budget(packages), metrics_dir=mdir,
            cwd=REPO)
        try:
            assert sum(m["resident"]
                       for m in c.hello["models"].values()) == 1
            x = np.ones((2, 6, 6, 1), np.float32)
            for name in ("alpha", "beta", "alpha", "beta"):
                assert "probs" in c.request(name, x, timeout=60)
            assert c.stats()["counters"]["serve.spills"] >= 2
        finally:
            c.close()
        assert _journal_events(mdir, "serve.model_spilled")
        assert not _journal_events(mdir,
                                   "serve.model_sharded_resident")


class TestHeterogeneousPlacement:
    """(c) pure placement math against per-replica capacities."""

    def _policy(self, **kw):
        from veles_tpu.serve.fleet import PlacementPolicy
        return PlacementPolicy(**kw)

    def test_capacities_override_uniform_budget(self):
        pl = self._policy(budget_bytes=100).assign(
            {"a": 40, "b": 40, "c": 40, "d": 40}, 2,
            capacities=[100, 800])
        # the hot prefix still needs room on EVERY replica: a and b
        # replicate, c overflows the small replica and the tail lands
        # on the roomy mesh replica
        assert pl["a"] == [0, 1] and pl["b"] == [0, 1]
        assert pl["c"] == [1] and pl["d"] == [1]

    def test_model_over_small_replica_fits_mesh_replica(self):
        pl = self._policy(budget_bytes=100).assign(
            {"big": 500}, 2, capacities=[100, 800])
        assert pl["big"] == [1]

    def test_none_capacity_falls_back_to_budget(self):
        pl = self._policy(budget_bytes=100).assign(
            {"a": 40, "b": 40, "c": 40}, 2, capacities=[None, 300])
        assert pl["a"] == [0, 1] and pl["b"] == [0, 1]
        assert pl["c"] == [1]

    def test_uniform_capacities_match_legacy_tiebreak(self):
        pol = self._policy(budget_bytes=100)
        legacy = pol.assign({"a": 40, "b": 40, "c": 40, "d": 10}, 2)
        hetero = pol.assign({"a": 40, "b": 40, "c": 40, "d": 10}, 2,
                            capacities=[None, None])
        assert hetero == legacy

    def test_tail_prefers_most_free_bytes(self):
        # c replicates (fits both); d ends the hot prefix and the
        # tail goes where the most BYTES remain — the mesh replica
        # (210 free vs 10), twice — not round-robin by count
        pl = self._policy(budget_bytes=100).assign(
            {"c": 90, "d": 90, "e": 90}, 2, capacities=[100, 300])
        assert pl["c"] == [0, 1]
        assert pl["d"] == [1] and pl["e"] == [1]


class TestFleetWithMeshReplica:
    """(d) the mixed fleet: replica 0 owns one device, replica 1 owns
    an 8-device mesh — one fleet, real subprocesses."""

    @pytest.fixture(scope="class")
    def router(self, packages, tmp_path_factory):
        from veles_tpu.serve.router import FleetRouter
        mdir = str(tmp_path_factory.mktemp("prism_fleet"))
        r = FleetRouter(
            {"alpha": packages["alpha"]["pkg"],
             "beta": packages["beta"]["pkg"]},
            n_replicas=2, backend="cpu", max_batch=16, max_wait_ms=5,
            mesh={1: 8}, metrics_dir=mdir, cwd=REPO)
        yield r
        r.close()

    def test_mixed_topology_comes_up(self, router):
        assert len(router.replicas) == 2
        assert all(r.healthy for r in router.replicas)
        assert router.replicas[0].devices == 1
        assert router.replicas[1].devices == 8
        # capacity = devices x per-device budget, from each hello
        c0 = router.replicas[0].capacity_bytes
        c1 = router.replicas[1].capacity_bytes
        assert c0 and c1 and c1 == 8 * c0

    def test_fleet_status_reports_devices(self, router):
        st = router.fleet_status()
        devs = [row["devices"] for row in st["replicas"]]
        assert devs == [1, 8]
        for row in st["replicas"]:
            assert row["device_budget"] and row["device_budget"] > 0

    def test_round_trip_matches_oracle(self, router, packages):
        rng = np.random.default_rng(123)
        for name in ("alpha", "beta"):
            x = rng.standard_normal((2, 6, 6, 1)).astype(np.float32)
            r = router.request(name, x, timeout=60)
            assert "probs" in r, r
            np.testing.assert_allclose(
                np.asarray(r["probs"], np.float32),
                _host_oracle(packages[name], x), atol=1e-4)

    def test_obs_fleet_rows_show_mesh_shape(self, router):
        # request traffic above flushed the replicas' gauges; the
        # merged fleet view (and /api/metrics through it) reports the
        # per-replica topology + per-device resident charge
        from veles_tpu import telemetry
        from veles_tpu.obs import fleet_rows, render_fleet
        telemetry.flush()
        deadline = time.monotonic() + 30
        rows = []
        while time.monotonic() < deadline:
            rows = fleet_rows(router.metrics_dir)
            if len(rows) == 2 and all(
                    r.get("resident_mib_per_device") is not None
                    for r in rows):
                break
            time.sleep(0.5)
        assert [r["devices"] for r in rows] == [1, 8], rows
        for r in rows:
            assert r["resident_mib_per_device"] > 0, rows
        out = render_fleet(router.metrics_dir)
        assert "MiB/dev" in out and "devs" in out

    def test_parse_mesh_cli_forms(self):
        from veles_tpu.serve.router import parse_mesh
        assert parse_mesh(None) is None
        assert parse_mesh(["8"]) == 8
        assert parse_mesh(["1=8"]) == {1: 8}
        assert parse_mesh(["0=2", "3=8"]) == {0: 2, 3: 8}
        with pytest.raises(ValueError):
            parse_mesh(["8", "1=8"])


class TestAdaptiveWait:
    """(e) the adaptive coalescing window, deterministically: feed the
    gap histogram by hand and read ``_wait_left`` — no sleeps, no
    timing races."""

    def _batcher(self, **kw):
        from veles_tpu.serve.batcher import MicroBatcher
        kw.setdefault("max_batch", 64)
        kw.setdefault("max_wait_s", 0.02)
        return MicroBatcher(lambda xb: xb.sum(axis=(1,)), **kw)

    def test_cold_start_is_static(self):
        b = self._batcher()
        try:
            assert b._adaptive and b._gap_hist is not None
            now = time.perf_counter()
            with b._cond:
                left = b._wait_left(now, now - 0.005)
            # no gaps observed yet: exactly max_wait_s - age
            assert abs(left - (0.02 - 0.005)) < 1e-9
        finally:
            b.close()

    def test_stall_collapses_stretched_window(self):
        # a window held open past the static deadline whose flow then
        # stops flushes NOW — but never before the static deadline,
        # so the static aggregation behaviour stays the floor
        from veles_tpu import telemetry
        b = self._batcher(max_batch=8)
        try:
            for _ in range(12):
                b._gap_hist.record(0.002)
            now = time.perf_counter()
            with b._cond:
                b._last_arrival = now - 1.0   # way past 2x median gap
                c0 = telemetry.counter("serve.wait_collapsed").value
                # still inside the static window: holds to static
                left = b._wait_left(now, now - 0.001)
                assert abs(left - (b.max_wait_s - 0.001)) < 1e-9
                # past the static deadline: collapse, flush now
                assert b._wait_left(
                    now, now - b.max_wait_s - 0.001) == 0.0
                assert telemetry.counter(
                    "serve.wait_collapsed").value == c0 + 1
        finally:
            b.close()

    def test_filling_batch_stretches_window(self):
        # few rows missing + sub-ms cadence: the batch is predicted
        # to fill well inside the stretched window, so it holds open
        # past the static deadline
        b = self._batcher(max_batch=8)
        try:
            for _ in range(12):
                b._gap_hist.record(0.001)
            now = time.perf_counter()
            with b._cond:
                gap = b._gap_estimate(now)
                assert gap is not None
                b._last_arrival = now   # an arrival THIS instant
                # older than the static window, yet still held open
                left = b._wait_left(now, now - 1.5 * b.max_wait_s)
            assert left > 0.0
            assert left <= b._stretch * b.max_wait_s
        finally:
            b.close()

    def test_trickle_that_cannot_fill_stays_static(self):
        # arrivals keep pace but the cadence can NEVER fill 64 rows
        # inside the stretched window: the request pays the static
        # deadline, not stretch x it
        b = self._batcher(max_batch=64)
        try:
            for _ in range(12):
                b._gap_hist.record(0.002)
            now = time.perf_counter()
            with b._cond:
                assert b._gap_estimate(now) is not None
                b._last_arrival = now
                left = b._wait_left(now, now - 1.5 * b.max_wait_s)
            assert left <= 0.0
        finally:
            b.close()

    def test_stretch_cap_still_flushes(self):
        b = self._batcher(max_batch=8)
        try:
            for _ in range(12):
                b._gap_hist.record(0.001)
            now = time.perf_counter()
            with b._cond:
                b._gap_estimate(now)
                b._last_arrival = now
                left = b._wait_left(
                    now, now - b._stretch * b.max_wait_s - 0.001)
            assert left <= 0.0   # the age cap is stretch x static
        finally:
            b.close()

    def test_sparse_traffic_never_waits_past_static(self):
        # observed gaps FAR above the window: the pace bar clamps at
        # max_wait_s, so a lone request still flushes at the static
        # deadline instead of waiting out 2x a huge median gap (or
        # the stretched window)
        b = self._batcher()
        try:
            for _ in range(12):
                b._gap_hist.record(0.5)
            now = time.perf_counter()
            with b._cond:
                assert b._gap_estimate(now) is not None
                b._last_arrival = now - b.max_wait_s - 0.001
                assert b._wait_left(now, now - b.max_wait_s
                                    - 0.001) <= 0.0
        finally:
            b.close()

    def test_full_batch_does_not_stretch(self):
        # queued rows at capacity: nothing left to fill — the limit
        # stays static even while arrivals keep pace
        b = self._batcher(max_batch=4)
        try:
            for _ in range(12):
                b._gap_hist.record(0.002)
            now = time.perf_counter()
            with b._cond:
                assert b._gap_estimate(now) is not None
                b._last_arrival = now
                b._queued_rows = 4
                left = b._wait_left(now, now - 0.001)
                b._queued_rows = 0
            # bounded by the static deadline (no stretch) and by the
            # stall re-check wake-up — never past static remaining
            assert 0.0 < left <= b.max_wait_s - 0.001 + 1e-9
        finally:
            b.close()

    def test_knob_off_disables_estimator(self, monkeypatch):
        monkeypatch.setenv("VELES_SERVE_ADAPTIVE_WAIT", "0")
        b = self._batcher()
        try:
            assert not b._adaptive and b._gap_hist is None
            now = time.perf_counter()
            with b._cond:
                left = b._wait_left(now, now - 0.001)
            assert abs(left - (0.02 - 0.001)) < 1e-9
        finally:
            b.close()
