"""SPMD data parallelism (veles_tpu/parallel/): the sharded fused step
on an 8-device virtual CPU mesh must reproduce the single-device
training trajectory — the allreduce-in-compiler replacement for the
reference's master--slave aggregation (SURVEY.md §3.4, §5.8)."""

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import JaxDevice
from veles_tpu.datasets import synthetic_classification
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow
from veles_tpu.parallel import (DataParallel, MeshJaxDevice, batch_sharding,
                                make_mesh, replicated_sharding)


def build_workflow(mb=48, max_epochs=2, momentum=0.9, **loader_kw):
    prng.seed_all(777)
    train, valid, _ = synthetic_classification(
        480, 192, (12, 12, 1), n_classes=10, seed=42)
    gd = {"learning_rate": 0.1, "weight_decay": 0.0001,
          "gradient_moment": momentum}
    return StandardWorkflow(
        loader_factory=lambda w: ArrayLoader(
            w, train=train, valid=valid, minibatch_size=mb,
            name="loader", **loader_kw),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 32},
             "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 10},
             "<-": gd},
        ],
        decision_config={"max_epochs": max_epochs},
        name="dp_test")


def valid_history(w):
    return [h for h in w.decision.history if h["class"] == "validation"]


class TestMesh:
    def test_make_mesh(self):
        mesh = make_mesh(8)
        assert mesh.devices.size == 8
        assert mesh.axis_names == ("data",)

    def test_make_mesh_too_many(self):
        with pytest.raises(ValueError, match="need 64 devices"):
            make_mesh(64)

    def test_shardings(self):
        import jax
        mesh = make_mesh(4)
        x = jax.device_put(np.zeros((8, 3), np.float32),
                           batch_sharding(mesh))
        assert not x.is_fully_replicated
        r = jax.device_put(np.zeros((8, 3), np.float32),
                           replicated_sharding(mesh))
        assert r.is_fully_replicated


class TestDataParallel:
    def test_install_rejects_indivisible(self):
        w = build_workflow(mb=50)
        dp = DataParallel(w, 8)
        with pytest.raises(ValueError, match="not divisible"):
            dp.install()

    def test_rejects_clamped_static_batch(self):
        """minibatch_size divisible but every class smaller: the static
        shape clamps to max_minibatch_size — must fail with a clear
        error at initialize, not crash inside device_put."""
        prng.seed_all(777)
        train, valid, _ = synthetic_classification(
            100, 40, (8, 8, 1), n_classes=4, seed=1)
        w = StandardWorkflow(
            loader_factory=lambda wf: ArrayLoader(
                wf, train=train, valid=valid, minibatch_size=128,
                name="loader"),
            layers=[{"type": "softmax", "->": {"output_sample_shape": 4},
                     "<-": {"learning_rate": 0.1}}],
            decision_config={"max_epochs": 1}, name="clamped")
        dp = DataParallel(w, 8)
        dev = dp.install()   # passes: 128 % 8 == 0
        with pytest.raises(ValueError, match="max_minibatch_size"):
            w.initialize(device=dev)

    def test_mesh_device_put_replicates(self):
        dev = MeshJaxDevice(make_mesh(8))
        buf = dev.put(np.arange(16, dtype=np.float32))
        assert buf.is_fully_replicated
        np.testing.assert_array_equal(np.asarray(buf), np.arange(16))

    def test_dp_matches_single_device(self):
        """The sharded global-minibatch step must follow the same
        trajectory as the unsharded fused step (same seed)."""
        w1 = build_workflow()
        w1.initialize(device=JaxDevice(platform="cpu"))
        w1.run()

        w8 = build_workflow()
        dp = DataParallel(w8, 8)
        w8.initialize(device=dp.install())
        w8.run()

        h1, h8 = valid_history(w1), valid_history(w8)
        assert len(h1) == len(h8) == 2
        for a, b in zip(h1, h8):
            assert abs(a["loss"] - b["loss"]) < 5e-3, (a, b)
            assert abs(a["n_err"] - b["n_err"]) <= 3, (a, b)

    def test_dp_learns_and_params_replicated(self):
        w = build_workflow(max_epochs=8)
        dp = DataParallel(w, 8)
        w.initialize(device=dp.install())
        w.run()
        assert w.decision.epoch_error_pct[1] < 40.0, \
            w.decision.epoch_error_pct
        # updated weights must still be replicated across the mesh
        # (anything else means the partitioner failed to allreduce)
        wts = w.fused._params[w.forwards[0].name]["weights"]
        assert wts.is_fully_replicated
        assert np.isfinite(np.asarray(wts)).all()

    def test_dp_snapshot_roundtrip(self, tmp_path):
        """Mesh never reaches the pickle; resumed run re-installs DP."""
        import pickle
        w = build_workflow(max_epochs=1)
        dp = DataParallel(w, 4)
        w.initialize(device=dp.install())
        w.run()
        blob = pickle.dumps(w)
        w2 = pickle.loads(blob)
        assert w2.fused.mesh is None
        dp2 = DataParallel(w2, 4)
        w2.decision.max_epochs = 2
        w2.initialize(device=dp2.install())
        w2.run()
        assert len(valid_history(w2)) >= 1


class TestLauncherDP:
    def test_launcher_dp_flag(self):
        from veles_tpu.launcher import Launcher
        launcher = Launcher(backend="cpu", seed=777, dp=8)
        launcher.create_workflow(lambda l: build_workflow(max_epochs=1))
        launcher.initialize()
        assert isinstance(launcher.device, MeshJaxDevice)
        launcher.run()
        assert len(valid_history(launcher.workflow)) == 1


class TestStreamingDataParallel:
    def test_streaming_dp_matches_single_device_streaming(self):
        """The combination: host-streaming batches (no HBM-resident
        dataset) entering the SHARDED fused step.  _run_streaming
        device_puts the assembled superstep batch with the mesh's
        batch sharding; trajectory must match single-device streaming
        (the dp story cannot be resident-only — ImageNet-scale data is
        why streaming exists)."""
        w1 = build_workflow(max_resident_bytes=0)
        w1.initialize(device=JaxDevice(platform="cpu"))
        assert w1.fused.streaming
        w1.run()

        w8 = build_workflow(max_resident_bytes=0)
        dp = DataParallel(w8, 8)
        w8.initialize(device=dp.install())
        assert w8.fused.streaming
        w8.run()

        h1, h8 = valid_history(w1), valid_history(w8)
        assert len(h1) == len(h8) == 2
        for a, b in zip(h1, h8):
            assert abs(a["loss"] - b["loss"]) < 5e-3, (a, b)
            assert abs(a["n_err"] - b["n_err"]) <= 3, (a, b)
        wts = w8.fused._params[w8.forwards[0].name]["weights"]
        assert wts.is_fully_replicated
