"""Parallel, restartable GA tuner (round-2 VERDICT next #6):
subprocess-per-genome isolation, N workers, per-generation checkpoint,
resume after an uncontrolled kill."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.genetics import GeneticOptimizer, Tune

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def quad(v):
    return (v["x"] - 2.0) ** 2 + (v["y"] + 1.0) ** 2


TUNES = {"x": Tune(5.0, -10.0, 10.0), "y": Tune(-3.0, -10.0, 10.0)}


class TestCheckpointResume:
    def test_interrupted_run_resumes_bit_identically(self, tmp_path):
        state = str(tmp_path / "ga.json")

        prng.seed_all(4242)
        best_ref, fit_ref = GeneticOptimizer(
            quad, TUNES, population=8, generations=6).run()

        # same seed, but die mid-generation-3 (KeyboardInterrupt is not
        # swallowed by the bad-gene guard)
        calls = {"n": 0}

        def dying(v):
            calls["n"] += 1
            if calls["n"] > 20:
                raise KeyboardInterrupt
            return quad(v)

        prng.seed_all(4242)
        with pytest.raises(KeyboardInterrupt):
            GeneticOptimizer(dying, TUNES, population=8, generations=6,
                             state_path=state).run()
        assert os.path.exists(state)
        gen_at_death = json.load(open(state))["generation"]
        assert 0 < gen_at_death < 6

        # resume: rng state comes from the file, so the completed run
        # must equal the uninterrupted one exactly
        prng.seed_all(999999)  # proves the stream seed is irrelevant
        best2, fit2 = GeneticOptimizer(
            quad, TUNES, population=8, generations=6,
            state_path=state).run()
        assert best2 == pytest.approx(best_ref)
        assert fit2 == pytest.approx(fit_ref)
        assert json.load(open(state))["generation"] == 6

    def test_stale_state_for_other_genes_rejected(self, tmp_path):
        state = str(tmp_path / "ga.json")
        prng.seed_all(1)
        GeneticOptimizer(quad, TUNES, population=4, generations=1,
                         state_path=state).run()
        with pytest.raises(ValueError, match="stale"):
            GeneticOptimizer(lambda v: v["z"],
                             {"z": Tune(0.0, -1.0, 1.0)},
                             population=4, generations=1,
                             state_path=state).run()

    def test_evaluate_many_used(self):
        batches = []

        def many(values_list):
            batches.append(len(values_list))
            return [quad(v) for v in values_list]

        prng.seed_all(7)
        GeneticOptimizer(quad, TUNES, population=6, generations=2,
                         evaluate_many=many).run()
        assert batches[0] == 6          # initial population as a batch
        assert all(b == 4 for b in batches[1:])  # pop - elite


class TestDeviceRacePolicy:
    """Parallel genome workers must never race to initialize an
    exclusive TPU chip (round-3 VERDICT next #8).  Since ISSUE 3 the
    ``auto`` answer is the chip-owning evaluator: ONE serve-mode
    subprocess owns the device, the N workers become host prep threads
    — the chip is used by default AND the race is structurally gone."""

    def test_auto_routes_to_chip_evaluator(self):
        from veles_tpu.__main__ import _resolve_ga_execution
        assert _resolve_ga_execution("auto", 4) == (4, "tpu-evaluator")
        assert _resolve_ga_execution("auto", 1) == (1, "tpu-evaluator")
        assert _resolve_ga_execution("tpu-evaluator", 3) == \
            (3, "tpu-evaluator")

    def test_explicit_tpu_parallel_serializes(self):
        from veles_tpu.__main__ import _resolve_ga_execution
        assert _resolve_ga_execution("tpu", 4) == (1, "tpu")
        assert _resolve_ga_execution("jax", 2) == (1, "jax")

    def test_cpu_and_single_worker_unchanged(self):
        from veles_tpu.__main__ import _resolve_ga_execution
        assert _resolve_ga_execution("cpu", 4) == (4, "cpu")
        assert _resolve_ga_execution("numpy", 3) == (3, "numpy")
        assert _resolve_ga_execution("tpu", 1) == (1, "tpu")


@pytest.fixture
def tuned_workflow(tmp_path):
    wf = tmp_path / "wf.py"
    wf.write_text(textwrap.dedent("""
        from veles_tpu.models import mnist

        def run(launcher):
            launcher.create_workflow(mnist.create_workflow)
            launcher.initialize()
            launcher.run()
    """))
    cfg = tmp_path / "cfg.py"
    cfg.write_text(textwrap.dedent("""
        from veles_tpu.config import root
        from veles_tpu.genetics import Tune

        root.mnist.loader = {"minibatch_size": 25, "n_train": 100,
                             "n_valid": 40}
        root.mnist.decision = {"max_epochs": 1}
        root.mnist.layers = [
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": Tune(16, 8, 32)},
             "<-": {"learning_rate": Tune(0.1, 0.01, 1.0)}},
            {"type": "softmax", "->": {"output_sample_shape": 10},
             "<-": {"learning_rate": 0.1}},
        ]
    """))
    return str(wf), str(cfg)


def ga_cmd(wf, cfg, state, pop_gen="3:2", workers="2"):
    return [sys.executable, "-m", "veles_tpu", "-b", "cpu",
            "--optimize", pop_gen, "--ga-workers", workers,
            "--ga-state", state, wf, cfg]


class TestChipEvaluatorPool:
    """The tpu-evaluator execution mode (round-4/5 VERDICT weak:
    `_resolve_ga_execution("auto", N>1)` used to idle the chip):
    exactly one serve-mode evaluator process owns the device and
    evaluates every genome; prep workers are host threads."""

    def serve_cmd(self, wf, cfg, backend="cpu"):
        return [sys.executable, "-m", "veles_tpu.genetics.worker",
                "--serve", wf, cfg, "-b", backend, "-s", "1234"]

    def test_one_process_evaluates_all_genomes(self, tuned_workflow):
        from veles_tpu.genetics.pool import ChipEvaluatorPool
        wf, cfg = tuned_workflow
        good = {"mnist.layers[0]['->']['output_sample_shape']": 16,
                "mnist.layers[0]['<-']['learning_rate']": 0.1}
        other = dict(good)
        other["mnist.layers[0]['<-']['learning_rate']"] = 0.3
        with ChipEvaluatorPool(self.serve_cmd(wf, cfg), workers=2,
                               timeout=300) as pool:
            hello = pool.hello
            assert hello["ready"] and hello["pid"] > 0
            # in the CPU suite the device is XLA:CPU — not an
            # accelerator, which is exactly what the `auto` fallback
            # policy keys on
            assert hello["platform"] == "cpu"
            assert not pool.is_accelerator
            fits = pool.evaluate_many([good, other])
            assert len(fits) == 2
            assert all(np.isfinite(f) for f in fits), fits
            # different genomes produced different trainings
            assert fits[0] != fits[1] or fits[0] >= 0
            # a later call reuses the SAME evaluator process
            pid_before = pool.hello["pid"]
            assert np.isfinite(pool.evaluate_one(good))
            assert pool.hello["pid"] == pid_before

    def test_bad_genome_scores_inf_and_evaluator_survives(
            self, tuned_workflow):
        from veles_tpu.genetics.pool import ChipEvaluatorPool
        wf, cfg = tuned_workflow
        good = {"mnist.layers[0]['->']['output_sample_shape']": 16,
                "mnist.layers[0]['<-']['learning_rate']": 0.1}
        bad = dict(good)
        bad["mnist.layers[0]['->']['output_sample_shape']"] = -5
        with ChipEvaluatorPool(self.serve_cmd(wf, cfg), workers=2,
                               timeout=300) as pool:
            fits = pool.evaluate_many([good, bad, good])
            assert np.isfinite(fits[0])
            assert fits[1] == float("inf")
            assert np.isfinite(fits[2])  # the queue kept draining

    def test_cli_explicit_tpu_evaluator_mode(self, tuned_workflow):
        """End to end through `python -m veles_tpu -b tpu-evaluator
        --optimize`: one evaluator process, N>1 prep workers, finite
        best fitness."""
        wf, cfg = tuned_workflow
        res = subprocess.run(
            [sys.executable, "-m", "veles_tpu", "-b", "tpu-evaluator",
             "--optimize", "3:1", "--ga-workers", "2", wf, cfg],
            capture_output=True, text=True, cwd=REPO, timeout=600)
        assert res.returncode == 0, res.stderr[-2000:]
        assert "tpu-evaluator mode" in res.stderr
        out = json.loads(res.stdout.strip().splitlines()[-1])
        assert np.isfinite(out["fitness"])

    def test_cli_auto_falls_back_without_accelerator(
            self, tuned_workflow):
        """`-b auto` probes the device ONLY inside the evaluator
        child; with no accelerator (this suite pins XLA:CPU) the run
        falls back to the classic cpu subprocess fan-out and still
        completes."""
        wf, cfg = tuned_workflow
        res = subprocess.run(
            [sys.executable, "-m", "veles_tpu", "-b", "auto",
             "--optimize", "2:1", "--ga-workers", "2", wf, cfg],
            capture_output=True, text=True, cwd=REPO, timeout=600)
        assert res.returncode == 0, res.stderr[-2000:]
        assert "falling back" in res.stderr
        out = json.loads(res.stdout.strip().splitlines()[-1])
        assert np.isfinite(out["fitness"])

    def test_tpu_evaluator_without_optimize_rejected(self):
        res = subprocess.run(
            [sys.executable, "-m", "veles_tpu", "-b", "tpu-evaluator",
             "nonexistent_wf.py"],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert res.returncode == 2
        assert "--optimize" in res.stderr


DYING_WORKER = """
import json, os, sys

sentinel = sys.argv[1]
print(json.dumps({"ready": True, "pid": os.getpid(),
                  "backend": "cpu", "platform": "cpu",
                  "is_accelerator": False}), flush=True)
for line in sys.stdin:
    job = json.loads(line)
    if job.get("op") == "shutdown":
        break
    if not os.path.exists(sentinel):
        # first delivery EVER: die mid-genome (simulates an
        # evaluator-side crash that is not the genome's fault)
        open(sentinel, "w").close()
        os._exit(1)
    print(json.dumps({"id": job["id"],
                      "fitness": float(job["values"]["x"])}),
          flush=True)
"""


class TestEvaluatorDeathRetry:
    """An evaluator-side death must not condemn the in-flight genome:
    it is retried ONCE on the fresh evaluator before scoring inf."""

    def make_pool(self, tmp_path, timeout=60):
        from veles_tpu.genetics.pool import ChipEvaluatorPool
        worker = tmp_path / "dying_worker.py"
        worker.write_text(DYING_WORKER)
        sentinel = tmp_path / "died_once"
        return ChipEvaluatorPool(
            [sys.executable, str(worker), str(sentinel)],
            workers=2, timeout=timeout)

    def test_in_flight_genome_retried_once_then_scores(self, tmp_path):
        with self.make_pool(tmp_path) as pool:
            first_pid = pool.hello["pid"]
            fits = pool.evaluate_many([{"x": 1.5}, {"x": 2.5}])
            # the worker died on genome 1's first delivery; the retry
            # on the fresh evaluator succeeded — NO unfair inf
            assert fits == [1.5, 2.5]
            assert pool.hello["pid"] != first_pid   # restarted

    def test_twice_lost_genome_scores_inf(self, tmp_path):
        with self.make_pool(tmp_path) as pool:
            # poison pill: the worker dies whenever x is the string
            # "die" (float() raises -> worker crashes uncleanly)
            import os
            sentinel = tmp_path / "died_once"
            open(sentinel, "w").close()   # skip the one-time death
            assert os.path.exists(sentinel)
            fits = pool.evaluate_many([{"x": "die"}, {"x": 3.5}])
            assert fits[0] == float("inf")   # lost twice -> inf
            assert fits[1] == 3.5            # queue kept draining


class TestSubprocessGA:
    def test_worker_evaluates_one_genome(self, tuned_workflow):
        wf, cfg = tuned_workflow
        res = subprocess.run(
            [sys.executable, "-m", "veles_tpu.genetics.worker",
             wf, cfg, "-b", "cpu", "--values",
             json.dumps({"mnist.layers[0]['->']"
                         "['output_sample_shape']": 16,
                         "mnist.layers[0]['<-']"
                         "['learning_rate']": 0.1})],
            capture_output=True, text=True, cwd=REPO, timeout=300)
        assert res.returncode == 0, res.stderr[-2000:]
        fit = json.loads(res.stdout.strip().splitlines()[-1])["fitness"]
        assert np.isfinite(fit) and fit >= 0

    def test_parallel_ga_completes_and_resumes_after_kill(
            self, tuned_workflow, tmp_path):
        wf, cfg = tuned_workflow
        state = str(tmp_path / "ga_state.json")

        # start, then kill -9 once generation 1 is checkpointed
        proc = subprocess.Popen(ga_cmd(wf, cfg, state),
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True,
                                cwd=REPO)
        deadline = time.time() + 600
        killed = False
        while time.time() < deadline:
            if os.path.exists(state) and \
                    json.load(open(state))["generation"] >= 1:
                os.kill(proc.pid, signal.SIGKILL)
                killed = True
                break
            if proc.poll() is not None:
                break  # finished before we could kill: still fine
            time.sleep(0.5)
        proc.wait(timeout=60)
        assert killed or proc.returncode == 0

        # resume (or re-run) to completion
        res = subprocess.run(ga_cmd(wf, cfg, state),
                             capture_output=True, text=True, cwd=REPO,
                             timeout=600)
        assert res.returncode == 0, res.stderr[-2000:]
        out = json.loads(res.stdout.strip().splitlines()[-1])
        assert np.isfinite(out["fitness"])
        assert json.load(open(state))["generation"] == 2
        if killed:
            assert "resumed GA at generation" in res.stderr
