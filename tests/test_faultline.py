"""Faultline: deterministic fault injection + the supervision layer
it drills (ISSUE 6).

Covers: the registry's arming/matching semantics, CRC-checked
snapshot and GA-checkpoint persistence with newest-intact-predecessor
fallback, streaming-loader corrupt-file skip/count/threshold-abort,
OOM bounded degradation, and the headline acceptance: a HUNG (not
crashed) evaluator is detected and its genome re-dispatched within
the heartbeat deadline, with the generation completing at fitness
parity.
"""

import json
import os
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from veles_tpu import faults, prng
from veles_tpu.genetics import GeneticOptimizer, Tune
from veles_tpu.genetics.pool import ChipEvaluatorPool
from veles_tpu.snapshotter import (SnapshotCorruptError, load_workflow,
                                   save_workflow)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends disarmed, whatever it armed."""
    faults.arm("")
    yield
    faults.arm("")


pytestmark = pytest.mark.chaos


class TestFaultRegistry:
    def test_disarmed_is_noop(self):
        assert not faults.active()
        assert faults.fire("evaluator.hang", seq=0) is None

    def test_qualifier_matching(self):
        faults.arm("stream.corrupt_file@index=7")
        assert faults.fire("stream.corrupt_file", index=3) is None
        hit = faults.fire("stream.corrupt_file", index=7)
        assert hit and hit["point"] == "stream.corrupt_file"

    def test_missing_context_key_never_matches(self):
        # @gen=2 must be inert at call sites that don't know gen
        faults.arm("checkpoint.corrupt@gen=2")
        assert faults.fire("checkpoint.corrupt") is None
        assert faults.fire("checkpoint.corrupt", gen=1) is None
        assert faults.fire("checkpoint.corrupt", gen=2)

    def test_times_budget_default_one(self):
        faults.arm("evaluator.garbage_line")
        assert faults.fire("evaluator.garbage_line", seq=0)
        assert faults.fire("evaluator.garbage_line", seq=1) is None

    def test_times_n_and_unlimited(self):
        faults.arm("evaluator.garbage_line@times=2,"
                   "snapshot.torn_write@times=*")
        assert faults.fire("evaluator.garbage_line")
        assert faults.fire("evaluator.garbage_line")
        assert faults.fire("evaluator.garbage_line") is None
        for _ in range(5):
            assert faults.fire("snapshot.torn_write")

    def test_knobs_ride_payload_not_matching(self):
        faults.arm("evaluator.hang@seq=1&silent=1&seconds=30")
        hit = faults.fire("evaluator.hang", seq=1)
        assert hit["silent"] == "1" and float(hit["seconds"]) == 30.0

    def test_unknown_point_rejected(self):
        with pytest.raises(ValueError, match="unknown injection"):
            faults.arm("evaluator.hagn")

    def test_env_inheritance(self, monkeypatch):
        """arm(None) reads the env var — what spawned children do at
        import."""
        monkeypatch.setenv(faults.ENV_VAR, "snapshot.torn_write")
        faults.arm(None)
        assert faults.fire("snapshot.torn_write", path="x")

    def test_garbage_is_deterministic_and_not_json(self):
        a = faults.garbage_text(point="evaluator")
        assert a == faults.garbage_text(point="evaluator")
        with pytest.raises(ValueError):
            json.loads(a)


class TestSnapshotIntegrity:
    def test_crc_roundtrip(self, tmp_path):
        p = str(tmp_path / "snap_epoch1.pickle.gz")
        save_workflow({"k": [1, 2, 3]}, p)
        assert load_workflow(p) == {"k": [1, 2, 3]}

    def test_torn_write_detected_and_falls_back(self, tmp_path):
        p1 = str(tmp_path / "snap_epoch1.pickle.gz")
        p2 = str(tmp_path / "snap_epoch2.pickle.gz")
        save_workflow({"marker": 1}, p1)
        faults.arm("snapshot.torn_write")
        save_workflow({"marker": 2}, p2)
        with pytest.raises(SnapshotCorruptError):
            load_workflow(p2)
        # fallback: newest INTACT predecessor, not a crash and not a
        # silent fresh start
        assert load_workflow(p2, fallback=True) == {"marker": 1}

    def test_bitflip_detected_by_crc(self, tmp_path):
        # uncompressed container so the flip hits the payload, not a
        # gzip header the codec would catch first
        p = str(tmp_path / "snap_epoch1.pickle")
        save_workflow({"marker": 1}, p)
        raw = bytearray(open(p, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        open(p, "wb").write(bytes(raw))
        with pytest.raises(SnapshotCorruptError):
            load_workflow(p)

    def test_no_intact_predecessor_raises(self, tmp_path):
        p = str(tmp_path / "snap_epoch1.pickle.gz")
        save_workflow({"marker": 1}, p)
        os.truncate(p, os.path.getsize(p) // 2)
        with pytest.raises(SnapshotCorruptError):
            load_workflow(p, fallback=True)

    def test_legacy_format_still_loads(self, tmp_path):
        import gzip
        import pickle

        from veles_tpu import prng as _prng
        p = str(tmp_path / "snap_epoch1.pickle.gz")
        payload = {"format": 1, "workflow": {"legacy": True},
                   "prng": _prng.snapshot_state(), "timestamp": 0.0}
        with gzip.open(p, "wb") as f:
            pickle.dump(payload, f)
        assert load_workflow(p) == {"legacy": True}

    def test_concurrent_writers_do_not_tear(self, tmp_path):
        """The old shared ``path + '.tmp'`` name let two writers tear
        each other; pid/thread-unique temp files + os.replace make
        concurrent saves atomic — the survivor is always intact."""
        p = str(tmp_path / "snap_epoch1.pickle")
        errors = []

        def writer(marker):
            try:
                for _ in range(10):
                    save_workflow({"marker": marker}, p)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        ts = [threading.Thread(target=writer, args=(m,))
              for m in (1, 2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errors
        assert load_workflow(p)["marker"] in (1, 2)
        assert not [f for f in os.listdir(tmp_path)
                    if f.endswith(".tmp")]


TUNES = {"x": Tune(5.0, -10.0, 10.0), "y": Tune(-3.0, -10.0, 10.0)}


def quad(v):
    return (v["x"] - 2.0) ** 2 + (v["y"] + 1.0) ** 2


class TestGACheckpointIntegrity:
    def test_corrupt_checkpoint_falls_back_bit_identically(
            self, tmp_path):
        state = str(tmp_path / "ga.json")
        prng.seed_all(4242)
        _, fit_ref = GeneticOptimizer(
            quad, TUNES, population=6, generations=4,
            state_path=str(tmp_path / "ref.json")).run()
        # run again with the FINAL checkpoint write torn by the
        # injected fault, then resume: .prev must carry it to the
        # same answer bit-identically
        prng.seed_all(4242)
        faults.arm("checkpoint.corrupt@gen=4")
        GeneticOptimizer(quad, TUNES, population=6, generations=4,
                         state_path=state).run()
        faults.arm("")
        prng.seed_all(999999)   # resume restores the rng from disk
        _, fit2 = GeneticOptimizer(quad, TUNES, population=6,
                                   generations=4,
                                   state_path=state).run()
        assert fit2 == pytest.approx(fit_ref, abs=0)

    def test_both_corrupt_raises_never_fresh_start(self, tmp_path):
        state = str(tmp_path / "ga.json")
        prng.seed_all(1)
        GeneticOptimizer(quad, TUNES, population=4, generations=2,
                         state_path=state).run()
        os.truncate(state, os.path.getsize(state) // 2)
        os.truncate(state + ".prev",
                    os.path.getsize(state + ".prev") // 2)
        with pytest.raises(SnapshotCorruptError):
            GeneticOptimizer(quad, TUNES, population=4, generations=2,
                             state_path=state).run()

    def test_state_file_is_plain_json_with_crc(self, tmp_path):
        state = str(tmp_path / "ga.json")
        prng.seed_all(1)
        GeneticOptimizer(quad, TUNES, population=4, generations=1,
                         state_path=state).run()
        st = json.load(open(state))
        assert st["generation"] == 1 and "crc32" in st

    def test_embedded_crc_catches_value_corruption(self, tmp_path):
        state = str(tmp_path / "ga.json")
        prng.seed_all(1)
        GeneticOptimizer(quad, TUNES, population=4, generations=1,
                         state_path=state).run()
        st = json.load(open(state))
        st["fits"][0] = 0.0    # a bit-flip that stays valid JSON
        json.dump(st, open(state, "w"))
        os.remove(state + ".prev")
        with pytest.raises(SnapshotCorruptError):
            GeneticOptimizer(quad, TUNES, population=4, generations=1,
                             state_path=state).run()


class TestLoaderCorruptFiles:
    @pytest.fixture
    def image_tree(self, tmp_path):
        PIL = pytest.importorskip("PIL.Image")
        rng = np.random.default_rng(7)
        paths = []
        for i in range(12):
            p = str(tmp_path / f"img_{i:02d}.png")
            PIL.fromarray(
                rng.integers(0, 255, (8, 8, 3), dtype="uint8")).save(p)
            paths.append((p, i % 3))
        return paths

    def _loader(self, paths, **kw):
        from veles_tpu.loader.image import FileListImageLoader
        kw.setdefault("corrupt_tolerance", 0.1)
        kw.setdefault("streaming", False)
        return FileListImageLoader(
            train=paths, minibatch_size=4, target_shape=(8, 8, 3),
            name="chaosldr", **kw)

    def test_corrupt_file_skipped_and_counted(self, image_tree):
        faults.arm("stream.corrupt_file@index=7")
        ld = self._loader(image_tree)
        ld.load_data()
        assert ld.corrupt_indices == {7}
        data = ld.original_data.mem
        assert not data[7].any()          # zero row substituted
        assert all(data[i].any() for i in range(12) if i != 7)

    def test_really_corrupt_file_skipped(self, image_tree, tmp_path):
        """No injection: an actually-truncated PNG takes the same
        path."""
        bad_path = image_tree[5][0]
        raw = open(bad_path, "rb").read()
        open(bad_path, "wb").write(raw[: len(raw) // 3])
        ld = self._loader(image_tree)
        ld.load_data()
        assert ld.corrupt_indices == {5}

    def test_over_threshold_aborts_loudly(self, image_tree):
        faults.arm("stream.corrupt_file@index=3,"
                   "stream.corrupt_file@index=4,"
                   "stream.corrupt_file@index=5")
        ld = self._loader(image_tree)
        with pytest.raises(RuntimeError, match="corrupt_tolerance"):
            ld.load_data()

    def test_zero_tolerance_aborts_on_first(self, image_tree):
        faults.arm("stream.corrupt_file@index=2")
        ld = self._loader(image_tree, corrupt_tolerance=0.0)
        with pytest.raises(RuntimeError, match="corrupt_tolerance"):
            ld.load_data()

    def test_streaming_mode_skips_mid_epoch(self, image_tree):
        """The streaming decode path (assemble_rows on the prefetch
        thread) skips-and-counts the same way."""
        faults.arm("stream.corrupt_file@index=9")
        ld = self._loader(image_tree, streaming=True)
        ld.load_data()
        assert ld._stream
        ld.post_load_data()
        data, labels, _ = ld.assemble_rows(np.arange(12))
        assert ld.corrupt_indices == {9}
        assert not data[9].any() and data[0].any()


class TestOOMDegradation:
    def _workflow(self, streaming):
        from veles_tpu.datasets import synthetic_classification
        from veles_tpu.loader import ArrayLoader
        from veles_tpu.ops.standard_workflow import StandardWorkflow
        prng.seed_all(1357)
        train, valid, _ = synthetic_classification(
            160, 40, (8, 8, 1), n_classes=4, seed=7)
        kw = {"max_resident_bytes": 0} if streaming else {}
        gd = {"learning_rate": 0.1}
        return StandardWorkflow(
            loader_factory=lambda w: ArrayLoader(
                w, train=train, valid=valid, minibatch_size=20,
                name="loader", **kw),
            layers=[
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 16}, "<-": gd},
                {"type": "softmax", "->": {"output_sample_shape": 4},
                 "<-": gd},
            ],
            decision_config={"max_epochs": 2}, name="oom_wf")

    def test_resident_upload_oom_degrades_to_streaming(self):
        from veles_tpu.backends import JaxDevice
        w = self._workflow(streaming=False)
        faults.arm("device.oom_on_put@site=resident_dataset")
        w.initialize(device=JaxDevice(platform="cpu"))
        faults.arm("")
        assert not w.loader.device_resident
        assert w.fused.streaming
        w.run()
        hist = [h for h in w.decision.history
                if h["class"] == "validation"]
        assert hist and np.isfinite(hist[-1]["loss"])
        w.stop()

    def test_streaming_put_oom_drains_and_retries(self):
        from veles_tpu.backends import JaxDevice
        w = self._workflow(streaming=True)
        w.initialize(device=JaxDevice(platform="cpu"))
        faults.arm("device.oom_on_put@site=stream")
        w.run()
        faults.arm("")
        assert w.fused.stream_oom_retries == 1
        hist = [h for h in w.decision.history
                if h["class"] == "validation"]
        assert hist and np.isfinite(hist[-1]["loss"])
        w.stop()


HANG_WORKER = """
import json, os, sys, threading, time

hang_seq = int(sys.argv[1])        # job ordinal to hang on
silent = sys.argv[2] == "silent"   # stop heartbeats while hung
hb_every = float(sys.argv[3])
sentinel = sys.argv[4]             # hang only once across restarts
state = {"silent": False}
lock = threading.Lock()

def emit(o):
    with lock:
        print(json.dumps(o), flush=True)

emit({"ready": True, "pid": os.getpid(), "backend": "cpu",
      "platform": "cpu", "is_accelerator": False})

def hb():
    n = 0
    while True:
        time.sleep(hb_every)
        if not state["silent"]:
            emit({"hb": n, "pid": os.getpid()})
            n += 1

if hb_every > 0:
    threading.Thread(target=hb, daemon=True).start()

seq = 0
for line in sys.stdin:
    job = json.loads(line)
    if job.get("op") == "shutdown":
        break
    if seq == hang_seq and not os.path.exists(sentinel):
        open(sentinel, "w").close()
        state["silent"] = silent
        time.sleep(3600)           # the hang: alive but stuck
    time.sleep(0.2)                # "training"
    emit({"id": job["id"], "fitness": float(job["values"]["x"])})
    seq += 1
"""


class TestHungEvaluatorSupervision:
    """Acceptance: an injected evaluator HANG (process alive, no
    crash) is detected and the genome re-dispatched within the
    heartbeat deadline, the generation completes, and fitness parity
    is preserved."""

    def make_pool(self, tmp_path, hang_seq, mode, **kw):
        worker = tmp_path / "hang_worker.py"
        worker.write_text(HANG_WORKER)
        kw.setdefault("heartbeat_deadline", 2.0)
        kw.setdefault("restart_backoff", 0.1)
        return ChipEvaluatorPool(
            [sys.executable, str(worker), str(hang_seq), mode, "0.2",
             str(tmp_path / "hung_once")],
            workers=2, timeout=120, **kw)

    def test_silent_hang_caught_by_heartbeat_deadline(self, tmp_path):
        pool = self.make_pool(tmp_path, hang_seq=1, mode="silent",
                              min_genome_deadline=60)
        t0 = time.monotonic()
        with pool:
            fits = pool.evaluate_many(
                [{"x": 1.0}, {"x": 2.0}, {"x": 3.0}])
        wall = time.monotonic() - t0
        assert fits == [1.0, 2.0, 3.0]        # parity: no unfair inf
        assert pool.hangs_detected == 1
        assert pool.last_hang_kind == "heartbeat"
        # detection within the deadline (+ one 1s poll slice of slack)
        assert pool.last_hang_wait <= 2.0 + 1.5
        assert wall < 30.0

    def test_live_hang_caught_by_adaptive_deadline(self, tmp_path):
        """Heartbeats keep flowing (the process is alive, the genome
        is stuck) — the EMA-scaled per-genome deadline catches it
        without waiting for the 120s whole-genome timeout."""
        pool = self.make_pool(tmp_path, hang_seq=2, mode="live",
                              min_genome_deadline=1.0,
                              genome_deadline_factor=4.0)
        with pool:
            fits = pool.evaluate_many(
                [{"x": 1.0}, {"x": 2.0}, {"x": 3.0}, {"x": 4.0}])
        assert fits == [1.0, 2.0, 3.0, 4.0]
        assert pool.hangs_detected == 1
        assert pool.last_hang_kind == "genome_deadline"
        assert pool.genome_duration_ema < 2.0
        assert pool.last_hang_wait < 10.0

    def test_twice_hung_genome_scores_inf_and_queue_drains(
            self, tmp_path):
        # hang keyed on the GENOME (x == 1.0), not the job ordinal:
        # the poisoned genome hangs EVERY evaluator it reaches — lost
        # twice, it must score inf without condemning its neighbors
        worker = tmp_path / "hang_worker.py"
        worker.write_text(HANG_WORKER.replace(
            "if seq == hang_seq and not os.path.exists(sentinel):",
            "if job[\"values\"][\"x\"] == 1.0:"))
        pool = ChipEvaluatorPool(
            [sys.executable, str(worker), "0", "silent", "0.2",
             str(tmp_path / "unused")],
            workers=2, timeout=120, heartbeat_deadline=2.0,
            restart_backoff=0.1)
        with pool:
            fits = pool.evaluate_many([{"x": 1.0}, {"x": 2.0}])
        # the always-hanging genome 1 lost two evaluators -> inf; the
        # NEXT genome still resolves on the third evaluator
        assert fits[0] == float("inf")
        assert fits[1] == 2.0
        assert pool.hangs_detected >= 2

    def test_real_evaluator_hang_injected_via_env(self, tmp_path,
                                                  monkeypatch):
        """End to end on the REAL serve-mode evaluator: VELES_FAULTS
        hangs it silently mid-genome; the pool replaces it within the
        heartbeat deadline and the generation completes with finite
        fitnesses."""
        wf = tmp_path / "wf.py"
        wf.write_text(textwrap.dedent("""
            from veles_tpu.models import wine

            def run(launcher):
                launcher.create_workflow(wine.create_workflow)
                launcher.initialize()
                launcher.run()
        """))
        cfg = tmp_path / "cfg.py"
        cfg.write_text(textwrap.dedent("""
            from veles_tpu.config import root
            from veles_tpu.genetics import Tune

            root.wine.decision = {"max_epochs": 2}
            root.wine.layers = [
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 8},
                 "<-": {"learning_rate": Tune(0.3, 0.01, 1.0)}},
                {"type": "softmax", "->": {"output_sample_shape": 3},
                 "<-": {"learning_rate": 0.3}},
            ]
        """))
        # job=2&seq=1: the hang fires when wire-job 2 runs as the
        # SECOND job of an evaluator — true on the first evaluator,
        # false on the replacement (where the retried job 2 comes
        # first), so the drill injects exactly one hang
        monkeypatch.setenv(
            "VELES_FAULTS",
            "evaluator.hang@job=2&seq=1&silent=1&seconds=600,"
            "evaluator.garbage_line@job=1")
        lr = "wine.layers[0]['<-']['learning_rate']"
        pool = ChipEvaluatorPool(
            [sys.executable, "-m", "veles_tpu.genetics.worker",
             "--serve", str(wf), str(cfg), "-b", "cpu", "-s", "1234",
             "--heartbeat-every", "0.5"],
            workers=2, timeout=600, heartbeat_deadline=8.0,
            restart_backoff=0.1)
        first_pid = None
        with pool:
            first_pid = pool.hello["pid"]
            fits = pool.evaluate_many(
                [{lr: 0.1}, {lr: 0.3}, {lr: 0.6}])
        assert all(np.isfinite(f) for f in fits), fits
        assert pool.hangs_detected == 1
        assert pool.last_hang_kind == "heartbeat"
        assert pool.last_hang_wait <= 8.0 + 2.0   # within the deadline
        assert pool.hello["pid"] != first_pid     # replaced

    def test_restart_backoff_applied_on_storms(self, tmp_path):
        """Consecutive restarts back off exponentially (with jitter):
        an evaluator that dies instantly cannot respawn-storm."""
        worker = tmp_path / "crash_worker.py"
        worker.write_text(textwrap.dedent("""
            import json, os, sys
            print(json.dumps({"ready": True, "pid": os.getpid(),
                              "backend": "cpu", "platform": "cpu",
                              "is_accelerator": False}), flush=True)
            for line in sys.stdin:
                os._exit(1)   # dies on EVERY job
        """))
        pool = ChipEvaluatorPool(
            [sys.executable, str(worker)], workers=1, timeout=30,
            heartbeat_deadline=5.0, restart_backoff=0.2,
            restart_backoff_cap=1.0, max_barren_restarts=3)
        t0 = time.monotonic()
        with pool:
            fits = pool.evaluate_many([{"x": 1.0}, {"x": 2.0}])
        wall = time.monotonic() - t0
        assert fits == [float("inf")] * 2
        assert pool.restarts >= 2
        # at least one backoff sleep happened (>= 0.75 * 0.2s), and
        # the bailout kept the whole thing bounded
        assert 0.15 < wall < 30.0


class TestGenerationTagging:
    def test_optimizer_exports_generation_env(self):
        gens = []

        def spy(values_list):
            gens.append(os.environ.get("VELES_GA_GENERATION"))
            return [quad(v) for v in values_list]

        prng.seed_all(7)
        GeneticOptimizer(quad, TUNES, population=4, generations=2,
                         evaluate_many=spy).run()
        assert gens == ["0", "1", "2"]


class TestCompileCachePolicy:
    def test_cpu_device_does_not_enable_persistent_cache(self):
        """Root-caused this session: XLA:CPU executables round-tripped
        through the persistent compile cache nondeterministically
        produce NaN trainings / deserialization crashes (the box's
        recurring "flaky tier-1" family).  The cache exists for the
        tunneled TPU's minutes-long compiles; CPU must never enable
        it."""
        import jax

        from veles_tpu.backends import JaxDevice
        JaxDevice(platform="cpu")
        assert jax.config.jax_compilation_cache_dir in (None, "")


class TestCorruptCacheCounting:
    def test_cifar_corrupt_cache_counted_once(self, tmp_path):
        from veles_tpu import datasets
        from veles_tpu.config import root
        root.common.data_dir = str(tmp_path)
        d = tmp_path / "cifar10"
        d.mkdir()
        for name in ([b + ".bin" for b in
                      datasets._CIFAR10_TRAIN_BATCHES]
                     + [datasets._CIFAR10_TEST_BATCH + ".bin"]):
            (d / name).write_bytes(b"garbage" * 1000)
        before = datasets.corrupt_cache_count()
        assert datasets.try_load_real_cifar10() is None
        assert datasets.corrupt_cache_count() == before + 1
