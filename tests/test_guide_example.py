"""docs/guide.md §3's custom-unit example, executed — pins the
public extension API (ForwardUnit/GradientUnit subclassing + registry)
so the documentation cannot drift from the code."""

import numpy as np

from veles_tpu import prng
from veles_tpu.backends import make_device
from veles_tpu.loader.fullbatch import ArrayLoader
from veles_tpu.ops.nn_units import ForwardUnit, GradientUnit
from veles_tpu.ops import registry
from veles_tpu.ops.standard_workflow import StandardWorkflow


class Scale(ForwardUnit):
    def output_shape_for(self, s):
        return tuple(s)

    def param_shapes(self, s):
        return {"weights": (s[-1],)}

    def apply(self, params, inputs, rng=None):
        return {"output": inputs["input"] * params["weights"]}


class GDScale(GradientUnit):
    def backward_from_saved(self, params, saved, err_output):
        x, _out = saved
        return (err_output * params["weights"],
                {"weights": (err_output * x).sum(0)})


def _build():
    prng.seed_all(7)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((256, 8)).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int32)
    return StandardWorkflow(
        loader_factory=lambda wf: ArrayLoader(
            wf, name="loader", train=(x[64:], y[64:]),
            valid=(x[:64], y[:64]), minibatch_size=32),
        layers=[
            {"type": "scale", "->": {}, "<-": {"learning_rate": 0.05}},
            {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
             "<-": {"learning_rate": 0.05}},
            {"type": "softmax", "->": {"output_sample_shape": 2},
             "<-": {"learning_rate": 0.05}},
        ],
        loss_function="softmax",
        decision_config={"max_epochs": 6},
        name="GuideScale")


class TestGuideCustomUnit:
    def setup_method(self):
        if "scale" not in registry.forward_registry:
            registry.register("scale", Scale, GDScale)

    def test_trains_fused_jax(self):
        w = _build()
        w.initialize(device=make_device("cpu"))
        w.run()
        hist = [h for h in w.decision.history
                if h["class"] == "validation"]
        assert hist[-1]["error_pct"] < hist[0]["error_pct"]

    def test_trains_numpy_golden(self):
        w = _build()
        w.initialize(device=make_device("numpy"))
        w.run()
        hist = [h for h in w.decision.history
                if h["class"] == "validation"]
        assert hist[-1]["error_pct"] < hist[0]["error_pct"]
