"""zmq master--slave DCN compat mode (veles_tpu/server.py, client.py):
reference-parity data parallelism with centralized aggregation
(SURVEY.md §4.2).  Master and slaves run in threads over localhost."""

import socket
import threading

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.backends import JaxDevice, NumpyDevice
from veles_tpu.client import SlaveClient
from veles_tpu.datasets import synthetic_classification
from veles_tpu.loader import ArrayLoader
from veles_tpu.ops.standard_workflow import StandardWorkflow
from veles_tpu.server import MasterServer


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def build_workflow(max_epochs=2, momentum=0.9):
    prng.seed_all(777)
    train, valid, _ = synthetic_classification(
        300, 120, (10, 10, 1), n_classes=5, seed=42)
    gd = {"learning_rate": 0.1, "weight_decay": 0.0001,
          "gradient_moment": momentum}
    return StandardWorkflow(
        loader_factory=lambda w: ArrayLoader(
            w, train=train, valid=valid, minibatch_size=30, name="loader"),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 24},
             "<-": gd},
            {"type": "softmax", "->": {"output_sample_shape": 5},
             "<-": gd},
        ],
        decision_config={"max_epochs": max_epochs},
        name="ms_test")


def run_cluster(n_slaves, max_epochs=2, momentum=0.9):
    addr = f"tcp://127.0.0.1:{free_port()}"
    master_w = build_workflow(max_epochs, momentum)
    master_w.initialize(device=NumpyDevice())
    slave_ws = []
    for _ in range(n_slaves):
        w = build_workflow(max_epochs, momentum)
        w.initialize(device=JaxDevice(platform="cpu"))
        slave_ws.append(w)

    server = MasterServer(master_w, addr, job_timeout=30.0, linger_s=0.5)
    clients = [SlaveClient(w, addr, timeout_ms=30000) for w in slave_ws]
    threads = [threading.Thread(target=c.serve, daemon=True)
               for c in clients]
    mt = threading.Thread(target=server.serve, daemon=True)
    mt.start()
    for t in threads:
        t.start()
    mt.join(timeout=120)
    assert not mt.is_alive(), "master did not finish"
    for t in threads:
        t.join(timeout=30)
    return master_w, clients


def valid_history(w):
    return [h for h in w.decision.history if h["class"] == "validation"]


class TestMasterSlave:
    def test_single_slave_matches_standalone(self):
        """One slave + in-order application == the standalone fused
        trajectory (fp32 add-roundtrip tolerance only)."""
        w_ref = build_workflow()
        w_ref.initialize(device=JaxDevice(platform="cpu"))
        w_ref.run()

        master_w, clients = run_cluster(1)
        assert clients[0].jobs_done > 0
        h_ref, h_ms = valid_history(w_ref), valid_history(master_w)
        assert len(h_ref) == len(h_ms) == 2
        for a, b in zip(h_ref, h_ms):
            assert abs(a["loss"] - b["loss"]) < 1e-4, (a, b)
            assert abs(a["n_err"] - b["n_err"]) <= 1, (a, b)
        # canonical master weights track the slave's updates
        w_fin = master_w.forwards[0].weights.map_read()
        r_fin = np.asarray(w_ref.fused._params[
            w_ref.forwards[0].name]["weights"])
        np.testing.assert_allclose(w_fin, r_fin, atol=1e-4)

    def test_three_slaves_train(self):
        """Async DP with 3 slaves: protocol terminates at max_epochs,
        spreads work, and the loss decreases (bounded-staleness SGD is
        NOISIER than sync — don't expect the standalone trajectory)."""
        master_w, clients = run_cluster(3, max_epochs=8, momentum=0.0)
        assert bool(master_w.decision.complete)
        # the issue-ahead window must stop any one slave racing ahead:
        # every slave gets a meaningful share of the ~112 jobs
        assert all(c.jobs_done >= 10 for c in clients), \
            [c.jobs_done for c in clients]
        hist = [h for h in master_w.decision.history
                if h["class"] == "train"]
        assert hist[0]["epoch"] == 1 and hist[-1]["epoch"] == 8
        # Staleness noise moves the FINAL epoch's loss by ~0.1 run to
        # run (thread-schedule dependent), so gate the clear-margin
        # decrease on the trajectory's best epoch and only require the
        # last epoch to stay below the start.
        losses = [h["loss"] for h in hist]
        assert min(losses) < losses[0] - 0.2, \
            [(h["epoch"], h["loss"]) for h in hist]
        assert losses[-1] < losses[0], \
            [(h["epoch"], h["loss"]) for h in hist]
        assert np.isfinite(master_w.forwards[0].weights.map_read()).all()

    def test_jax_device_master_forced_eager(self):
        """A master workflow initialized on a jax device gets fused
        wiring by default — MasterServer must force eager semantics
        (metrics from evaluator Vectors, one minibatch per job) or
        Decision sees all-zero metrics and 7/8 of the data is skipped
        (round-1 ADVICE high #2)."""
        addr = f"tcp://127.0.0.1:{free_port()}"
        master_w = build_workflow()
        master_w.initialize(device=JaxDevice(platform="cpu"))
        assert master_w.decision.metrics_source is not None  # fused
        sw = build_workflow()
        sw.initialize(device=JaxDevice(platform="cpu"))

        server = MasterServer(master_w, addr, job_timeout=30.0,
                              linger_s=0.5)
        c1 = SlaveClient(sw, addr, timeout_ms=30000)
        mt = threading.Thread(target=server.serve, daemon=True)
        t1 = threading.Thread(target=c1.serve, daemon=True)
        mt.start()
        t1.start()
        mt.join(timeout=120)
        assert not mt.is_alive(), "master did not finish"
        t1.join(timeout=30)

        # serve() reset the fused wiring leftovers
        assert master_w.decision.metrics_source is None
        assert master_w.loader.superstep == 1
        # one job per minibatch: 2 epochs x (10 train + 4 valid)
        assert server._applied == 28, server._applied
        # and the metrics are real, matching the standalone trajectory
        w_ref = build_workflow()
        w_ref.initialize(device=JaxDevice(platform="cpu"))
        w_ref.run()
        h_ref, h_ms = valid_history(w_ref), valid_history(master_w)
        assert len(h_ref) == len(h_ms) == 2
        for a, b in zip(h_ref, h_ms):
            assert a["loss"] > 0 and abs(a["loss"] - b["loss"]) < 1e-4

    def test_numpy_slave_rejected_with_clear_error(self):
        """ADVICE low: a slave without a fused runner must fail loudly
        at construction, not AttributeError mid-serve."""
        w = build_workflow()
        w.initialize(device=NumpyDevice())
        with pytest.raises(ValueError, match="jax backend"):
            SlaveClient(w, "tcp://127.0.0.1:1")

    def test_zombie_slave_job_requeued_and_master_terminates(self):
        """Elasticity + liveness: a slave that takes a job and vanishes
        must not wedge the in-order application head (job requeued after
        job_timeout) nor prevent termination at complete."""
        import pickle
        import zmq

        addr = f"tcp://127.0.0.1:{free_port()}"
        master_w = build_workflow(max_epochs=2, momentum=0.0)
        master_w.initialize(device=NumpyDevice())
        sw = build_workflow(max_epochs=2, momentum=0.0)
        sw.initialize(device=JaxDevice(platform="cpu"))

        server = MasterServer(master_w, addr, job_timeout=1.5,
                              linger_s=0.5)
        mt = threading.Thread(target=server.serve, daemon=True)
        mt.start()

        # zombie: handshake, grab the FIRST job, never report back
        ctx = zmq.Context.instance()
        zombie = ctx.socket(zmq.REQ)
        zombie.setsockopt(zmq.RCVTIMEO, 10000)
        zombie.setsockopt(zmq.LINGER, 0)
        zombie.connect(addr)
        zombie.send(pickle.dumps({"type": "handshake", "id": "zombie"}))
        pickle.loads(zombie.recv())
        zombie.send(pickle.dumps({"type": "job_request"}))
        job = pickle.loads(zombie.recv())
        assert job["type"] == "job" and job["seq"] == 0
        zombie.close(0)

        c1 = SlaveClient(sw, addr, timeout_ms=30000)
        t1 = threading.Thread(target=c1.serve, daemon=True)
        t1.start()
        mt.join(timeout=90)
        assert not mt.is_alive(), "master wedged by zombie slave"
        t1.join(timeout=30)
        assert bool(master_w.decision.complete)
        # job 0 was reissued to the live slave and applied
        assert server._applied >= 28  # 2 epochs x 14 minibatches

    def test_late_joining_slave_gets_current_weights(self):
        """Elasticity: a slave that connects mid-run receives canonical
        weights in its handshake, not initial ones."""
        addr = f"tcp://127.0.0.1:{free_port()}"
        master_w = build_workflow(max_epochs=10)
        master_w.initialize(device=NumpyDevice())
        w0 = np.array(master_w.forwards[0].weights.map_read())

        # build BOTH slave workflows up front so the late join below is
        # instant (no jit warm-up racing the master's finish)
        first = build_workflow(max_epochs=10)
        first.initialize(device=JaxDevice(platform="cpu"))
        second = build_workflow(max_epochs=10)
        second.initialize(device=JaxDevice(platform="cpu"))

        server = MasterServer(master_w, addr, linger_s=0.5)
        mt = threading.Thread(target=server.serve, daemon=True)
        mt.start()

        c1 = SlaveClient(first, addr, timeout_ms=30000)
        t1 = threading.Thread(target=c1.serve, daemon=True)
        t1.start()

        # wait until some jobs are applied, then join a second slave
        import time
        deadline = time.monotonic() + 60
        while server._applied < 5 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert server._applied >= 5

        c2 = SlaveClient(second, addr, timeout_ms=30000)
        got = {}
        orig = c2._rpc

        def spy(sock, msg):
            reply = orig(sock, msg)
            if msg.get("type") == "handshake":
                got["params"] = reply["params"]
            return reply

        c2._rpc = spy
        t2 = threading.Thread(target=c2.serve, daemon=True)
        t2.start()
        mt.join(timeout=120)
        assert not mt.is_alive()
        t1.join(timeout=30)
        t2.join(timeout=30)
        hs = got["params"][master_w.forwards[0].name]["weights"]
        assert not np.allclose(hs, w0), "handshake sent initial weights"
