"""The driver-contract multichip dryrun must be hermetic to CPU
(round-5 VERDICT missing #1): MULTICHIP_r05 went red because the
dryrun targets a virtual CPU mesh yet left the process's default JAX
backend on the TPU, so a transient libtpu breakage killed an eager op
the check never needed the chip for.  These tests run the dryrun in
the CPU suite every CI run AND prove that the non-CPU backend cannot
be touched even when the environment offers one."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


class TestHermeticDryrun:
    def test_dryrun_2_devices_in_process(self):
        """The contract call, in the CPU suite's own process (jax is
        already up on XLA:CPU with 8 virtual devices — the in-process
        fast path).  All six mesh stages must execute: the
        data-parallel step, the row-sharded resident-gather step, the
        member-sharded population-cohort step (Lattice), the
        member-sharded serve dispatch (Prism), the Keel-unified
        streaming-cohort → member-sharded-serving handoff, and the
        member-sharded SOM cohort (Menagerie)."""
        from __graft_entry__ import dryrun_multichip
        stages = dryrun_multichip(2)
        assert stages == {"data_parallel": True,
                          "sharded_residency": True,
                          "member_sharded_cohort": True,
                          "member_sharded_serve": True,
                          "keel_handoff": True,
                          "member_sharded_som_cohort": True}

    def test_dryrun_pins_itself_with_noncpu_poisoned(self):
        """A fresh process with NO JAX_PLATFORMS pin from the caller
        and the TPU plugin poisoned (a nonexistent libtpu path): the
        dryrun must pin itself to CPU before JAX initializes.  If the
        pinning ever regresses, the poisoned backend turns this red
        instead of letting TPU-environment weather decide."""
        env = os.environ.copy()
        env.pop("JAX_PLATFORMS", None)
        env.pop("JAX_PLATFORM_NAME", None)
        env.pop("XLA_FLAGS", None)
        env["TPU_LIBRARY_PATH"] = "/nonexistent/poisoned-libtpu.so"
        res = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "__graft_entry__.py"), "2"],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=600)
        assert res.returncode == 0, res.stderr[-2000:]
        assert "dryrun_multichip(2) OK" in res.stdout

    def test_dryrun_reexecs_when_backend_unsuitable(self):
        """jax already initialized with a single CPU device (no
        virtual-device flag): the dryrun cannot build a 2-mesh in this
        process and must re-exec a pinned child instead of failing."""
        code = (
            "import jax; jax.devices()\n"
            "from __graft_entry__ import dryrun_multichip\n"
            "dryrun_multichip(2)\n"
            "print('REEXEC_OK')\n")
        env = os.environ.copy()
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)   # exactly 1 cpu device
        res = subprocess.run([sys.executable, "-c", code], env=env,
                             cwd=REPO, capture_output=True, text=True,
                             timeout=600)
        assert res.returncode == 0, res.stderr[-2000:]
        assert "REEXEC_OK" in res.stdout

    def test_host_device_flags(self):
        from __graft_entry__ import _host_device_flags
        assert _host_device_flags("", 4) == \
            "--xla_force_host_platform_device_count=4"
        assert _host_device_flags(
            "--xla_force_host_platform_device_count=2 --other", 8) == \
            "--xla_force_host_platform_device_count=8 --other"
        kept = "--xla_force_host_platform_device_count=8"
        assert _host_device_flags(kept, 2) == kept
