"""Flightline (ISSUE 16): fleet-wide causal tracing + the crash-proof
flight recorder.

Unit tier: context minting (error-diffusion sampling is EXACT), wire
round-trips, the always-armed ring + atomic dumps, the journal's
monotonic skew correction, histogram tail exemplars, the critical-path
decomposition, and the veleslint rule pinning trace wire keys to the
protocol registry.

Integration tier: REAL fleets (router + replica subprocesses — three
or more processes per assembled trace).  A hedged request must
assemble into ONE trace with BOTH legs recorded and the winner
attributed; a SIGKILL failover retry must share the original
trace_id; a slow replica's ejection must leave a flight-recorder
dump on disk.
"""

import glob
import json
import os
import textwrap
import threading
import time

import numpy as np
import pytest

from veles_tpu import events, telemetry, trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestTraceContext:
    def test_mint_samples_exact_fraction_by_error_diffusion(self):
        env = {"VELES_TRACE_SAMPLE": "0.25"}
        hits = sum(trace.mint(env).sampled for _ in range(400))
        # error diffusion, not a coin flip: the fraction is EXACT
        # (+-1 for the accumulator's resident remainder)
        assert abs(hits - 100) <= 1

    def test_mint_rate_bounds(self):
        assert not trace.mint({"VELES_TRACE_SAMPLE": "0"}).sampled
        assert trace.mint({"VELES_TRACE_SAMPLE": "1"}).sampled
        # malformed falls back to the default (1.0), never raises
        assert trace.mint({"VELES_TRACE_SAMPLE": "bogus"}).sampled

    def test_child_keeps_trace_parents_span(self):
        root = trace.TraceContext("aa" * 8)
        kid = root.child()
        assert kid.trace_id == root.trace_id
        assert kid.parent_id == root.span_id
        assert kid.span_id != root.span_id
        assert kid.sampled == root.sampled

    def test_wire_round_trip(self):
        ctx = trace.TraceContext("ab" * 8, "cd" * 4, "ef" * 4)
        msg = trace.to_wire({"cmd": "request"}, ctx)
        assert msg["trace"] == ctx.trace_id
        assert msg["span"] == ctx.span_id
        assert msg["parent"] == ctx.parent_id
        back = trace.from_wire(msg)
        assert (back.trace_id, back.span_id, back.parent_id) == \
            (ctx.trace_id, ctx.span_id, ctx.parent_id)
        assert back.sampled

    def test_unsampled_context_never_rides_the_wire(self):
        ctx = trace.TraceContext("ab" * 8, sampled=False)
        msg = trace.to_wire({"cmd": "request"}, ctx)
        assert set(msg) == {"cmd"}     # rate 0 adds ZERO bytes
        assert trace.from_wire(msg) is None
        assert trace.from_wire({"cmd": "x"}) is None

    def test_use_parks_thread_locally_and_restores(self):
        ctx = trace.TraceContext("ab" * 8)
        assert trace.current() is None
        with trace.use(ctx):
            assert trace.current() is ctx
            seen = []
            t = threading.Thread(
                target=lambda: seen.append(trace.current()))
            t.start()
            t.join()
            assert seen == [None]      # thread-local, not global
        assert trace.current() is None

    def test_journaled_events_auto_carry_the_current_trace(self):
        ctx = trace.TraceContext("ab" * 8)
        with trace.use(ctx):
            telemetry.event("flightline.probe", detail=1)
        ev = telemetry.recent_events("flightline.probe")[-1]
        assert ev["trace"] == ctx.trace_id
        assert ev["span"] == ctx.span_id
        # explicit caller fields WIN over the provider
        with trace.use(ctx):
            telemetry.event("flightline.probe2", trace="override")
        assert telemetry.recent_events(
            "flightline.probe2")[-1]["trace"] == "override"


class TestFlightRecorder:
    def test_ring_records_without_io_and_dump_is_atomic(
            self, tmp_path):
        telemetry.configure(str(tmp_path))
        ctx = trace.TraceContext("ab" * 8)
        trace.record("probe.hop", ctx=ctx, replica=3)
        entries = trace.ring_entries()
        assert entries[-1]["ev"] == "probe.hop"
        assert entries[-1]["trace"] == ctx.trace_id
        assert entries[-1]["replica"] == 3
        path = trace.dump("unit test/../reason")
        assert path and os.path.isfile(path)
        # the reason is sanitized into the filename
        assert "unit_test_.._reason" in os.path.basename(path)
        payload = json.load(open(path))
        assert payload["pid"] == os.getpid()
        assert any(e["ev"] == "probe.hop" for e in payload["ring"])
        assert "journal_tail" in payload
        # no torn dump tempfile left behind (the background metrics
        # flush may legitimately have its own metrics-*.tmp in flight)
        assert not glob.glob(str(tmp_path / "flightrec-*.tmp"))
        # the dump itself is journaled
        assert telemetry.recent_events(events.EV_FLIGHTREC_DUMP)

    def test_dump_without_metrics_dir_is_a_noop(self):
        assert trace.dump("nowhere") is None


class TestSkewCorrection:
    def test_interleaving_follows_monotonic_not_wall_clock(
            self, tmp_path):
        """Two processes whose wall clocks disagree by 10s: the merged
        timeline must order events by the per-pid skew-corrected
        monotonic stamp, not the raw ``ts`` (satellite: the journal
        interleaving bug)."""
        from veles_tpu.obs import load_dir
        a = [{"ts": 1000.0 + i, "mono": 5.0 + i, "event": f"a{i}"}
             for i in range(3)]
        # pid B's wall clock runs 10s AHEAD but its events really
        # happened BETWEEN pid A's (mono 5.5, 6.5)
        b = [{"ts": 1010.5, "mono": 5.5, "event": "b0"},
             {"ts": 1011.5, "mono": 6.5, "event": "b1"}]
        with open(tmp_path / "journal-111.jsonl", "w") as f:
            f.writelines(json.dumps(e) + "\n" for e in a)
        with open(tmp_path / "journal-222.jsonl", "w") as f:
            f.writelines(json.dumps(e) + "\n" for e in b)
        _reg, _snaps, _journals, evs = load_dir(str(tmp_path))
        order = [e["event"] for e in evs]
        assert order == ["a0", "b0", "a1", "b1", "a2"]
        # raw-ts ordering (the old bug) would have pushed b* last
        assert sorted(order, key=lambda n: dict(
            (e["event"], e["ts"]) for e in a + b)[n])[-2:] == \
            ["b0", "b1"]


class TestTailExemplars:
    def test_exemplars_survive_snapshot_merge_and_name_the_tail(
            self, tmp_path):
        from veles_tpu.obs import tail_exemplars
        from veles_tpu.telemetry import Registry
        h = telemetry.histogram("probe.seconds")
        for _ in range(200):
            h.record(0.001)
        h.record(0.5, exemplar="feedbeef" * 2)       # the p99 tail
        h.record(0.0001, exemplar="aa" * 8)          # deep body
        merged = Registry()
        merged.merge_snapshot(telemetry.snapshot())
        tail = tail_exemplars(merged, "probe.seconds", q=0.99)
        assert ("feedbeef" * 2) in [t for _, t in tail]
        # the deep-body exemplar (its bucket sits entirely below the
        # p99 threshold) is NOT in the tail
        assert ("aa" * 8) not in [t for _, t in tail]

    def test_unsampled_records_leave_no_exemplar(self):
        h = telemetry.histogram("probe2.seconds")
        h.record(0.1, exemplar=None)
        assert h.exemplars == {}


class TestCriticalPath:
    def _trace(self):
        tid = "ab" * 8
        return [
            {"event": "trace.request", "trace": tid, "span": "r1",
             "model": "m", "outcome": "ok", "seconds": 0.010,
             "_t": 1.0, "_pid": "1"},
            {"event": "trace.leg", "trace": tid, "span": "l1",
             "parent": "r1", "replica": 1, "verdict": "ok",
             "seconds": 0.009, "hedge": False, "winner": True,
             "_t": 1.001, "_pid": "1"},
            {"event": "trace.serve", "trace": tid, "span": "s1",
             "parent": "l1", "label": "m", "rows": 1,
             "wait_s": 0.002, "dispatch_s": 0.004, "total_s": 0.007,
             "_t": 1.002, "_pid": "2", "_replica": 1},
        ]

    def test_decomposition(self):
        from veles_tpu.obs import critical_path
        cp = critical_path(self._trace())
        assert cp["outcome"] == "ok"
        assert cp["legs"] == 1 and not cp["hedged"] \
            and not cp["retried"]
        assert cp["replica"] == 1
        assert cp["pre_route_s"] == pytest.approx(0.001)
        assert cp["wire_s"] == pytest.approx(0.002)
        assert cp["batch_wait_s"] == pytest.approx(0.002)
        assert cp["dispatch_s"] == pytest.approx(0.004)

    def test_render_trace_indents_and_names_the_dominant_hop(self):
        from veles_tpu.obs import render_trace
        text = render_trace(self._trace())
        assert "ab" * 8 in text
        assert "trace.serve" in text
        assert "critical path" in text
        assert "dispatch" in text        # 4ms dominates


class TestTraceWireKeyRule:
    def _check(self, source, path="veles_tpu/trace.py"):
        from veles_tpu.analysis.concurrency import TraceWireKeyRule
        from veles_tpu.analysis.engine import Config, ModuleContext
        return TraceWireKeyRule().check(
            ModuleContext(path, source, Config()))

    def test_real_trace_module_is_clean(self):
        src = open(os.path.join(REPO, "veles_tpu", "trace.py")).read()
        assert self._check(src) == []

    def test_unregistered_wire_field_is_flagged_zero_waivers(self):
        bad = textwrap.dedent("""
            K_TRACE = "trace"
            WIRE_FIELDS = ("trace", "smuggled_key")
        """)
        findings = self._check(bad)
        assert findings, "unregistered wire key must be flagged"
        assert any("smuggled_key" in f.message for f in findings)

    def test_missing_wire_fields_tuple_is_flagged(self):
        findings = self._check("K_TRACE = 'trace'\n")
        assert findings

    def test_other_files_are_ignored(self):
        assert self._check("WIRE_FIELDS = ('bogus',)",
                           path="veles_tpu/other.py") == []


class TestLoggerJournal:
    def test_warnings_route_to_the_journal_and_keep_stderr(self):
        import logging

        from veles_tpu.logger import (Logger, _HookHandler,
                                      setup_logging)
        setup_logging()

        class Unit(Logger):
            pass

        u = Unit()
        u.warning("flightline probe %d", 7)
        u.info("below the threshold")
        evs = telemetry.recent_events(events.EV_LOG_RECORD)
        assert any(e["message"] == "flightline probe 7"
                   and e["level"] == "WARNING" for e in evs)
        assert not any(e.get("message") == "below the threshold"
                       for e in evs)
        # the console path is PRESERVED — the journal route rides a
        # SEPARATE handler next to the stderr one, on both namespaces
        vlog = logging.getLogger("veles")
        assert any(type(h) is logging.StreamHandler
                   for h in vlog.handlers)
        assert any(isinstance(h, _HookHandler)
                   for h in vlog.handlers)
        flog = logging.getLogger("veles_tpu")
        assert any(type(h) is logging.StreamHandler
                   for h in flog.handlers)
        # propagate untouched: pytest caplog and operator root
        # configs keep seeing veles_tpu.* records
        assert flog.propagate
        # the warning also lands in the flight-recorder ring
        assert any(e["ev"] == "log.warning"
                   for e in trace.ring_entries())


WF_TEXT = textwrap.dedent("""
    from veles_tpu import prng
    from veles_tpu.datasets import synthetic_classification
    from veles_tpu.loader import ArrayLoader
    from veles_tpu.ops.standard_workflow import StandardWorkflow

    def create_workflow(launcher):
        prng.seed_all(4242)
        train, valid, _ = synthetic_classification(
            64, 16, (6, 6, 1), n_classes=3, seed=5)
        return StandardWorkflow(
            loader_factory=lambda w: ArrayLoader(
                w, train=train, valid=valid, minibatch_size=16,
                name="loader"),
            layers=[
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 12},
                 "<-": {"learning_rate": 0.1}},
                {"type": "softmax", "->": {"output_sample_shape": 3},
                 "<-": {"learning_rate": 0.1}},
            ],
            decision_config={"max_epochs": 2}, name="flightline_wf")
""")


def _build_package(d, name, seed, n_members=3):
    from veles_tpu import prng
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.ensemble.packaging import pack_ensemble
    from veles_tpu.launcher import load_workflow_module

    wf_path = os.path.join(d, f"wf_{name}.py")
    with open(wf_path, "w") as f:
        f.write(WF_TEXT)
    mod = load_workflow_module(wf_path)

    class FL:
        workflow = None

    prng.seed_all(seed)
    w = mod.create_workflow(FL())
    w.initialize(device=NumpyDevice())
    base = {fw.name: {k: np.asarray(v) for k, v in
                      fw.gather_params().items()}
            for fw in w.forwards}
    rng = np.random.default_rng(seed)
    members = []
    for _ in range(n_members):
        params = {fn: {pn: (a + 0.05 * rng.standard_normal(a.shape)
                            .astype(np.float32))
                       for pn, a in p.items()}
                  for fn, p in base.items()}
        members.append({"params": params, "valid_error": 0.0,
                        "seed": seed,
                        "forward_names": [fw.name
                                          for fw in w.forwards],
                        "values": None})
    pkg = os.path.join(d, f"{name}.vpkg")
    pack_ensemble(pkg, name, members, wf_path)
    return pkg


@pytest.fixture(scope="module")
def package(tmp_path_factory):
    return _build_package(
        str(tmp_path_factory.mktemp("flightline_pkgs")), "alpha", 11)


def _assembled(mdir):
    from veles_tpu.obs import assemble_traces, load_tree
    telemetry.flush()
    _reg, merged = load_tree(mdir)
    return assemble_traces(merged), merged


class TestHedgedTraceAssembly:
    """One hedged request = ONE trace across >= 3 real processes
    (router + 2 replicas), both legs recorded, winner attributed; the
    slow replica's eventual ejection leaves a flight-recorder dump."""

    def test_hedged_request_assembles_into_one_trace(
            self, package, tmp_path_factory):
        from veles_tpu.obs import critical_path, render_trace
        from veles_tpu.serve.router import FleetRouter
        mdir = str(tmp_path_factory.mktemp("flightline_hedge"))
        router = FleetRouter(
            {"alpha": package}, n_replicas=2, backend="cpu",
            max_batch=16, max_wait_ms=5, metrics_dir=mdir, cwd=REPO,
            deadline_ms=8000, hedge_min_ms=60, hedge_budget=1.0,
            eject_threshold=4,
            env_overrides={0: {"VELES_FAULTS":
                               "hive.slow_dispatch@label=alpha"
                               "&times=8&seconds=1.5"}})
        try:
            x = np.ones((1, 6, 6, 1), np.float32)
            hedges0 = telemetry.counter("fleet.hedge.issued").value
            for _ in range(24):
                r = router.request("alpha", x, timeout=60)
                assert "probs" in r, r
                if telemetry.counter(
                        "fleet.hedge.issued").value > hedges0:
                    break
            assert telemetry.counter(
                "fleet.hedge.issued").value > hedges0
            # let ejection strikes accrue, then drain the late losers
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and telemetry.counter(
                    "fleet.eject.total").value < 1:
                router.request("alpha", x, timeout=60)
                time.sleep(0.05)
        finally:
            router.close()

        traces, merged = _assembled(mdir)
        # >= 3 processes contributed to the merged timeline
        assert len({e.get("_pid") for e in merged
                    if e.get("_pid")}) >= 3
        hedged = [evs for evs in traces.values()
                  if sum(1 for e in evs
                         if e.get("event") == "trace.leg"
                         and e.get("hedge")) >= 1]
        assert hedged, "no hedged trace assembled"
        evs = hedged[0]
        legs = [e for e in evs if e.get("event") == "trace.leg"]
        assert len(legs) >= 2              # BOTH attempts recorded
        winners = [e for e in legs if e.get("winner")]
        assert len(winners) == 1           # winner attributed once
        tids = {e.get("trace") for e in evs}
        assert len(tids) == 1              # ONE trace
        root = [e for e in evs
                if e.get("event") == "trace.request"]
        assert len(root) == 1
        # every leg parents on the root span
        assert all(leg.get("parent") == root[0]["span"]
                   for leg in legs)
        cp = critical_path(evs)
        assert cp["hedged"] and cp["legs"] >= 2
        assert cp["total_s"] is not None
        # the hedge fired after hedge_min_ms: visible as pre-route
        assert cp["pre_route_s"] is None or cp["pre_route_s"] >= 0
        text = render_trace(evs)
        assert evs[0]["trace"] in text and "critical path" in text

        # the ejection left a crash-proof dump in the router's dir
        dumps = glob.glob(os.path.join(mdir, "**",
                                       "flightrec-*-ejection.json"),
                          recursive=True)
        assert dumps, "ejection produced no flight-recorder dump"
        payload = json.load(open(dumps[0]))
        assert payload["reason"] == "ejection"
        assert any(e.get("ev") == "sentinel.eject"
                   for e in payload["ring"])


class TestFailoverTraceAssembly:
    """SIGKILL the primary mid-request: the retry on the healthy peer
    shares the ORIGINAL trace_id — died leg + winning leg in one
    assembled trace."""

    def test_failover_retry_shares_the_trace_id(
            self, package, tmp_path_factory):
        from veles_tpu.serve.router import FleetRouter
        mdir = str(tmp_path_factory.mktemp("flightline_kill"))
        router = FleetRouter(
            {"alpha": package}, n_replicas=2, backend="cpu",
            max_batch=16, max_wait_ms=5, metrics_dir=mdir, cwd=REPO,
            respawn_backoff=0.25)
        try:
            x = np.ones((2, 6, 6, 1), np.float32)
            assert "probs" in router.request("alpha", x)   # warm
            retries0 = telemetry.counter("fleet.retries").value
            results, errs = [], []
            per_worker = 12

            def worker(i):
                try:
                    for k in range(per_worker):
                        if i == 0 and k == 2:
                            router.replicas[0].client.proc.kill()
                        results.append(
                            router.request("alpha", x, timeout=60))
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs, errs
            assert all("probs" in r for r in results)
            assert telemetry.counter("fleet.retries").value > retries0
        finally:
            router.close(kill=True)

        traces, _merged = _assembled(mdir)
        retried = []
        for evs in traces.values():
            legs = [e for e in evs if e.get("event") == "trace.leg"
                    and not e.get("hedge")]
            if len(legs) >= 2 and any(
                    e.get("verdict") == "died" for e in legs):
                retried.append((evs, legs))
        assert retried, \
            "no trace carries both the died leg and its retry"
        evs, legs = retried[0]
        assert len({e.get("trace") for e in evs}) == 1
        # the retry WON on the surviving peer
        winners = [e for e in legs if e.get("winner")]
        assert winners and winners[0]["verdict"] == "ok"
        died = [e for e in legs if e.get("verdict") == "died"]
        assert died[0]["replica"] != winners[0]["replica"]
