"""Swarm fleet serving (ISSUE 11 tentpole): N Hive replicas behind
one SLO-aware router — placement, least-loaded routing, canary traffic
mirroring, admission-control shedding, and SIGKILL failover with zero
lost requests.

The subprocess suites spawn REAL 2-replica fleets (each replica is a
full ``--serve-models`` child) and drive them with concurrent client
threads, asserting (a) responses match the host member-loop oracle,
(b) requests spread over both replicas, (c) a canary registered as
``canary-of:alpha`` receives its traffic split within tolerance,
(d) overload sheds with an explicit ``overloaded`` response (never a
timeout), and (e) killing one replica mid-load loses ZERO in-flight
requests — they are retried once on the healthy peer while the
monitor respawns the corpse.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WF_TEXT = textwrap.dedent("""
    from veles_tpu import prng
    from veles_tpu.datasets import synthetic_classification
    from veles_tpu.loader import ArrayLoader
    from veles_tpu.ops.standard_workflow import StandardWorkflow

    def create_workflow(launcher):
        prng.seed_all(4242)
        train, valid, _ = synthetic_classification(
            64, 16, (6, 6, 1), n_classes=3, seed=5)
        return StandardWorkflow(
            loader_factory=lambda w: ArrayLoader(
                w, train=train, valid=valid, minibatch_size=16,
                name="loader"),
            layers=[
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 12},
                 "<-": {"learning_rate": 0.1}},
                {"type": "softmax", "->": {"output_sample_shape": 3},
                 "<-": {"learning_rate": 0.1}},
            ],
            decision_config={"max_epochs": 2}, name="fleet_wf")
""")


def _build_package(d, name, seed, n_members=3):
    """One Forge ensemble package + its host oracle ingredients
    (the test_serve recipe)."""
    from veles_tpu import prng
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.ensemble.packaging import pack_ensemble
    from veles_tpu.launcher import load_workflow_module

    wf_path = os.path.join(d, f"wf_{name}.py")
    with open(wf_path, "w") as f:
        f.write(WF_TEXT)
    mod = load_workflow_module(wf_path)

    class FL:
        workflow = None

    prng.seed_all(seed)
    w = mod.create_workflow(FL())
    w.initialize(device=NumpyDevice())
    base = {fw.name: {k: np.asarray(v) for k, v in
                      fw.gather_params().items()}
            for fw in w.forwards}
    rng = np.random.default_rng(seed)
    members = []
    for _ in range(n_members):
        params = {fn: {pn: (a + 0.05 * rng.standard_normal(a.shape)
                            .astype(np.float32))
                       for pn, a in p.items()}
                  for fn, p in base.items()}
        members.append({"params": params, "valid_error": 0.0,
                        "seed": seed,
                        "forward_names": [fw.name
                                          for fw in w.forwards],
                        "values": None})
    pkg = os.path.join(d, f"{name}.vpkg")
    pack_ensemble(pkg, name, members, wf_path)
    return {"pkg": pkg, "members": members, "workflow": w}


def _host_oracle(model, x):
    acc = None
    for m in model["members"]:
        out = np.asarray(x, np.float32)
        for fw in model["workflow"].forwards:
            p = {k: np.asarray(v)
                 for k, v in m["params"][fw.name].items()}
            out, _ = fw.apply_fwd(p, out, rng=None, train=False)
        out = np.asarray(out)
        acc = out if acc is None else acc + out
    return acc / len(model["members"])


@pytest.fixture(scope="module")
def packages(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("fleet_pkgs"))
    return {"alpha": _build_package(d, "alpha", 11),
            "beta": _build_package(d, "beta", 22)}


class TestPlacementPolicy:
    """Pure placement math: hot prefix replicated, tail partitioned."""

    def _policy(self, **kw):
        from veles_tpu.serve.fleet import PlacementPolicy
        return PlacementPolicy(**kw)

    def test_hot_prefix_replicates_until_budget(self):
        pl = self._policy(budget_bytes=100).assign(
            {"a": 40, "b": 40, "c": 40, "d": 10}, 2)
        assert pl["a"] == [0, 1] and pl["b"] == [0, 1]
        # c would overflow 100 on every replica: the hot prefix ends
        # and the tail partitions onto least-filled bins
        assert len(pl["c"]) == 1 and len(pl["d"]) == 1
        assert pl["c"] != pl["d"]

    def test_explicit_hot_set_overrides_prefix(self):
        pl = self._policy(budget_bytes=100, hot={"c"}).assign(
            {"a": 40, "b": 40, "c": 40}, 3)
        assert pl["c"] == [0, 1, 2]
        assert len(pl["a"]) == 1 and len(pl["b"]) == 1

    def test_everything_fits_everything_replicates(self):
        pl = self._policy(budget_bytes=1 << 30).assign(
            {"a": 10, "b": 10}, 4)
        assert pl == {"a": [0, 1, 2, 3], "b": [0, 1, 2, 3]}

    def test_single_replica_degenerates_to_hive(self):
        pl = self._policy(budget_bytes=50).assign(
            {"a": 40, "b": 40}, 1)
        assert pl == {"a": [0], "b": [0]}


class TestFleetRoundTrip:
    """(a)-(d) against one real 2-replica fleet: oracle parity under
    concurrent clients, request spreading, the canary split, and
    shed-on-overload semantics."""

    @pytest.fixture(scope="class")
    def router(self, packages, tmp_path_factory):
        from veles_tpu.serve.router import FleetRouter
        mdir = str(tmp_path_factory.mktemp("fleet_metrics"))
        r = FleetRouter(
            {"alpha": packages["alpha"]["pkg"],
             "beta": packages["beta"]["pkg"]},
            n_replicas=2, backend="cpu", max_batch=16, max_wait_ms=5,
            canaries={"beta": ("alpha", 0.25)},
            metrics_dir=mdir, cwd=REPO)
        r.metrics_dir_path = mdir
        yield r
        r.close()

    def test_fleet_comes_up_with_placement(self, router):
        assert len(router.replicas) == 2
        assert all(r.healthy for r in router.replicas)
        # both tiny models fit every replica's budget: replicated
        assert router.placement == {"alpha": [0, 1], "beta": [0, 1]}
        assert router.canaries == {"beta": ("alpha", 0.25)}

    def test_concurrent_responses_match_host_oracle(self, router,
                                                    packages):
        errs = []

        def worker(i):
            try:
                rng = np.random.default_rng(100 + i)
                name = "alpha" if i % 2 == 0 else "beta"
                for _ in range(4):
                    x = rng.standard_normal((2, 6, 6, 1)) \
                        .astype(np.float32)
                    r = router.request(name, x, timeout=60)
                    assert "probs" in r, r
                    got = np.asarray(r["probs"], np.float32)
                    want = _host_oracle(packages[name], x)
                    np.testing.assert_allclose(got, want, atol=1e-4)
            except Exception as e:  # noqa: BLE001 — collected below
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs

    def test_requests_spread_over_both_replicas(self, router):
        # enough sequential traffic that least-loaded routing must
        # alternate (an idle peer is always less loaded)
        x = np.ones((1, 6, 6, 1), np.float32)
        for _ in range(8):
            assert "probs" in router.request("alpha", x)
        counts = router.routed_counts()
        assert len(counts) == 2 and all(c > 0 for c in counts), counts

    def test_canary_receives_its_traffic_split(self, router):
        from veles_tpu import telemetry
        x = np.ones((1, 6, 6, 1), np.float32)
        req0 = telemetry.counter("fleet.model.alpha.requests").value
        mir0 = telemetry.counter("fleet.model.beta.mirrored").value
        n = 40
        for _ in range(n):
            assert "probs" in router.request("alpha", x)
        d_req = telemetry.counter(
            "fleet.model.alpha.requests").value - req0
        d_mir = telemetry.counter(
            "fleet.model.beta.mirrored").value - mir0
        assert d_req == n
        # deterministic stride sampling: 0.25 of 40 = 10 mirrors
        # (+-1 for the accumulator's starting phase)
        assert abs(d_mir / n - 0.25) <= 0.05, (d_mir, n)
        # the mirrors resolve asynchronously and land in the canary's
        # own latency/error split
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            done = telemetry.histogram(
                "fleet.model.beta.request_seconds").count \
                + telemetry.counter("fleet.model.beta.errors").value
            if done >= d_mir:
                break
            time.sleep(0.1)
        assert telemetry.histogram(
            "fleet.model.beta.request_seconds").count > 0
        assert telemetry.counter("fleet.model.beta.errors").value == 0

    def test_overload_sheds_explicitly_not_by_timeout(self, router):
        from veles_tpu import telemetry
        x = np.ones((1, 6, 6, 1), np.float32)
        shed0 = telemetry.counter("fleet.shed").value
        saved_slo, saved_inflight = router.slo_p99_ms, \
            router.max_inflight
        try:
            # (1) the SLO estimate path: an impossible 0.5ms target
            # means even an idle replica's batching window blows it
            router.slo_p99_ms = 0.5
            t0 = time.perf_counter()
            r = router.request("alpha", x, timeout=60)
            dt = time.perf_counter() - t0
            assert r.get("overloaded") is True, r
            assert r["error"] == "overloaded"
            assert "est_ms" in r
            assert dt < 5.0   # a shed answers immediately, never by
            #                   waiting out the request timeout
            # (2) the bounded-queue path
            router.slo_p99_ms = 0.0
            router.max_inflight = 0
            r = router.request("alpha", x, timeout=60)
            assert r.get("overloaded") is True, r
        finally:
            router.slo_p99_ms, router.max_inflight = saved_slo, \
                saved_inflight
        assert telemetry.counter("fleet.shed").value - shed0 == 2
        assert telemetry.counter(
            "fleet.model.alpha.shed").value >= 2
        # admission restored: the fleet serves again
        assert "probs" in router.request("alpha", x)

    def test_per_replica_metrics_dirs_written(self, router):
        from veles_tpu import telemetry
        telemetry.flush()
        for i in (0, 1):
            d = os.path.join(router.metrics_dir_path, f"replica-{i}")
            assert os.path.isdir(d), d
            # each replica flushed at least its hello-time snapshot
            files = os.listdir(d)
            assert any(fn.startswith("journal-") for fn in files), \
                files

    def test_obs_fleet_view_reads_real_replica_dirs(self, router):
        from veles_tpu.obs import fleet_rows, render_fleet
        rows = fleet_rows(router.metrics_dir_path)
        assert [r["replica"] for r in rows] == [0, 1]
        live_pids = {r.pid for r in router.replicas}
        assert {r["pid"] for r in rows} == live_pids
        out = render_fleet(router.metrics_dir_path)
        assert "fleet replicas" in out


class TestFleetFailover:
    """(e) SIGKILL one replica mid-load: zero lost requests (retried
    once on the healthy peer), and the monitor respawns the corpse
    with its warm install dir."""

    def test_sigkill_mid_load_loses_nothing(self, packages,
                                            tmp_path_factory):
        from veles_tpu import telemetry
        from veles_tpu.serve.router import FleetRouter
        mdir = str(tmp_path_factory.mktemp("fleet_kill"))
        router = FleetRouter(
            {"alpha": packages["alpha"]["pkg"]},
            n_replicas=2, backend="cpu", max_batch=16, max_wait_ms=5,
            metrics_dir=mdir, cwd=REPO, respawn_backoff=0.25)
        try:
            x = np.ones((2, 6, 6, 1), np.float32)
            want = _host_oracle(packages["alpha"], x)
            assert "probs" in router.request("alpha", x)   # warm
            results = []
            errs = []
            per_worker = 15

            def worker(i):
                try:
                    for k in range(per_worker):
                        if i == 0 and k == 3:
                            # SIGKILL mid-load, synchronously: the
                            # other five closed-loop workers have
                            # requests in flight on both replicas
                            router.replicas[0].client.proc.kill()
                        r = router.request("alpha", x, timeout=60)
                        results.append(r)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs, errs
            # ZERO lost: every request answered with real
            # probabilities (no errors, no timeouts), oracle-exact
            assert len(results) == 6 * per_worker
            for r in results:
                assert "probs" in r, r
                np.testing.assert_allclose(
                    np.asarray(r["probs"], np.float32), want,
                    atol=1e-4)
            # at least one in-flight request was retried on the peer
            assert telemetry.counter("fleet.retries").value >= 1
            # the monitor (0.25s tick) observes the death
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline \
                    and router.replicas[0].deaths < 1:
                time.sleep(0.1)
            assert router.replicas[0].deaths >= 1
            # the monitor respawns the replica (warm install dir)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if router.replicas[0].healthy:
                    break
                time.sleep(0.25)
            assert router.replicas[0].healthy, \
                "replica 0 was not respawned"
            assert "probs" in router.request("alpha", x)
            assert telemetry.counter(
                "fleet.replica_respawns").value >= 1
        finally:
            router.close(kill=True)


class TestFleetGrayFailures:
    """ISSUE 12 (Sentinel): gray failures — a replica that is slow,
    wedged, or corrupt while remaining process-alive and heartbeating.
    One replica of a REAL 2-replica fleet is armed via a per-replica
    VELES_FAULTS override; the router's deadline/hedge/integrity
    machinery must keep every client answer clean and bounded, eject
    the sick replica from routing, and (once the fault budget
    exhausts) reinstate it after consecutive clean probes."""

    def _gray_router(self, packages, tmp_path_factory, fault, name,
                     **kw):
        from veles_tpu.serve.router import FleetRouter
        mdir = str(tmp_path_factory.mktemp(name))
        defaults = dict(
            n_replicas=2, backend="cpu", max_batch=16, max_wait_ms=5,
            metrics_dir=mdir, cwd=REPO,
            env_overrides={0: {"VELES_FAULTS": fault}})
        defaults.update(kw)
        r = FleetRouter({"alpha": packages["alpha"]["pkg"]},
                        **defaults)
        r.metrics_dir_path = mdir
        return r

    @staticmethod
    def _ctr(name):
        from veles_tpu import telemetry
        return telemetry.counter(name).value

    def test_slow_replica_hedged_ejected_then_reinstated(
            self, packages, tmp_path_factory):
        # replica 0's every dispatch stalls 1.5s for the first 6
        # firings (requests AND probes consume the budget), then the
        # fault exhausts and the replica is genuinely healthy again
        router = self._gray_router(
            packages, tmp_path_factory,
            "hive.slow_dispatch@label=alpha&times=6&seconds=1.5",
            "fleet_gray_slow",
            deadline_ms=8000, hedge_min_ms=60, hedge_budget=1.0,
            probe_interval=0.2, probe_ok=2, probe_backoff_cap=0.4)
        try:
            hedges0 = self._ctr("fleet.hedge.issued")
            wins0 = self._ctr("fleet.hedge.wins")
            eject0 = self._ctr("fleet.eject.total")
            x = np.ones((1, 6, 6, 1), np.float32)
            want = _host_oracle(packages["alpha"], x)
            lats = []
            for _ in range(30):
                t0 = time.perf_counter()
                r = router.request("alpha", x, timeout=30)
                lats.append(time.perf_counter() - t0)
                # EVERY answer is clean despite the slow replica: the
                # hedge (or post-ejection routing) covered it
                assert "probs" in r, r
                np.testing.assert_allclose(
                    np.asarray(r["probs"], np.float32), want,
                    atol=1e-4)
                if self._ctr("fleet.eject.total") > eject0:
                    break
            assert self._ctr("fleet.hedge.issued") > hedges0
            assert self._ctr("fleet.hedge.wins") > wins0
            assert self._ctr("fleet.eject.total") == eject0 + 1
            st = router.sentinel.status(router.replicas[0])
            assert st["state"] in ("ejected", "probing"), st
            assert st["strikes"].get("hedge_loss", 0) >= 1, st
            # post-ejection traffic routes around the sick replica and
            # p99 stays bounded: nothing waits out the 1.5s stall
            post = []
            for _ in range(10):
                t0 = time.perf_counter()
                r = router.request("alpha", x, timeout=30)
                assert "probs" in r, r
                post.append(time.perf_counter() - t0)
            assert max(post) < 1.0, post
            # the fault budget exhausts under probing; PROBE_OK=2
            # consecutive clean probes reinstate the replica
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                st = router.sentinel.status(router.replicas[0])
                if st["state"] == "healthy" \
                        and st["reinstatements"] >= 1:
                    break
                time.sleep(0.2)
            assert st["state"] == "healthy", st
            assert st["reinstatements"] >= 1, st
            assert self._ctr("fleet.eject.reinstated_total") >= 1
            # reinstated means ROUTABLE again: the fleet serves fine
            assert "probs" in router.request("alpha", x, timeout=30)
            # the hedge losers' late answers were dropped as stale,
            # never leaked into other waiters (all answers were clean)
            assert self._ctr("fleet.stale_response") >= 1
        finally:
            router.close(kill=True)

    def test_wedged_replica_detected_without_heartbeat_loss(
            self, packages, tmp_path_factory):
        # replica 0 swallows EVERY model request forever while its
        # heartbeats and stats keep flowing — invisible to the
        # heartbeat-deadline monitor, caught only by the sentinel
        router = self._gray_router(
            packages, tmp_path_factory, "hive.wedge@times=*",
            "fleet_gray_wedge",
            deadline_ms=5000, hedge_min_ms=60, hedge_budget=1.0,
            probe_interval=0.25, probe_ok=2, heartbeat_every=0.2)
        try:
            eject0 = self._ctr("fleet.eject.total")
            probe_fail0 = self._ctr("fleet.probe.fail")
            x = np.ones((1, 6, 6, 1), np.float32)
            for _ in range(25):
                # every request still answers (hedged onto the peer)
                assert "probs" in router.request("alpha", x,
                                                 timeout=30)
                if self._ctr("fleet.eject.total") > eject0:
                    break
            assert self._ctr("fleet.eject.total") == eject0 + 1
            st = router.sentinel.status(router.replicas[0])
            assert st["state"] in ("ejected", "probing"), st
            # DETECTION WITHOUT HEARTBEAT LOSS: the monitor never saw
            # a death (no EOF, no silence) — the process is alive and
            # chatting the whole time
            assert router.replicas[0].deaths == 0
            assert router.replicas[0].healthy
            assert router.replicas[0].client.heartbeats > 0
            # probes are swallowed too: the wedged replica can NEVER
            # pass its canary, so it stays out of rotation
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline \
                    and self._ctr("fleet.probe.fail") <= probe_fail0:
                time.sleep(0.1)
            assert self._ctr("fleet.probe.fail") > probe_fail0
            st = router.sentinel.status(router.replicas[0])
            assert st["state"] in ("ejected", "probing"), st
        finally:
            router.close(kill=True)

    def test_garbage_response_never_reaches_a_client(
            self, packages, tmp_path_factory):
        # replica 0 corrupts every probability payload AFTER the crc
        # echo was computed from the clean one: the router's integrity
        # check must strike + retry on the peer so oracle parity holds
        router = self._gray_router(
            packages, tmp_path_factory,
            "hive.garbage_response@times=*", "fleet_gray_garbage",
            deadline_ms=8000, hedge_budget=0.0,
            probe_interval=0.25, probe_ok=2)
        try:
            strikes0 = self._ctr("fleet.integrity_strikes")
            retries0 = self._ctr("fleet.retries")
            eject0 = self._ctr("fleet.eject.total")
            x = np.ones((2, 6, 6, 1), np.float32)
            want = _host_oracle(packages["alpha"], x)
            for _ in range(20):
                r = router.request("alpha", x, timeout=30)
                # ZERO corrupt answers reach a client — every response
                # is oracle-exact (the corrupt ones were caught by the
                # checksum echo and retried on the healthy peer)
                assert "probs" in r, r
                np.testing.assert_allclose(
                    np.asarray(r["probs"], np.float32), want,
                    atol=1e-4)
            assert self._ctr("fleet.integrity_strikes") > strikes0
            assert self._ctr("fleet.retries") > retries0
            assert self._ctr("fleet.eject.total") == eject0 + 1
            st = router.sentinel.status(router.replicas[0])
            assert st["state"] in ("ejected", "probing"), st
            assert st["strikes"].get("integrity", 0) >= 2, st
            # probes read garbage too: reinstatement is impossible
            # while the fault is armed
            assert st["reinstatements"] == 0, st
            # the sentinel overlay reaches the operator surfaces
            fs = router.fleet_status()
            assert fs["replicas"][0]["sentinel"]["state"] in (
                "ejected", "probing")
            from veles_tpu import telemetry
            telemetry.flush()
            from veles_tpu.obs import fleet_rows
            rows = fleet_rows(router.metrics_dir_path)
            assert rows[0]["state"] in ("ejected", "probing"), rows
            assert rows[0]["health_score"] is not None
            assert rows[1]["state"] == "healthy", rows
        finally:
            router.close(kill=True)


class TestFleetCliProtocol:
    """The real ``python -m veles_tpu --serve-fleet N`` front end: the
    hello line carries fleet/placement/canary state, requests answer
    over the same JSONL protocol as a single hive, op=fleet reports
    per-replica health, and shutdown drains cleanly."""

    def test_cli_round_trip(self, packages):
        proc = subprocess.Popen(
            [sys.executable, "-m", "veles_tpu", "--serve-fleet", "2",
             f"alpha={packages['alpha']['pkg']}",
             f"beta={packages['beta']['pkg']}",
             "--canary", "beta=alpha:0.5",
             "-b", "cpu", "--max-batch", "8", "--max-wait-ms", "5"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, bufsize=1, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        try:
            def read_msg(timeout=180):
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    line = proc.stdout.readline()
                    if not line:
                        raise AssertionError(
                            f"fleet died rc={proc.poll()}")
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        msg = json.loads(line)
                    except ValueError:
                        continue
                    if "hb" in msg:
                        continue
                    return msg
                raise AssertionError("no message in time")

            hello = read_msg()
            assert hello["ready"] and hello["fleet"] == 2
            assert set(hello["models"]) == {"alpha", "beta"}
            assert hello["canaries"]["beta"]["of"] == "alpha"
            assert len(hello["replica_pids"]) == 2

            x = np.ones((1, 6, 6, 1), np.float32)
            proc.stdin.write(json.dumps(
                {"id": 1, "model": "alpha",
                 "rows": x.tolist()}) + "\n")
            proc.stdin.flush()
            resp = read_msg()
            assert resp["id"] == 1 and "probs" in resp, resp

            proc.stdin.write(json.dumps(
                {"op": "fleet", "id": 2}) + "\n")
            proc.stdin.flush()
            st = read_msg()
            assert st["id"] == 2
            assert len(st["fleet"]["replicas"]) == 2
            assert all(r["healthy"]
                       for r in st["fleet"]["replicas"])

            proc.stdin.write(json.dumps({"op": "shutdown"}) + "\n")
            proc.stdin.flush()
            assert proc.wait(timeout=60) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
