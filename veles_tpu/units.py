"""The Unit: node of the dataflow graph.

Reference parity: veles/units.py — a ``Unit`` has ``initialize()`` and
``run()``; control edges are made with ``link_from(src)`` (the unit
fires when ALL linked predecessors have fired since its last firing);
data edges with ``link_attrs(src, "a", ("mine", "theirs"))`` which alias
attributes to the source unit.  ``gate_block`` stops propagation through
the unit entirely; ``gate_skip`` skips ``run()`` but still propagates —
both are lazily-evaluated ``Bool``s so Decision's ``complete`` flag can
gate the training loop.

TPU-first note: the graph engine is pure host-side Python and carries no
tensors itself — compute lives in jitted step functions (see
veles_tpu/ops/fused.py).  The scheduler is synchronous and
deterministic; per-unit wall time is accumulated for the end-of-run
timing report (reference: workflow unit-timing table).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Set, Tuple, Union

from veles_tpu.logger import Logger
from veles_tpu.mutable import Bool, LinkableAttribute


class Unit(Logger):
    """A schedulable node. Subclasses override ``initialize`` and ``run``."""

    def __init__(self, workflow: Optional["Unit"] = None,
                 name: Optional[str] = None, **kwargs: Any) -> None:
        self._name = name
        self.workflow = None
        self.links_from: Dict[Unit, bool] = {}
        self.links_to: Set[Unit] = set()
        self.gate_block = Bool(False)
        self.gate_skip = Bool(False)
        self._initialized = False
        self.run_count = 0
        self.run_time = 0.0
        if workflow is not None:
            workflow.add_unit(self)
        self.__dict__.setdefault("_attr_links", {})

    # -- identity ------------------------------------------------------

    @property
    def name(self) -> str:
        return self._name or type(self).__name__

    @name.setter
    def name(self, value: str) -> None:
        self._name = value

    def __repr__(self) -> str:
        return f"<{type(self).__name__} '{self.name}'>"

    # -- attribute linking (data edges) -------------------------------

    def __getattr__(self, name: str) -> Any:
        links = self.__dict__.get("_attr_links")
        if links and name in links:
            return links[name].get()
        raise AttributeError(
            f"{type(self).__name__} '{self.__dict__.get('_name') or ''}' "
            f"has no attribute {name!r}")

    def __setattr__(self, name: str, value: Any) -> None:
        links = self.__dict__.get("_attr_links")
        if links and name in links:
            links[name].set(value)
            return
        object.__setattr__(self, name, value)

    def link_attrs(self, other: "Unit",
                   *names: Union[str, Tuple[str, str]]) -> "Unit":
        """Alias attributes of ``self`` to attributes of ``other``.

        Each name is either ``"attr"`` (same name on both sides) or a
        tuple ``("mine", "theirs")``.  Reads/writes pass through to the
        source unit, so downstream units always observe the producer's
        current value (reference: Unit.link_attrs).
        """
        for n in names:
            mine, theirs = (n, n) if isinstance(n, str) else n
            LinkableAttribute(self, mine, other, theirs)
        return self

    # -- control edges -------------------------------------------------

    def link_from(self, *units: "Unit") -> "Unit":
        for u in units:
            self.links_from[u] = False
            u.links_to.add(self)
        return self

    def unlink_from(self, *units: "Unit") -> "Unit":
        for u in units:
            self.links_from.pop(u, None)
            u.links_to.discard(self)
        return self

    def unlink_all(self) -> None:
        for u in list(self.links_from):
            self.unlink_from(u)
        for u in list(self.links_to):
            u.unlink_from(self)

    @property
    def ready(self) -> bool:
        return all(self.links_from.values()) if self.links_from else True

    def _reset_trigger_state(self) -> None:
        for u in self.links_from:
            self.links_from[u] = False

    # -- lifecycle -----------------------------------------------------

    def initialize(self, **kwargs: Any) -> None:
        """Allocate state. Called by Workflow.initialize in dependency
        order, possibly more than once until it stops raising."""

    def run(self) -> None:
        """Do the unit's work for one firing."""

    def stop(self) -> None:
        """Called when the workflow is stopping (cleanup hook)."""

    # -- scheduler internals (called by Workflow) ----------------------

    # -- snapshot support (SURVEY.md §7 "whole-workflow pickling") -----

    _unpicklable = ("device", "_compiled")

    def __getstate__(self) -> dict:
        """Drop device handles and compiled executables; everything else
        (including the unit graph's cyclic refs) pickles.  Resume
        re-attaches devices and re-jits (reference: snapshot contract,
        SURVEY.md §4.4)."""
        d = dict(self.__dict__)
        for k in self._unpicklable:
            d.pop(k, None)
        return d

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)
        for k in self._unpicklable:
            self.__dict__.setdefault(k, None)
        self._initialized = False

    def fire(self) -> bool:
        """Execute one firing; returns True if ``run()`` actually ran."""
        if bool(self.gate_skip):
            return False
        t0 = time.perf_counter()
        self.run()
        self.run_time += time.perf_counter() - t0
        self.run_count += 1
        return True


class TrivialUnit(Unit):
    """A no-op pass-through unit (reference: veles/units.py)."""


class Container(Unit):
    """A unit that owns other units (base of Workflow)."""

    def __init__(self, workflow: Optional[Unit] = None, **kwargs: Any) -> None:
        self.units: list = []
        super().__init__(workflow, **kwargs)

    def add_unit(self, unit: Unit) -> None:
        self.units.append(unit)
        unit.workflow = self

    def del_unit(self, unit: Unit) -> None:
        if unit in self.units:
            self.units.remove(unit)
            unit.unlink_all()
