"""Profiling: analytic FLOPs accounting, MFU, and jax.profiler traces.

Reference parity: SURVEY.md §5.1 — the reference accumulates per-unit
wall time around ``run()`` and prints a summary (that part lives in
veles_tpu/workflow.py); OpenCL event timing and block-size autotuning
have no TPU meaning (XLA autotunes).  The TPU-era replacement specified
by the survey is "``jax.profiler`` traces + per-unit host timers" plus
the accounting this module adds: analytic per-layer FLOPs for the
models built through StandardWorkflow, so throughput can be reported as
**MFU** (model FLOPs utilization = achieved FLOP/s over the chip's peak)
and physically impossible numbers are caught at the source.

FLOPs conventions (standard practice, e.g. the public scaling-book
accounting):

- one multiply-accumulate = 2 FLOPs;
- training step = forward + backward, where the backward of a weighted
  layer costs ~2x its forward (grad wrt input + grad wrt weights), so a
  weighted layer contributes 3x forward FLOPs and a weightless layer
  2x;
- elementwise/pooling/normalization ops are counted by output elements
  — they are HBM-bound, not MXU work, but keeping them in the total
  makes the estimate conservative (MFU is *under*-reported).
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

#: peak dense-matmul FLOP/s by device_kind substring, first match
#: wins.  bf16 numbers (the MXU's native format and what the fused
#: path computes in on TPU).  Public spec-sheet values.
PEAK_FLOPS = (
    ("v5 lite", 197e12),      # TPU v5e
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v6", 918e12),           # Trillium
    ("v3", 123e12),
    ("v2", 45e12),
)


def device_peak_flops(device) -> Optional[float]:
    """Peak bf16 FLOP/s for a jax device, or None if unknown (CPU)."""
    kind = getattr(device, "device_kind", "").lower()
    if not kind or "cpu" in kind:
        return None
    for sub, peak in PEAK_FLOPS:
        if sub in kind:
            return peak
    return None


def _numel(shape: Iterable[int]) -> int:
    return int(np.prod([int(s) for s in shape])) if shape else 0


def forward_flops_per_sample(unit) -> float:
    """Analytic forward-pass FLOPs for ONE sample through a forward
    unit.  Shapes must be resolved (call after workflow.initialize)."""
    out_shape = tuple(unit.output.shape)
    out_elems = _numel(out_shape[1:])
    kind = type(unit).__name__

    if hasattr(unit, "n_kernels") and hasattr(unit, "kx"):
        # conv family: 2 * ky*kx*c_in * n_kernels per output pixel.
        # deconv runs the same MACs laid out over its INPUT pixels.
        c_in = int(unit.input.shape[-1])
        macs_per_px = unit.ky * unit.kx * c_in * unit.n_kernels
        if "Deconv" in kind:
            spatial = _numel(unit.input.shape[1:3])
        else:
            spatial = _numel(out_shape[1:3])
        return 2.0 * macs_per_px * spatial
    if hasattr(unit, "output_sample_shape"):
        # all2all (dense): 2 * in_features * out_features
        in_feat = _numel(unit.input.shape[1:])
        return 2.0 * in_feat * _numel(unit.output_sample_shape)
    if hasattr(unit, "kx"):        # pooling: window reduce per output
        return float(unit.ky * unit.kx * out_elems)
    if "LRN" in kind:
        return 10.0 * out_elems
    return float(out_elems)        # activation / dropout / etc.


def unit_has_weights(unit) -> bool:
    w = getattr(unit, "weights", None)
    return w is not None and getattr(w, "mem", None) is not None


def model_flops_per_sample(forwards: List[Any]) -> Dict[str, float]:
    """{"forward": F, "train": T} FLOPs for one sample, with the 3x/2x
    weighted/weightless training multipliers."""
    fwd = 0.0
    train = 0.0
    for u in forwards:
        f = forward_flops_per_sample(u)
        fwd += f
        train += f * (3.0 if unit_has_weights(u) else 2.0)
    return {"forward": fwd, "train": train}


def layer_flops_table(forwards: List[Any]) -> List[Dict[str, Any]]:
    """Per-layer rows for the timing/profile report."""
    rows = []
    for u in forwards:
        f = forward_flops_per_sample(u)
        rows.append({
            "name": u.name,
            "type": type(u).__name__,
            "output_shape": tuple(int(s) for s in u.output.shape),
            "fwd_flops_per_sample": f,
            "train_flops_per_sample":
                f * (3.0 if unit_has_weights(u) else 2.0),
            "params": (_numel(u.weights.shape)
                       if unit_has_weights(u) else 0) +
                      (_numel(u.bias.shape)
                       if getattr(u, "bias", None) and
                       getattr(u.bias, "mem", None) is not None else 0),
        })
    return rows


def mfu(images_per_sec: float, train_flops_per_sample: float,
        device) -> Optional[float]:
    """Model FLOPs utilization in [0, 1]; None when peak is unknown."""
    peak = device_peak_flops(device)
    if not peak:
        return None
    return images_per_sec * train_flops_per_sample / peak


@contextlib.contextmanager
def trace(log_dir: Optional[str]):
    """``jax.profiler`` trace context; no-op when log_dir is falsy.

    The captured trace is a TensorBoard/perfetto-compatible directory —
    the survey's §5.1 "jax.profiler traces" deliverable."""
    if not log_dir:
        yield
        return
    import os

    import jax
    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield
