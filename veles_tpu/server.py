"""Master server: zmq master--slave data parallelism (DCN compat mode).

**LEGACY surface.**  Kept for reference parity and heterogeneous
clusters without an ICI/DCN mesh; it is NOT on the roadmap's serving
or scaling paths.  Training-scale distribution is SPMD over the mesh
(veles_tpu/parallel/, ``--dp``/``--multihost``); ONLINE INFERENCE is
the Hive serving tier (veles_tpu/serve, ``--serve-models`` — see
docs/guide.md "Online serving").

Reference parity: veles/server.py — the master owns canonical weights,
serves minibatch jobs to slaves, aggregates their weight updates, and
tolerates slaves joining/leaving mid-run (jobs of dead slaves are
requeued; SURVEY.md §4.2).

Protocol (pickle over zmq REQ/ROUTER):

    slave -> {"type": "handshake"}          -> {"type": "init", params}
    slave -> {"type": "job_request"}        -> {"type": "job", seq,
                                                loader, flags, params}
                                             | {"type": "bye"}
    slave -> {"type": "job_done", seq, ...} -> {"type": "ack"}

Jobs are issued on demand but their results are APPLIED in issue
order — Decision then observes exactly the standalone metric sequence
(with one slave the whole run is bit-identical to standalone).
"""

from __future__ import annotations

import pickle
import time
from collections import OrderedDict
from typing import Any, Dict

import numpy as np

from veles_tpu.logger import Logger


class _Job:
    __slots__ = ("seq", "payload", "slave", "issued_at", "result")

    def __init__(self, seq: int, payload: dict) -> None:
        self.seq = seq
        self.payload = payload
        self.slave = None
        self.issued_at = 0.0
        self.result = None


class MasterServer(Logger):
    def __init__(self, workflow, listen_address: str,
                 job_timeout: float = 60.0,
                 linger_s: float = 2.0,
                 max_ahead: int = 0) -> None:
        self.workflow = workflow
        self.listen_address = listen_address
        self.job_timeout = job_timeout
        self.linger_s = linger_s
        #: bound on issued-but-unapplied jobs; 0 = auto (2x slaves).
        #: Without it a fast slave can race through the whole run
        #: computing every diff against frozen initial weights while a
        #: stalled peer blocks in-order application.
        self.max_ahead = max_ahead
        self._seq = 0
        #: issue-ordered ring of outstanding jobs (applied from the head)
        self._pending: "OrderedDict[int, _Job]" = OrderedDict()
        self._requeue: list = []
        self._applied = 0
        self._slaves: set = set()

    # -- job construction ---------------------------------------------

    def _canonical_params(self) -> Dict[str, Dict[str, np.ndarray]]:
        """Master-side canonical weights live in the forwards' host
        Vectors (master never computes minibatches)."""
        out = {}
        for f in self.workflow.forwards:
            p = {}
            if f.weights:
                p["weights"] = np.asarray(f.weights.map_read())
            if f.bias and f.include_bias:
                p["bias"] = np.asarray(f.bias.map_read())
            out[f.name] = p
        return out

    def _apply_diff(self, diff) -> None:
        for fname, d in diff.items():
            f = next(u for u in self.workflow.forwards if u.name == fname)
            for pname, delta in d.items():
                vec = getattr(f, pname)
                vec.map_write()
                vec.mem += delta

    def _issue_payload(self) -> dict:
        """Advance the loader one minibatch and capture everything the
        slave needs plus the flag snapshot Decision will need at apply
        time."""
        ld = self.workflow.loader
        ld.run()
        # the scheduler isn't running on the master — fire the LR
        # schedule by hand or slaves train at a frozen initial LR
        lr_adjust = getattr(self.workflow, "lr_adjust", None)
        if lr_adjust is not None:
            lr_adjust.run()
        flags = {"minibatch_class": ld.minibatch_class,
                 "class_ended": bool(ld.class_ended),
                 "epoch_ended": bool(ld.epoch_ended),
                 "last_minibatch": bool(ld.last_minibatch),
                 "train_ended": bool(ld.train_ended),
                 "epoch_number": ld.epoch_number}
        fused = getattr(self.workflow, "fused", None)
        payload = {"loader": ld.generate_data_for_slave(),
                   "flags": flags,
                   "params": self._canonical_params(),
                   "lr_rates": fused.lr_rates
                   if fused is not None else None}
        return payload

    # -- in-order application -----------------------------------------

    def _apply_ready(self) -> None:
        ld = self.workflow.loader
        decision = self.workflow.decision
        ev = self.workflow.evaluator
        while self._pending:
            head = next(iter(self._pending.values()))
            if head.result is None:
                break
            self._pending.popitem(last=False)
            res = head.result
            if res.get("params_diff"):
                self._apply_diff(res["params_diff"])
            m = res["metrics"]
            ev.n_err.reset(np.float32([m["n_err"]]))
            ev.loss.reset(np.float32([m["loss_sum"]]))
            ev.count.reset(np.float32([m["count"]]))
            # replay the issue-time loader flags for Decision
            flags = head.payload["flags"]
            live = {"minibatch_class": ld.minibatch_class,
                    "epoch_number": ld.epoch_number,
                    "class_ended": bool(ld.class_ended),
                    "epoch_ended": bool(ld.epoch_ended),
                    "last_minibatch": bool(ld.last_minibatch),
                    "train_ended": bool(ld.train_ended)}
            self._set_loader_flags(flags)
            decision.run()
            self._set_loader_flags(live)
            self._applied += 1
            snap = self.workflow.snapshotter
            if snap is not None and bool(decision.improved):
                snap.run()

    def _set_loader_flags(self, flags: dict) -> None:
        ld = self.workflow.loader
        ld.minibatch_class = flags["minibatch_class"]
        ld.epoch_number = flags["epoch_number"]
        ld.class_ended.set(flags["class_ended"])
        ld.epoch_ended.set(flags["epoch_ended"])
        ld.last_minibatch.set(flags["last_minibatch"])
        ld.train_ended.set(flags["train_ended"])

    # -- elasticity ----------------------------------------------------

    def _reap_dead_jobs(self) -> None:
        now = time.monotonic()
        for job in self._pending.values():
            if job.result is None and job.slave is not None \
                    and now - job.issued_at > self.job_timeout:
                self.warning("job %d on slave %r timed out; requeueing",
                             job.seq, job.slave)
                dead = job.slave
                job.slave = None
                self._slaves.discard(dead)
                self._requeue.append(job)
                for u in self.workflow.units:
                    u_drop = getattr(u, "drop_slave", None)
                    if u_drop is not None:
                        u_drop(dead)

    # -- serve loop ----------------------------------------------------

    def serve(self) -> None:
        import zmq

        w = self.workflow
        w.loader.host_fill_enabled = False  # indices only on the master
        # Defense in depth for workflows initialized outside Launcher:
        # the master's job protocol is one minibatch per job and its
        # metrics arrive from slaves through the evaluator Vectors —
        # fused-mode loader grouping / metric routing must be off here.
        w.loader.superstep = 1
        if getattr(w.decision, "metrics_source", None) is not None:
            self.warning("master workflow was wired fused; forcing "
                         "eager metric intake (evaluator Vectors)")
            w.decision.metrics_source = None
        decision = w.decision
        ctx = zmq.Context.instance()
        sock = ctx.socket(zmq.ROUTER)
        sock.bind(self.listen_address)
        self.info("master listening on %s", self.listen_address)
        deadline_idle = None
        try:
            while True:
                if sock.poll(100):
                    ident, _, raw = sock.recv_multipart()
                    msg = pickle.loads(raw)
                    reply = self._handle(msg, ident)
                    sock.send_multipart([ident, b"",
                                         pickle.dumps(reply, protocol=4)])
                self._apply_ready()
                self._reap_dead_jobs()
                if bool(decision.complete):
                    # training is over: outstanding jobs (e.g. held by a
                    # dead slave) would never unblock the head — discard
                    # them instead of hanging; late results get "ack"ed
                    # and ignored
                    self._pending.clear()
                    self._requeue.clear()
                    # grace window so connected slaves get their "bye"
                    if deadline_idle is None:
                        deadline_idle = time.monotonic() + self.linger_s
                    elif time.monotonic() > deadline_idle:
                        break
                else:
                    deadline_idle = None
        finally:
            sock.close(0)
        self.info("master done: %d jobs applied, final valid error %.2f%%",
                  self._applied, decision.epoch_error_pct[1])

    def _handle(self, msg: dict, ident: bytes) -> dict:
        kind = msg.get("type")
        if kind == "handshake":
            self.info("slave %s connected", msg.get("id", ident.hex()))
            self._slaves.add(ident)
            return {"type": "init", "params": self._canonical_params()}
        if kind == "job_request":
            # a slave reaped by a conservative job_timeout may still be
            # alive and requesting — count it again for the issue window
            self._slaves.add(ident)
            if bool(self.workflow.decision.complete):
                return {"type": "bye"}
            if self._requeue:
                job = self._requeue.pop(0)
            elif len(self._pending) >= (self.max_ahead or
                                        2 * max(len(self._slaves), 1)):
                # issue window full: the head job is straggling; make
                # the requester back off instead of training ahead on
                # stale canonical weights
                return {"type": "wait", "delay_ms": 20}
            else:
                job = _Job(self._seq, self._issue_payload())
                self._seq += 1
                self._pending[job.seq] = job
            job.slave = ident
            job.issued_at = time.monotonic()
            return {"type": "job", "seq": job.seq, **job.payload}
        if kind == "job_done":
            job = self._pending.get(msg["seq"])
            if job is not None and job.result is None:
                job.result = msg
            return {"type": "ack"}
        return {"type": "error", "error": f"unknown message {kind!r}"}
