"""Lazily-evaluated booleans and linkable attributes.

Reference parity: veles/mutable.py — ``Bool`` objects compose with
``&``, ``|``, ``~`` into expression trees evaluated at read time; units
use them as gates (``gate_block``, ``gate_skip``) so one Decision unit's
``complete`` flag can simultaneously gate the loop-back edge and the end
point.  ``LinkableAttribute`` aliases an attribute of one object to an
attribute of another (the data edges of ``link_attrs``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional


class Bool:
    """A mutable boolean whose value may be derived from an expression
    over other Bools, evaluated lazily at each read."""

    __slots__ = ("_value", "_expr", "on_change")

    def __init__(self, value: bool = False) -> None:
        self._value = bool(value)
        self._expr: Optional[Callable[[], bool]] = None
        self.on_change: Optional[Callable[[bool], None]] = None

    @classmethod
    def from_expr(cls, expr: Callable[[], bool]) -> "Bool":
        b = cls()
        b._expr = expr
        return b

    def __bool__(self) -> bool:
        if self._expr is not None:
            return bool(self._expr())
        return self._value

    def __invert__(self) -> "Bool":
        return Bool.from_expr(lambda: not bool(self))

    def __and__(self, other: Any) -> "Bool":
        return Bool.from_expr(lambda: bool(self) and bool(other))

    def __or__(self, other: Any) -> "Bool":
        return Bool.from_expr(lambda: bool(self) or bool(other))

    def __lshift__(self, value: Any) -> "Bool":
        """``b << True`` — assign (reference's Bool uses <<= idiom)."""
        self.set(bool(value))
        return self

    def set(self, value: bool) -> None:
        if self._expr is not None:
            raise ValueError("cannot assign to a derived Bool")
        changed = self._value != bool(value)
        self._value = bool(value)
        if changed and self.on_change is not None:
            self.on_change(self._value)

    def __repr__(self) -> str:
        kind = "expr" if self._expr is not None else "value"
        return f"Bool({bool(self)}, {kind})"

    # Derived Bools hold closures; snapshots must not pickle them.
    def __getstate__(self) -> dict:
        return {"value": bool(self)}

    def __setstate__(self, state: dict) -> None:
        self._value = state["value"]
        self._expr = None
        self.on_change = None


class LinkableAttribute:
    """Alias ``owner.name`` to ``source.attr`` (two-way by default, like
    the reference: writes through to the source object).

    Installed on the owner *class* lazily as a data descriptor keyed by
    instance, so different instances may link to different sources.
    """

    def __init__(self, owner: Any, name: str, source: Any, attr: str,
                 two_way: bool = True) -> None:
        self.source = source
        self.attr = attr
        self.two_way = two_way
        links = owner.__dict__.get("_attr_links")
        if links is None:
            links = {}
            object.__setattr__(owner, "_attr_links", links)
        links[name] = self
        # Remove any instance attribute shadowing the link.
        owner.__dict__.pop(name, None)

    def get(self) -> Any:
        return getattr(self.source, self.attr)

    def set(self, value: Any) -> None:
        if not self.two_way:
            raise AttributeError(f"attribute linked one-way to "
                                 f"{self.source}.{self.attr}")
        setattr(self.source, self.attr, value)
