"""Lockstep's static half: flow-aware concurrency analysis.

PR 9's veleslint rules are per-file and syntactic; the bug classes
that actually bit the fleet-era code (PRs 10-12) are FLOW properties:
a lock acquired while another is held three calls away, a blocking
wait buried in a helper invoked under a lock, a waiter created on one
path and forgotten on the exception edge.  This module builds the
whole-program model those rules need:

- :class:`Project` — a cross-module index of classes, functions,
  imports, lock definitions (canonical witness names from
  ``witness.lock("...")`` creation sites, derived
  ``module.Class.attr`` identities otherwise), and lightweight type
  bindings (``self.sentinel = Sentinel(...)``, module singletons,
  locals assigned from return-annotated calls) — enough to resolve
  ``self.sentinel.record_died(...)`` or
  ``telemetry.histogram(...).record(...)`` to their defs;
- :func:`build_lock_graph` — the lock acquisition graph: each lock is
  a node, and acquiring B while A is held (lexically nested ``with``
  blocks, or a call chain from inside A's scope that reaches a
  ``with B``) is a directed edge A->B.  Cycles are deadlocks-in-
  waiting; the acyclic graph is serialized as
  ``analysis/lock_order.json`` — the checked-in locking law the
  runtime witness (witness.py) verifies against real execution;
- :func:`blocking_findings` — calls that can stall indefinitely
  (``time.sleep``, subprocess waits, untimed ``Queue.get/put``,
  ``Future.result()``, socket/pipe reads, jax dispatch) made while a
  lock is held, directly or through resolvable callees (the
  batcher/router stall class);
- :func:`waiter_findings` — a statement-level CFG (if/while/for/try
  with exception edges) + a reachability check that every created
  waiter (``.submit(...)`` handle, ``Future()``, ``Event()``) is
  resolved, cancelled, or handed off on EVERY path out of its
  creating function, exception edges included (the exact PR 12
  leaked-waiter class).  An exception that propagates out of the
  function transfers the obligation to the caller and is not flagged.

Everything here is stdlib-``ast`` only and deliberately conservative:
what cannot be resolved statically is skipped, not flagged.
"""

from __future__ import annotations

import ast
import json
import os
import tempfile
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from veles_tpu.analysis.engine import Finding, ModuleContext

#: follow-call depth for effects (lock acquires / blocking behaviour
#: of callees) — deep enough for telemetry.histogram -> Registry
#: -> Histogram chains, bounded so resolution noise cannot run away
MAX_DEPTH = 5

_THREADING_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock",
                         "Condition": "condition"}
_WITNESS_CTORS = {"lock": "lock", "rlock": "rlock",
                  "condition": "condition"}

#: waiter-creating calls: attribute spellings whose result is a
#: handle somebody must eventually collect/cancel/hand off
_SUBMIT_ATTRS = frozenset(("submit",))
_WAITER_CTOR_NAMES = frozenset(("Future", "Event"))

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def module_base(path: str) -> str:
    """``veles_tpu/serve/batcher.py`` -> ``serve.batcher`` (the
    package prefix is noise in lock identities)."""
    p = path[:-3] if path.endswith(".py") else path
    parts = p.split("/")
    if parts and parts[0] == "veles_tpu":
        parts = parts[1:]
    return ".".join(parts) or p


def dotted_name(path: str) -> str:
    """``veles_tpu/serve/batcher.py`` -> ``veles_tpu.serve.batcher``."""
    p = path[:-3] if path.endswith(".py") else path
    return p.replace("/", ".")


def _attr_chain(node: ast.expr) -> Optional[List[str]]:
    """``a.b.c`` -> ["a", "b", "c"]; None when not a pure chain."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        parts.reverse()
        return parts
    return None


class LockDef:
    """One lock definition site."""

    __slots__ = ("name", "kind", "path", "line", "witnessed")

    def __init__(self, name: str, kind: str, path: str, line: int,
                 witnessed: bool) -> None:
        self.name = name
        self.kind = kind
        self.path = path
        self.line = line
        self.witnessed = witnessed


class FuncInfo:
    """One function/method definition and its lexical context."""

    __slots__ = ("node", "path", "cls", "chain", "qualname")

    def __init__(self, node: ast.AST, path: str, cls: Optional[str],
                 chain: Tuple[int, ...], qualname: str) -> None:
        self.node = node
        self.path = path
        self.cls = cls
        #: id()s of enclosing function nodes, outermost first
        self.chain = chain
        self.qualname = qualname


class ModuleInfo:
    """Everything the flow analyses index about one module."""

    def __init__(self, ctx: ModuleContext) -> None:
        self.ctx = ctx
        self.path = ctx.path
        self.base = module_base(ctx.path)
        #: local alias -> dotted module name (``import x.y as z`` and
        #: ``from pkg import mod``)
        self.mod_aliases: Dict[str, str] = {}
        #: local name -> (dotted module, original name) for
        #: ``from mod import name``
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        self.functions: Dict[str, ast.AST] = {}       # module level
        self.methods: Dict[Tuple[str, str], ast.AST] = {}
        #: every function anywhere in the module, by id(node)
        self.funcs: Dict[int, FuncInfo] = {}
        #: nested defs: id(parent fn) -> {name: child fn node}
        self.nested: Dict[int, Dict[str, ast.AST]] = {}
        # lock bindings
        self.module_locks: Dict[str, LockDef] = {}
        self.attr_locks: Dict[Tuple[str, str], LockDef] = {}
        self.local_locks: Dict[Tuple[int, str], LockDef] = {}
        # type bindings: -> (module_path, class_name)
        self.module_var_types: Dict[str, Tuple[str, str]] = {}
        self.attr_types: Dict[Tuple[str, str],
                              Tuple[str, str]] = {}
        self.local_var_types: Dict[Tuple[int, str],
                                   Tuple[str, str]] = {}
        self._index_defs()

    def _index_defs(self) -> None:
        for node in self.ctx.tree.body:
            if isinstance(node, (ast.Import,)):
                for alias in node.names:
                    self.mod_aliases[alias.asname
                                     or alias.name.split(".")[0]] = \
                        alias.name if alias.asname else \
                        alias.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    local = alias.asname or alias.name
                    # ``from pkg import mod`` is a module alias when
                    # pkg.mod is a module; recorded as BOTH — the
                    # project resolves whichever exists
                    self.mod_aliases.setdefault(
                        local, f"{node.module}.{alias.name}")
                    self.from_imports[local] = (node.module,
                                                alias.name)
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                for sub in node.body:
                    if isinstance(sub, _FUNC_DEFS):
                        self.methods[(node.name, sub.name)] = sub
            elif isinstance(node, _FUNC_DEFS):
                self.functions[node.name] = node

        # every function with its lexical context
        def walk(body: Iterable[ast.stmt], cls: Optional[str],
                 chain: Tuple[int, ...], prefix: str) -> None:
            for node in body:
                if isinstance(node, _FUNC_DEFS):
                    qual = f"{prefix}{node.name}"
                    self.funcs[id(node)] = FuncInfo(
                        node, self.path, cls, chain, qual)
                    if chain:
                        self.nested.setdefault(
                            chain[-1], {})[node.name] = node
                    walk(node.body, cls, chain + (id(node),),
                         qual + ".")
                elif isinstance(node, ast.ClassDef):
                    walk(node.body, node.name, chain,
                         f"{node.name}.")
                elif isinstance(node, (ast.If, ast.Try, ast.With,
                                       ast.For, ast.While)):
                    for field in ("body", "orelse", "finalbody",
                                  "handlers"):
                        sub = getattr(node, field, None) or []
                        for s in sub:
                            if isinstance(s, ast.ExceptHandler):
                                walk(s.body, cls, chain, prefix)
                            else:
                                walk([s], cls, chain, prefix)
        walk(self.ctx.tree.body, None, (), "")


class Project:
    """The whole-program index over every scanned module."""

    def __init__(self, contexts: List[ModuleContext]) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_dotted: Dict[str, str] = {}
        for ctx in contexts:
            mi = ModuleInfo(ctx)
            self.modules[ctx.path] = mi
            self.by_dotted[dotted_name(ctx.path)] = ctx.path
        for mi in self.modules.values():
            self._index_locks_and_types(mi)
        self._effects_memo: Dict[int, Dict[str, Any]] = {}
        self._effects_stack: Set[int] = set()

    # -- indexing ------------------------------------------------------

    def module_for_alias(self, mi: ModuleInfo,
                         name: str) -> Optional[ModuleInfo]:
        dotted = mi.mod_aliases.get(name)
        if dotted is None:
            return None
        path = self.by_dotted.get(dotted)
        if path is None and "." not in dotted:
            # bare ``import telemetry``-style alias inside the package
            path = self.by_dotted.get(f"veles_tpu.{dotted}")
        return self.modules.get(path) if path else None

    def resolve_class(self, mi: ModuleInfo, name: str
                      ) -> Optional[Tuple[ModuleInfo, str]]:
        if name in mi.classes:
            return mi, name
        imp = mi.from_imports.get(name)
        if imp:
            target = self.modules.get(
                self.by_dotted.get(f"{imp[0]}.{imp[1]}", ""))
            # ``from a import b`` where a.b is a module: not a class
            if target is not None:
                return None
            src = self.modules.get(self.by_dotted.get(imp[0], ""))
            if src and imp[1] in src.classes:
                return src, imp[1]
        return None

    def _lock_ctor(self, mi: ModuleInfo, value: ast.expr,
                   derived: str) -> Optional[Tuple[str, str, bool]]:
        """(lock name, kind, witnessed) when ``value`` constructs a
        lock; ``derived`` is the fallback identity."""
        if not isinstance(value, ast.Call):
            return None
        f = value.func
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name):
            base, attr = f.value.id, f.attr
            if base == "threading" and \
                    attr in _THREADING_LOCK_CTORS:
                return derived, _THREADING_LOCK_CTORS[attr], False
            if base == "witness" and attr in _WITNESS_CTORS:
                name = derived
                if value.args and \
                        isinstance(value.args[0], ast.Constant) and \
                        isinstance(value.args[0].value, str):
                    name = value.args[0].value
                return name, _WITNESS_CTORS[attr], True
        return None

    def _type_of_value(self, mi: ModuleInfo, value: ast.expr
                       ) -> Optional[Tuple[str, str]]:
        """(module path, class name) of an assigned value when it is
        a direct class instantiation or a call with a resolvable
        return annotation."""
        if not isinstance(value, ast.Call):
            return None
        f = value.func
        if isinstance(f, ast.Name):
            cls = self.resolve_class(mi, f.id)
            if cls:
                return cls[0].path, cls[1]
            fn = mi.functions.get(f.id)
            if fn is not None:
                return self._return_type(mi, fn)
        elif isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name):
            target = self.module_for_alias(mi, f.value.id)
            if target is not None:
                if f.attr in target.classes:
                    return target.path, f.attr
                fn = target.functions.get(f.attr)
                if fn is not None:
                    return self._return_type(target, fn)
        return None

    def _return_type(self, mi: ModuleInfo, fn: ast.AST
                     ) -> Optional[Tuple[str, str]]:
        ann = getattr(fn, "returns", None)
        name: Optional[str] = None
        if isinstance(ann, ast.Name):
            name = ann.id
        elif isinstance(ann, ast.Constant) and \
                isinstance(ann.value, str):
            name = ann.value.split("[")[0].strip()
        elif isinstance(ann, ast.Attribute):
            name = ann.attr
        if not name:
            return None
        cls = self.resolve_class(mi, name)
        return (cls[0].path, cls[1]) if cls else None

    def _index_locks_and_types(self, mi: ModuleInfo) -> None:
        if mi.path.startswith("veles_tpu/analysis/"):
            return   # the analyzer/witness plumbing is not the law

        def visit(body, cls: Optional[str], fn: Optional[int]):
            for node in body:
                if isinstance(node, _FUNC_DEFS):
                    visit(node.body, cls, id(node))
                    continue
                if isinstance(node, ast.ClassDef):
                    visit(node.body, node.name, fn)
                    continue
                targets: List[ast.expr] = []
                value: Optional[ast.expr] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                if value is not None and len(targets) == 1:
                    t = targets[0]
                    self._bind(mi, t, value, cls, fn)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(node, field, None)
                    if sub:
                        visit(sub, cls, fn)
                for h in getattr(node, "handlers", []) or []:
                    visit(h.body, cls, fn)
        visit(mi.ctx.tree.body, None, None)

    def _bind(self, mi: ModuleInfo, target: ast.expr,
              value: ast.expr, cls: Optional[str],
              fn: Optional[int]) -> None:
        if isinstance(target, ast.Name):
            scope = f"{cls}." if cls and fn is None else ""
            derived = f"{mi.base}.{scope}{target.id}"
            lock = self._lock_ctor(mi, value, derived)
            if lock is not None:
                ld = LockDef(lock[0], lock[1], mi.path,
                             value.lineno, lock[2])
                if fn is not None:
                    mi.local_locks[(fn, target.id)] = ld
                else:
                    mi.module_locks[target.id] = ld
                return
            typ = self._type_of_value(mi, value)
            if typ is not None:
                if fn is not None:
                    mi.local_var_types[(fn, target.id)] = typ
                else:
                    mi.module_var_types[target.id] = typ
        elif isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self" and cls is not None:
            derived = f"{mi.base}.{cls}.{target.attr}"
            lock = self._lock_ctor(mi, value, derived)
            if lock is not None:
                mi.attr_locks[(cls, target.attr)] = LockDef(
                    lock[0], lock[1], mi.path, value.lineno, lock[2])
                return
            typ = self._type_of_value(mi, value)
            if typ is not None:
                mi.attr_types[(cls, target.attr)] = typ

    # -- resolution ----------------------------------------------------

    def resolve_lock(self, mi: ModuleInfo, expr: ast.expr,
                     fi: FuncInfo) -> Optional[LockDef]:
        """The lock a ``with``-item / receiver refers to, if any."""
        if isinstance(expr, ast.Name):
            for fid in (fi.chain + (id(fi.node),))[::-1]:
                ld = mi.local_locks.get((fid, expr.id))
                if ld is not None:
                    return ld
            return mi.module_locks.get(expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            if expr.value.id == "self" and fi.cls is not None:
                return mi.attr_locks.get((fi.cls, expr.attr))
            # module-qualified: other_module._some_lock
            target = self.module_for_alias(mi, expr.value.id)
            if target is not None:
                return target.module_locks.get(expr.attr)
        return None

    def _var_type(self, mi: ModuleInfo, fi: FuncInfo,
                  name: str) -> Optional[Tuple[str, str]]:
        for fid in (fi.chain + (id(fi.node),))[::-1]:
            t = mi.local_var_types.get((fid, name))
            if t is not None:
                return t
        return mi.module_var_types.get(name)

    def resolve_call(self, mi: ModuleInfo, fi: FuncInfo,
                     call: ast.Call
                     ) -> Optional[Tuple[ModuleInfo, ast.AST,
                                         Optional[str]]]:
        """(module, function node, class name) of the callee when it
        is statically resolvable; None otherwise."""
        f = call.func
        if isinstance(f, ast.Name):
            # innermost enclosing scope first: nested defs shadow
            for fid in (fi.chain + (id(fi.node),))[::-1]:
                child = mi.nested.get(fid, {}).get(f.id)
                if child is not None:
                    return mi, child, fi.cls
            fn = mi.functions.get(f.id)
            if fn is not None:
                return mi, fn, None
            cls = self.resolve_class(mi, f.id)
            if cls is not None:
                init = cls[0].methods.get((cls[1], "__init__"))
                if init is not None:
                    return cls[0], init, cls[1]
            imp = mi.from_imports.get(f.id)
            if imp:
                src = self.modules.get(
                    self.by_dotted.get(imp[0], ""))
                if src:
                    fn = src.functions.get(imp[1])
                    if fn is not None:
                        return src, fn, None
            return None
        if not (isinstance(f, ast.Attribute)):
            return None
        base = f.value
        # self.method(...)
        if isinstance(base, ast.Name) and base.id == "self" \
                and fi.cls is not None:
            m = mi.methods.get((fi.cls, f.attr))
            if m is not None:
                return mi, m, fi.cls
            # self.attr.method(...) handled below via attr type
            return None
        # module.func(...) / module.Class(...)
        if isinstance(base, ast.Name):
            target = self.module_for_alias(mi, base.id)
            if target is not None:
                fn = target.functions.get(f.attr)
                if fn is not None:
                    return target, fn, None
                if f.attr in target.classes:
                    init = target.methods.get((f.attr, "__init__"))
                    if init is not None:
                        return target, init, f.attr
                return None
            typ = self._var_type(mi, fi, base.id)
            if typ is not None:
                return self._method_of(typ, f.attr)
            return None
        # self.attr.method(...)
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self" and fi.cls is not None:
            typ = mi.attr_types.get((fi.cls, base.attr))
            if typ is not None:
                return self._method_of(typ, f.attr)
            return None
        # chained: expr().method(...) via the inner call's return type
        if isinstance(base, ast.Call):
            inner = self.resolve_call(mi, fi, base)
            if inner is not None:
                tmi, tfn, tcls = inner
                rt = self._return_type(tmi, tfn)
                if rt is None and tcls is not None and \
                        isinstance(base.func, (ast.Name,
                                               ast.Attribute)):
                    # a constructor call returns its class
                    callee_name = base.func.id \
                        if isinstance(base.func, ast.Name) \
                        else base.func.attr
                    if callee_name == tcls or callee_name \
                            == "__init__":
                        rt = (tmi.path, tcls)
                if rt is not None:
                    return self._method_of(rt, f.attr)
        return None

    def _method_of(self, typ: Tuple[str, str], meth: str
                   ) -> Optional[Tuple[ModuleInfo, ast.AST, str]]:
        tmi = self.modules.get(typ[0])
        if tmi is None:
            return None
        m = tmi.methods.get((typ[1], meth))
        if m is None:
            return None
        return tmi, m, typ[1]

    # -- effects -------------------------------------------------------

    def effects(self, mi: ModuleInfo, fnode: ast.AST,
                depth: int = MAX_DEPTH) -> Dict[str, Any]:
        """What calling ``fnode`` may do, transitively (bounded):
        ``{"acquires": {lock name: chain str},
        "blocking": {desc: chain str}}``."""
        key = id(fnode)
        memo = self._effects_memo.get(key)
        if memo is not None:
            return memo
        if key in self._effects_stack or depth <= 0:
            return {"acquires": {}, "blocking": {}}
        self._effects_stack.add(key)
        fi = mi.funcs.get(key)
        acquires: Dict[str, str] = {}
        blocking: Dict[str, str] = {}
        label = f"{module_base(mi.path)}." \
                f"{fi.qualname if fi else '?'}"
        try:
            if fi is None:
                return {"acquires": {}, "blocking": {}}
            for node in self._own_nodes(fnode):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        ld = self.resolve_lock(
                            mi, item.context_expr, fi)
                        if ld is not None:
                            acquires.setdefault(ld.name, label)
                elif isinstance(node, ast.Call):
                    desc = self.classify_blocking(mi, fi, node,
                                                  held=())
                    if desc is not None:
                        blocking.setdefault(desc, label)
                    target = self.resolve_call(mi, fi, node)
                    if target is not None:
                        tmi, tfn, _tcls = target
                        sub = self.effects(tmi, tfn, depth - 1)
                        for name, chain in sub["acquires"].items():
                            acquires.setdefault(
                                name, f"{label} -> {chain}")
                        for desc, chain in sub["blocking"].items():
                            blocking.setdefault(
                                desc, f"{label} -> {chain}")
        finally:
            self._effects_stack.discard(key)
        out = {"acquires": acquires, "blocking": blocking}
        self._effects_memo[key] = out
        return out

    @staticmethod
    def _own_nodes(fnode: ast.AST) -> Iterable[ast.AST]:
        """Walk a function body, NOT descending into nested function
        definitions (they run when called, not here)."""
        stack = list(ast.iter_child_nodes(fnode))
        while stack:
            node = stack.pop()
            if isinstance(node, _FUNC_DEFS + (ast.Lambda,)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # -- blocking classification ---------------------------------------

    _SUBPROCESS_FUNCS = frozenset((
        "run", "call", "check_call", "check_output"))
    _READ_ATTRS = frozenset(("recv", "readline"))

    def classify_blocking(self, mi: ModuleInfo, fi: FuncInfo,
                          call: ast.Call,
                          held: Tuple[str, ...]) -> Optional[str]:
        """A short description when ``call`` can stall indefinitely;
        None otherwise.  ``held`` is the lexically held lock set —
        a ``wait`` on the ONLY held condition is exempt (it releases
        that lock for the duration)."""
        f = call.func
        kwnames = {kw.arg for kw in call.keywords}
        if isinstance(f, ast.Name):
            if f.id == "sleep" and \
                    mi.from_imports.get("sleep", ("",))[0] == "time":
                return "time.sleep()"
            return None
        if not isinstance(f, ast.Attribute):
            return None
        base = f.value
        base_name = base.id if isinstance(base, ast.Name) else None
        if f.attr == "sleep" and base_name == "time":
            return "time.sleep()"
        if base_name == "subprocess" and \
                f.attr in self._SUBPROCESS_FUNCS:
            return f"subprocess.{f.attr}()"
        if base_name == "os" and f.attr == "read":
            return "os.read()"
        if f.attr in self._READ_ATTRS:
            return f".{f.attr}() pipe/socket read"
        if f.attr == "result" and not call.args and \
                "timeout" not in kwnames:
            return ".result() with no timeout"
        if f.attr in ("block_until_ready",):
            return ".block_until_ready() device sync"
        if f.attr in ("wait", "wait_for", "join", "get", "put"):
            ld = self.resolve_lock(mi, base, fi)
            if ld is not None and f.attr in ("wait", "wait_for"):
                if held and set(held) == {ld.name}:
                    return None   # cond.wait releases the only lock
                return (f"condition {ld.name}.wait() while other "
                        f"locks are held")
            typ = self._typed_receiver(mi, fi, base)
            if typ is None:
                return None
            if typ == "Event" and f.attr == "wait" and \
                    not call.args and "timeout" not in kwnames:
                return "Event.wait() with no timeout"
            if typ in ("Popen",) and f.attr == "wait" and \
                    not call.args and "timeout" not in kwnames:
                return "Popen.wait() with no timeout"
            if typ == "Thread" and f.attr == "join" and \
                    not call.args and "timeout" not in kwnames:
                return "Thread.join() with no timeout"
            if typ in ("Queue", "SimpleQueue") and f.attr == "get" \
                    and "timeout" not in kwnames:
                return "Queue.get() with no timeout"
            if typ == "Queue" and f.attr == "put" and \
                    "timeout" not in kwnames:
                return "Queue.put() with no timeout"
        return None

    def _typed_receiver(self, mi: ModuleInfo, fi: FuncInfo,
                        base: ast.expr) -> Optional[str]:
        """The stdlib concurrency type of a receiver expression, by
        spelled-out constructor binding (``x = queue.Queue()``,
        ``self._proc = subprocess.Popen(...)``...)."""
        ctor = self._ctor_of(mi, fi, base)
        if ctor is None:
            return None
        chain = _attr_chain(ctor.func) if isinstance(ctor, ast.Call) \
            else None
        if not chain:
            return None
        leaf = chain[-1]
        if leaf in ("Queue", "LifoQueue", "PriorityQueue"):
            return "Queue"
        if leaf == "SimpleQueue":
            return "SimpleQueue"
        if leaf in ("Event", "Popen", "Thread"):
            return leaf
        return None

    def _ctor_of(self, mi: ModuleInfo, fi: FuncInfo,
                 base: ast.expr) -> Optional[ast.Call]:
        """The constructor call a receiver was bound to, scanning the
        module for ``name = Ctor()`` / ``self.attr = Ctor()``."""
        want_attr: Optional[Tuple[str, str]] = None
        want_name: Optional[str] = None
        if isinstance(base, ast.Name):
            want_name = base.id
        elif isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self" and fi.cls is not None:
            want_attr = (fi.cls, base.attr)
        else:
            return None
        for node in ast.walk(mi.ctx.tree):
            targets: List[ast.expr] = []
            value: Optional[ast.expr] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value:
                targets, value = [node.target], node.value
            if not isinstance(value, ast.Call):
                continue
            for t in targets:
                if want_name is not None and \
                        isinstance(t, ast.Name) and \
                        t.id == want_name:
                    return value
                if want_attr is not None and \
                        isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self" and \
                        t.attr == want_attr[1]:
                    return value
        return None


# -- the lock acquisition graph ----------------------------------------

class LockGraph:
    """Nodes (LockDef by name) + directed edges with provenance."""

    def __init__(self) -> None:
        self.nodes: Dict[str, LockDef] = {}
        #: (holder, acquired) -> first provenance string
        self.edges: Dict[Tuple[str, str], str] = {}

    def add_node(self, ld: LockDef) -> None:
        self.nodes.setdefault(ld.name, ld)

    def add_edge(self, holder: str, acquired: str,
                 via: str) -> None:
        if holder == acquired:
            return
        self.edges.setdefault((holder, acquired), via)

    def cycles(self) -> List[List[str]]:
        """Every elementary cycle reachable in the edge set (DFS;
        deduplicated by rotation)."""
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, []).append(b)
        seen_cycles: Set[Tuple[str, ...]] = set()
        out: List[List[str]] = []

        def dfs(start: str, node: str, path: List[str],
                on_path: Set[str]) -> None:
            for nxt in adj.get(node, ()):
                if nxt == start:
                    cyc = path[:]
                    rot = min(range(len(cyc)),
                              key=lambda i: cyc[i])
                    canon = tuple(cyc[rot:] + cyc[:rot])
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        out.append(list(canon))
                elif nxt not in on_path and nxt > start:
                    # only walk nodes ordered after start: each
                    # cycle is found exactly once, from its
                    # smallest node
                    on_path.add(nxt)
                    dfs(start, nxt, path + [nxt], on_path)
                    on_path.discard(nxt)

        for start in sorted(adj):
            dfs(start, start, [start], {start})
        return out

    def to_payload(self, manual: Optional[List[Dict[str, str]]]
                   = None) -> Dict[str, Any]:
        return {
            "format": 1,
            "comment": ("GENERATED lock acquisition graph (the "
                        "repo's locking law) — regenerate with "
                        "`python scripts/veleslint.py "
                        "--sync-lock-order`; hand-add edges only "
                        "under manual_edges, with a justification."),
            "nodes": [
                {"name": n.name, "kind": n.kind,
                 "defined": f"{n.path}:{n.line}",
                 "witnessed": n.witnessed}
                for n in sorted(self.nodes.values(),
                                key=lambda n: n.name)],
            "edges": [
                {"from": a, "to": b, "via": via}
                for (a, b), via in sorted(self.edges.items())],
            "manual_edges": manual or [],
        }

    def edge_pairs(self) -> Set[Tuple[str, str]]:
        return set(self.edges)


def build_project(contexts: List[ModuleContext]) -> Project:
    return Project(contexts)


def _iter_with_items(node: ast.AST) -> List[ast.expr]:
    return [item.context_expr for item in node.items] \
        if isinstance(node, (ast.With, ast.AsyncWith)) else []


def _walk_held(project: Project, mi: ModuleInfo, fi: FuncInfo,
               on_with, on_call) -> None:
    """Walk one function's own statements tracking the lexically held
    lock stack; ``on_with(lockdef, node, held)`` fires at each
    resolved lock acquisition, ``on_call(call, held)`` at each call
    made while any lock is held."""

    def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, _FUNC_DEFS + (ast.Lambda,)):
            return   # runs later, on its own stack
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for expr in _iter_with_items(node):
                ld = project.resolve_lock(mi, expr, fi)
                if ld is not None:
                    on_with(ld, node, inner)
                    inner = inner + (ld.name,)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, ast.Call) and held:
            on_call(node, held)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fi.node.body:
        visit(stmt, ())


def build_lock_graph(project: Project,
                     scope: Optional[List[str]] = None) -> LockGraph:
    """The cross-module lock acquisition graph.  ``scope`` limits
    which modules' FUNCTIONS are walked for acquisition sites (the
    thread-spawning modules); lock definitions and call-following
    cover every scanned module regardless, so an edge from a scoped
    module into telemetry's locks is still found."""
    graph = LockGraph()
    for mi in project.modules.values():
        if mi.path.startswith("veles_tpu/analysis/"):
            continue
        for ld in mi.module_locks.values():
            graph.add_node(ld)
        for ld in mi.attr_locks.values():
            graph.add_node(ld)
        for ld in mi.local_locks.values():
            graph.add_node(ld)

    for mi in project.modules.values():
        if scope is not None and mi.path not in scope:
            continue
        for fi in mi.funcs.values():
            def on_with(ld: LockDef, node: ast.AST,
                        held: Tuple[str, ...],
                        mi=mi, fi=fi) -> None:
                for holder in held:
                    graph.add_edge(
                        holder, ld.name,
                        f"{mi.path}:{node.lineno} "
                        f"({fi.qualname})")

            def on_call(call: ast.Call, held: Tuple[str, ...],
                        mi=mi, fi=fi) -> None:
                # explicit .acquire() on a resolvable lock
                f = call.func
                if isinstance(f, ast.Attribute) and \
                        f.attr == "acquire":
                    ld = project.resolve_lock(mi, f.value, fi)
                    if ld is not None:
                        for holder in held:
                            graph.add_edge(
                                holder, ld.name,
                                f"{mi.path}:{call.lineno} "
                                f"({fi.qualname})")
                        return
                target = project.resolve_call(mi, fi, call)
                if target is None:
                    return
                tmi, tfn, _tcls = target
                eff = project.effects(tmi, tfn)
                for name, chain in eff["acquires"].items():
                    for holder in held:
                        graph.add_edge(
                            holder, name,
                            f"{mi.path}:{call.lineno} "
                            f"({fi.qualname} -> {chain})")

            _walk_held(project, mi, fi, on_with, on_call)
    return graph


# -- lock_order.json I/O -----------------------------------------------

def load_lock_order(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def declared_edges(payload: Dict[str, Any]) -> Set[Tuple[str, str]]:
    out = set()
    for e in payload.get("edges", []) or []:
        out.add((e["from"], e["to"]))
    for e in payload.get("manual_edges", []) or []:
        out.add((e["from"], e["to"]))
    return out


def write_lock_order(path: str, graph: LockGraph,
                     keep_manual: bool = True) -> None:
    manual: List[Dict[str, str]] = []
    if keep_manual:
        old = load_lock_order(path)
        if old:
            manual = list(old.get("manual_edges", []) or [])
    payload = graph.to_payload(manual)
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".lockorder.",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def render_lock_table(payload: Dict[str, Any]) -> str:
    """The guide's threading-model table, generated from
    lock_order.json."""
    rows = ["| Held lock | May acquire | Where |",
            "| --- | --- | --- |"]
    for e in payload.get("edges", []) or []:
        via = e.get("via", "")
        rows.append(f"| `{e['from']}` | `{e['to']}` | {via} |")
    for e in payload.get("manual_edges", []) or []:
        rows.append(f"| `{e['from']}` | `{e['to']}` | "
                    f"(manual: {e.get('justification', '')}) |")
    if len(rows) == 2:
        rows.append("| (none) | (none) | no nested acquisition "
                    "anywhere |")
    return "\n".join(rows) + "\n"


# -- blocking-under-lock findings --------------------------------------

RULE_BLOCKING = "blocking-under-lock"


def blocking_findings(project: Project,
                      scope: List[str]) -> List[Finding]:
    out: List[Finding] = []
    for path in scope:
        mi = project.modules.get(path)
        if mi is None:
            continue
        for fi in mi.funcs.values():
            def on_with(ld, node, held):
                pass

            def on_call(call: ast.Call, held: Tuple[str, ...],
                        mi=mi, fi=fi) -> None:
                desc = project.classify_blocking(mi, fi, call,
                                                 held)
                if desc is None:
                    target = project.resolve_call(mi, fi, call)
                    if target is not None:
                        tmi, tfn, _ = target
                        eff = project.effects(tmi, tfn)
                        for d, chain in eff["blocking"].items():
                            desc = f"{d} (via {chain})"
                            break
                if desc is None:
                    return
                out.append(Finding(
                    RULE_BLOCKING, mi.path, call.lineno,
                    call.col_offset,
                    f"{fi.qualname}:{desc}",
                    f"{desc} while holding "
                    f"{', '.join(sorted(set(held)))} in "
                    f"{fi.qualname!r}: a stalled call under a lock "
                    f"wedges every thread contending for it — move "
                    f"the blocking work outside the critical "
                    f"section or bound it with a timeout"))

            _walk_held(project, mi, fi, on_with, on_call)
    return out


# -- waiter discipline -------------------------------------------------

RULE_WAITER = "waiter-discipline"

_EXIT = "exit"


class _CFG:
    """Statement-level control-flow graph of one function body."""

    def __init__(self) -> None:
        self.succ_norm: Dict[Any, Set[Any]] = {}
        self.succ_exc: Dict[Any, Set[Any]] = {}

    def _edge(self, table: Dict[Any, Set[Any]], a: Any,
              b: Any) -> None:
        table.setdefault(id(a) if not isinstance(a, str) else a,
                         set()).add(b)

    def norm(self, a, b) -> None:
        self._edge(self.succ_norm, a, b)

    def exc(self, a, b) -> None:
        self._edge(self.succ_exc, a, b)

    def successors(self, node) -> Tuple[Set[Any], Set[Any]]:
        key = id(node) if not isinstance(node, str) else node
        return (self.succ_norm.get(key, set()),
                self.succ_exc.get(key, set()))


def _build_cfg(body: List[ast.stmt]) -> _CFG:
    cfg = _CFG()

    def first(stmts: List[ast.stmt], follow):
        return stmts[0] if stmts else follow

    def build(stmts: List[ast.stmt], follow, handlers,
              loop) -> None:
        for i, stmt in enumerate(stmts):
            nxt = stmts[i + 1] if i + 1 < len(stmts) else follow
            build_stmt(stmt, nxt, handlers, loop)

    def build_stmt(stmt: ast.stmt, nxt, handlers, loop) -> None:
        for h in handlers:
            cfg.exc(stmt, h)
        if isinstance(stmt, ast.Return):
            cfg.norm(stmt, _EXIT)
        elif isinstance(stmt, ast.Raise):
            # propagates out (obligation transfers to the caller)
            # unless an enclosing handler catches it — the exc edges
            # above model the catch
            pass
        elif isinstance(stmt, ast.Break):
            if loop:
                cfg.norm(stmt, loop[-1][1])
        elif isinstance(stmt, ast.Continue):
            if loop:
                cfg.norm(stmt, loop[-1][0])
        elif isinstance(stmt, ast.If):
            body_e = first(stmt.body, nxt)
            else_e = first(stmt.orelse, nxt)
            cfg.norm(stmt, body_e)
            cfg.norm(stmt, else_e)
            build(stmt.body, nxt, handlers, loop)
            build(stmt.orelse, nxt, handlers, loop)
        elif isinstance(stmt, (ast.While, ast.For)):
            body_e = first(stmt.body, stmt)
            cfg.norm(stmt, body_e)
            else_e = first(stmt.orelse, nxt)
            cfg.norm(stmt, else_e)
            build(stmt.body, stmt, handlers,
                  loop + [(stmt, nxt)])
            build(stmt.orelse, nxt, handlers, loop)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            body_e = first(stmt.body, nxt)
            cfg.norm(stmt, body_e)
            build(stmt.body, nxt, handlers, loop)
        elif isinstance(stmt, ast.Try):
            h_entries = []
            fin_entry = first(stmt.finalbody, nxt) \
                if stmt.finalbody else nxt
            for h in stmt.handlers:
                h_entries.append(first(h.body, fin_entry))
            body_follow = first(stmt.orelse, fin_entry) \
                if stmt.orelse else fin_entry
            body_e = first(stmt.body, body_follow)
            cfg.norm(stmt, body_e)
            build(stmt.body, body_follow,
                  handlers + h_entries, loop)
            # the else clause runs after the body completed without
            # raising, and ITS exceptions are NOT caught by this
            # try's handlers — outer handlers only
            build(stmt.orelse, fin_entry, handlers, loop)
            for h in stmt.handlers:
                build(h.body, fin_entry, handlers, loop)
            build(stmt.finalbody, nxt, handlers, loop)
        else:
            cfg.norm(stmt, nxt)
    build(body, _EXIT, [], [])
    return cfg


def _mentions(stmt: ast.stmt, var: str) -> bool:
    """Does executing THIS statement (not the statements nested
    inside it) touch ``var``?  Compound statements contribute only
    their header expression — their bodies are separate CFG nodes; a
    nested function definition capturing the name counts in full (the
    closure is a handoff)."""
    if isinstance(stmt, (ast.If, ast.While)):
        probe: List[ast.AST] = [stmt.test]
    elif isinstance(stmt, ast.For):
        probe = [stmt.iter, stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        probe = [i.context_expr for i in stmt.items]
    elif isinstance(stmt, ast.Try):
        probe = []
    else:
        probe = [stmt]
    for root in probe:
        for node in ast.walk(root):
            if isinstance(node, ast.Name) and node.id == var:
                return True
            if isinstance(node, _FUNC_DEFS):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) and \
                            sub.id == var:
                        return True
    return False


def _waiter_creator(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _SUBMIT_ATTRS:
        return ".submit()"
    name = None
    if isinstance(f, ast.Name):
        name = f.id
    elif isinstance(f, ast.Attribute):
        name = f.attr
    if name in _WAITER_CTOR_NAMES:
        return f"{name}()"
    return None


def waiter_findings(project: Project,
                    scope: List[str]) -> List[Finding]:
    out: List[Finding] = []
    for path in scope:
        mi = project.modules.get(path)
        if mi is None:
            continue
        for fi in mi.funcs.values():
            out.extend(_check_function_waiters(mi, fi))
    return out


def _check_function_waiters(mi: ModuleInfo,
                            fi: FuncInfo) -> List[Finding]:
    body = list(fi.node.body)
    cfg = _build_cfg(body)
    out: List[Finding] = []

    # index every statement of THIS function (nested defs are their
    # own functions with their own CFG — double-reporting otherwise)
    all_stmts: Dict[int, ast.stmt] = {}

    def collect(stmts: List[ast.stmt]) -> None:
        for s in stmts:
            if isinstance(s, _FUNC_DEFS + (ast.ClassDef,)):
                all_stmts[id(s)] = s
                continue
            all_stmts[id(s)] = s
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(s, field, None)
                if sub:
                    collect(sub)
            for h in getattr(s, "handlers", []) or []:
                collect(h.body)
    collect(body)

    for s in all_stmts.values():
        creations = _creations_in(s, fi)
        for var, what, call in creations:
            if var is None:
                out.append(Finding(
                    RULE_WAITER, mi.path, call.lineno,
                    call.col_offset,
                    f"{fi.qualname}:dropped:{what}:{call.lineno}",
                    f"{what} result dropped in {fi.qualname!r}: "
                    f"nobody will collect this waiter (its errors "
                    f"vanish) — assign it and resolve/cancel/hand "
                    f"it off on every path"))
                continue
            leak = _leaks(cfg, s, var)
            if leak is not None:
                out.append(Finding(
                    RULE_WAITER, mi.path, call.lineno,
                    call.col_offset,
                    f"{fi.qualname}:{var}:{what}",
                    f"waiter {var!r} from {what} in "
                    f"{fi.qualname!r} is abandoned on "
                    f"{'an exception path' if leak == 'exc' else 'a normal path'}"
                    f" — every control-flow path (exception edges "
                    f"included) must resolve, cancel, or hand it "
                    f"off (the PR 12 leaked-waiter class)"))
    return out


def _creations_in(stmt: ast.stmt, fi: FuncInfo
                  ) -> List[Tuple[Optional[str], str, ast.Call]]:
    """(var or None-if-dropped, creator desc, call) for waiter
    creations at statement level."""
    out: List[Tuple[Optional[str], str, ast.Call]] = []
    if isinstance(stmt, ast.Assign) and \
            isinstance(stmt.value, ast.Call) and \
            len(stmt.targets) == 1 and \
            isinstance(stmt.targets[0], ast.Name):
        what = _waiter_creator(stmt.value)
        if what:
            out.append((stmt.targets[0].id, what, stmt.value))
    elif isinstance(stmt, ast.Expr) and \
            isinstance(stmt.value, ast.Call):
        what = _waiter_creator(stmt.value)
        if what:
            out.append((None, what, stmt.value))
    return out


def _leaks(cfg: _CFG, creation: ast.stmt,
           var: str) -> Optional[str]:
    """'exc' / 'norm' when an exit is reachable from the creation with
    the waiter unresolved (and how the leaking hop was reached);
    None when every path resolves it.  The obligation starts on the
    creation's NORMAL successors only — an exception inside the
    creating call means nothing was created."""
    norm0, _exc0 = cfg.successors(creation)
    frontier: List[Tuple[Any, str]] = [(n, "norm") for n in norm0]
    seen: Set[Tuple[Any, str]] = set()
    while frontier:
        node, how = frontier.pop()
        key = (id(node) if not isinstance(node, str) else node, how)
        if key in seen:
            continue
        seen.add(key)
        if node == _EXIT:
            return how
        assert isinstance(node, ast.stmt)
        resolved = _mentions(node, var)
        norm, exc = cfg.successors(node)
        if not resolved:
            for n in norm:
                frontier.append((n, how))
        for n in exc:
            frontier.append((n, "exc"))
    return None
