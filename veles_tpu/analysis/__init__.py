"""Veleslint: repo-specific AST static analysis.

PRs 6-8 hardened this codebase around invariants that, until this
package, lived only in convention: persistent state writes must be
atomic (tempfile + ``os.replace``), every ``VELES_*`` env knob must be
declared in the central registry (veles_tpu/knobs.py), telemetry
journal/metric names must be declared constants (veles_tpu/events.py),
traced functions must not host-sync, the 13/14 exit-code contract must
flow from the named constants, and module-level mutable state in the
thread-spawning modules must be mutated under a lock.  Veleslint turns
each invariant into a machine-checked rule that runs in tier-1
(tests/test_veleslint.py) and as the ``veleslint`` CLI
(scripts/veleslint.py), with inline ``# veleslint: disable=<rule>``
waivers and a checked-in baseline (analysis/baseline.json) for
justified grandfathered findings.

See docs/guide.md section 10 for the rule catalog and workflow.
"""

from veles_tpu.analysis.engine import (  # noqa: F401
    Config,
    Finding,
    check_knob_table,
    load_baseline,
    load_config,
    load_contexts,
    new_findings,
    project_findings,
    repo_root,
    repo_scan,
    run_lint,
    scan_source,
    write_baseline,
)
from veles_tpu.analysis.rules import (  # noqa: F401
    PROJECT_RULES,
    RULES,
    rule_names,
)
