"""The veleslint engine: file discovery, AST scaffolding, waivers,
baseline bookkeeping, and the docs-sync check.

The engine is deliberately dependency-free (stdlib ``ast`` only) and
jax-free, so the full-repo scan runs in tier-1 in well under a second
and the CLI works on a box with nothing installed.

Scanning model: each file parses once into a :class:`ModuleContext`
(AST + parent links + resolved module/class string constants + source
lines), every rule visits the context, and findings are filtered
through inline waivers (``# veleslint: disable=<rule>[,<rule>...]`` on
the flagged line; bare ``disable`` waives all rules) and then against
the baseline.  A finding's identity is ``rule | path | detail`` — NOT
the line number — so baselined findings survive unrelated edits to the
same file.
"""

from __future__ import annotations

import ast
import json
import os
import re
import tempfile
from typing import Any, Dict, Iterable, List, Optional, Tuple

WAIVER_RE = re.compile(
    r"#\s*veleslint:\s*disable(?:=([A-Za-z0-9_,\- ]+))?")

#: markers bracketing the generated knob table in docs/guide.md
KNOB_TABLE_BEGIN = "<!-- veleslint:knobs:begin -->"
KNOB_TABLE_END = "<!-- veleslint:knobs:end -->"


class Finding:
    """One lint finding.  ``detail`` is the stable identity component
    (an env name, an event literal, a function name...) so baseline
    matching survives line drift."""

    __slots__ = ("rule", "path", "line", "col", "detail", "message")

    def __init__(self, rule: str, path: str, line: int, col: int,
                 detail: str, message: str) -> None:
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.detail = detail
        self.message = message

    @property
    def key(self) -> str:
        return f"{self.rule}|{self.path}|{self.detail}"

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.message}")

    def to_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path,
                "line": self.line, "col": self.col,
                "detail": self.detail, "message": self.message,
                "key": self.key}

    def __repr__(self) -> str:
        return f"Finding({self.format()})"


# -- configuration -----------------------------------------------------

_DEFAULTS: Dict[str, Any] = {
    # scan roots, relative to the repo root
    "paths": ["veles_tpu", "scripts", "bench.py",
              "__graft_entry__.py"],
    # directory basenames never descended into
    "exclude": ["__pycache__", "native", "tests", "tests_tpu",
                "build", "dist"],
    "baseline": "veles_tpu/analysis/baseline.json",
    "guide": "docs/guide.md",
    # atomic-write applies only under these prefixes (scripts write
    # scratch files freely; the package writes persistent state)
    "atomic_write_scope": ["veles_tpu"],
    # exit-code-literals applies only to the modules that own the
    # 0/13/14 contract (elsewhere a bare 13 is just a number)
    "exit_code_modules": [
        "veles_tpu/launcher.py", "veles_tpu/supervisor.py",
        "veles_tpu/__main__.py", "veles_tpu/genetics/core.py",
        "veles_tpu/genetics/worker.py", "veles_tpu/genetics/pool.py",
        "veles_tpu/online/tap.py", "veles_tpu/online/buffer.py",
        "veles_tpu/online/trainer.py", "veles_tpu/online/promote.py",
        "scripts/chaos_drill.py", "scripts/gauntlet.py"],
    # lock-discipline / blocking-under-lock / the lock-order graph
    # walk apply to the thread-spawning modules
    "lock_modules": [
        "veles_tpu/faults.py", "veles_tpu/telemetry.py",
        "veles_tpu/launcher.py", "veles_tpu/supervisor.py",
        "veles_tpu/web_status.py", "veles_tpu/genetics/pool.py",
        "veles_tpu/genetics/worker.py",
        "veles_tpu/serve/batcher.py", "veles_tpu/serve/hive.py",
        "veles_tpu/serve/client.py", "veles_tpu/serve/residency.py",
        "veles_tpu/serve/fleet.py", "veles_tpu/serve/router.py",
        "veles_tpu/serve/sentinel.py", "veles_tpu/serve/traffic.py",
        "veles_tpu/serve/autoscale.py", "veles_tpu/online/tap.py",
        "veles_tpu/online/buffer.py", "veles_tpu/online/trainer.py",
        "veles_tpu/online/promote.py"],
    # waiter-discipline applies to the serve tier + the GA pool
    "waiter_modules": [
        "veles_tpu/serve/batcher.py", "veles_tpu/serve/client.py",
        "veles_tpu/serve/fleet.py", "veles_tpu/serve/hive.py",
        "veles_tpu/serve/residency.py", "veles_tpu/serve/router.py",
        "veles_tpu/serve/sentinel.py", "veles_tpu/serve/traffic.py",
        "veles_tpu/serve/autoscale.py", "veles_tpu/genetics/pool.py",
        "veles_tpu/online/tap.py", "veles_tpu/online/buffer.py",
        "veles_tpu/online/trainer.py", "veles_tpu/online/promote.py"],
    # wire-protocol applies to the modules that build JSONL lines
    "wire_modules": [
        "veles_tpu/serve/router.py", "veles_tpu/serve/client.py",
        "veles_tpu/serve/hive.py", "veles_tpu/serve/batcher.py",
        "veles_tpu/serve/sentinel.py", "veles_tpu/serve/traffic.py",
        "veles_tpu/online/tap.py",
        "veles_tpu/online/trainer.py", "veles_tpu/online/promote.py"],
    # thread-lifecycle applies to every thread-spawning module
    "thread_modules": [
        "veles_tpu/faults.py", "veles_tpu/telemetry.py",
        "veles_tpu/launcher.py", "veles_tpu/supervisor.py",
        "veles_tpu/web_status.py", "veles_tpu/genetics/pool.py",
        "veles_tpu/genetics/worker.py",
        "veles_tpu/serve/batcher.py", "veles_tpu/serve/hive.py",
        "veles_tpu/serve/client.py", "veles_tpu/serve/fleet.py",
        "veles_tpu/serve/router.py", "veles_tpu/serve/sentinel.py",
        "veles_tpu/serve/traffic.py", "veles_tpu/serve/autoscale.py",
        "veles_tpu/online/trainer.py", "bench.py"],
    # the residency/donation seam: the ONLY modules allowed to call
    # jax.device_put or pass donate_argnums — everything else goes
    # through engine.core.ExecutionCore (put / donating_jit)
    "engine_seam_modules": [
        "veles_tpu/engine/core.py", "veles_tpu/serve/residency.py",
        "veles_tpu/parallel/mesh.py"],
    #: the checked-in locking law the lock-order rule verifies
    "lock_order": "veles_tpu/analysis/lock_order.json",
    # the registries themselves declare names as literals by design
    "registry_exempt": ["veles_tpu/knobs.py", "veles_tpu/events.py"],
    # rules to run (all by default)
    "rules": [],
}


class Config:
    """Veleslint configuration (defaults overlaid with
    ``[tool.veleslint]`` from pyproject.toml)."""

    def __init__(self, **overrides: Any) -> None:
        self._values = dict(_DEFAULTS)
        for k, v in overrides.items():
            if k not in _DEFAULTS:
                raise ValueError(f"[tool.veleslint]: unknown key {k!r}"
                                 f" (known: {sorted(_DEFAULTS)})")
            self._values[k] = v

    def __getattr__(self, name: str) -> Any:
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(name) from None


def _mini_toml_table(text: str, table: str) -> Dict[str, Any]:
    """A minimal TOML-subset reader for one table — python 3.10 has no
    tomllib and this repo may not install one.  Supports exactly what
    ``[tool.veleslint]`` uses: bare ``key = value`` with string, int,
    bool, and (possibly multi-line) string-array values."""
    out: Dict[str, Any] = {}
    in_table = False
    pending_key: Optional[str] = None
    pending_items: List[str] = []

    def parse_scalar(tok: str) -> Any:
        tok = tok.strip().rstrip(",").strip()
        if not tok:
            return None
        if tok in ("true", "false"):
            return tok == "true"
        if (tok.startswith('"') and tok.endswith('"')) or \
                (tok.startswith("'") and tok.endswith("'")):
            return tok[1:-1]
        try:
            return int(tok)
        except ValueError:
            return tok

    for raw in text.splitlines():
        line = raw.split("#", 1)[0].rstrip() if '"' not in raw \
            else raw.rstrip()
        stripped = line.strip()
        if stripped.startswith("["):
            in_table = stripped == f"[{table}]"
            continue
        if not in_table or not stripped:
            continue
        if pending_key is not None:
            body = stripped
            closed = body.endswith("]")
            if closed:
                body = body[:-1]
            pending_items += [s for s in
                              (parse_scalar(t) for t in body.split(","))
                              if s is not None]
            if closed:
                out[pending_key] = pending_items
                pending_key, pending_items = None, []
            continue
        if "=" not in stripped:
            continue
        key, _, val = stripped.partition("=")
        key, val = key.strip(), val.strip()
        if val.startswith("["):
            body = val[1:]
            closed = body.endswith("]")
            if closed:
                body = body[:-1]
            items = [s for s in
                     (parse_scalar(t) for t in body.split(","))
                     if s is not None]
            if closed:
                out[key] = items
            else:
                pending_key, pending_items = key, items
        else:
            out[key] = parse_scalar(val)
    return out


def load_config(root: Optional[str] = None) -> Config:
    """Config from ``<root>/pyproject.toml``'s ``[tool.veleslint]``
    (defaults when the file or table is absent)."""
    root = root or repo_root()
    path = os.path.join(root, "pyproject.toml")
    if not os.path.isfile(path):
        return Config()
    with open(path, "rb") as f:
        raw = f.read()
    try:
        import tomllib  # python >= 3.11
        table = tomllib.loads(raw.decode()).get(
            "tool", {}).get("veleslint", {})
    except ImportError:
        table = _mini_toml_table(raw.decode(), "tool.veleslint")
    return Config(**table)


def repo_root() -> str:
    """The repository root (two levels above this package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


# -- module context ----------------------------------------------------

class ModuleContext:
    """Everything a rule needs about one parsed file."""

    def __init__(self, path: str, source: str, config: Config) -> None:
        self.path = path          # repo-relative, posix separators
        self.source = source
        self.config = config
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        #: module- and class-level ``NAME = "literal"`` string
        #: constants, for resolving env/event names referenced by
        #: constant instead of literal.  Class attrs are flattened by
        #: bare attribute name (``self.PREEMPT_GRACE_ENV`` ->
        #: ``PREEMPT_GRACE_ENV``).
        self.str_consts: Dict[str, str] = {}
        self._collect_consts()

    def _collect_consts(self) -> None:
        def grab(body: Iterable[ast.stmt]) -> None:
            for stmt in body:
                targets: List[ast.expr] = []
                value: Optional[ast.expr] = None
                if isinstance(stmt, ast.Assign):
                    targets, value = stmt.targets, stmt.value
                elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                    targets, value = [stmt.target], stmt.value
                if not (isinstance(value, ast.Constant)
                        and isinstance(value.value, str)):
                    continue
                for t in targets:
                    if isinstance(t, ast.Name):
                        self.str_consts.setdefault(t.id, value.value)
        grab(self.tree.body)
        for stmt in self.tree.body:
            if isinstance(stmt, ast.ClassDef):
                grab(stmt.body)

    def resolve_str(self, node: ast.expr) -> Optional[str]:
        """The string value of ``node`` when statically resolvable:
        a literal, a module/class constant referenced by Name, or by
        Attribute (``self.CONST`` / ``Cls.CONST``).  None otherwise —
        unresolvable names are skipped, not flagged (an imported
        constant is checked where it is defined)."""
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return self.str_consts.get(node.id)
        if isinstance(node, ast.Attribute):
            return self.str_consts.get(node.attr)
        return None

    def enclosing(self, node: ast.AST,
                  kinds: Tuple[type, ...]) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                return cur
            cur = self.parents.get(cur)
        return None

    def in_function(self, node: ast.AST) -> bool:
        return self.enclosing(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)) is not None

    def under_lock(self, node: ast.AST) -> bool:
        """Is ``node`` lexically inside a ``with <...lock...>:``
        block?  A lock is any context expression containing a
        Name/Attribute whose identifier contains "lock"."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    for sub in ast.walk(item.context_expr):
                        ident = None
                        if isinstance(sub, ast.Name):
                            ident = sub.id
                        elif isinstance(sub, ast.Attribute):
                            ident = sub.attr
                        if ident and "lock" in ident.lower():
                            return True
            cur = self.parents.get(cur)
        return False

    def waived(self, line: int, rule: str) -> bool:
        if not (1 <= line <= len(self.lines)):
            return False
        m = WAIVER_RE.search(self.lines[line - 1])
        if not m:
            return False
        which = m.group(1)
        if which is None:
            return True
        return rule in {r.strip() for r in which.split(",")}


# -- scanning ----------------------------------------------------------

def _iter_files(root: str, config: Config) -> List[str]:
    """Repo-relative paths of every .py file under the configured scan
    roots, exclusions applied."""
    exclude = set(config.exclude)
    out: List[str] = []
    for entry in config.paths:
        full = os.path.join(root, entry)
        if os.path.isfile(full):
            out.append(entry.replace(os.sep, "/"))
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in exclude)
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name),
                                      root)
                out.append(rel.replace(os.sep, "/"))
    return out


def _scan_ctx(ctx: ModuleContext,
              rules: Optional[List[str]]) -> List[Finding]:
    """Per-file rules over one parsed module context."""
    from veles_tpu.analysis.rules import RULES
    findings: List[Finding] = []
    for rule in RULES:
        if rules and rule.name not in rules:
            continue
        for f in rule.check(ctx):
            if not ctx.waived(f.line, f.rule):
                findings.append(f)
    return findings


def scan_source(path: str, source: str, config: Optional[Config] = None,
                rules: Optional[List[str]] = None) -> List[Finding]:
    """Run the (selected) per-file rules over one in-memory module.
    ``path`` is the repo-relative path used for scoping and
    reporting.  The whole-program Lockstep rules (lock-order,
    blocking-under-lock, waiter-discipline) need every module at once
    and only run through :func:`run_lint` /
    :func:`project_findings`."""
    config = config or Config()
    try:
        ctx = ModuleContext(path, source, config)
    except SyntaxError as e:
        return [Finding("parse-error", path, e.lineno or 0, 0,
                        "syntax", f"does not parse: {e.msg}")]
    selected = rules if rules is not None else \
        (config.rules or None)
    findings = _scan_ctx(ctx, selected)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def load_contexts(root: str, config: Config
                  ) -> List[ModuleContext]:
    """Parse every configured file once (parse errors surface as
    findings through run_lint; unparsable files are skipped here)."""
    out: List[ModuleContext] = []
    for rel in _iter_files(root, config):
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        try:
            out.append(ModuleContext(rel, source, config))
        except SyntaxError:
            continue
    return out


def project_findings(contexts: List[ModuleContext], root: str,
                     config: Config,
                     rules: Optional[List[str]] = None
                     ) -> List[Finding]:
    """The whole-program Lockstep rules over the parsed contexts,
    inline waivers applied (a project finding anchored in a scanned
    file honors `# veleslint: disable=...` on its line)."""
    from veles_tpu.analysis.concurrency import PROJECT_RULES
    from veles_tpu.analysis.flow import build_project
    selected = rules if rules is not None else \
        (config.rules or None)
    wanted = [r for r in PROJECT_RULES
              if not selected or r.name in selected]
    if not wanted:
        return []
    project = build_project(contexts)
    by_path = {ctx.path: ctx for ctx in contexts}
    findings: List[Finding] = []
    for rule in wanted:
        for f in rule.check_project(project, config, root):
            ctx = by_path.get(f.path)
            if ctx is not None and ctx.waived(f.line, f.rule):
                continue
            findings.append(f)
    return findings


def run_lint(root: Optional[str] = None,
             config: Optional[Config] = None,
             rules: Optional[List[str]] = None,
             check_docs: bool = True,
             only_paths: Optional[List[str]] = None) -> List[Finding]:
    """The full scan: per-file rules over every configured file, the
    whole-program Lockstep rules over the project, and the docs-sync
    check of the generated knob table.

    ``only_paths`` (the CLI's ``--changed-only`` fast mode) restricts
    REPORTING to those files: the project is still parsed and the
    lock-order law still checked whole (the graph is meaningless
    piecemeal), but per-file and per-function findings outside the
    set are dropped.  The full scan remains the tier-1 gate."""
    root = root or repo_root()
    config = config or load_config(root)
    selected = rules if rules is not None else \
        (config.rules or None)
    only = set(only_paths) if only_paths is not None else None
    findings: List[Finding] = []
    contexts: List[ModuleContext] = []
    for rel in _iter_files(root, config):
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        try:
            ctx = ModuleContext(rel, source, config)
        except SyntaxError as e:
            if only is None or rel in only:
                findings.append(Finding(
                    "parse-error", rel, e.lineno or 0, 0, "syntax",
                    f"does not parse: {e.msg}"))
            continue
        contexts.append(ctx)
        if only is not None and rel not in only:
            continue
        findings += _scan_ctx(ctx, selected)
    for f in project_findings(contexts, root, config, rules):
        if only is not None and f.path in only and \
                f.path.endswith(".py"):
            findings.append(f)
        elif only is None or not f.path.endswith(".py"):
            # law-level findings (lock_order.json drift/cycles,
            # guide table) always report — the graph is global
            findings.append(f)
    if check_docs and (rules is None or "env-registry" in rules):
        doc = check_knob_table(root, config)
        if doc is not None:
            findings.append(doc)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# -- docs sync ---------------------------------------------------------

def knob_table_block() -> str:
    """The full generated block, markers included."""
    from veles_tpu import knobs
    return (f"{KNOB_TABLE_BEGIN}\n"
            "<!-- GENERATED from veles_tpu/knobs.py by `python "
            "scripts/veleslint.py --sync-docs`; do not edit. -->\n"
            f"{knobs.render_table()}"
            f"{KNOB_TABLE_END}")


def check_knob_table(root: str, config: Config) -> Optional[Finding]:
    """None when the guide's knob table matches the registry; a
    finding otherwise (missing markers count as out of sync)."""
    guide = os.path.join(root, config.guide)
    try:
        with open(guide, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return Finding(
            "env-registry", config.guide, 1, 0, "knob-table",
            "guide file is missing — the generated VELES_* knob table "
            "must live here (scripts/veleslint.py --sync-docs)")
    begin = text.find(KNOB_TABLE_BEGIN)
    end = text.find(KNOB_TABLE_END)
    if begin < 0 or end < 0:
        return Finding(
            "env-registry", config.guide, 1, 0, "knob-table",
            f"knob-table markers not found ({KNOB_TABLE_BEGIN} ... "
            f"{KNOB_TABLE_END}); run scripts/veleslint.py --sync-docs")
    current = text[begin:end + len(KNOB_TABLE_END)]
    if current.strip() != knob_table_block().strip():
        line = text[:begin].count("\n") + 1
        return Finding(
            "env-registry", config.guide, line, 0, "knob-table",
            "the VELES_* knob table is out of sync with "
            "veles_tpu/knobs.py; run scripts/veleslint.py --sync-docs")
    return None


def sync_knob_table(root: Optional[str] = None,
                    config: Optional[Config] = None) -> str:
    """Rewrite the guide's knob table from the registry (atomically);
    returns the guide path.  Appends a fresh block when the markers
    are missing."""
    root = root or repo_root()
    config = config or load_config(root)
    guide = os.path.join(root, config.guide)
    with open(guide, encoding="utf-8") as f:
        text = f.read()
    block = knob_table_block()
    begin = text.find(KNOB_TABLE_BEGIN)
    end = text.find(KNOB_TABLE_END)
    if begin >= 0 and end >= 0:
        text = text[:begin] + block + text[end + len(KNOB_TABLE_END):]
    else:
        text = text.rstrip("\n") + "\n\n" + block + "\n"
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(guide),
                               prefix=".guide.", suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(text)
        os.replace(tmp, guide)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    return guide


# -- baseline ----------------------------------------------------------

def load_baseline(path: str) -> Dict[str, str]:
    """``{finding key: justification}``.  Raises ValueError when an
    entry lacks a written justification — a grandfathered finding
    without a reason is just a suppressed bug."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return {}
    out: Dict[str, str] = {}
    for entry in data.get("findings", []):
        key = entry.get("key", "")
        just = (entry.get("justification") or "").strip()
        if not key:
            continue
        if not just or just.lower().startswith("todo"):
            raise ValueError(
                f"{path}: baseline entry {key!r} has no written "
                "justification — fix the finding or justify why it is "
                "grandfathered")
        out[key] = just
    return out


def write_baseline(path: str, findings: List[Finding],
                   existing: Optional[Dict[str, str]] = None) -> None:
    """Write ``findings`` as the new baseline, keeping existing
    justifications and stamping new entries with a TODO the loader
    refuses — committing an unjustified baseline fails tier-1 by
    design."""
    existing = existing or {}
    entries = []
    seen = set()
    for f in findings:
        if f.key in seen:
            continue
        seen.add(f.key)
        entries.append({
            "key": f.key,
            "line": f.line,
            "message": f.message,
            "justification": existing.get(
                f.key, "TODO: justify this grandfathered finding or "
                       "fix it"),
        })
    payload = {"format": 1, "findings": entries}
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".baseline.",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def new_findings(findings: List[Finding],
                 baseline: Dict[str, str]) -> List[Finding]:
    return [f for f in findings if f.key not in baseline]


def repo_scan(root: Optional[str] = None
              ) -> Tuple[List[Finding], Dict[str, str]]:
    """The canonical full-repo scan: (non-baselined findings, the
    baseline) — what the tier-1 test and bench.py both record."""
    root = root or repo_root()
    config = load_config(root)
    baseline = load_baseline(os.path.join(root, config.baseline))
    findings = run_lint(root, config)
    return new_findings(findings, baseline), baseline
