"""Lockstep's rules: the flow-aware concurrency checks.

Three whole-program rules built on :mod:`veles_tpu.analysis.flow`
(they see every scanned module at once, so a lock acquired three
calls away still makes an edge):

- ``lock-order`` — the cross-module lock acquisition graph must be
  cycle-free AND match the checked-in ``analysis/lock_order.json``
  (regenerate with ``veleslint --sync-lock-order``); the guide's
  threading-model table must match the json.  The runtime witness
  (witness.py) asserts real execution stays inside this law.
- ``blocking-under-lock`` — no indefinitely-blocking call
  (``time.sleep``, subprocess waits, untimed ``Queue.get/put``,
  ``Future.result()``, pipe/socket reads, device syncs) while a lock
  is held, directly or through resolvable callees.
- ``waiter-discipline`` — every created waiter (``.submit()`` handle,
  ``Future()``, ``Event()``) in the serve+pool modules is resolved,
  cancelled, or handed off on every control-flow path out of its
  creating function, exception edges included.

Plus two per-module rules in the registry style of PR 9:

- ``thread-lifecycle`` — every ``threading.Thread`` in the
  thread-spawning modules is ``daemon=True`` or provably joined.
- ``wire-protocol`` — string keys of dict literals flowing to the
  JSONL wire (``emit``/``_send``/``json.dumps`` arguments,
  assigned-then-sent locals, returned response dicts) must be
  declared in ``veles_tpu/serve/protocol.py`` — the same typo class
  events.py closed for telemetry names.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from veles_tpu.analysis import flow
from veles_tpu.analysis.engine import (Config, Finding,
                                       ModuleContext)

#: markers bracketing the generated threading-model table in the guide
LOCK_TABLE_BEGIN = "<!-- veleslint:lockorder:begin -->"
LOCK_TABLE_END = "<!-- veleslint:lockorder:end -->"


def _in_scope(path: str, modules: List[str]) -> bool:
    return path in modules


# -- per-module rules --------------------------------------------------

class ThreadLifecycleRule:
    """A non-daemon thread in a long-lived module outlives shutdown
    paths silently; every spawn must be ``daemon=True`` or the module
    must provably join it."""

    name = "thread-lifecycle"
    doc = ("`threading.Thread(...)` in a thread-spawning module "
           "without `daemon=True` and without any `.join(...)` in "
           "the module — an unjoined non-daemon thread blocks "
           "interpreter exit on every shutdown path")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if not _in_scope(ctx.path, ctx.config.thread_modules):
            return []
        has_join = any(
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "join"
            for n in ast.walk(ctx.tree))
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "Thread"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "threading"):
                continue
            daemon = None
            label = None
            for kw in node.keywords:
                if kw.arg == "daemon" and \
                        isinstance(kw.value, ast.Constant):
                    daemon = bool(kw.value.value)
                if kw.arg == "name" and \
                        isinstance(kw.value, ast.Constant):
                    label = str(kw.value.value)
                if kw.arg == "target":
                    t = kw.value
                    if label is None and isinstance(t, ast.Name):
                        label = t.id
                    elif label is None and \
                            isinstance(t, ast.Attribute):
                        label = t.attr
            if daemon is True:
                continue
            if daemon is None and has_join:
                # non-daemon but the module joins threads — the
                # shutdown path is explicit
                continue
            out.append(Finding(
                self.name, ctx.path, node.lineno, node.col_offset,
                f"thread:{label or node.lineno}",
                f"thread {label or '<unnamed>'} is not daemon=True "
                f"and the module never joins: it outlives every "
                f"shutdown path — mark it daemon or join it on "
                f"close"))
        return out


class WireProtocolRule:
    """JSONL wire fields are declared once in serve/protocol.py; an
    ad-hoc key in a dict flowing to the wire is the emitter/reader
    typo class (a misspelled field is emitted forever and read
    never)."""

    name = "wire-protocol"
    doc = ("string key in a dict literal flowing to the JSONL wire "
           "(emit/_send/json.dumps arguments, assigned-then-sent "
           "locals, returned response dicts) that is not declared in "
           "veles_tpu/serve/protocol.py")

    _SEND_FUNCS = frozenset(("emit", "_send", "send"))

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if not _in_scope(ctx.path, ctx.config.wire_modules):
            return []
        from veles_tpu.serve import protocol
        wire_dicts: List[ast.Dict] = []
        sent_names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                fname = None
                f = node.func
                if isinstance(f, ast.Name):
                    fname = f.id
                elif isinstance(f, ast.Attribute):
                    fname = f.attr
                if fname in self._SEND_FUNCS or fname == "dumps":
                    for arg in node.args:
                        if isinstance(arg, ast.Name):
                            sent_names.add(arg.id)
                        wire_dicts.extend(self._dicts_in(arg))
            elif isinstance(node, ast.Return) and node.value:
                wire_dicts.extend(self._dicts_in(node.value))
        # assigned-then-sent locals: hello = {...}; emit(hello)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id in sent_names:
                wire_dicts.extend(self._dicts_in(node.value))
        out: List[Finding] = []
        seen: Set[Tuple[str, int]] = set()
        for d in wire_dicts:
            for key in d.keys:
                if not (isinstance(key, ast.Constant)
                        and isinstance(key.value, str)):
                    continue
                if protocol.known(key.value):
                    continue
                mark = (key.value, key.lineno)
                if mark in seen:
                    continue
                seen.add(mark)
                out.append(Finding(
                    self.name, ctx.path, key.lineno,
                    key.col_offset, key.value,
                    f"undeclared wire key {key.value!r}: declare it "
                    f"in veles_tpu/serve/protocol.py (or it is a "
                    f"typo of a declared field)"))
        return out

    @staticmethod
    def _dicts_in(expr: ast.expr) -> List[ast.Dict]:
        """Dict literals within ``expr``, NOT descending into call
        arguments (a dict handed to a constructor is that callee's
        business, not a wire payload)."""
        out: List[ast.Dict] = []
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Call):
                continue
            if isinstance(node, ast.Dict):
                out.append(node)
            stack.extend(ast.iter_child_nodes(node))
        return out


class TraceWireKeyRule:
    """veles_tpu/trace.py deliberately does NOT import
    serve/protocol.py (it must stay import-light for the GA worker
    and telemetry consumers), so its trace-propagation field names are
    duplicated literals.  This rule is the static pin that makes the
    duplication safe: every string in trace.py's ``WIRE_FIELDS``
    tuple (and every ``K_*`` field constant) must be declared in the
    serve/protocol.py wire-key registry — zero waivers, so a trace
    context key can never ride the wire undeclared."""

    name = "trace-wire-key"
    doc = ("trace-propagation field in veles_tpu/trace.py "
           "(WIRE_FIELDS / K_* literals) that is not declared in the "
           "serve/protocol.py wire-key registry")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        norm = ctx.path.replace("\\", "/")
        if not norm.endswith("veles_tpu/trace.py"):
            return []
        from veles_tpu.serve import protocol
        out: List[Finding] = []
        saw_wire_fields = False
        for node in ctx.tree.body:
            if not isinstance(node, ast.Assign) \
                    or len(node.targets) != 1 \
                    or not isinstance(node.targets[0], ast.Name):
                continue
            tname = node.targets[0].id
            if tname == "WIRE_FIELDS" \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                saw_wire_fields = True
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str) \
                            and not protocol.known(elt.value):
                        out.append(Finding(
                            self.name, ctx.path, elt.lineno,
                            elt.col_offset, elt.value,
                            f"trace wire field {elt.value!r} is not "
                            f"in the serve/protocol.py registry — "
                            f"declare it there (zero waivers: an "
                            f"undeclared propagation key is silently "
                            f"dropped by readers)"))
            elif tname.startswith("K_") \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str) \
                    and not protocol.known(node.value.value):
                out.append(Finding(
                    self.name, ctx.path, node.lineno,
                    node.col_offset, node.value.value,
                    f"trace field constant {tname} = "
                    f"{node.value.value!r} is not in the "
                    f"serve/protocol.py registry"))
        if not saw_wire_fields:
            out.append(Finding(
                self.name, ctx.path, 1, 0, "WIRE_FIELDS",
                "veles_tpu/trace.py must pin its propagation keys in "
                "a module-level WIRE_FIELDS tuple for this rule to "
                "cross-check against serve/protocol.py"))
        return out


# -- whole-program rules -----------------------------------------------

class BlockingUnderLockRule:
    name = flow.RULE_BLOCKING
    doc = ("indefinitely-blocking call (time.sleep, subprocess "
           "waits, untimed Queue.get/put, Future.result(), "
           "pipe/socket reads, device syncs) while a lock is held — "
           "directly or through resolvable callees (the "
           "batcher/router stall class)")

    def check_project(self, project: flow.Project, config: Config,
                      root: str) -> List[Finding]:
        return flow.blocking_findings(project, config.lock_modules)


class WaiterDisciplineRule:
    name = flow.RULE_WAITER
    doc = ("a created waiter (`.submit()` handle, `Future()`, "
           "`Event()`) in the serve+pool modules that some "
           "control-flow path — exception edges included — abandons "
           "without resolving, cancelling, or handing off (the "
           "PR 12 leaked-waiter class)")

    def check_project(self, project: flow.Project, config: Config,
                      root: str) -> List[Finding]:
        return flow.waiter_findings(project, config.waiter_modules)


class LockOrderRule:
    name = "lock-order"
    doc = ("the cross-module lock acquisition graph (every "
           "`with <lock>:` inside another lock's scope, followed "
           "through direct calls) must be cycle-free and match the "
           "checked-in analysis/lock_order.json + the guide's "
           "threading-model table (`veleslint --sync-lock-order`)")

    def check_project(self, project: flow.Project, config: Config,
                      root: str) -> List[Finding]:
        graph = flow.build_lock_graph(project,
                                      scope=config.lock_modules)
        out: List[Finding] = []
        law_rel = config.lock_order
        law_path = os.path.join(root, law_rel)
        payload = flow.load_lock_order(law_path)
        declared_manual: Set[Tuple[str, str]] = set()
        if payload is not None:
            for e in payload.get("manual_edges", []) or []:
                just = (e.get("justification") or "").strip()
                if not just or just.lower().startswith("todo"):
                    out.append(Finding(
                        self.name, law_rel, 1, 0,
                        f"manual:{e.get('from')}->{e.get('to')}",
                        "manual lock-order edge "
                        f"{e.get('from')} -> {e.get('to')} has no "
                        "written justification"))
                declared_manual.add((e["from"], e["to"]))
        # cycles over computed + manual edges: a declared cycle is a
        # latent deadlock no matter who declared it
        check = flow.LockGraph()
        check.nodes = dict(graph.nodes)
        check.edges = dict(graph.edges)
        for (a, b) in declared_manual:
            check.add_edge(a, b, "manual")
        for cyc in check.cycles():
            loop = " -> ".join(cyc + [cyc[0]])
            vias = "; ".join(
                graph.edges.get((cyc[i], cyc[(i + 1) % len(cyc)]),
                                "manual")
                for i in range(len(cyc)))
            out.append(Finding(
                self.name, law_rel, 1, 0, f"cycle:{loop}",
                f"lock-order CYCLE {loop} (latent deadlock): two "
                f"threads walking it in opposite phases stop "
                f"forever — break the cycle by moving one "
                f"acquisition outside the other's scope [{vias}]"))
        # drift vs the checked-in law
        computed = graph.edge_pairs()
        if payload is None:
            out.append(Finding(
                self.name, law_rel, 1, 0, "missing",
                f"{law_rel} is missing — the locking law must be "
                f"checked in; run scripts/veleslint.py "
                f"--sync-lock-order"))
        else:
            declared = {(e["from"], e["to"])
                        for e in payload.get("edges", []) or []}
            decl_nodes = {n["name"]
                          for n in payload.get("nodes", []) or []}
            comp_nodes = set(graph.nodes)
            missing = sorted(computed - declared)
            stale = sorted(declared - computed)
            if missing or stale or decl_nodes != comp_nodes:
                parts = []
                if missing:
                    parts.append("undeclared edge(s) " + ", ".join(
                        f"{a}->{b}" for a, b in missing))
                if stale:
                    parts.append("stale declared edge(s) "
                                 + ", ".join(f"{a}->{b}"
                                             for a, b in stale))
                if decl_nodes != comp_nodes:
                    parts.append(
                        "node set drift (+%s/-%s)" % (
                            sorted(comp_nodes - decl_nodes),
                            sorted(decl_nodes - comp_nodes)))
                out.append(Finding(
                    self.name, law_rel, 1, 0, "drift",
                    "lock acquisition graph drifted from the "
                    "checked-in law: " + "; ".join(parts)
                    + " — review the change and run "
                    "scripts/veleslint.py --sync-lock-order"))
        # the guide's generated threading-model table
        guide_f = self._check_guide(root, config, payload)
        if guide_f is not None:
            out.append(guide_f)
        return out

    def _check_guide(self, root: str, config: Config,
                     payload) -> Optional[Finding]:
        guide = os.path.join(root, config.guide)
        try:
            with open(guide, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            return None   # env-registry already reports a lost guide
        begin = text.find(LOCK_TABLE_BEGIN)
        end = text.find(LOCK_TABLE_END)
        if begin < 0 or end < 0:
            return Finding(
                self.name, config.guide, 1, 0, "lock-table",
                f"threading-model table markers not found "
                f"({LOCK_TABLE_BEGIN} ... {LOCK_TABLE_END}); run "
                f"scripts/veleslint.py --sync-lock-order")
        current = text[begin:end + len(LOCK_TABLE_END)]
        if payload is None:
            return None   # the drift finding already fired
        if current.strip() != lock_table_block(payload).strip():
            line = text[:begin].count("\n") + 1
            return Finding(
                self.name, config.guide, line, 0, "lock-table",
                "the threading-model table is out of sync with "
                "analysis/lock_order.json; run "
                "scripts/veleslint.py --sync-lock-order")
        return None


def lock_table_block(payload) -> str:
    """The guide's generated threading-model block, markers
    included."""
    return (f"{LOCK_TABLE_BEGIN}\n"
            "<!-- GENERATED from veles_tpu/analysis/lock_order.json "
            "by `python scripts/veleslint.py --sync-lock-order`; "
            "do not edit. -->\n"
            f"{flow.render_lock_table(payload)}"
            f"{LOCK_TABLE_END}")


def sync_lock_order(root: str, config: Config,
                    contexts: List[ModuleContext]) -> str:
    """Regenerate analysis/lock_order.json from the live scan and
    rewrite the guide's threading-model table from it.  Returns the
    json path."""
    import tempfile
    project = flow.build_project(contexts)
    graph = flow.build_lock_graph(project,
                                  scope=config.lock_modules)
    law_path = os.path.join(root, config.lock_order)
    flow.write_lock_order(law_path, graph)
    payload = flow.load_lock_order(law_path)
    guide = os.path.join(root, config.guide)
    try:
        with open(guide, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return law_path
    block = lock_table_block(payload)
    begin = text.find(LOCK_TABLE_BEGIN)
    end = text.find(LOCK_TABLE_END)
    if begin >= 0 and end >= 0:
        text = text[:begin] + block + text[end
                                          + len(LOCK_TABLE_END):]
    else:
        text = text.rstrip("\n") + "\n\n" + block + "\n"
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(guide),
                               prefix=".guide.", suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(text)
        os.replace(tmp, guide)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    return law_path


PROJECT_RULES = [
    LockOrderRule(),
    BlockingUnderLockRule(),
    WaiterDisciplineRule(),
]
