"""The six veleslint rules.

Each rule is one class with a ``name``, a one-line ``doc`` (the
catalog in docs/guide.md section 10 is written from these), and
``check(ctx) -> [Finding]`` over one :class:`ModuleContext`.  Rules
are syntactic and deliberately conservative: a name that cannot be
resolved statically is SKIPPED, not flagged — every finding should be
actionable, and the inline waiver / baseline machinery exists for the
rare justified exception, not for noise.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from veles_tpu.analysis.engine import Finding, ModuleContext

#: attribute calls that force a device->host sync (or are host-only)
#: inside traced code
_HOST_SYNC_METHODS = frozenset((
    "item", "block_until_ready", "numpy", "tolist"))
#: numpy-module functions that materialize a tracer on the host
_NUMPY_MATERIALIZERS = frozenset((
    "asarray", "array", "save", "savez", "frombuffer"))
#: mutating container methods for the lock-discipline rule
_MUTATORS = frozenset((
    "append", "appendleft", "add", "clear", "pop", "popleft",
    "popitem", "update", "setdefault", "remove", "discard", "extend",
    "insert", "sort", "reverse"))
#: telemetry entry points whose first argument is a registry name
_TELEMETRY_FUNCS = frozenset((
    "event", "counter", "gauge", "histogram", "span",
    "recent_events"))
#: the exit codes owned by the launcher/supervisor contract
_CONTRACT_CODES = (13, 14)


def _in_scope(path: str, prefixes: List[str]) -> bool:
    return any(path == p or path.startswith(p.rstrip("/") + "/")
               for p in prefixes)


def _call_name(func: ast.expr) -> Optional[str]:
    """Trailing identifier of a call target: ``jit`` for both
    ``jit(...)`` and ``jax.jit(...)``."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


class AtomicWriteRule:
    """Persistent-state files must be written tempfile-then-
    ``os.replace``; a bare ``open(path, "w")`` tears under crashes and
    concurrent writers (the PR-6 compile-cache corruption family)."""

    name = "atomic-write"
    doc = ("bare `open(..., \"w\")` in package code — route through "
           "the tempfile+os.replace helpers "
           "(snapshotter.atomic_write / write_json_atomic)")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if not _in_scope(ctx.path, ctx.config.atomic_write_scope):
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "open"):
                continue
            mode: Optional[ast.expr] = None
            if len(node.args) >= 2:
                mode = node.args[1]
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if not (isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)
                    and "w" in mode.value):
                continue
            out.append(Finding(
                self.name, ctx.path, node.lineno, node.col_offset,
                f"open-{mode.value}",
                f"bare open(..., {mode.value!r}) is a torn-write "
                "window: write via snapshotter.atomic_write / "
                "write_json_atomic (tempfile + os.replace)"))
        return out


class EnvRegistryRule:
    """Every ``os.environ`` read/write of a ``VELES_*`` name must be
    declared in veles_tpu/knobs.py (which also generates the guide's
    knob table); an undeclared knob is read forever and set never."""

    name = "env-registry"
    doc = ("`VELES_*` environment access whose name is not declared "
           "in veles_tpu/knobs.py (also verifies the generated "
           "docs/guide.md knob table is in sync)")

    def _env_key_nodes(self, ctx: ModuleContext
                       ) -> Iterator[ast.expr]:
        for node in ast.walk(ctx.tree):
            # os.environ.get/pop/setdefault(KEY, ...), os.getenv(KEY)
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and node.args:
                    base = f.value
                    if (isinstance(base, ast.Attribute)
                            and base.attr == "environ"
                            and f.attr in ("get", "pop",
                                           "setdefault")):
                        yield node.args[0]
                    elif (isinstance(base, ast.Name)
                          and base.id == "os"
                          and f.attr == "getenv"):
                        yield node.args[0]
            # os.environ[KEY] in any expression context
            elif isinstance(node, ast.Subscript):
                v = node.value
                if isinstance(v, ast.Attribute) and \
                        v.attr == "environ":
                    yield node.slice

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if ctx.path in ctx.config.registry_exempt:
            return []
        from veles_tpu import knobs
        declared = knobs.names()
        out: List[Finding] = []
        for key_node in self._env_key_nodes(ctx):
            name = ctx.resolve_str(key_node)
            if name is None or not name.startswith("VELES_"):
                continue
            if name in declared:
                continue
            out.append(Finding(
                self.name, ctx.path, key_node.lineno,
                key_node.col_offset, name,
                f"undeclared env knob {name!r}: declare it in "
                "veles_tpu/knobs.py (name, default, parser, doc) and "
                "regenerate the guide table"))
        return out


class EventRegistryRule:
    """Telemetry names (journal events, counters, gauges, histograms,
    spans) must be the declared constants from veles_tpu/events.py,
    never ad-hoc string literals — an emitter/asserter typo otherwise
    only surfaces when a chaos drill reads an event that never
    fired."""

    name = "event-registry"
    doc = ("string literal passed to telemetry.event / counter / "
           "gauge / histogram / span / recent_events — use the "
           "declared constant from veles_tpu/events.py")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if ctx.path in ctx.config.registry_exempt or \
                ctx.path == "veles_tpu/telemetry.py":
            # telemetry.py forwards caller-supplied names; the
            # registries declare literals by design
            return []
        from veles_tpu import events
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            f = node.func
            is_telemetry_call = (
                isinstance(f, ast.Attribute)
                and f.attr in _TELEMETRY_FUNCS
                and isinstance(f.value, ast.Name)
                and f.value.id == "telemetry")
            if not is_telemetry_call:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue   # constants/variables/f-strings pass
            literal = arg.value
            if events.known(literal):
                hint = ("declared in veles_tpu/events.py — import "
                        "and use its constant instead of the literal")
            else:
                hint = ("NOT declared in veles_tpu/events.py — a "
                        "typo, or a new name missing its registry "
                        "entry")
            out.append(Finding(
                self.name, ctx.path, arg.lineno, arg.col_offset,
                literal,
                f"ad-hoc telemetry name literal {literal!r}: {hint}"))
        return out


class TracerHygieneRule:
    """Functions traced by jit/vmap/pmap/shard_map must not host-sync
    (``.item()``, ``np.asarray``, ``print``, ``block_until_ready``,
    float/int casts of traced args) or branch in Python on traced
    values — each is a silent round-trip or a trace-time error that
    only fires on the chip."""

    name = "tracer-hygiene"
    doc = ("host sync or Python control flow on traced values inside "
           "a jit/vmap/pmap/shard_map-traced function")

    _TRACERS = frozenset(("jit", "vmap", "pmap", "shard_map"))

    def _traced_functions(self, ctx: ModuleContext
                          ) -> List[ast.FunctionDef]:
        traced_names = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    _call_name(node.func) in self._TRACERS:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        traced_names.add(arg.id)
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name in traced_names:
                out.append(node)
                continue
            for dec in node.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                name = _call_name(d)
                if name in self._TRACERS or (
                        name == "partial"
                        and isinstance(dec, ast.Call) and dec.args
                        and _call_name(dec.args[0]) in self._TRACERS):
                    out.append(node)
                    break
        return out

    def _flag(self, ctx: ModuleContext, node: ast.AST, fn_name: str,
              what: str, out: List[Finding]) -> None:
        out.append(Finding(
            self.name, ctx.path, node.lineno,
            getattr(node, "col_offset", 0),
            f"{fn_name}:{what}",
            f"{what} inside traced function {fn_name!r}: forces a "
            "host sync (or a trace-time error on the chip) — keep "
            "traced code device-pure"))

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for fn in self._traced_functions(ctx):
            params = {a.arg for a in fn.args.args
                      + fn.args.posonlyargs + fn.args.kwonlyargs}
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Attribute) and \
                            f.attr in _HOST_SYNC_METHODS:
                        self._flag(ctx, node, fn.name,
                                   f".{f.attr}()", out)
                    elif isinstance(f, ast.Attribute) and \
                            isinstance(f.value, ast.Name) and \
                            f.value.id in ("np", "numpy") and \
                            f.attr in _NUMPY_MATERIALIZERS:
                        self._flag(ctx, node, fn.name,
                                   f"np.{f.attr}()", out)
                    elif isinstance(f, ast.Attribute) and \
                            f.attr == "device_get":
                        self._flag(ctx, node, fn.name,
                                   "device_get()", out)
                    elif isinstance(f, ast.Name) and \
                            f.id == "print":
                        self._flag(ctx, node, fn.name, "print()",
                                   out)
                    elif isinstance(f, ast.Name) and \
                            f.id in ("float", "int", "bool") and \
                            len(node.args) == 1 and \
                            isinstance(node.args[0], ast.Name) and \
                            node.args[0].id in params:
                        self._flag(
                            ctx, node, fn.name,
                            f"{f.id}({node.args[0].id})", out)
                elif isinstance(node, (ast.If, ast.While)):
                    for sub in ast.walk(node.test):
                        if isinstance(sub, ast.Call) and \
                                isinstance(sub.func, ast.Attribute) \
                                and isinstance(sub.func.value,
                                               ast.Name) \
                                and sub.func.value.id == "jnp":
                            self._flag(
                                ctx, node, fn.name,
                                "python branch on jnp value", out)
                            break
        return out


class ExitCodeLiteralsRule:
    """The 13/14 exit-code contract flows from the named constants
    (Launcher.MULTIHOST_ABORT_EXIT / PREEMPT_EXIT, supervisor.EXIT_*);
    a bare 13 or 14 in an exit call or comparison silently forks the
    contract."""

    name = "exit-code-literals"
    doc = ("literal 13/14 in exit calls or comparisons inside the "
           "exit-contract modules — use the launcher/supervisor "
           "constants")

    _EXIT_CALLS = frozenset(("_exit", "exit", "SystemExit"))

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if ctx.path not in ctx.config.exit_code_modules:
            return []
        out: List[Finding] = []

        def flag(node: ast.AST, value: int, where: str) -> None:
            out.append(Finding(
                self.name, ctx.path, node.lineno,
                getattr(node, "col_offset", 0),
                f"{where}-{value}",
                f"exit-code literal {value} in {where}: use the "
                "named constant (Launcher.PREEMPT_EXIT / "
                "MULTIHOST_ABORT_EXIT, supervisor.EXIT_PREEMPTED / "
                "EXIT_MULTIHOST_ABORT)"))

        def contract_consts(node: ast.expr) -> Iterator[ast.Constant]:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Constant) and \
                        sub.value in _CONTRACT_CODES and \
                        isinstance(sub.value, int):
                    yield sub

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    _call_name(node.func) in self._EXIT_CALLS:
                for arg in node.args:
                    for c in contract_consts(arg):
                        flag(c, c.value, "exit-call")
            elif isinstance(node, ast.Compare):
                for side in [node.left] + list(node.comparators):
                    for c in contract_consts(side):
                        flag(c, c.value, "comparison")
        return out


class LockDisciplineRule:
    """Module-level mutable containers in the thread-spawning modules
    must be mutated under a held lock (``with <...lock...>:``) —
    anything else is a data race a drill can only catch by luck."""

    name = "lock-discipline"
    doc = ("module-level mutable container mutated outside a held "
           "lock in a thread-spawning module")

    _CTORS = frozenset(("dict", "list", "set", "deque",
                        "defaultdict", "OrderedDict"))

    def _module_mutables(self, ctx: ModuleContext) -> set:
        names = set()
        for stmt in ctx.tree.body:
            targets: List[ast.expr] = []
            value: Optional[ast.expr] = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                targets, value = [stmt.target], stmt.value
            if value is None:
                continue
            mutable = isinstance(value, (ast.Dict, ast.List,
                                         ast.Set)) or (
                isinstance(value, ast.Call)
                and _call_name(value.func) in self._CTORS)
            if not mutable:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        return names

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if ctx.path not in ctx.config.lock_modules:
            return []
        mutables = self._module_mutables(ctx)
        if not mutables:
            return []
        out: List[Finding] = []

        def flag(node: ast.AST, name: str, how: str) -> None:
            out.append(Finding(
                self.name, ctx.path, node.lineno,
                getattr(node, "col_offset", 0),
                f"{name}.{how}",
                f"module-level mutable {name!r} mutated ({how}) "
                "outside a held lock in a thread-spawning module — "
                "wrap in `with <lock>:` (or waive with a written "
                "reason if provably single-threaded/GIL-atomic)"))

        for node in ast.walk(ctx.tree):
            # import-time statements run before any thread exists
            if not ctx.in_function(node):
                continue
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in mutables and \
                    node.func.attr in _MUTATORS:
                if not ctx.under_lock(node):
                    flag(node, node.func.value.id, node.func.attr)
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in mutables and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                if not ctx.under_lock(node):
                    flag(node, node.value.id, "setitem")
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Subscript) and \
                    isinstance(node.target.value, ast.Name) and \
                    node.target.value.id in mutables:
                if not ctx.under_lock(node):
                    flag(node, node.target.value.id, "augassign")
        return out


class EngineResidencySeamRule:
    """Data residency and buffer donation are the execution core's
    job: a stray ``jax.device_put`` bypasses the HBM arbiter's ledger
    and a stray ``donate_argnums`` bypasses the core's donation
    policy, so both may only appear inside the seam modules
    (engine/core.py, serve/residency.py, parallel/mesh.py) —
    everything else routes through ``ExecutionCore.put`` /
    ``donating_jit``."""

    name = "engine-residency-seam"
    doc = ("`jax.device_put` call or `donate_argnums=` keyword "
           "outside the residency seam (engine/core.py, "
           "serve/residency.py, parallel/mesh.py) — route through "
           "engine.core.ExecutionCore.put / donating_jit")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        if _in_scope(ctx.path, ctx.config.engine_seam_modules):
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node.func) == "device_put":
                out.append(Finding(
                    self.name, ctx.path, node.lineno,
                    node.col_offset, "device_put",
                    "jax.device_put outside the residency seam "
                    "bypasses the HBM arbiter ledger — place arrays "
                    "through engine.core.ExecutionCore.put (or "
                    "engine.core.put for one-off host transfers)"))
            for kw in node.keywords:
                if kw.arg == "donate_argnums":
                    out.append(Finding(
                        self.name, ctx.path, kw.value.lineno,
                        kw.value.col_offset, "donate_argnums",
                        "donate_argnums outside the residency seam "
                        "bypasses the core's donation policy — "
                        "compile through engine.core.donating_jit "
                        "(or ExecutionCore.jit(fn, donate=...))"))
        return out


from veles_tpu.analysis.concurrency import (  # noqa: E402 — the
    # concurrency module needs Finding/ModuleContext from engine, so
    # it cannot be imported before them
    PROJECT_RULES,
    ThreadLifecycleRule,
    TraceWireKeyRule,
    WireProtocolRule,
)

RULES = [
    AtomicWriteRule(),
    EnvRegistryRule(),
    EventRegistryRule(),
    TracerHygieneRule(),
    ExitCodeLiteralsRule(),
    LockDisciplineRule(),
    EngineResidencySeamRule(),
    ThreadLifecycleRule(),
    WireProtocolRule(),
    TraceWireKeyRule(),
]


def rule_names() -> List[str]:
    """Every rule, per-file and whole-program alike (the CLI's
    --rule choices and the guide's catalog order)."""
    return [r.name for r in RULES] + [r.name for r in PROJECT_RULES]
