"""Lockstep's runtime half: the lock-order witness.

The static side (veles_tpu/analysis/flow.py + the ``lock-order``
rule) derives the repo's lock acquisition graph from the AST and
checks it in as ``analysis/lock_order.json`` — the reviewed statement
of the locking law.  A static model is only worth what it is checked
against, so this module watches the REAL locks at runtime: when
``$VELES_LOCK_WITNESS=1``, every instrumented acquire records
``(already-held lock, acquired lock)`` pairs into a process-wide
table, and a tier-1 test asserts every observed edge is declared in
``lock_order.json`` — in both directions the comparison is meaningful
(an observed-but-undeclared edge is a model gap; a declared cycle is a
latent deadlock the witness would eventually walk into).

Instrumentation is by construction, not by patching: the
thread-spawning modules create their locks through the factories here
(``witness.lock("batcher.queue")`` instead of a bare
``threading.Lock()``), which also gives every lock the canonical NAME
the static analyzer and the checked-in law share.  Cost when the knob
is off: the factories return the bare ``threading`` primitive — the
serving hot path pays literally nothing (pinned by a type-identity
test).  Cost when on: one thread-local list append per acquire plus a
dict upsert under a private leaf lock.

The table is telemetry-backed (``lockstep.*`` gauges/counters) and
flushed next to the Sightline snapshot: ``telemetry.flush()`` calls
:func:`write_snapshot`, which drops an atomic
``lockwitness-<pid>.json`` into the metrics dir, so a witnessed
subprocess fleet leaves one observation file per process for the
subset assertion to union.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

#: declared in veles_tpu/knobs.py (declaration, not routing, is the
#: registry contract); read directly so this module stays import-light
ENV_VAR = "VELES_LOCK_WITNESS"

_tls = threading.local()

#: observed (holder, acquired) -> count; the witness's OWN lock is a
#: bare primitive and a leaf by construction (nothing is acquired
#: under it), so it can never participate in an order violation — and
#: it is deliberately NOT itself witnessed
_table_lock = threading.Lock()
_edges: Dict[Tuple[str, str], int] = {}
_acquire_count = 0


def enabled(environ: Optional[Dict[str, str]] = None) -> bool:
    """Is the witness armed?  Checked at lock CREATION time — an
    armed process instruments every lock it makes from then on; a
    disarmed one pays nothing, ever."""
    env = os.environ if environ is None else environ
    raw = env.get(ENV_VAR, "")
    return bool(raw) and raw != "0"


def _held() -> List[str]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _suppressed() -> bool:
    return bool(getattr(_tls, "busy", False))


def _after_acquire(name: str) -> None:
    """Record the (held -> acquired) edges and push the name.  This
    runs while the acquired lock IS held, so it must never call into
    telemetry (whose own locks are witnessed — a gauge registration
    here would re-acquire the very lock being recorded and deadlock);
    the lockstep gauges are published from :func:`publish_metrics`
    on the flush path instead."""
    global _acquire_count
    if _suppressed():
        return
    held = _held()
    with _table_lock:
        _acquire_count += 1
        for holder in held:
            if holder == name:
                continue   # re-entrant RLock: not an order edge
            key = (holder, name)
            _edges[key] = _edges.get(key, 0) + 1
    held.append(name)


def _after_release(name: str) -> None:
    if _suppressed():
        return
    held = _held()
    # remove the LAST occurrence: nested reacquisition unwinds LIFO
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


def publish_metrics() -> None:
    """Surface the table through the Sightline gauges
    (``lockstep.edges_observed`` / ``lockstep.acquires``).  Called by
    :func:`write_snapshot` — i.e., next to the telemetry flush — at a
    point where the calling thread holds no witnessed lock; skipped
    (and recording suppressed) otherwise, because the gauge
    registration itself takes telemetry's witnessed registry lock."""
    if _suppressed() or _held():
        return
    _tls.busy = True
    try:
        from veles_tpu import events, telemetry
        with _table_lock:
            n_edges = len(_edges)
            n_acq = _acquire_count
        telemetry.gauge(events.GAUGE_LOCKSTEP_EDGES).set(n_edges)
        telemetry.gauge(events.GAUGE_LOCKSTEP_ACQUIRES).set(n_acq)
    except Exception:  # noqa: BLE001 — the witness must never take
        pass           # down the run it is observing
    finally:
        _tls.busy = False


class _WitnessLock:
    """Recording proxy over a ``threading.Lock``/``RLock``."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, inner) -> None:
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _after_acquire(self.name)
        return ok

    def release(self) -> None:
        self._inner.release()
        _after_release(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "_WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _WitnessCondition:
    """Recording proxy over a ``threading.Condition``.  ``wait``
    releases the underlying lock for its duration, so the held-set
    drops the name across the wait and re-records on wakeup."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str) -> None:
        self.name = name
        self._inner = threading.Condition()

    def acquire(self, *args):
        ok = self._inner.acquire(*args)
        if ok:
            _after_acquire(self.name)
        return ok

    def release(self) -> None:
        self._inner.release()
        _after_release(self.name)

    def __enter__(self) -> "_WitnessCondition":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        _after_release(self.name)
        try:
            return self._inner.wait(timeout)
        finally:
            _after_acquire(self.name)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        _after_release(self.name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            _after_acquire(self.name)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


# -- the factories the instrumented modules call -----------------------

def lock(name: str):
    """A named mutex: the bare ``threading.Lock()`` when the witness
    is off (zero overhead by construction), a recording proxy when
    armed.  ``name`` is the canonical lock identity shared with the
    static analyzer and ``analysis/lock_order.json``."""
    if not enabled():
        return threading.Lock()
    return _WitnessLock(name, threading.Lock())


def rlock(name: str):
    if not enabled():
        return threading.RLock()
    return _WitnessLock(name, threading.RLock())


def condition(name: str):
    if not enabled():
        return threading.Condition()
    return _WitnessCondition(name)


# -- reading / flushing the table --------------------------------------

def observed_edges() -> List[Tuple[str, str]]:
    """Every (holder, acquired) pair seen so far, sorted."""
    with _table_lock:
        return sorted(_edges)


def acquire_count() -> int:
    with _table_lock:
        return _acquire_count


def reset() -> None:
    """Clear the table (test isolation)."""
    global _acquire_count
    with _table_lock:
        _edges.clear()
        _acquire_count = 0


def write_snapshot(directory: Optional[str] = None) -> Optional[str]:
    """Atomically write this process's observation table as
    ``lockwitness-<pid>.json`` into ``directory`` (default: the
    Sightline metrics dir).  Called by ``telemetry.flush()`` when the
    witness is armed, so witnessed subprocesses leave their edges
    behind for the tier-1 subset assertion.  None when there is
    nowhere to write or nothing observed."""
    publish_metrics()
    if directory is None:
        directory = os.environ.get("VELES_METRICS_DIR") or None
    if not directory:
        return None
    with _table_lock:
        if not _edges and not _acquire_count:
            return None
        payload = {
            "pid": os.getpid(),
            "acquires": _acquire_count,
            "edges": [{"from": h, "to": a, "count": c}
                      for (h, a), c in sorted(_edges.items())],
        }
    try:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory,
                            f"lockwitness-{os.getpid()}.json")
        fd, tmp = tempfile.mkstemp(dir=directory,
                                   prefix=".lockwitness.",
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=1)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        return path
    except OSError:
        return None


def read_snapshots(directory: str) -> List[Tuple[str, str]]:
    """Union of the observed edges across every
    ``lockwitness-*.json`` under ``directory`` (recursive — fleet
    replicas write into per-replica child dirs)."""
    out = set()
    for dirpath, _dirnames, filenames in os.walk(directory):
        for fn in filenames:
            if not (fn.startswith("lockwitness-")
                    and fn.endswith(".json")):
                continue
            try:
                with open(os.path.join(dirpath, fn),
                          encoding="utf-8") as f:
                    data = json.load(f)
            except (OSError, ValueError):
                continue
            for e in data.get("edges", []):
                out.add((e["from"], e["to"]))
    return sorted(out)
