"""The ``veleslint`` command line (also ``scripts/veleslint.py``).

Exit codes: 0 clean (no non-baselined finding), 1 new findings, 2 a
usage/config/baseline error (e.g. a baseline entry without a written
justification).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    from veles_tpu.analysis import engine, rules as rules_mod
    p = argparse.ArgumentParser(
        prog="veleslint",
        description="repo-specific AST invariant checks "
                    "(docs/guide.md section 10)")
    p.add_argument("--root", default=None,
                   help="repository root (default: autodetected)")
    p.add_argument("--rule", action="append", default=None,
                   metavar="NAME", choices=rules_mod.rule_names(),
                   help="run only this rule (repeatable)")
    p.add_argument("--format", choices=("text", "json"),
                   default="text")
    p.add_argument("--all", action="store_true",
                   help="report every finding, baselined ones "
                        "included (marked)")
    p.add_argument("--write-baseline", action="store_true",
                   help="grandfather the current findings into the "
                        "baseline file; new entries get a TODO "
                        "justification that MUST be hand-edited "
                        "(the loader refuses TODOs)")
    p.add_argument("--sync-docs", action="store_true",
                   help="regenerate the VELES_* knob table in "
                        "docs/guide.md from veles_tpu/knobs.py")
    p.add_argument("--sync-lock-order", action="store_true",
                   help="regenerate analysis/lock_order.json (the "
                        "locking law) and the guide's threading-"
                        "model table from the live scan; review the "
                        "diff before committing")
    p.add_argument("--changed-only", action="store_true",
                   help="fast inner-loop mode: report per-file "
                        "findings only for git-changed files (the "
                        "lock-order law is still checked whole; the "
                        "full scan stays the tier-1 gate)")
    p.add_argument("--no-docs-check", action="store_true",
                   help="skip the guide knob-table sync check")
    args = p.parse_args(argv)

    root = args.root or engine.repo_root()
    try:
        config = engine.load_config(root)
    except ValueError as e:
        print(f"veleslint: {e}", file=sys.stderr)
        return 2

    if args.sync_docs:
        guide = engine.sync_knob_table(root, config)
        print(f"veleslint: knob table regenerated in {guide}")
        return 0

    if args.sync_lock_order:
        from veles_tpu.analysis.concurrency import sync_lock_order
        contexts = engine.load_contexts(root, config)
        law = sync_lock_order(root, config, contexts)
        print(f"veleslint: locking law regenerated in {law} "
              f"(+ the guide threading-model table)")
        return 0

    only_paths = None
    if args.changed_only:
        only_paths = _git_changed_paths(root)
        if only_paths is None:
            print("veleslint: --changed-only needs a git checkout; "
                  "falling back to the full scan", file=sys.stderr)

    baseline_path = os.path.join(root, config.baseline)
    try:
        baseline = engine.load_baseline(baseline_path)
    except ValueError as e:
        print(f"veleslint: {e}", file=sys.stderr)
        return 2

    findings = engine.run_lint(root, config, rules=args.rule,
                               check_docs=not args.no_docs_check,
                               only_paths=only_paths)

    if args.write_baseline:
        engine.write_baseline(baseline_path, findings, baseline)
        print(f"veleslint: {len(findings)} finding(s) written to "
              f"{baseline_path}; edit every TODO justification "
              "before committing")
        return 0

    new = engine.new_findings(findings, baseline)
    shown = findings if args.all else new
    if args.format == "json":
        print(json.dumps({
            "new": [f.to_dict() for f in new],
            "baselined": len(findings) - len(new),
            "baseline_total": len(baseline),
        }, indent=1))
    else:
        for f in shown:
            tag = "" if f.key not in baseline else " (baselined)"
            print(f.format() + tag)
        # staleness is only decidable from a full scan: a --rule or
        # --changed-only run never produces the other findings
        stale = [] if args.rule or only_paths is not None else \
            [k for k in baseline
             if k not in {f.key for f in findings}]
        if stale:
            print(f"veleslint: note: {len(stale)} stale baseline "
                  "entr(y/ies) no longer found — prune them:",
                  file=sys.stderr)
            for k in stale:
                print(f"  {k}", file=sys.stderr)
        print(f"veleslint: {len(new)} new finding(s), "
              f"{len(findings) - len(new)} baselined, "
              f"{len(baseline)} baseline entr(y/ies)")
    return 1 if new else 0


def _git_changed_paths(root: str) -> Optional[List[str]]:
    """Repo-relative .py paths with uncommitted changes (staged,
    unstaged, or untracked); None when git is unavailable — the
    caller falls back to the full scan."""
    import subprocess
    try:
        proc = subprocess.run(
            ["git", "-C", root, "status", "--porcelain"],
            capture_output=True, text=True, timeout=15)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    out: List[str] = []
    for line in proc.stdout.splitlines():
        path = line[3:].strip()
        if " -> " in path:          # rename: scan the new name
            path = path.split(" -> ", 1)[1]
        path = path.strip('"')
        if path.endswith(".py"):
            out.append(path)
    return out


if __name__ == "__main__":
    sys.exit(main())
