"""The ``veleslint`` command line (also ``scripts/veleslint.py``).

Exit codes: 0 clean (no non-baselined finding), 1 new findings, 2 a
usage/config/baseline error (e.g. a baseline entry without a written
justification).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    from veles_tpu.analysis import engine, rules as rules_mod
    p = argparse.ArgumentParser(
        prog="veleslint",
        description="repo-specific AST invariant checks "
                    "(docs/guide.md section 10)")
    p.add_argument("--root", default=None,
                   help="repository root (default: autodetected)")
    p.add_argument("--rule", action="append", default=None,
                   metavar="NAME", choices=rules_mod.rule_names(),
                   help="run only this rule (repeatable)")
    p.add_argument("--format", choices=("text", "json"),
                   default="text")
    p.add_argument("--all", action="store_true",
                   help="report every finding, baselined ones "
                        "included (marked)")
    p.add_argument("--write-baseline", action="store_true",
                   help="grandfather the current findings into the "
                        "baseline file; new entries get a TODO "
                        "justification that MUST be hand-edited "
                        "(the loader refuses TODOs)")
    p.add_argument("--sync-docs", action="store_true",
                   help="regenerate the VELES_* knob table in "
                        "docs/guide.md from veles_tpu/knobs.py")
    p.add_argument("--no-docs-check", action="store_true",
                   help="skip the guide knob-table sync check")
    args = p.parse_args(argv)

    root = args.root or engine.repo_root()
    try:
        config = engine.load_config(root)
    except ValueError as e:
        print(f"veleslint: {e}", file=sys.stderr)
        return 2

    if args.sync_docs:
        guide = engine.sync_knob_table(root, config)
        print(f"veleslint: knob table regenerated in {guide}")
        return 0

    baseline_path = os.path.join(root, config.baseline)
    try:
        baseline = engine.load_baseline(baseline_path)
    except ValueError as e:
        print(f"veleslint: {e}", file=sys.stderr)
        return 2

    findings = engine.run_lint(root, config, rules=args.rule,
                               check_docs=not args.no_docs_check)

    if args.write_baseline:
        engine.write_baseline(baseline_path, findings, baseline)
        print(f"veleslint: {len(findings)} finding(s) written to "
              f"{baseline_path}; edit every TODO justification "
              "before committing")
        return 0

    new = engine.new_findings(findings, baseline)
    shown = findings if args.all else new
    if args.format == "json":
        print(json.dumps({
            "new": [f.to_dict() for f in new],
            "baselined": len(findings) - len(new),
            "baseline_total": len(baseline),
        }, indent=1))
    else:
        for f in shown:
            tag = "" if f.key not in baseline else " (baselined)"
            print(f.format() + tag)
        # staleness is only decidable from a full-rule scan: a
        # --rule run never produces the other rules' findings
        stale = [] if args.rule else \
            [k for k in baseline
             if k not in {f.key for f in findings}]
        if stale:
            print(f"veleslint: note: {len(stale)} stale baseline "
                  "entr(y/ies) no longer found — prune them:",
                  file=sys.stderr)
            for k in stale:
                print(f"  {k}", file=sys.stderr)
        print(f"veleslint: {len(new)} new finding(s), "
              f"{len(findings) - len(new)} baselined, "
              f"{len(baseline)} baseline entr(y/ies)")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
