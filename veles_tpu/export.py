"""Export a trained workflow to the VTPN binary format for the native
C++ inference runtime (native/src/libveles.cc — the libVeles/libZnicz
equivalent, SURVEY.md §3.3).

The format carries only what inference needs: the forward op chain with
shapes, hyperparameters, and float32 weights.  Training-only units
(dropout keeps its slot as identity so layer indices match the source
workflow) are preserved structurally.
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict, List, Tuple

import numpy as np

# op / activation / attr enums — must match native/src/libveles.cc
OP_DENSE, OP_CONV, OP_MAXPOOL, OP_AVGPOOL, OP_LRN, OP_DROPOUT, \
    OP_DECONV, OP_ACTIVATION, OP_STOCHPOOL_EVAL, OP_BINARIZE \
    = range(1, 11)
ACT = {"linear": 0, "tanh": 1, "relu": 2, "sigmoid": 3, "softmax": 4,
       "log": 5}
A_KX, A_KY, A_SX, A_SY, A_PX, A_PY, A_NKERN, A_LRN_N, A_ALPHA, \
    A_BETA, A_K = range(11)

MAGIC = b"VTPN"
VERSION = 1


def _op_record(unit) -> Tuple[int, int, Dict[int, float],
                              Dict[int, np.ndarray]]:
    """(op_type, act, attrs, tensors) for one forward unit."""
    from veles_tpu.ops.activation import ActivationBase
    from veles_tpu.ops.all2all import All2All
    from veles_tpu.ops.conv import Conv
    from veles_tpu.ops.deconv import Deconv
    from veles_tpu.ops.dropout import Dropout
    from veles_tpu.ops.lrn import LRNormalizer
    from veles_tpu.ops.pooling import (AvgPooling, MaxPooling,
                                       StochasticPooling)
    from veles_tpu.ops.rbm import Binarization

    act = ACT.get(unit.activation_mode, 0)
    tensors: Dict[int, np.ndarray] = {}
    if getattr(unit, "weights", None) and unit.weights:
        tensors[0] = np.asarray(unit.weights.map_read(), np.float32)
    if getattr(unit, "bias", None) and unit.bias and unit.include_bias:
        tensors[1] = np.asarray(unit.bias.map_read(), np.float32)

    if isinstance(unit, Deconv):
        py, px = unit.padding
        sy, sx = unit.sliding
        return OP_DECONV, act, {A_KX: unit.kx, A_KY: unit.ky,
                                A_SX: sx, A_SY: sy, A_PX: px, A_PY: py,
                                A_NKERN: unit.n_kernels}, tensors
    if isinstance(unit, Conv):
        py, px = unit.padding
        sy, sx = unit.sliding
        return OP_CONV, act, {A_KX: unit.kx, A_KY: unit.ky,
                              A_SX: sx, A_SY: sy, A_PX: px, A_PY: py,
                              A_NKERN: unit.n_kernels}, tensors
    if isinstance(unit, All2All):
        return OP_DENSE, act, {}, tensors
    if isinstance(unit, StochasticPooling):
        sy, sx = unit.sliding
        return OP_STOCHPOOL_EVAL, 0, {A_KX: unit.kx, A_KY: unit.ky,
                                      A_SX: sx, A_SY: sy}, {}
    if isinstance(unit, MaxPooling):
        sy, sx = unit.sliding
        return OP_MAXPOOL, 0, {A_KX: unit.kx, A_KY: unit.ky,
                               A_SX: sx, A_SY: sy}, {}
    if isinstance(unit, AvgPooling):
        sy, sx = unit.sliding
        return OP_AVGPOOL, 0, {A_KX: unit.kx, A_KY: unit.ky,
                               A_SX: sx, A_SY: sy}, {}
    if isinstance(unit, LRNormalizer):
        return OP_LRN, 0, {A_LRN_N: unit.n, A_ALPHA: unit.alpha,
                           A_BETA: unit.beta, A_K: unit.k}, {}
    if isinstance(unit, Dropout):
        return OP_DROPOUT, 0, {}, {}
    if isinstance(unit, Binarization):
        # inference semantics = the unit's eval mode: x > 0.5
        return OP_BINARIZE, 0, {}, {}
    if isinstance(unit, ActivationBase):
        return OP_ACTIVATION, act, {}, {}
    raise ValueError(
        f"unit {unit.name} ({type(unit).__name__}) has no native "
        f"inference equivalent")


def _write_op(f: BinaryIO, op_type: int, act: int,
              attrs: Dict[int, float],
              tensors: Dict[int, np.ndarray]) -> None:
    f.write(struct.pack("<III", op_type, act, len(attrs)))
    for key in sorted(attrs):
        f.write(struct.pack("<Id", key, float(attrs[key])))
    f.write(struct.pack("<I", len(tensors)))
    for tid in sorted(tensors):
        arr = np.ascontiguousarray(tensors[tid], np.float32)
        f.write(struct.pack("<II", tid, arr.ndim))
        f.write(struct.pack(f"<{arr.ndim}q", *arr.shape))
        f.write(arr.tobytes())


def export_model(workflow, path: str) -> str:
    """Serialize an initialized workflow's forward chain to ``path``."""
    forwards: List[Any] = list(workflow.forwards)
    if not forwards:
        raise ValueError("workflow has no forward units")
    fused = getattr(workflow, "fused", None)
    if fused is not None and fused._params is not None:
        fused.sync_params_to_vectors()  # pull trained HBM state to host
    in_shape = tuple(forwards[0].input.shape[1:])
    records = [_op_record(u) for u in forwards]
    from veles_tpu.snapshotter import atomic_write
    with atomic_write(path, "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<II", VERSION, len(records)))
        f.write(struct.pack("<q", len(in_shape)))
        f.write(struct.pack(f"<{len(in_shape)}q", *in_shape))
        for rec in records:
            _write_op(f, *rec)
    return path
