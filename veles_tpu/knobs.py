"""The central registry of ``VELES_*`` environment knobs.

Every environment variable this framework reads is declared HERE —
name, default, parser, and a one-line doc — and nowhere else.  The
declarations serve three consumers:

- **veleslint's env-registry rule** (veles_tpu/analysis): any
  ``os.environ`` read of a ``VELES_*`` name that is not declared here
  is a lint finding, so a typo'd knob (read forever, set never) can't
  ship;
- **docs/guide.md**: the knob table in the guide is GENERATED from
  this module (``python scripts/veleslint.py --sync-docs``) and the
  same lint rule fails when the table drifts out of sync;
- **call sites**, which may read through ``get(name)`` for parsed
  values but are equally free to keep their existing
  ``os.environ.get(...)`` reads — declaration, not routing, is the
  contract.

Parsers: ``flag`` knobs are armed by any non-empty value except
``"0"`` (matching the scattered ``== "1"`` / truthiness idioms the
call sites actually use); the rest parse with the declared type and
fall back to the default on a malformed value rather than raising —
an env typo must degrade, not take down a run.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional


def flag(raw: str) -> bool:
    """The repo's env-flag convention: set-and-not-"0" means on."""
    return bool(raw) and raw != "0"


class Knob:
    """One declared environment knob."""

    __slots__ = ("name", "default", "parser", "doc")

    def __init__(self, name: str, default: Any,
                 parser: Callable[[str], Any], doc: str) -> None:
        self.name = name
        self.default = default
        self.parser = parser
        self.doc = doc

    @property
    def type_name(self) -> str:
        return self.parser.__name__

    def read(self, environ: Optional[Dict[str, str]] = None) -> Any:
        env = os.environ if environ is None else environ
        raw = env.get(self.name)
        if raw is None or raw == "":
            return self.default
        try:
            return self.parser(raw)
        except (TypeError, ValueError):
            return self.default

    def __repr__(self) -> str:
        return f"Knob({self.name}={self.default!r})"


#: every declared knob, by name — the single source of truth
KNOBS: Dict[str, Knob] = {}


def _knob(name: str, default: Any, parser: Callable[[str], Any],
          doc: str) -> str:
    assert name.startswith("VELES_"), name
    assert name not in KNOBS, f"duplicate knob {name}"
    KNOBS[name] = Knob(name, default, parser, doc)
    return name


# -- robustness / supervision (Faultline, Phoenix) ---------------------

FAULTS = _knob(
    "VELES_FAULTS", "", str,
    "Arm Faultline injection points: `point[@qual=v[&qual=v...]]`, "
    "comma-separated; inherited by child processes (faults.py).")
FAULTS_SEED = _knob(
    "VELES_FAULTS_SEED", 0, int,
    "Seed for the deterministic garbage/rng of injected faults.")
PREEMPT_GRACE = _knob(
    "VELES_PREEMPT_GRACE", 25.0, float,
    "Seconds a graceful stop may take before the watchdog "
    "hard-snapshots and exits 14.")
PREEMPT_DISABLE = _knob(
    "VELES_PREEMPT_DISABLE", False, flag,
    "Opt this process out of SIGTERM/SIGINT graceful-stop handlers "
    "(set for GA evaluator children).")
SUPERVISE_ATTEMPT = _knob(
    "VELES_SUPERVISE_ATTEMPT", 0, int,
    "Exported by the supervisor to each child: 0 first launch, "
    "incrementing per restart (fault qualifiers target one attempt).")
SUPERVISE_MAX_CRASHES = _knob(
    "VELES_SUPERVISE_MAX_CRASHES", 5, int,
    "Genuine crashes inside the crash window before --supervise "
    "gives up loudly.")
SUPERVISE_CRASH_WINDOW = _knob(
    "VELES_SUPERVISE_CRASH_WINDOW", 300.0, float,
    "Seconds of sliding window the supervisor counts crashes in.")
RESUME_MANIFEST = _knob(
    "VELES_RESUME_MANIFEST", "", str,
    "Extra path every snapshot/checkpoint writer merge-updates the "
    "resume manifest at (the supervisor exports it).")

# -- multihost ---------------------------------------------------------

MULTIHOST_HEARTBEAT = _knob(
    "VELES_MULTIHOST_HEARTBEAT", 2.0, float,
    "Seconds between KV-store liveness heartbeats of a --multihost "
    "peer.")
MULTIHOST_DEADLINE = _knob(
    "VELES_MULTIHOST_DEADLINE", 15.0, float,
    "Seconds without a peer heartbeat before the watchdog declares "
    "peer death (final snapshot + exit 13).")
MULTIHOST_ALLOW_SOLO = _knob(
    "VELES_MULTIHOST_ALLOW_SOLO", False, flag,
    "Accept single-process semantics when "
    "jax.distributed.initialize() refuses a --multihost launch.")

# -- genetic search ----------------------------------------------------

GA_GENERATION = _knob(
    "VELES_GA_GENERATION", 0, int,
    "Exported by the GA parent so evaluator jobs and fault "
    "qualifiers (`@gen=N`) can target one generation.")
HEARTBEAT_EVERY = _knob(
    "VELES_HEARTBEAT_EVERY", 5.0, float,
    "Seconds between serve-mode evaluator heartbeat lines "
    "(0 disables).")
TPU_GA_HBM_BUDGET = _knob(
    "VELES_TPU_GA_HBM_BUDGET", 8 << 30, int,
    "LEGACY fallback (superseded by $VELES_HBM_BUDGET): HBM byte "
    "budget for population-batched cohort sizing when the device "
    "reports no bytes_limit.")

# -- online serving (Hive) ---------------------------------------------

SERVE_MAX_WAIT_MS = _knob(
    "VELES_SERVE_MAX_WAIT_MS", 5.0, float,
    "Longest a queued serving request may wait for co-batchable "
    "traffic before its micro-batch dispatches anyway (the "
    "latency/throughput tradeoff knob of veles_tpu/serve).")
SERVE_MAX_BATCH = _knob(
    "VELES_SERVE_MAX_BATCH", 64, int,
    "Rows per serving micro-batch: the batcher flushes as soon as "
    "this many rows coalesce (also the ONE fixed dispatch shape — "
    "zero steady-state recompiles).")
HBM_BUDGET = _knob(
    "VELES_HBM_BUDGET", 0, int,
    "Unified PER-DEVICE HBM byte budget of the process-wide arbiter "
    "(engine/core.py charges training, GA cohorts, and serving "
    "against ONE ledger): non-zero overrides the device's probed "
    "bytes_limit and the legacy per-subsystem fallbacks "
    "($VELES_SERVE_HBM_BUDGET, $VELES_TPU_GA_HBM_BUDGET); 0 keeps "
    "probe-then-fallback.")
SERVE_HBM_BUDGET = _knob(
    "VELES_SERVE_HBM_BUDGET", 8 << 30, int,
    "LEGACY fallback (superseded by $VELES_HBM_BUDGET): HBM byte "
    "budget for resident serving models when the device reports no "
    "bytes_limit; over budget the LRU model spills to host.")
SERVE_MESH = _knob(
    "VELES_SERVE_MESH", 0, int,
    "Devices a hive replica owns (the Prism arm of --serve-models): "
    ">1 binds an N-device mesh instead of a single device, so the "
    "fleet topology becomes replicas x mesh and residency budgets "
    "are charged per device (0/1 keeps the single-device replica).")
SERVE_MESH_SHARD = _knob(
    "VELES_SERVE_MESH_SHARD", "auto", str,
    "Shard the stacked member axis of a served ensemble over the "
    "replica's mesh (P/N members per device, replicated request "
    "rows): `auto` shards only when the model exceeds ONE device's "
    "residency budget but fits sharded — the over-budget placement "
    "becomes member-sharded-RESIDENT instead of LRU spill — "
    "`always` shards every model on a mesh replica, `never`/`0` "
    "keeps the replicated placement.")
SERVE_ADAPTIVE_WAIT = _knob(
    "VELES_SERVE_ADAPTIVE_WAIT", True, flag,
    "Let the serving micro-batcher track the windowed arrival rate "
    "(the Sentinel delta-quantile estimator) and adapt its flush "
    "wait: stretch past the static deadline only while the cadence "
    "predicts the batch fills, collapse a stalled stretch back to "
    "it.  Strictly additive — no window flushes before the static "
    "$VELES_SERVE_MAX_WAIT_MS deadline; off disables stretching.")
SERVE_WAIT_STRETCH = _knob(
    "VELES_SERVE_WAIT_STRETCH", 2.0, float,
    "Upper bound of the adaptive batching wait as a multiple of "
    "$VELES_SERVE_MAX_WAIT_MS: the oldest queued request never "
    "waits longer than stretch x the static window even when "
    "arrivals keep trickling in.  2x keeps the stretched tail "
    "inside ~1.1x the static p99 on a busy box; raise it when "
    "batch fill matters more than tail latency.")

# -- fleet serving (Swarm) ---------------------------------------------

FLEET_SLO_P99_MS = _knob(
    "VELES_FLEET_SLO_P99_MS", 0.0, float,
    "Fleet admission-control SLO target: when a request's estimated "
    "completion (queue depth x observed per-dispatch time + batching "
    "window) would exceed this many milliseconds on EVERY candidate "
    "replica, the router sheds it with an explicit `overloaded` "
    "response instead of letting p99 run away (0 disables shedding).")
FLEET_MAX_INFLIGHT = _knob(
    "VELES_FLEET_MAX_INFLIGHT", 64, int,
    "Hard per-replica bound on router-side in-flight requests (the "
    "bounded router queue); a request that finds every candidate "
    "replica at the bound is shed `overloaded`.")
FLEET_HEARTBEAT_DEADLINE = _knob(
    "VELES_FLEET_HEARTBEAT_DEADLINE", 30.0, float,
    "Seconds of replica stdout silence (no heartbeat, no response) "
    "before the fleet monitor declares the replica hung, kills it, "
    "and respawns (0 disables).")
FLEET_CANARY_FRACTION = _knob(
    "VELES_FLEET_CANARY_FRACTION", 0.1, float,
    "Default traffic fraction mirrored to a `canary-of:NAME` model "
    "when its registration does not carry an explicit split.")
FLEET_RESPAWN_BACKOFF = _knob(
    "VELES_FLEET_RESPAWN_BACKOFF", 0.5, float,
    "Initial seconds the fleet monitor backs off before respawning a "
    "dead replica (doubles per consecutive death, capped at 30s).")

# -- elastic fleet (Gauntlet) ------------------------------------------

FLEET_SCALE_MIN = _knob(
    "VELES_FLEET_SCALE_MIN", 1, int,
    "Floor of the elastic fleet's replica count: the scale "
    "controller never retires below this many replicas.")
FLEET_SCALE_MAX = _knob(
    "VELES_FLEET_SCALE_MAX", 4, int,
    "Ceiling of the elastic fleet's replica count: once the fleet is "
    "at the ceiling, sustained pressure engages the graceful-"
    "degradation ladder instead of spawning.")
FLEET_SCALE_UP_MS = _knob(
    "VELES_FLEET_SCALE_UP_MS", 200.0, float,
    "Scale-up pressure threshold: when the BEST candidate replica's "
    "estimated completion (queue depth x observed dispatch cadence) "
    "stays above this many milliseconds for "
    "$VELES_FLEET_SCALE_UP_SUSTAIN seconds, the controller spawns a "
    "replica into a warm install dir.")
FLEET_SCALE_DOWN_MS = _knob(
    "VELES_FLEET_SCALE_DOWN_MS", 25.0, float,
    "Scale-down idle threshold: when fleet pressure stays below this "
    "many milliseconds for $VELES_FLEET_SCALE_DOWN_SUSTAIN seconds, "
    "the controller retires the youngest replica (drain its router "
    "queue, re-place its exclusive tail models, then SIGTERM).")
FLEET_SCALE_UP_SUSTAIN = _knob(
    "VELES_FLEET_SCALE_UP_SUSTAIN", 1.0, float,
    "Seconds the scale-up pressure must be SUSTAINED before the "
    "controller acts (the hysteresis half that keeps one burst from "
    "spawning a replica).")
FLEET_SCALE_DOWN_SUSTAIN = _knob(
    "VELES_FLEET_SCALE_DOWN_SUSTAIN", 3.0, float,
    "Seconds the fleet must stay idle below the scale-down threshold "
    "before the controller retires a replica (longer than the up "
    "sustain on purpose: spawning is slow, flapping is worse).")
FLEET_SCALE_COOLDOWN = _knob(
    "VELES_FLEET_SCALE_COOLDOWN", 5.0, float,
    "Seconds between ANY two scale/degradation actions — the "
    "controller's refractory period, which also keeps a respawn-"
    "backoff storm (fleet.replica_flap) from compounding into a "
    "spawn hot-loop.")
FLEET_SCALE_INTERVAL = _knob(
    "VELES_FLEET_SCALE_INTERVAL", 0.25, float,
    "Seconds between autoscaler signal polls (the controller "
    "observes fleet pressure on this cadence).")

# -- traffic replay (Gauntlet) -----------------------------------------

TRAFFIC_SEED = _knob(
    "VELES_TRAFFIC_SEED", 0, int,
    "Seed of the open-loop traffic generator: the whole arrival "
    "schedule (times, model mix, burst placement) is a pure function "
    "of the spec + this seed, so a logged trace replays bit-"
    "identically.")
TRAFFIC_DURATION_S = _knob(
    "VELES_TRAFFIC_DURATION_S", 60.0, float,
    "Length of the generated production day in seconds.")
TRAFFIC_PEAK_RPS = _knob(
    "VELES_TRAFFIC_PEAK_RPS", 60.0, float,
    "Arrival rate at the top of the diurnal sine (requests/second); "
    "the trough is peak / $VELES_TRAFFIC_SWING.")
TRAFFIC_SWING = _knob(
    "VELES_TRAFFIC_SWING", 10.0, float,
    "Peak-to-trough ratio of the diurnal arrival curve (>= 10x is "
    "the production-day acceptance bar).")
TRAFFIC_BURST_MULT = _knob(
    "VELES_TRAFFIC_BURST_MULT", 2.0, float,
    "Rate multiplier inside a Poisson-placed burst window (bursts "
    "ride ON TOP of the diurnal curve).")
TRAFFIC_ZIPF_S = _knob(
    "VELES_TRAFFIC_ZIPF_S", 1.1, float,
    "Zipf exponent of the multi-model popularity skew: model rank k "
    "draws traffic proportional to 1/k^s — the long tail that makes "
    "shed-tail-before-hot-prefix degradation mean something.")

# -- online learning (Evergreen) ---------------------------------------

ONLINE = _knob(
    "VELES_ONLINE", False, flag,
    "Arm the Evergreen online-learning tier inside a hive "
    "(--serve-models): tapped live traffic fills a replay buffer, a "
    "scavenger trainer fine-tunes shadow params in serving idle gaps, "
    "and the promotion gate hot-swaps them HBM-to-HBM when the "
    "held-out slice improves past $VELES_ONLINE_PROMOTE_MARGIN.")
ONLINE_TAP_FRAC = _knob(
    "VELES_ONLINE_TAP_FRAC", 1.0, float,
    "Deterministic fraction of admitted hive requests the online tap "
    "mirrors into the replay buffer (an error-diffusion accumulator, "
    "not a coin flip — the tapped subsequence is reproducible).")
ONLINE_BUFFER_ROWS = _knob(
    "VELES_ONLINE_BUFFER_ROWS", 4096, int,
    "Replay-buffer capacity in sample rows per learning model "
    "(reservoir-sampled once full); rows store uint8-quantized when "
    "the model's ingest codec round-trips them, stacking the PR 2 4x "
    "on the buffer's residency charge.")
ONLINE_HOLDOUT_EVERY = _knob(
    "VELES_ONLINE_HOLDOUT_EVERY", 8, int,
    "Every Nth labeled tapped request lands in the held-out slice "
    "the promotion gate scores (never trained on).")
ONLINE_MICRO_BATCH = _knob(
    "VELES_ONLINE_MICRO_BATCH", 32, int,
    "Rows per scavenged fine-tune micro-step — the ONE fixed train "
    "dispatch shape (compiles once, like the serving micro-batch).")
ONLINE_MIN_STEPS = _knob(
    "VELES_ONLINE_MIN_STEPS", 8, int,
    "Fine-tune steps between promotion-gate evaluations (and before "
    "the first one).")
ONLINE_PROMOTE_MARGIN = _knob(
    "VELES_ONLINE_PROMOTE_MARGIN", 1.0, float,
    "Held-out error-pct margin the shadow must beat the incumbent by "
    "before the gate promotes it (the anti-noise hysteresis); a "
    "shadow WORSE by this margin after a full gate round rolls back "
    "to the incumbent's params and journals.")
ONLINE_IDLE_MS = _knob(
    "VELES_ONLINE_IDLE_MS", 2.0, float,
    "Milliseconds every serving batcher must have been idle (empty "
    "queue, nothing in flight) before the scavenger fires a "
    "fine-tune step — serving latency owns the chip, learning eats "
    "the gaps.")
ONLINE_SLO_P99_MS = _knob(
    "VELES_ONLINE_SLO_P99_MS", 0.0, float,
    "SLO headroom gate for the scavenger (the PR 11 admission-"
    "estimator move applied to learning): when the EMA fine-tune "
    "step cost exceeds this many milliseconds the step is skipped "
    "even on an idle chip — a step that long would blow the p99 of "
    "a request arriving under it (0 disables the check).")
ONLINE_LR_SCALE = _knob(
    "VELES_ONLINE_LR_SCALE", 0.1, float,
    "Fine-tune learning-rate scale applied to each gradient unit's "
    "packaged training rate (online steps nudge a converged model; "
    "full training rates overshoot).")
ONLINE_DUTY = _knob(
    "VELES_ONLINE_DUTY", 0.5, float,
    "Ceiling on the scavenger's duty cycle (fraction of wall it may "
    "spend stepping, 0..1): after each step it rests at least "
    "cost*(1-duty)/duty, so even an all-idle chip keeps host cores "
    "and GIL mostly free for the serving threads — the lever behind "
    "the <=1.2x learner-on p99 bar.")

# -- gray-failure defense (Sentinel) -----------------------------------

FLEET_DEADLINE_MS = _knob(
    "VELES_FLEET_DEADLINE_MS", 10000.0, float,
    "Default per-request deadline the fleet router stamps onto every "
    "request; it rides the JSONL protocol end-to-end so a hive "
    "batcher drops already-expired rows before dispatch and a waiter "
    "never burns more than this against a wedged replica.")
FLEET_HEDGE_MIN_MS = _knob(
    "VELES_FLEET_HEDGE_MIN_MS", 25.0, float,
    "Floor of the adaptive hedge threshold: a request older than "
    "max(this, the model's measured p95 latency) is hedged on a "
    "second replica and the first answer wins.")
FLEET_HEDGE_BUDGET = _knob(
    "VELES_FLEET_HEDGE_BUDGET", 0.05, float,
    "Cap on hedged requests as a fraction of admitted fleet traffic "
    "(0 disables hedging) — hedges fight tail latency, the budget "
    "keeps them from melting an already-overloaded fleet.")
FLEET_EJECT_THRESHOLD = _knob(
    "VELES_FLEET_EJECT_THRESHOLD", 3.0, float,
    "Health-score level (decaying weighted strikes: deadline misses, "
    "deaths, integrity failures, hedge losses, latency outliers) at "
    "which the sentinel ejects a replica from routing; ejection is "
    "capped at N-1 replicas so the fleet degrades, never "
    "self-destructs.")
FLEET_PROBE_OK = _knob(
    "VELES_FLEET_PROBE_OK", 3, int,
    "Consecutive clean synthetic probes an ejected replica must "
    "answer before the sentinel reinstates it into routing.")
FLEET_PROBE_INTERVAL = _knob(
    "VELES_FLEET_PROBE_INTERVAL", 0.5, float,
    "Initial seconds between synthetic canary probes of an ejected "
    "replica (a failed probe doubles it, capped at 10s; a clean one "
    "resets it).")

# -- observability -----------------------------------------------------

LOCK_WITNESS = _knob(
    "VELES_LOCK_WITNESS", False, flag,
    "Arm the Lockstep lock-order witness: locks created through "
    "analysis/witness.py record (holder -> acquired) pairs, flushed "
    "as lockwitness-<pid>.json next to the Sightline snapshot; a "
    "tier-1 test asserts every observed edge is declared in "
    "analysis/lock_order.json.  Off (the default) the factories "
    "return bare threading primitives — zero overhead.")
METRICS_DIR = _knob(
    "VELES_METRICS_DIR", "", str,
    "Arm Sightline persistence: journal-<pid>.jsonl + atomic "
    "metrics-<pid>.json snapshots under this directory; inherited by "
    "children.")
PLOTS_DIR = _knob(
    "VELES_PLOTS_DIR", "plots", str,
    "Output directory of the graphics server's rendered plot "
    "artifacts.")
TRACE_SAMPLE = _knob(
    "VELES_TRACE_SAMPLE", 1.0, float,
    "Flightline head-based trace sampling rate in [0, 1]: the "
    "fraction of fleet requests minted with the sampled bit set "
    "(error diffusion, so the rate is exact, not a coin flip).  A "
    "sampled request carries trace/span/parent wire keys on every "
    "hop and journals trace.* events for cross-process assembly; 0 "
    "disables causal tracing (the bench trace phase's overhead "
    "baseline).")
FLIGHTREC_CAP = _knob(
    "VELES_FLIGHTREC_CAP", 512, int,
    "Entries the per-process flight-recorder ring retains (recent "
    "spans/events, in memory, always armed).  The ring dumps to "
    "flightrec-<pid>-<n>-<reason>.json in the metrics dir on "
    "SIGTERM, injected SIGKILL, sentinel ejection, and promotion-"
    "gate verdicts, so every ejection/rollback ships with the trace "
    "tail that explains it.")

# -- mesh execution (Lattice) ------------------------------------------

MESH_SHARD_DATA = _knob(
    "VELES_MESH_SHARD_DATA", "auto", str,
    "Row-shard the HBM-resident dataset over the device mesh (each "
    "device holds 1/N of the rows): `auto` shards only when the "
    "dataset exceeds ONE device's residency budget but fits sharded "
    "(so a dataset N x one chip's budget goes resident instead of "
    "degrading to host streaming), `always` shards any mesh-resident "
    "dataset, `never`/`0` keeps the replicated placement.")
MESH_SHARD_MEMBERS = _knob(
    "VELES_MESH_SHARD_MEMBERS", "auto", str,
    "Shard the stacked member axis of population-batched GA cohorts "
    "over the mesh (P/N members per device, raising the HBM cohort "
    "cap by the device count): `auto`/`always` shard whenever the "
    "engine is handed a mesh, `never`/`0` keeps single-device "
    "stacking.")

# -- device / kernel tuning --------------------------------------------

MAX_RESIDENT_BYTES = _knob(
    "VELES_MAX_RESIDENT_BYTES", 8 << 30, int,
    "PER-DEVICE HBM byte budget for device-resident datasets; over "
    "budget degrades to host streaming (on a mesh with "
    "$VELES_MESH_SHARD_DATA, a dataset over one device's budget "
    "first tries the row-sharded placement at total/N per device).")
TPU_SCAN_UNROLL = _knob(
    "VELES_TPU_SCAN_UNROLL", 1, int,
    "Unroll factor of the fused train loop's lax.scan (>1 trades "
    "compile time for scheduling overlap).")
TPU_CONV_S2D = _knob(
    "VELES_TPU_CONV_S2D", False, flag,
    "Use the space-to-depth conv formulation for stride-matched "
    "first layers.")
TPU_LRN_PALLAS = _knob(
    "VELES_TPU_LRN_PALLAS", False, flag,
    "Route LRN through the hand-written pallas kernel instead of the "
    "XLA lowering.")
TPU_LRN_RECOMPUTE = _knob(
    "VELES_TPU_LRN_RECOMPUTE", False, flag,
    "Recompute LRN normalizers in the backward pass instead of "
    "saving them (HBM for FLOPs).")
SOM_FUSED = _knob(
    "VELES_SOM_FUSED", True, flag,
    "Train Kohonen SOM workflows as fused donated epoch scans on jax "
    "devices (ONE dispatch per superstep group, schedule applied per "
    "step inside the trace); `0` falls back to the eager "
    "per-minibatch dispatch loop.")
SOM_SUPERSTEP = _knob(
    "VELES_SOM_SUPERSTEP", 0, int,
    "Minibatches per fused SOM dispatch group (the SOM loader's "
    "superstep); 0 groups the WHOLE class per firing — one dispatch "
    "per epoch.")
TPU_SYNTH_CACHE = _knob(
    "VELES_TPU_SYNTH_CACHE", False, flag,
    "Cache large synthetic datasets in-process across loader "
    "constructions (bench/ablation runs).")

# -- XLA compile cache -------------------------------------------------

TPU_NO_COMPILE_CACHE = _knob(
    "VELES_TPU_NO_COMPILE_CACHE", False, flag,
    "Disable the persistent XLA compile cache entirely.")
TPU_COMPILE_CACHE_DIR = _knob(
    "VELES_TPU_COMPILE_CACHE_DIR", "", str,
    "Override the era-namespaced default directory of the persistent "
    "XLA compile cache.")


def names() -> frozenset:
    """Every declared knob name (the env-registry rule's whitelist)."""
    return frozenset(KNOBS)


def get(name: str, environ: Optional[Dict[str, str]] = None) -> Any:
    """The parsed value of a declared knob (default when unset or
    malformed).  Raises KeyError on an undeclared name — reading an
    unregistered knob is exactly the bug the registry exists to
    catch."""
    return KNOBS[name].read(environ)


def render_table() -> str:
    """The guide's knob table, generated (markdown, sorted by name).
    ``scripts/veleslint.py --sync-docs`` writes it between the
    ``veleslint:knobs`` markers in docs/guide.md and the env-registry
    rule fails when the checked-in copy drifts."""
    rows = ["| Knob | Default | Type | Meaning |",
            "| --- | --- | --- | --- |"]
    for name in sorted(KNOBS):
        k = KNOBS[name]
        default = ("on" if k.default else "off") \
            if k.parser is flag else \
            ("(unset)" if k.default == "" else repr(k.default))
        rows.append(f"| `{name}` | {default} | {k.type_name} | "
                    f"{k.doc} |")
    return "\n".join(rows) + "\n"
