"""The central registry of Sightline telemetry names.

Every journal event, counter, gauge, histogram, and span name this
framework emits is declared HERE as an importable constant.  Call
sites use the constants (``telemetry.event(events.EV_SNAPSHOT_SAVE,
...)``), and veleslint's event-registry rule flags any ad-hoc string
literal passed to ``telemetry.event / counter / gauge / histogram /
span / recent_events`` — the typo class chaos_drill's journal
assertions could previously only catch at runtime (an emitter and an
asserter disagreeing on a name means the drill reads an event that
never fires) is now a parse-time finding.

A few hot-path names are *families* keyed by the fused step kind and
are necessarily built dynamically (``fused.<kind>_dispatch_seconds``
histograms, ``fused.first_<kind>_dispatch_seconds`` gauges,
``fused.<kind>_seconds`` / ``fused.<kind>_images`` counters); the lint
rule checks literals only, and the families are documented here so the
registry stays the one place a name is looked up.
"""

from __future__ import annotations

from typing import Set

EVENTS: Set[str] = set()
COUNTERS: Set[str] = set()
GAUGES: Set[str] = set()
HISTOGRAMS: Set[str] = set()
SPANS: Set[str] = set()


def _ev(name: str) -> str:
    EVENTS.add(name)
    return name


def _ctr(name: str) -> str:
    COUNTERS.add(name)
    return name


def _gauge(name: str) -> str:
    GAUGES.add(name)
    return name


def _hist(name: str) -> str:
    HISTOGRAMS.add(name)
    return name


def _span(name: str) -> str:
    # a journaled span emits an event AND feeds the histogram of the
    # same name — it lives in every namespace it touches
    SPANS.add(name)
    EVENTS.add(name)
    HISTOGRAMS.add(name)
    return name


# -- journal events ----------------------------------------------------

EV_FUSED_FIRST_DISPATCH = _ev("fused.first_dispatch")
EV_FUSED_SUMMARY = _ev("fused.summary")

EV_DEVICE_OOM_RETRY = _ev("device.oom_retry")
EV_DEVICE_OOM_DEGRADED = _ev("device.oom_degraded")

EV_SNAPSHOT_SAVE = _ev("snapshot.save")
EV_SNAPSHOT_FALLBACK = _ev("snapshot.fallback")
EV_SNAPSHOT_UNRECOVERABLE = _ev("snapshot.unrecoverable")

EV_LOADER_EPOCH = _ev("loader.epoch")
EV_LOADER_SHARD_RESIDENT = _ev("loader.shard_resident")
EV_LOADER_CORRUPT_FILE = _ev("loader.corrupt_file")
EV_LOADER_CORRUPT_OVER_TOLERANCE = _ev("loader.corrupt_over_tolerance")

EV_GA_GENERATION = _ev("ga.generation")
EV_GA_GENERATION_EVALUATED = _ev("ga.generation_evaluated")
EV_GA_HANG_DETECTED = _ev("ga.hang_detected")
EV_GA_EVALUATOR_RESTART = _ev("ga.evaluator_restart")
EV_GA_GENOME_LOST = _ev("ga.genome_lost")
EV_GA_GENOME_RETRY = _ev("ga.genome_retry")
EV_GA_CHECKPOINT_FALLBACK = _ev("ga.checkpoint_fallback")
EV_GA_CHECKPOINT_UNRECOVERABLE = _ev("ga.checkpoint_unrecoverable")
EV_GA_RESUMED = _ev("ga.resumed")
EV_GA_HANDOFF = _ev("ga.handoff")

EV_DBN_STAGE_HANDOFF = _ev("dbn.stage_handoff")

EV_PREEMPT_REQUESTED = _ev("preempt.requested")
EV_PREEMPT_DEADLINE_EXCEEDED = _ev("preempt.deadline_exceeded")
EV_PREEMPT_FINAL_SNAPSHOT = _ev("preempt.final_snapshot")
EV_PREEMPT_PEER_BROADCAST = _ev("preempt.peer_broadcast")
EV_PREEMPT_GA_STOP = _ev("preempt.ga_stop")
EV_PREEMPT_GA_EXIT = _ev("preempt.ga_exit")

EV_MULTIHOST_EMERGENCY_SNAPSHOT = _ev("multihost.emergency_snapshot")
EV_MULTIHOST_COLLECTIVE_FAILED = _ev("multihost.collective_failed")
EV_MULTIHOST_PEER_DEATH = _ev("multihost.peer_death")
EV_MULTIHOST_INIT_REFUSED = _ev("multihost.init_refused")

EV_SERVE_READY = _ev("serve.ready")
EV_SERVE_MODEL_LOADED = _ev("serve.model_loaded")
EV_SERVE_MODEL_SPILLED = _ev("serve.model_spilled")
EV_SERVE_MODEL_RESTORED = _ev("serve.model_restored")
EV_SERVE_MODEL_SHARDED = _ev("serve.model_sharded_resident")
EV_SERVE_FIRST_DISPATCH = _ev("serve.first_dispatch")
EV_SERVE_DRAIN = _ev("serve.drain")
EV_SERVE_SHUTDOWN = _ev("serve.shutdown")

EV_FLEET_READY = _ev("fleet.ready")
EV_FLEET_PLACEMENT = _ev("fleet.placement")
EV_FLEET_REPLICA_SPAWNED = _ev("fleet.replica_spawned")
EV_FLEET_REPLICA_DIED = _ev("fleet.replica_died")
EV_FLEET_REPLICA_RESPAWNED = _ev("fleet.replica_respawned")
EV_FLEET_DRAIN = _ev("fleet.drain")
EV_FLEET_SHUTDOWN = _ev("fleet.shutdown")
EV_FLEET_REPLICA_EJECTED = _ev("fleet.eject.replica")
EV_FLEET_REPLICA_REINSTATED = _ev("fleet.eject.reinstated")
EV_FLEET_PROBE_RESULT = _ev("fleet.probe.result")
EV_FLEET_SCALE_UP = _ev("fleet.scale.up")
EV_FLEET_SCALE_DOWN = _ev("fleet.scale.down")
EV_FLEET_REPLICA_RETIRED = _ev("fleet.replica_retired")
EV_FLEET_DEGRADE_ENGAGE = _ev("fleet.degrade.engage")
EV_FLEET_DEGRADE_RELEASE = _ev("fleet.degrade.release")

EV_TRAFFIC_TRACE = _ev("traffic.trace")
EV_TRAFFIC_DONE = _ev("traffic.done")

EV_ONLINE_ARMED = _ev("online.armed")
EV_ONLINE_GATE = _ev("online.gate")
EV_ONLINE_PROMOTED = _ev("online.promoted")
EV_ONLINE_ROLLBACK = _ev("online.rollback")

EV_TRACE_REQUEST = _ev("trace.request")
EV_TRACE_LEG = _ev("trace.leg")
EV_TRACE_SERVE = _ev("trace.serve")
EV_TRACE_BATCH = _ev("trace.batch")
EV_FLIGHTREC_DUMP = _ev("flightrec.dump")
EV_LOG_RECORD = _ev("log.record")

EV_SUPERVISOR_RESTART = _ev("supervisor.restart")
EV_SUPERVISOR_RESUMED = _ev("supervisor.resumed")
EV_SUPERVISOR_SHUTDOWN = _ev("supervisor.shutdown")
EV_SUPERVISOR_DONE = _ev("supervisor.done")
EV_SUPERVISOR_GIVEUP = _ev("supervisor.giveup")

# -- counters ----------------------------------------------------------

CTR_FUSED_DISPATCHES = _ctr("fused.dispatches")
CTR_FUSED_MINIBATCHES = _ctr("fused.minibatches")
CTR_FUSED_STREAM_TRANSFER_BYTES = _ctr("fused.stream_transfer_bytes")
CTR_FUSED_STREAM_TRANSFER_SECONDS = _ctr(
    "fused.stream_transfer_seconds")
CTR_FUSED_STREAM_OOM_RETRIES = _ctr("fused.stream_oom_retries")

CTR_ENSEMBLE_CHUNKS = _ctr("ensemble.chunks")
CTR_ENSEMBLE_SECONDS = _ctr("ensemble.seconds")
CTR_ENSEMBLE_IMAGES = _ctr("ensemble.images")
CTR_ENSEMBLE_MEMBER_IMAGES = _ctr("ensemble.member_images")

CTR_GA_COHORTS = _ctr("ga.cohorts")
CTR_GA_COHORT_MEMBERS = _ctr("ga.cohort_members")
CTR_GA_EVALUATIONS = _ctr("ga.evaluations")
CTR_GA_EVAL_SECONDS = _ctr("ga.eval_seconds")
CTR_GA_HANGS_DETECTED = _ctr("ga.hangs_detected")
CTR_GA_EVALUATOR_RESTARTS = _ctr("ga.evaluator_restarts")
CTR_GA_GENOMES_LOST = _ctr("ga.genomes_lost")
CTR_GA_GENOME_RETRIES = _ctr("ga.genome_retries")
CTR_GA_CHECKPOINT_FALLBACKS = _ctr("ga.checkpoint_fallbacks")

CTR_SERVE_REQUESTS = _ctr("serve.requests")
CTR_SERVE_REQUEST_ERRORS = _ctr("serve.request_errors")
CTR_SERVE_ROWS = _ctr("serve.rows")
CTR_SERVE_MEMBER_ROWS = _ctr("serve.member_rows")
CTR_SERVE_BATCHES = _ctr("serve.batches")
CTR_SERVE_BATCH_SLOTS = _ctr("serve.batch_slots")
CTR_SERVE_COMPILES = _ctr("serve.compiles")
CTR_SERVE_SPILLS = _ctr("serve.spills")
CTR_SERVE_DEADLINE_DROPPED = _ctr("serve.deadline_dropped")
CTR_SERVE_WAIT_COLLAPSED = _ctr("serve.wait_collapsed")
CTR_SERVE_WAIT_STRETCHED = _ctr("serve.wait_stretched")

CTR_FLEET_REQUESTS = _ctr("fleet.requests")
CTR_FLEET_REQUEST_ERRORS = _ctr("fleet.request_errors")
CTR_FLEET_SHED = _ctr("fleet.shed")
CTR_FLEET_RETRIES = _ctr("fleet.retries")
CTR_FLEET_MIRRORED = _ctr("fleet.mirrored")
CTR_FLEET_REPLICA_DEATHS = _ctr("fleet.replica_deaths")
CTR_FLEET_REPLICA_RESPAWNS = _ctr("fleet.replica_respawns")
CTR_FLEET_HEDGES = _ctr("fleet.hedge.issued")
CTR_FLEET_HEDGE_WINS = _ctr("fleet.hedge.wins")
CTR_FLEET_HEDGE_DENIED = _ctr("fleet.hedge.denied")
CTR_FLEET_STALE_RESPONSES = _ctr("fleet.stale_response")
CTR_FLEET_DEADLINE_MISSES = _ctr("fleet.deadline_misses")
CTR_FLEET_INTEGRITY_STRIKES = _ctr("fleet.integrity_strikes")
CTR_FLEET_EJECTIONS = _ctr("fleet.eject.total")
CTR_FLEET_REINSTATEMENTS = _ctr("fleet.eject.reinstated_total")
CTR_FLEET_PROBES = _ctr("fleet.probe.sent")
CTR_FLEET_PROBES_OK = _ctr("fleet.probe.ok")
CTR_FLEET_PROBES_FAILED = _ctr("fleet.probe.fail")
CTR_FLEET_SCALE_UPS = _ctr("fleet.scale.ups")
CTR_FLEET_SCALE_DOWNS = _ctr("fleet.scale.downs")
CTR_FLEET_RETIRED = _ctr("fleet.replicas_retired")
CTR_TRAFFIC_SENT = _ctr("traffic.sent")
CTR_TRAFFIC_LATE = _ctr("traffic.late")

CTR_ONLINE_TAPPED_ROWS = _ctr("online.tapped_rows")
CTR_ONLINE_LABELED_ROWS = _ctr("online.labeled_rows")
CTR_ONLINE_LABEL_ORPHANS = _ctr("online.label_orphans")
CTR_ONLINE_STEPS = _ctr("online.steps")
CTR_ONLINE_STEP_ROWS = _ctr("online.step_rows")
CTR_ONLINE_STEP_SECONDS = _ctr("online.step_seconds")
CTR_ONLINE_STEPS_SKIPPED_BUSY = _ctr("online.steps_skipped_busy")
CTR_ONLINE_PROMOTIONS = _ctr("online.promotions")
CTR_ONLINE_ROLLBACKS = _ctr("online.rollbacks")

CTR_SOM_FUSED_DISPATCHES = _ctr("som.fused_dispatches")
CTR_SOM_FUSED_IMAGES = _ctr("som.fused_images")
CTR_SOM_COHORTS = _ctr("som.cohorts")
CTR_SOM_COHORT_MEMBERS = _ctr("som.cohort_members")

CTR_EVALUATOR_JOBS = _ctr("evaluator.jobs")
CTR_EVALUATOR_JOB_ERRORS = _ctr("evaluator.job_errors")

CTR_LOADER_EPOCHS = _ctr("loader.epochs")
CTR_LOADER_IMAGES_DECODED = _ctr("loader.images_decoded")
CTR_LOADER_CORRUPT_SKIPPED = _ctr("loader.corrupt_skipped")

CTR_SNAPSHOT_SAVES = _ctr("snapshot.saves")
CTR_SNAPSHOT_FALLBACKS = _ctr("snapshot.fallbacks")

CTR_DEVICE_OOM_DEGRADED = _ctr("device.oom_degraded")
CTR_MULTIHOST_EMERGENCY_SNAPSHOTS = _ctr(
    "multihost.emergency_snapshots")
CTR_PREEMPT_FINAL_SNAPSHOTS = _ctr("preempt.final_snapshots")
CTR_SUPERVISOR_RESTARTS = _ctr("supervisor.restarts")

# -- gauges ------------------------------------------------------------

GAUGE_FUSED_MFU = _gauge("fused.mfu")
GAUGE_FUSED_TRAIN_GFLOPS_PER_IMAGE = _gauge(
    "fused.train_gflops_per_image")
GAUGE_FUSED_TRAIN_IMAGES_PER_SEC_WALL = _gauge(
    "fused.train_images_per_sec_wall")
GAUGE_SERVE_QUEUE_DEPTH = _gauge("serve.queue_depth")
GAUGE_SERVE_MODELS_RESIDENT = _gauge("serve.models_resident")
GAUGE_SERVE_RESIDENT_BYTES = _gauge("serve.resident_bytes")
GAUGE_SERVE_RESIDENT_BYTES_PER_DEVICE = _gauge(
    "serve.resident_bytes_per_device")
GAUGE_SERVE_MESH_DEVICES = _gauge("serve.mesh_devices")
GAUGE_ARBITER_BUDGET_BYTES = _gauge("arbiter.budget_bytes")
GAUGE_ARBITER_RESIDENT_BYTES = _gauge("arbiter.resident_bytes")
GAUGE_SERVE_EFFECTIVE_WAIT_MS = _gauge("serve.effective_wait_ms")
GAUGE_SERVE_FIRST_DISPATCH_SECONDS = _gauge(
    "serve.first_dispatch_seconds")

GAUGE_FLEET_REPLICAS_HEALTHY = _gauge("fleet.replicas_healthy")
GAUGE_FLEET_INFLIGHT = _gauge("fleet.inflight")
GAUGE_FLEET_EST_WAIT_MS = _gauge("fleet.est_wait_ms")
GAUGE_FLEET_DISPATCH_EMA_MS = _gauge("fleet.dispatch_ema_ms")
GAUGE_FLEET_HEDGE_THRESHOLD_MS = _gauge("fleet.hedge.threshold_ms")
GAUGE_FLEET_REPLICAS_EJECTED = _gauge("fleet.eject.current")
GAUGE_FLEET_REPLICAS_TOTAL = _gauge("fleet.replicas_total")
GAUGE_FLEET_DEGRADE_RUNGS = _gauge("fleet.degrade.rungs")
GAUGE_FLEET_SCALE_PRESSURE_MS = _gauge("fleet.scale.pressure_ms")
GAUGE_TRAFFIC_RATE_RPS = _gauge("traffic.rate_rps")

GAUGE_ONLINE_BUFFER_ROWS = _gauge("online.buffer_rows")
GAUGE_ONLINE_BUFFER_BYTES = _gauge("online.buffer_bytes")
GAUGE_ONLINE_TIME_TO_SERVE = _gauge("online.time_to_serve")

GAUGE_LOCKSTEP_EDGES = _gauge("lockstep.edges_observed")
GAUGE_LOCKSTEP_ACQUIRES = _gauge("lockstep.acquires")

GAUGE_GA_LAST_HANG_WAIT = _gauge("ga.last_hang_wait")
GAUGE_PREEMPT_SNAPSHOT_SECONDS = _gauge("preempt.snapshot_seconds")
GAUGE_MULTIHOST_PEER_HEARTBEAT_AGE = _gauge(
    "multihost.peer_heartbeat_age")

# -- histograms --------------------------------------------------------

HIST_SNAPSHOT_SAVE_SECONDS = _hist("snapshot.save_seconds")
HIST_SNAPSHOT_LOAD_SECONDS = _hist("snapshot.load_seconds")
HIST_GA_GENOME_SECONDS = _hist("ga.genome_seconds")
HIST_GA_GENERATION_SECONDS = _hist("ga.generation_seconds")
HIST_LOADER_DECODE_SECONDS = _hist("loader.decode_seconds")
HIST_LOADER_EPOCH_SECONDS = _hist("loader.epoch_seconds")
HIST_ENSEMBLE_DISPATCH_SECONDS = _hist("ensemble.dispatch_seconds")
HIST_ENSEMBLE_SCORE_SECONDS = _hist("ensemble.score_seconds")
HIST_SUPERVISOR_DOWNTIME_SECONDS = _hist(
    "supervisor.downtime_seconds")
HIST_FLEET_REQUEST_SECONDS = _hist("fleet.request_seconds")
HIST_SERVE_REQUEST_SECONDS = _hist("serve.request_seconds")
HIST_SERVE_DISPATCH_SECONDS = _hist("serve.dispatch_seconds")
HIST_SERVE_BATCH_ROWS = _hist("serve.batch_rows")
HIST_SERVE_WAIT_SECONDS = _hist("serve.wait_seconds")
HIST_ONLINE_STEP_DISPATCH_SECONDS = _hist(
    "online.step_dispatch_seconds")
HIST_ONLINE_GATE_SECONDS = _hist("online.gate_seconds")

# -- journaled spans (event + histogram of the same name) --------------

SPAN_GA_COHORT_TRAIN = _span("ga.cohort_train")
SPAN_SOM_COHORT_TRAIN = _span("som.cohort_train")
SPAN_EVALUATOR_JOB_SECONDS = _span("evaluator.job_seconds")

#: dynamic name families (built with f-strings at the call site; the
#: lint rule checks literals only): ``fused.<kind>_dispatch_seconds``
#: histograms, ``fused.first_<kind>_dispatch_seconds`` gauges, and
#: ``fused.<kind>_seconds`` / ``fused.<kind>_images`` counters, where
#: <kind> is the fused step kind (train/eval/...)
#: ...plus the fleet router's per-model traffic split (the canary A/B
#: read): ``fleet.model.<name>.requests`` / ``.errors`` / ``.shed`` /
#: ``.mirrored`` counters and a ``fleet.model.<name>.request_seconds``
#: histogram, where <name> is the served model's registered name
#: ...plus the sentinel's per-replica health split (the fleet_rows
#: health column): a ``fleet.replica.<i>.health_score`` gauge and a
#: ``fleet.replica.<i>.hedge_wins`` counter, where <i> is the replica
#: index
DYNAMIC_FAMILIES = (
    "fused.<kind>_dispatch_seconds",
    "fused.first_<kind>_dispatch_seconds",
    "fused.<kind>_seconds",
    "fused.<kind>_images",
    "fleet.model.<name>.requests",
    "fleet.model.<name>.errors",
    "fleet.model.<name>.shed",
    "fleet.model.<name>.mirrored",
    "fleet.model.<name>.request_seconds",
    "fleet.replica.<i>.health_score",
    "fleet.replica.<i>.hedge_wins",
    "online.model.<name>.buffer_rows",
    "online.model.<name>.steps",
    "online.model.<name>.gate_state",
    "arbiter.pool.<pool>.resident_bytes",
)


def known(name: str) -> bool:
    """Is ``name`` declared in any telemetry namespace?"""
    return name in EVENTS or name in COUNTERS or name in GAUGES \
        or name in HISTOGRAMS or name in SPANS


def all_names() -> frozenset:
    return frozenset(EVENTS | COUNTERS | GAUGES | HISTOGRAMS | SPANS)
