"""Named, seeded pseudo-random generator streams.

Reference parity: veles/prng/random_generator.py — ``prng.get(name)``
returns a named deterministic stream; seeds come from the CLI so runs
are reproducible.

TPU-first design: each stream owns BOTH a numpy ``Generator`` (for
host-side work: shuffling, weight init on the numpy backend) and a JAX
PRNG key chain (for traced stochastic ops: dropout, stochastic pooling).
``stream.next_key()`` splits deterministically, and the key counter is
part of snapshot state so resume continues the exact stream.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

import jax


class RandomStream:
    def __init__(self, name: str, seed: int) -> None:
        self.name = name
        self.seed = seed
        self.numpy: np.random.Generator = np.random.default_rng(seed)
        self._key_counter = 0

    def next_key(self) -> jax.Array:
        """Deterministic JAX key #N of this stream (N increments)."""
        k = jax.random.fold_in(jax.random.key(self.seed), self._key_counter)
        self._key_counter += 1
        return k

    def key_at(self, counter: int) -> jax.Array:
        """Key for an explicit counter (used inside jitted steps where the
        counter is threaded as traced state)."""
        return jax.random.fold_in(jax.random.key(self.seed), counter)

    # -- snapshot support ---------------------------------------------

    def __getstate__(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "numpy_state": self.numpy.bit_generator.state,
            "key_counter": self._key_counter,
        }

    def __setstate__(self, state: dict) -> None:
        self.name = state["name"]
        self.seed = state["seed"]
        self.numpy = np.random.default_rng(self.seed)
        self.numpy.bit_generator.state = state["numpy_state"]
        self._key_counter = state["key_counter"]


_streams: Dict[str, RandomStream] = {}
_default_seed = 1234


def seed_all(seed: int) -> None:
    """Set the base seed and reset every existing stream (CLI --seed)."""
    global _default_seed
    _default_seed = seed
    names = list(_streams)
    _streams.clear()
    for n in names:
        get(n)


def get(name: str = "default", seed: Optional[int] = None) -> RandomStream:
    """The named stream, created on first use.

    Per-stream seeds derive from the base seed and the stream name, so
    streams are independent but fully determined by (base seed, name).
    """
    if name not in _streams:
        if seed is None:
            h = 14695981039346656037
            for ch in name.encode():
                h = ((h ^ ch) * 1099511628211) % (2**64)
            seed = (_default_seed ^ h) % (2**63)
        _streams[name] = RandomStream(name, seed)
    return _streams[name]


def snapshot_state() -> Dict[str, dict]:
    return {n: s.__getstate__() for n, s in _streams.items()}


def restore_state(state: Dict[str, dict]) -> None:
    for n, st in state.items():
        s = RandomStream.__new__(RandomStream)
        s.__setstate__(st)
        _streams[n] = s
