"""Forge: package, inspect, and install trained workflows.

Reference parity: veles/forge_client.py — package a workflow (manifest
+ code + snapshot) and publish it to the VelesForge marketplace
(SURVEY.md §3.1 "Forge client").  This environment has no network, so
the "marketplace" is a local/shared directory of packages; the archive
format is the deliverable (it also feeds the native inference runtime,
libveles-equivalent).

Package layout (.tar.gz):

    manifest.json     name, version, author, entry, files, sha256 map
    workflow.py       the workflow module
    *.py              config files
    snapshot.pkl.gz   trained state (optional but usual)

CLI:

    python -m veles_tpu.forge pack  out.vpkg --name X workflow.py \
        [config.py ...] [--snapshot snap.pkl.gz]
    python -m veles_tpu.forge info    pkg.vpkg
    python -m veles_tpu.forge install pkg.vpkg [dest_dir]
    python -m veles_tpu.forge list    [store_dir]
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import tarfile
import time
from typing import Any, Dict, List, Optional

from veles_tpu.logger import Logger

MANIFEST = "manifest.json"
FORMAT_VERSION = 1


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class ForgePackage(Logger):
    @staticmethod
    def pack(out_path: str, name: str, workflow_file: str,
             config_files: Optional[List[str]] = None,
             snapshot: Optional[str] = None,
             version: str = "1.0.0", author: str = "",
             description: str = "") -> str:
        files = [workflow_file] + list(config_files or [])
        if snapshot:
            files.append(snapshot)
        for f in files:
            if not os.path.isfile(f):
                raise FileNotFoundError(f)
        arcnames = {}
        seen = set()
        for f in files:
            base = os.path.basename(f)
            if base in seen:
                raise ValueError(f"duplicate file name in package: "
                                 f"{base}")
            seen.add(base)
            arcnames[f] = base
        manifest = {
            "format_version": FORMAT_VERSION,
            "name": name,
            "version": version,
            "author": author,
            "description": description,
            "created": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
            "entry": os.path.basename(workflow_file),
            "configs": [os.path.basename(c)
                        for c in (config_files or [])],
            "snapshot": os.path.basename(snapshot) if snapshot else None,
            "sha256": {arcnames[f]: _sha256(f) for f in files},
        }
        blob = json.dumps(manifest, indent=2).encode()
        with tarfile.open(out_path, "w:gz") as tar:
            info = tarfile.TarInfo(MANIFEST)
            info.size = len(blob)
            info.mtime = int(time.time())
            tar.addfile(info, io.BytesIO(blob))
            for f in files:
                tar.add(f, arcname=arcnames[f])
        return out_path

    @staticmethod
    def read_manifest(pkg_path: str) -> Dict[str, Any]:
        with tarfile.open(pkg_path, "r:gz") as tar:
            member = tar.getmember(MANIFEST)
            manifest = json.loads(tar.extractfile(member).read())
        if manifest.get("format_version", 0) > FORMAT_VERSION:
            raise ValueError(
                f"package format {manifest['format_version']} is newer "
                f"than this framework understands ({FORMAT_VERSION})")
        return manifest

    @staticmethod
    def install(pkg_path: str, dest_dir: str,
                verify: bool = True) -> Dict[str, Any]:
        """Extract + checksum-verify; returns the manifest with an
        added 'root' key pointing at the extracted directory."""
        import shutil
        import tempfile

        manifest = ForgePackage.read_manifest(pkg_path)
        target = os.path.join(dest_dir,
                              f"{manifest['name']}-{manifest['version']}")
        os.makedirs(dest_dir, exist_ok=True)
        # extract + verify in a staging dir so a failed verification
        # never leaves tampered files at the install path
        staging = tempfile.mkdtemp(dir=dest_dir, prefix=".staging-")
        try:
            with tarfile.open(pkg_path, "r:gz") as tar:
                for member in tar.getmembers():
                    # refuse path traversal — packages may come from
                    # anyone
                    mpath = os.path.normpath(member.name)
                    if mpath.startswith("..") or os.path.isabs(mpath) \
                            or not (member.isfile() or member.isdir()):
                        raise ValueError(
                            f"unsafe member in package: {member.name!r}")
                    # every extracted FILE must be covered by the
                    # manifest's checksums — an unmanifested member
                    # would install unverified (round-1 ADVICE low)
                    if verify and member.isfile() \
                            and mpath != "manifest.json" \
                            and mpath not in manifest["sha256"]:
                        raise ValueError(
                            f"package member {member.name!r} is not "
                            f"listed in the manifest checksums — "
                            f"refusing to install unverified content")
                try:
                    tar.extractall(staging, filter="data")
                except TypeError:  # pre-3.12 tarfile without filter=
                    tar.extractall(staging)  # members validated above
            if verify:
                for fname, want in manifest["sha256"].items():
                    got = _sha256(os.path.join(staging, fname))
                    if got != want:
                        raise ValueError(
                            f"checksum mismatch for {fname}: "
                            f"{got[:12]} != {want[:12]}")
            if os.path.isdir(target):
                shutil.rmtree(target)
            os.rename(staging, target)
        except Exception:
            shutil.rmtree(staging, ignore_errors=True)
            raise
        manifest["root"] = target
        return manifest

    @staticmethod
    def list_store(store_dir: str) -> List[Dict[str, Any]]:
        out = []
        if not os.path.isdir(store_dir):
            return out
        for fn in sorted(os.listdir(store_dir)):
            if fn.endswith((".vpkg", ".tar.gz")):
                try:
                    m = ForgePackage.read_manifest(
                        os.path.join(store_dir, fn))
                    m["file"] = fn
                    out.append(m)
                except (tarfile.TarError, KeyError, ValueError):
                    continue
        return out


def main(argv=None) -> int:
    import argparse
    import sys

    from veles_tpu.logger import setup_logging

    setup_logging()
    p = argparse.ArgumentParser(prog="veles_tpu.forge",
                                description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    pk = sub.add_parser("pack")
    pk.add_argument("out")
    pk.add_argument("workflow")
    pk.add_argument("configs", nargs="*")
    pk.add_argument("--name", required=True)
    pk.add_argument("--version", default="1.0.0")
    pk.add_argument("--author", default="")
    pk.add_argument("--description", default="")
    pk.add_argument("--snapshot", default=None)
    pi = sub.add_parser("info")
    pi.add_argument("pkg")
    ins = sub.add_parser("install")
    ins.add_argument("pkg")
    ins.add_argument("dest", nargs="?", default="forge_store")
    ls = sub.add_parser("list")
    ls.add_argument("store", nargs="?", default="forge_store")
    args = p.parse_args(argv)

    if args.cmd == "pack":
        path = ForgePackage.pack(
            args.out, args.name, args.workflow, args.configs,
            snapshot=args.snapshot, version=args.version,
            author=args.author, description=args.description)
        print(path)
    elif args.cmd == "info":
        print(json.dumps(ForgePackage.read_manifest(args.pkg),
                         indent=2))
    elif args.cmd == "install":
        m = ForgePackage.install(args.pkg, args.dest)
        print(m["root"])
    elif args.cmd == "list":
        for m in ForgePackage.list_store(args.store):
            print(f"{m['file']}: {m['name']} {m['version']} "
                  f"({m.get('description', '')})")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
