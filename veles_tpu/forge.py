"""Forge: package, inspect, and install trained workflows.

Reference parity: veles/forge_client.py — package a workflow (manifest
+ code + snapshot) and publish it to the VelesForge marketplace
(SURVEY.md §3.1 "Forge client").  This environment has no network, so
the "marketplace" is a local/shared directory of packages; the archive
format is the deliverable (it also feeds the native inference runtime,
libveles-equivalent).

Package layout (.tar.gz):

    manifest.json     name, version, author, entry, files, sha256 map
    workflow.py       the workflow module
    *.py              config files
    snapshot.pkl.gz   trained state (optional but usual)

CLI:

    python -m veles_tpu.forge pack  out.vpkg --name X workflow.py \
        [config.py ...] [--snapshot snap.pkl.gz]
    python -m veles_tpu.forge info    pkg.vpkg
    python -m veles_tpu.forge install pkg.vpkg [dest_dir]
    python -m veles_tpu.forge list    [store_dir]

Marketplace (the reference's VelesForge service, stdlib-http-shaped —
a shared store any host on the cluster can publish to / fetch from):

    python -m veles_tpu.forge serve   [store_dir] [--port 8188]
    python -m veles_tpu.forge publish pkg.vpkg http://host:8188
    python -m veles_tpu.forge fetch   NAME http://host:8188 [dest_dir]
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import tarfile
import time
from typing import Any, Dict, List, Optional

from veles_tpu.logger import Logger

MANIFEST = "manifest.json"
FORMAT_VERSION = 1


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class ForgePackage(Logger):
    @staticmethod
    def pack(out_path: str, name: str, workflow_file: str,
             config_files: Optional[List[str]] = None,
             snapshot: Optional[str] = None,
             version: str = "1.0.0", author: str = "",
             description: str = "") -> str:
        files = [workflow_file] + list(config_files or [])
        if snapshot:
            files.append(snapshot)
        for f in files:
            if not os.path.isfile(f):
                raise FileNotFoundError(f)
        arcnames = {}
        seen = set()
        for f in files:
            base = os.path.basename(f)
            if base in seen:
                raise ValueError(f"duplicate file name in package: "
                                 f"{base}")
            seen.add(base)
            arcnames[f] = base
        manifest = {
            "format_version": FORMAT_VERSION,
            "name": name,
            "version": version,
            "author": author,
            "description": description,
            "created": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
            "entry": os.path.basename(workflow_file),
            "configs": [os.path.basename(c)
                        for c in (config_files or [])],
            "snapshot": os.path.basename(snapshot) if snapshot else None,
            "sha256": {arcnames[f]: _sha256(f) for f in files},
        }
        blob = json.dumps(manifest, indent=2).encode()
        with tarfile.open(out_path, "w:gz") as tar:
            info = tarfile.TarInfo(MANIFEST)
            info.size = len(blob)
            info.mtime = int(time.time())
            tar.addfile(info, io.BytesIO(blob))
            for f in files:
                tar.add(f, arcname=arcnames[f])
        return out_path

    @staticmethod
    def read_manifest(pkg_path: str) -> Dict[str, Any]:
        with tarfile.open(pkg_path, "r:gz") as tar:
            # pack() writes the manifest first: tar.next() avoids
            # decompressing the whole archive (snapshots can be GBs)
            # just to list it.  Foreign archives fall back to a scan.
            member = tar.next()
            if member is None or member.name != MANIFEST:
                member = tar.getmember(MANIFEST)
            # a crafted archive can name a directory/link "manifest.json";
            # extractfile() then returns None — reject as a bad manifest
            # (ValueError is what list_store tolerates) instead of
            # crashing every store listing with AttributeError
            if not member.isfile():
                raise ValueError(
                    f"bad manifest member in {pkg_path!r}: not a file")
            manifest = json.loads(tar.extractfile(member).read())
        if manifest.get("format_version", 0) > FORMAT_VERSION:
            raise ValueError(
                f"package format {manifest['format_version']} is newer "
                f"than this framework understands ({FORMAT_VERSION})")
        return manifest

    @staticmethod
    def install(pkg_path: str, dest_dir: str,
                verify: bool = True) -> Dict[str, Any]:
        """Extract + checksum-verify; returns the manifest with an
        added 'root' key pointing at the extracted directory."""
        import shutil
        import tempfile

        manifest = ForgePackage.read_manifest(pkg_path)
        target = os.path.join(dest_dir,
                              f"{manifest['name']}-{manifest['version']}")
        os.makedirs(dest_dir, exist_ok=True)
        # extract + verify in a staging dir so a failed verification
        # never leaves tampered files at the install path
        staging = tempfile.mkdtemp(dir=dest_dir, prefix=".staging-")
        try:
            with tarfile.open(pkg_path, "r:gz") as tar:
                for member in tar.getmembers():
                    # refuse path traversal — packages may come from
                    # anyone
                    mpath = os.path.normpath(member.name)
                    if mpath.startswith("..") or os.path.isabs(mpath) \
                            or not (member.isfile() or member.isdir()):
                        raise ValueError(
                            f"unsafe member in package: {member.name!r}")
                    # every extracted FILE must be covered by the
                    # manifest's checksums — an unmanifested member
                    # would install unverified (round-1 ADVICE low)
                    if verify and member.isfile() \
                            and mpath != "manifest.json" \
                            and mpath not in manifest["sha256"]:
                        raise ValueError(
                            f"package member {member.name!r} is not "
                            f"listed in the manifest checksums — "
                            f"refusing to install unverified content")
                try:
                    tar.extractall(staging, filter="data")
                except TypeError:  # pre-3.12 tarfile without filter=
                    tar.extractall(staging)  # members validated above
            if verify:
                for fname, want in manifest["sha256"].items():
                    got = _sha256(os.path.join(staging, fname))
                    if got != want:
                        raise ValueError(
                            f"checksum mismatch for {fname}: "
                            f"{got[:12]} != {want[:12]}")
            if os.path.isdir(target):
                shutil.rmtree(target)
            os.rename(staging, target)
        except Exception:
            shutil.rmtree(staging, ignore_errors=True)
            raise
        manifest["root"] = target
        return manifest

    @staticmethod
    def list_store(store_dir: str) -> List[Dict[str, Any]]:
        out = []
        if not os.path.isdir(store_dir):
            return out
        for fn in sorted(os.listdir(store_dir)):
            if fn.endswith((".vpkg", ".tar.gz")):
                try:
                    m = ForgePackage.read_manifest(
                        os.path.join(store_dir, fn))
                    m["file"] = fn
                    out.append(m)
                except (tarfile.TarError, KeyError, ValueError):
                    continue
        return out


# -- marketplace over HTTP (reference: VelesForge upload/download) ----

def _safe_pkg_name(name: str) -> str:
    base = os.path.basename(name)
    if base != name or not base.endswith((".vpkg", ".tar.gz")) \
            or base.startswith("."):
        raise ValueError(f"bad package file name: {name!r}")
    return base


def make_forge_server(store_dir: str, port: int = 0,
                      host: str = "127.0.0.1"):
    """HTTP marketplace over a package store directory.

    GET  /forge/list        -> JSON array of manifests (+ "file")
    GET  /forge/pkg/<file>  -> package bytes
    POST /forge/upload/<file> (body = package bytes) -> manifest JSON

    Returns the ``ThreadingHTTPServer`` (caller: ``serve_forever`` or
    a thread + ``shutdown``).  Uploads are staged and must parse as a
    manifested package before they land in the store.  There is no
    authentication (the reference's Forge was an open marketplace):
    bind ``host`` to a trusted interface.
    """
    import tempfile
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    os.makedirs(store_dir, exist_ok=True)

    class Handler(BaseHTTPRequestHandler):
        timeout = 60  # a stalled upload must free its thread + staging

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _json(self, code: int, obj: Any) -> None:
            blob = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def do_GET(self):
            if self.path == "/forge/list":
                return self._json(200, ForgePackage.list_store(store_dir))
            if self.path.startswith("/forge/pkg/"):
                try:
                    fn = _safe_pkg_name(self.path[len("/forge/pkg/"):])
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
                full = os.path.join(store_dir, fn)
                if not os.path.isfile(full):
                    return self._json(404, {"error": f"no such package "
                                                     f"{fn}"})
                self.send_response(200)
                self.send_header("Content-Type", "application/gzip")
                self.send_header("Content-Length",
                                 str(os.path.getsize(full)))
                self.end_headers()
                with open(full, "rb") as f:
                    import shutil
                    shutil.copyfileobj(f, self.wfile)
                return None
            return self._json(404, {"error": "unknown endpoint"})

        def do_POST(self):
            if not self.path.startswith("/forge/upload/"):
                return self._json(404, {"error": "unknown endpoint"})
            try:
                fn = _safe_pkg_name(self.path[len("/forge/upload/"):])
            except ValueError as e:
                return self._json(400, {"error": str(e)})
            try:
                length = int(self.headers.get("Content-Length", 0))
            except (TypeError, ValueError):
                length = -1
            if not 0 < length <= 1 << 31:
                return self._json(400, {"error": "bad content length"})
            fd, staging = tempfile.mkstemp(dir=store_dir,
                                           prefix=".upload-")
            try:
                with os.fdopen(fd, "wb") as f:
                    remaining = length
                    while remaining:
                        chunk = self.rfile.read(min(1 << 20, remaining))
                        if not chunk:
                            raise ValueError("truncated upload")
                        f.write(chunk)
                        remaining -= len(chunk)
                manifest = ForgePackage.read_manifest(staging)
                os.replace(staging, os.path.join(store_dir, fn))
            except Exception as e:  # noqa: BLE001 — report to client
                try:
                    os.unlink(staging)
                except OSError:
                    pass
                return self._json(400, {"error": f"rejected: {e}"})
            manifest["file"] = fn
            return self._json(200, manifest)

    return ThreadingHTTPServer((host, port), Handler)


def _http_error_detail(e) -> str:
    """Extract the server's JSON ``error`` field from an HTTPError."""
    try:
        return json.loads(e.read()).get("error", str(e))
    except Exception:  # noqa: BLE001 — best-effort detail
        return str(e)


def publish(pkg_path: str, url: str) -> Dict[str, Any]:
    """Upload a package to a forge server; returns its manifest.
    The body is streamed from disk (snapshots can be GBs)."""
    from urllib.error import HTTPError
    from urllib.request import Request, urlopen

    fn = _safe_pkg_name(os.path.basename(pkg_path))
    size = os.path.getsize(pkg_path)
    with open(pkg_path, "rb") as f:
        req = Request(f"{url.rstrip('/')}/forge/upload/{fn}", data=f,
                      headers={"Content-Type": "application/gzip",
                               "Content-Length": str(size)})
        try:
            with urlopen(req, timeout=60) as resp:
                return json.loads(resp.read())
        except HTTPError as e:
            raise RuntimeError(
                f"publish refused: {_http_error_detail(e)}") from e


def fetch(name: str, url: str, dest_dir: str = ".") -> str:
    """Download the newest package named ``name``; returns its path.
    Streamed to a staging file and manifest-validated before the final
    name appears — a failed download leaves nothing behind."""
    import shutil
    import tempfile
    from urllib.request import urlopen

    base = url.rstrip("/")
    with urlopen(f"{base}/forge/list", timeout=60) as resp:
        listing = json.loads(resp.read())
    matches = [m for m in listing if m.get("name") == name]
    if not matches:
        raise FileNotFoundError(
            f"no package named {name!r} on {url} "
            f"(available: {sorted({m.get('name') for m in listing})})")
    best = max(matches,
               key=lambda m: tuple(
                   int(p) if p.isdigit() else 0
                   for p in str(m.get("version", "0")).split(".")))
    # the listing's "file" field is SERVER-SUPPLIED: validate it before
    # it reaches os.path.join or the download URL, or a malicious forge
    # can answer "../../x.vpkg" and write outside dest_dir (mirrors the
    # server-side check on upload)
    fn = _safe_pkg_name(best["file"])
    os.makedirs(dest_dir, exist_ok=True)
    out_path = os.path.join(dest_dir, fn)
    fd, staging = tempfile.mkstemp(dir=dest_dir, prefix=".fetch-")
    f = os.fdopen(fd, "wb")  # own the fd before anything can raise
    try:
        with urlopen(f"{base}/forge/pkg/{fn}",
                     timeout=300) as r:
            shutil.copyfileobj(r, f)
        f.close()
        ForgePackage.read_manifest(staging)  # validate or raise
        os.replace(staging, out_path)
    except Exception:
        f.close()
        try:
            os.unlink(staging)
        except OSError:
            pass
        raise
    return out_path


def main(argv=None) -> int:
    import argparse
    import sys

    from veles_tpu.logger import setup_logging

    setup_logging()
    p = argparse.ArgumentParser(prog="veles_tpu.forge",
                                description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    pk = sub.add_parser("pack")
    pk.add_argument("out")
    pk.add_argument("workflow")
    pk.add_argument("configs", nargs="*")
    pk.add_argument("--name", required=True)
    pk.add_argument("--version", default="1.0.0")
    pk.add_argument("--author", default="")
    pk.add_argument("--description", default="")
    pk.add_argument("--snapshot", default=None)
    pi = sub.add_parser("info")
    pi.add_argument("pkg")
    ins = sub.add_parser("install")
    ins.add_argument("pkg")
    ins.add_argument("dest", nargs="?", default="forge_store")
    ls = sub.add_parser("list")
    ls.add_argument("store", nargs="?", default="forge_store")
    srv = sub.add_parser("serve")
    srv.add_argument("store", nargs="?", default="forge_store")
    srv.add_argument("--port", type=int, default=8188)
    srv.add_argument("--host", default="127.0.0.1",
                     help="interface to bind; the upload endpoint has "
                          "no auth, so exposing beyond loopback is an "
                          "explicit opt-in (e.g. --host 0.0.0.0)")
    pub = sub.add_parser("publish")
    pub.add_argument("pkg")
    pub.add_argument("url")
    ft = sub.add_parser("fetch")
    ft.add_argument("name")
    ft.add_argument("url")
    ft.add_argument("dest", nargs="?", default=".")
    args = p.parse_args(argv)

    if args.cmd == "pack":
        path = ForgePackage.pack(
            args.out, args.name, args.workflow, args.configs,
            snapshot=args.snapshot, version=args.version,
            author=args.author, description=args.description)
        print(path)
    elif args.cmd == "info":
        print(json.dumps(ForgePackage.read_manifest(args.pkg),
                         indent=2))
    elif args.cmd == "install":
        m = ForgePackage.install(args.pkg, args.dest)
        print(m["root"])
    elif args.cmd == "list":
        for m in ForgePackage.list_store(args.store):
            print(f"{m['file']}: {m['name']} {m['version']} "
                  f"({m.get('description', '')})")
    elif args.cmd == "serve":
        server = make_forge_server(args.store, args.port, args.host)
        print(f"forge marketplace on port "
              f"{server.server_address[1]}, store={args.store}")
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            server.shutdown()
    elif args.cmd == "publish":
        m = publish(args.pkg, args.url)
        print(f"published {m['file']}: {m['name']} {m['version']}")
    elif args.cmd == "fetch":
        print(fetch(args.name, args.url, args.dest))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
