"""Dropout.

Reference parity: veles/znicz/dropout.py — forward multiplies by a
Bernoulli mask drawn through the framework PRNG; backward applies the
same mask.  Inverted scaling (kept units scaled by 1/(1-p)) so eval
mode is the identity.  The fused TPU path threads a per-step
``jax.random`` key (stochastic=True); the numpy golden path draws from
the named 'dropout' stream.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from veles_tpu.ops.nn_units import ForwardUnit, GradientUnit


class Dropout(ForwardUnit):
    has_params = False
    stochastic = True

    def __init__(self, workflow=None, dropout_ratio: float = 0.5,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.dropout_ratio = dropout_ratio

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def param_shapes(self, input_shape):
        return {}

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        return {"output": inputs["input"]}  # eval mode: identity

    def apply_fwd(self, params, x, rng=None, train=True):
        if not train:
            return x, (x, None)
        keep = 1.0 - self.dropout_ratio
        if isinstance(x, np.ndarray):
            from veles_tpu import prng as prng_mod
            gen = prng_mod.get("dropout").numpy
            mask = (gen.random(x.shape) < keep).astype(np.float32) / keep
        else:
            import jax
            if rng is None:
                raise ValueError(f"{self.name}: traced train mode "
                                 "needs an rng key")
            mask = jax.random.bernoulli(rng, keep, x.shape) \
                .astype(x.dtype) / keep
        return x * mask, (x, mask)

    def eager_rng(self):
        if self.device is not None and self.device.is_jax:
            from veles_tpu import prng as prng_mod
            return prng_mod.get("dropout").next_key()
        return None


class GDDropout(GradientUnit):
    def backward_from_saved(self, params, saved, err_output):
        _x, mask = saved
        if mask is None:
            return err_output, {}
        return err_output * mask, {}
