"""ResizableAll2All: a dense layer whose output width can change
between training phases, preserving the already-learned columns.

Reference parity: veles/znicz/resizable_all2all.py (SURVEY.md §3.2
"RBM / other" row — reconstructed from the survey description,
UNVERIFIED against the reference mount, which is empty; SURVEY.md §0).
Upstream grows/shrinks a layer mid-experiment (e.g. widening a
bottleneck between runs, or the genetics tuner mutating layer sizes
without discarding a warm start).

TPU-first note: a resize changes parameter SHAPES, which invalidates
the fused runner's traced step and cached pytrees; ``resize`` calls
``workflow.fused.invalidate_trace()`` when one is installed, so the
next firing re-collects params and re-jits (a deliberate, explicit
recompile — dynamic shapes inside the trace would be far worse).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from veles_tpu.ops.all2all import All2All, GradientDescent


class ResizableAll2All(All2All):
    def resize(self, new_output: int) -> None:
        """Change the output width to ``new_output``.  Kept columns
        carry their trained values; new columns are freshly filled
        through the 'weights' PRNG stream."""
        new_output = int(new_output)
        old = self.neurons_number
        if new_output == old:
            return
        if new_output <= 0:
            raise ValueError(f"{self.name}: resize to {new_output}")
        # flush the fused runner's cached param pytree into the unit
        # Vectors BEFORE touching shapes — afterwards the stale cache
        # would overwrite the resized weights on its way out
        fused = getattr(self.workflow, "fused", None)
        if fused is not None:
            fused.invalidate_trace()
        old_w = self.weights.map_read() if self.weights else None
        old_b = self.bias.map_read() if self.bias else None
        self.output_sample_shape = (new_output,)
        if old_w is not None:
            in_shape = (0, old_w.shape[0])  # batch dim unused
            self.weights.reset()
            self.bias.reset()
            self.fill_params(in_shape)
            n_keep = min(old, new_output)
            w = self.weights.mem
            w[:, :n_keep] = old_w[:, :n_keep]
            if old_b is not None and self.bias:
                self.bias.mem[:n_keep] = old_b[:n_keep]
            self.weights.initialize(self.device)
            self.bias.initialize(self.device)
        if self.output:
            self.output.mem = np.zeros(
                (self.output.shape[0], new_output), np.float32)
            self.output.initialize(self.device)
        self.info("resized %s: %d -> %d outputs", self.name, old,
                  new_output)


class GDResizableAll2All(GradientDescent):
    """Standard dense backward; momentum buffers re-shape after a
    resize (reconcile_velocities — prefix history preserved), both on
    re-initialize and lazily when the fused runner re-collects."""

    def initialize(self, device=None, **kwargs: Any) -> None:
        self.reconcile_velocities()
        super().initialize(device=device, **kwargs)
