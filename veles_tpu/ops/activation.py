"""Standalone activation units (forward + backward pairs).

Reference parity: veles/znicz/activation.py — separate activation
units usable between any two layers: tanh, sigmoid, log (asinh-style),
strict relu (max(0,x)), relu (softplus ln(1+e^x) — the reference's
historic "RELU").  Param-less; one xp-agnostic implementation serves
both backends.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from veles_tpu.ops.nn_units import ForwardUnit, GradientUnit


def _xp(x):
    if isinstance(x, np.ndarray):
        return np
    import jax.numpy as jnp
    return jnp


class ActivationBase(ForwardUnit):
    has_params = False

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def param_shapes(self, input_shape):
        return {}

    def fwd(self, xp, x):
        raise NotImplementedError

    def bwd(self, xp, x, y, err_output):
        """dL/dx from (x, y, dL/dy)."""
        raise NotImplementedError

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        x = inputs["input"]
        return {"output": self.fwd(_xp(x), x)}


class ActivationTanh(ActivationBase):
    def fwd(self, xp, x):
        return xp.tanh(x)

    def bwd(self, xp, x, y, err_output):
        return err_output * (1.0 - y * y)


class ActivationSigmoid(ActivationBase):
    def fwd(self, xp, x):
        return 1.0 / (1.0 + xp.exp(-x))

    def bwd(self, xp, x, y, err_output):
        return err_output * y * (1.0 - y)


class ActivationStrictRELU(ActivationBase):
    """max(0, x) (reference: StrictRELU)."""

    def fwd(self, xp, x):
        return xp.maximum(x, 0)

    def bwd(self, xp, x, y, err_output):
        return err_output * (y > 0).astype(err_output.dtype)


class ActivationRELU(ActivationBase):
    """ln(1 + e^x) — softplus, the reference's historic 'RELU'."""

    def fwd(self, xp, x):
        return xp.log1p(xp.exp(-xp.abs(x))) + xp.maximum(x, 0)

    def bwd(self, xp, x, y, err_output):
        return err_output / (1.0 + xp.exp(-x))


class ActivationLog(ActivationBase):
    """ln(x + sqrt(x^2 + 1)) = asinh(x) (reference: activation.log)."""

    def fwd(self, xp, x):
        return xp.arcsinh(x)

    def bwd(self, xp, x, y, err_output):
        return err_output / xp.sqrt(x * x + 1.0)


class GDActivation(GradientUnit):
    def backward_from_saved(self, params, saved, err_output):
        x, y = saved
        return self.forward.bwd(_xp(err_output), x, y, err_output), {}
