"""Fully-connected (All2All) units.

Reference parity: veles/znicz/all2all.py — ``All2All`` (GEMM layer),
``All2AllTanh``, ``All2AllRELU``, ``All2AllSoftmax``; and
veles/znicz/gd.py — ``GradientDescent`` + per-activation variants.

TPU-first: the GEMM is ``x @ W`` with W of shape (n_input, n_output) —
a single MXU-friendly matmul; forward and backward are written against
the shared numpy/jax array API, so ONE implementation serves the numpy
golden path, per-unit jax execution, and the fused whole-step trace.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from veles_tpu.ops.nn_units import ForwardUnit, GradientUnit


def _flat(x: Any) -> Any:
    return x.reshape(x.shape[0], -1)


class All2All(ForwardUnit):
    """y = x @ W + b (linear)."""

    activation_mode = "linear"

    def __init__(self, workflow=None, output_sample_shape=None,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        if output_sample_shape is None:
            raise ValueError(f"{self.name}: output_sample_shape required")
        if isinstance(output_sample_shape, int):
            output_sample_shape = (output_sample_shape,)
        self.output_sample_shape = tuple(output_sample_shape)

    @property
    def neurons_number(self) -> int:
        return int(np.prod(self.output_sample_shape))

    def output_shape_for(self, input_shape: Tuple[int, ...]) \
            -> Tuple[int, ...]:
        return (input_shape[0],) + self.output_sample_shape

    def param_shapes(self, input_shape: Tuple[int, ...]):
        n_in = int(np.prod(input_shape[1:]))
        shapes = {"weights": (n_in, self.neurons_number)}
        if self.include_bias:
            shapes["bias"] = (self.neurons_number,)
        return shapes

    # -- compute -------------------------------------------------------

    def pre_activation(self, params, x):
        v = _flat(x) @ params["weights"]
        if "bias" in params:
            v = v + params["bias"]
        return v.reshape((x.shape[0],) + self.output_sample_shape)

    def activation(self, v):
        return v

    def apply(self, params, inputs, rng=None) -> Dict[str, Any]:
        return {"output": self.activation(
            self.pre_activation(params, inputs["input"]))}


class All2AllTanh(All2All):
    activation_mode = "tanh"

    def activation(self, v):
        if isinstance(v, np.ndarray):
            return np.tanh(v)
        import jax.numpy as jnp
        return jnp.tanh(v)


class All2AllRELU(All2All):
    activation_mode = "relu"

    def activation(self, v):
        if isinstance(v, np.ndarray):
            return np.maximum(v, 0)
        import jax.numpy as jnp
        return jnp.maximum(v, 0)


class All2AllSigmoid(All2All):
    """Sigmoid dense layer (the RBM family's deterministic sibling —
    a trained RBM's weights/hidden-bias drop straight into one of
    these for fine-tuning a stacked net)."""

    activation_mode = "sigmoid"

    def activation(self, v):
        if isinstance(v, np.ndarray):
            return 1.0 / (1.0 + np.exp(-v))
        import jax
        return jax.nn.sigmoid(v)


class All2AllSoftmax(All2All):
    """Softmax output layer.  ``activation_mode == 'softmax'`` tells the
    evaluator/GD contract that err_output already IS d loss/d logits
    (the softmax+cross-entropy fusion; reference: EvaluatorSoftmax +
    gd softmax variant)."""

    activation_mode = "softmax"

    def activation(self, v):
        if isinstance(v, np.ndarray):
            e = np.exp(v - v.max(axis=-1, keepdims=True))
            return e / e.sum(axis=-1, keepdims=True)
        import jax
        return jax.nn.softmax(v, axis=-1)


class GradientDescent(GradientUnit):
    """Backward + update for any All2All variant.  One array-API
    implementation serves numpy and jax (reference: veles/znicz/gd.py)."""

    can_skip_err_input = True

    def backward_from_saved(self, params, saved, err_output,
                            need_err_input=True):
        x, out = saved
        err_pre = self.act_deriv(out, err_output)
        err_pre_flat = _flat(err_pre)
        xf = _flat(x)
        grads = {"weights": xf.T @ err_pre_flat}
        if "bias" in params:
            grads["bias"] = err_pre_flat.sum(axis=0)
        if not need_err_input:
            return None, grads
        err_input = (err_pre_flat @ params["weights"].T).reshape(x.shape)
        return err_input, grads


# per-activation aliases (reference: gd.GDTanh, gd.GDRELU, gd.GDSoftmax)
GDTanh = GradientDescent
GDRELU = GradientDescent
GDSoftmax = GradientDescent
GDSigmoid = GradientDescent
