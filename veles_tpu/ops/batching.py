"""Shared fixed-shape batching machinery of the fused engines.

FusedStepRunner, EnsembleEvalEngine, and PopulationTrainEngine (and
now the Hive serving tier) all rely on the same three mechanical
ideas, which used to live as near-identical private helpers inside the
ever-growing ops/fused.py:

- **member stacking**: N param pytrees stacked along a leading MEMBER
  axis and uploaded once, so ``jax.vmap`` turns an N-member sweep into
  one dispatch;
- **fixed-shape chunk + validity mask**: every dispatch sees the SAME
  array shape (ragged tails are zero-padded and masked out of the
  math), so a jitted step compiles exactly once per step kind — the
  property the serving tier's zero-recompile steady state rests on;
- **compute-dtype resolution + pytree casting**: matmuls/convs run in
  the device's compute dtype (bf16 on TPU) against f32 master params;
  each engine resolves the dtype the same way and casts the same way.

This module is the single home for all three (a concrete down payment
on the ROADMAP's "unify the fused engines" item): the engines import
from here, behavior unchanged — pinned by their existing parity tests.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np


def resolve_compute_dtype(compute_dtype: Any, device: Any):
    """The jnp dtype an engine computes in: an explicit
    ``compute_dtype`` wins, else the device's policy (bf16 on TPU, f32
    elsewhere), else float32."""
    import jax.numpy as jnp
    cd = compute_dtype
    if cd is None and device is not None:
        cd = device.compute_dtype
    return jnp.dtype(cd) if cd is not None else jnp.float32


def make_caster(cd):
    """``cast(tree)`` mapping every f32 leaf to ``cd`` (identity when
    ``cd`` IS f32) — the mixed-precision entry every engine applies to
    its param pytree before the forward chain."""
    import jax
    import jax.numpy as jnp
    if cd == jnp.float32:
        return lambda tree: tree

    def cast(tree):
        return jax.tree_util.tree_map(
            lambda a: a.astype(cd) if a.dtype == jnp.float32 else a,
            tree)
    return cast


def stack_member_params(forwards: List[Any],
                        member_params: List[Dict[str, Dict[str, Any]]],
                        device: Any, put: Any = None
                        ) -> Dict[str, Dict[str, Any]]:
    """{fwd_name: {pname: (n_members, ...)}} — every member's f32
    params stacked along a leading MEMBER axis and uploaded once.
    Shared by the vmapped engines: EnsembleEvalEngine stacks N distinct
    trained members; PopulationTrainEngine stacks P copies of one init
    (same-signature genomes share the weight-init draw by seed); the
    Hive residency manager re-uploads a spilled model through it.
    ``put`` overrides the placement (default ``device.put``,
    replicated on a mesh) — the member-sharded cohort path passes a
    member-sharded placement so each device uploads P/N members."""
    putf = put if put is not None else device.put
    return {
        f.name: {
            pn: putf(np.stack(
                [np.asarray(m[f.name][pn], np.float32)
                 for m in member_params]))
            for pn in member_params[0][f.name]}
        for f in forwards}


def stacked_param_bytes(member_params:
                        List[Dict[str, Dict[str, Any]]]) -> int:
    """HBM bytes :func:`stack_member_params` will occupy for these
    members (f32) — the residency-budget accounting the serving tier's
    LRU spill decisions read, computed host-side BEFORE any upload."""
    total = 0
    for m in member_params:
        for p in m.values():
            for arr in p.values():
                total += int(np.prod(np.shape(arr))) * 4
    return total


def pad_chunk(xb: np.ndarray, lb: np.ndarray,
              chunk: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Fixed-shape (rows, labels) chunk + validity mask: the consuming
    jit compiles exactly once; padded rows carry mask 0 and cannot
    score."""
    mask = np.ones(chunk, np.float32)
    if len(xb) < chunk:
        pad = chunk - len(xb)
        mask[len(xb):] = 0.0
        xb = np.concatenate(
            [xb, np.zeros((pad,) + xb.shape[1:], xb.dtype)])
        lb = np.concatenate([lb, np.zeros(pad, lb.dtype)])
    return xb, lb, mask


def pad_rows(x: np.ndarray,
             chunk: int) -> Tuple[np.ndarray, np.ndarray]:
    """Label-less variant of :func:`pad_chunk` — the serving tier's
    micro-batch assembly: rows zero-padded to the fixed ``chunk``
    shape plus the validity mask (padded rows are discarded host-side
    after the dispatch)."""
    mask = np.ones(chunk, np.float32)
    if len(x) < chunk:
        pad = chunk - len(x)
        mask[len(x):] = 0.0
        x = np.concatenate(
            [x, np.zeros((pad,) + x.shape[1:], x.dtype)])
    return x, mask


def make_sharded_row_gather(mesh):
    """Traced ``gather(indices, *stores) -> rows per store`` over
    ROW-SHARDED resident stores (each device holds 1/N of the rows;
    ``parallel.mesh.put_row_sharded`` placement).  One store returns
    its gathered rows bare — the SOM epoch builders
    (``engine_core.build_som_epoch`` / ``build_som_eval``) consume
    that form directly, target-less as the SOM is; several (dataset +
    labels/targets) return a tuple, gathered with ONE shard_map.

    The gather is a ``shard_map`` local gather + psum assembly: every
    device looks the full (replicated) index vector up in its OWN
    shard, zeroes the rows it does not own, and the psum across the
    data axis assembles the full minibatch on every device.  Exactly
    one device contributes each row, so the reduction sums one real
    value with N-1 zeros — f32-EXACT by IEEE-754 (x + 0.0 == x),
    which is what lets sharded residency pin bitwise parity against
    the replicated-residency oracle.  Integer stores (uint8 quantized
    datasets, int32 labels) ride the psum as int32 — narrow-int
    collectives are not universally lowered — and cast back, which is
    exact for any byte/label value.

    Indices must reference REAL rows only (< R); the padded tile tail
    exists purely as placement filler, and the loaders' index
    machinery (np.resize padding + validity masks) never points at
    it."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec

    axis = mesh.axis_names[0]
    n = int(mesh.devices.size)

    def _assemble(local_store, loc, hit):
        x = jnp.take(local_store, loc, axis=0)
        x = jnp.where(
            hit.reshape(hit.shape + (1,) * (x.ndim - hit.ndim)),
            x, jnp.zeros((), x.dtype))
        if jnp.issubdtype(x.dtype, jnp.floating):
            return lax.psum(x, axis)
        return lax.psum(x.astype(jnp.int32), axis).astype(x.dtype)

    def gather(indices, *stores):
        rows_local = stores[0].shape[0] // n   # static at trace time

        def local(idx, *local_stores):
            lo = lax.axis_index(axis) * rows_local
            loc = jnp.clip(idx - lo, 0, rows_local - 1)
            hit = (idx >= lo) & (idx < lo + rows_local)
            return tuple(_assemble(s, loc, hit) for s in local_stores)

        spec = PartitionSpec(axis)
        out = shard_map(
            local, mesh=mesh,
            in_specs=(PartitionSpec(),) + (spec,) * len(stores),
            out_specs=(PartitionSpec(),) * len(stores),
            check_rep=False)(indices, *stores)
        return out[0] if len(stores) == 1 else out

    return gather


def pad_members(arrays: List[np.ndarray],
                multiple: int) -> Tuple[List[np.ndarray], int]:
    """Pad each array's leading MEMBER axis to a whole multiple of
    ``multiple`` by repeating the first member's row — the
    member-sharded cohort convention: padded members train harmlessly
    (identical math to member 0) and their fitness rows are sliced
    off before anything reads them.  Returns the padded arrays and
    the padded member count."""
    p = len(arrays[0])
    p_pad = -(-p // multiple) * multiple
    if p_pad == p:
        return list(arrays), p
    out = []
    for a in arrays:
        filler = np.repeat(a[:1], p_pad - p, axis=0)
        out.append(np.concatenate([a, filler], axis=0))
    return out, p_pad


def padded_index_chunk(start: int, stop: int, chunk: int
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Fixed-shape index window [start, stop) + validity mask for the
    resident gather paths (indices are padded with 0 — a valid row
    index — and masked out of the scoring math)."""
    idx = np.arange(start, stop, dtype=np.int32)
    mask = np.ones(chunk, np.float32)
    if len(idx) < chunk:
        mask[len(idx):] = 0.0
        idx = np.pad(idx, (0, chunk - len(idx)))
    return idx, mask
