"""Shared fixed-shape batching machinery of the fused engines.

FusedStepRunner, EnsembleEvalEngine, and PopulationTrainEngine (and
now the Hive serving tier) all rely on the same three mechanical
ideas, which used to live as near-identical private helpers inside the
ever-growing ops/fused.py:

- **member stacking**: N param pytrees stacked along a leading MEMBER
  axis and uploaded once, so ``jax.vmap`` turns an N-member sweep into
  one dispatch;
- **fixed-shape chunk + validity mask**: every dispatch sees the SAME
  array shape (ragged tails are zero-padded and masked out of the
  math), so a jitted step compiles exactly once per step kind — the
  property the serving tier's zero-recompile steady state rests on;
- **compute-dtype resolution + pytree casting**: matmuls/convs run in
  the device's compute dtype (bf16 on TPU) against f32 master params;
  each engine resolves the dtype the same way and casts the same way.

This module is the single home for all three (a concrete down payment
on the ROADMAP's "unify the fused engines" item): the engines import
from here, behavior unchanged — pinned by their existing parity tests.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np


def resolve_compute_dtype(compute_dtype: Any, device: Any):
    """The jnp dtype an engine computes in: an explicit
    ``compute_dtype`` wins, else the device's policy (bf16 on TPU, f32
    elsewhere), else float32."""
    import jax.numpy as jnp
    cd = compute_dtype
    if cd is None and device is not None:
        cd = device.compute_dtype
    return jnp.dtype(cd) if cd is not None else jnp.float32


def make_caster(cd):
    """``cast(tree)`` mapping every f32 leaf to ``cd`` (identity when
    ``cd`` IS f32) — the mixed-precision entry every engine applies to
    its param pytree before the forward chain."""
    import jax
    import jax.numpy as jnp
    if cd == jnp.float32:
        return lambda tree: tree

    def cast(tree):
        return jax.tree_util.tree_map(
            lambda a: a.astype(cd) if a.dtype == jnp.float32 else a,
            tree)
    return cast


def stack_member_params(forwards: List[Any],
                        member_params: List[Dict[str, Dict[str, Any]]],
                        device: Any) -> Dict[str, Dict[str, Any]]:
    """{fwd_name: {pname: (n_members, ...)}} — every member's f32
    params stacked along a leading MEMBER axis and uploaded once.
    Shared by the vmapped engines: EnsembleEvalEngine stacks N distinct
    trained members; PopulationTrainEngine stacks P copies of one init
    (same-signature genomes share the weight-init draw by seed); the
    Hive residency manager re-uploads a spilled model through it."""
    return {
        f.name: {
            pn: device.put(np.stack(
                [np.asarray(m[f.name][pn], np.float32)
                 for m in member_params]))
            for pn in member_params[0][f.name]}
        for f in forwards}


def stacked_param_bytes(member_params:
                        List[Dict[str, Dict[str, Any]]]) -> int:
    """HBM bytes :func:`stack_member_params` will occupy for these
    members (f32) — the residency-budget accounting the serving tier's
    LRU spill decisions read, computed host-side BEFORE any upload."""
    total = 0
    for m in member_params:
        for p in m.values():
            for arr in p.values():
                total += int(np.prod(np.shape(arr))) * 4
    return total


def pad_chunk(xb: np.ndarray, lb: np.ndarray,
              chunk: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Fixed-shape (rows, labels) chunk + validity mask: the consuming
    jit compiles exactly once; padded rows carry mask 0 and cannot
    score."""
    mask = np.ones(chunk, np.float32)
    if len(xb) < chunk:
        pad = chunk - len(xb)
        mask[len(xb):] = 0.0
        xb = np.concatenate(
            [xb, np.zeros((pad,) + xb.shape[1:], xb.dtype)])
        lb = np.concatenate([lb, np.zeros(pad, lb.dtype)])
    return xb, lb, mask


def pad_rows(x: np.ndarray,
             chunk: int) -> Tuple[np.ndarray, np.ndarray]:
    """Label-less variant of :func:`pad_chunk` — the serving tier's
    micro-batch assembly: rows zero-padded to the fixed ``chunk``
    shape plus the validity mask (padded rows are discarded host-side
    after the dispatch)."""
    mask = np.ones(chunk, np.float32)
    if len(x) < chunk:
        pad = chunk - len(x)
        mask[len(x):] = 0.0
        x = np.concatenate(
            [x, np.zeros((pad,) + x.shape[1:], x.dtype)])
    return x, mask


def padded_index_chunk(start: int, stop: int, chunk: int
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Fixed-shape index window [start, stop) + validity mask for the
    resident gather paths (indices are padded with 0 — a valid row
    index — and masked out of the scoring math)."""
    idx = np.arange(start, stop, dtype=np.int32)
    mask = np.ones(chunk, np.float32)
    if len(idx) < chunk:
        mask[len(idx):] = 0.0
        idx = np.pad(idx, (0, chunk - len(idx)))
    return idx, mask
