"""Pallas TPU kernels for LRN (AlexNet's local response normalization).

Why a hand kernel when the banded-matmul XLA form (ops/lrn.py) already
rides the MXU: LRN is pure memory traffic — the op reads/writes the
largest activations in the network — and XLA still materializes the
windowed sum, the saved ``den`` residual, and the backward's regathered
intermediates as separate HBM round trips.  These kernels do the whole
op in ONE VMEM pass each way:

- forward: read x -> x^2 -> banded matmul (MXU) -> k + alpha*s ->
  rsqrt chain (zero transcendentals for beta=3/4) -> write y.  The
  ONLY residual is x itself (which the scan already has): ``den`` is
  never stored.
- backward: read x and err -> recompute den with the same tiny matmul
  (MXU FLOPs are free here; HBM bytes are not) -> err_input in one
  write.

HBM traffic drops from ~8 array passes (fwd materialize + den
store/load + bwd regather) to 5 (x, y | x, err, err_input).

The channel window always lives entirely inside a tile: tiles span the
full channel axis (C <= 256 in every real config) and rows are
independent, so the grid only splits rows.  Rows per tile are chosen as
a divisor of the row count — no padding pass, no masked tail.

Reference parity: veles/znicz/normalization.py semantics, same formula
as ops/lrn.py (whose numpy shifted-adds path remains the independent
oracle; tests/test_ops.py compares the three implementations).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import numpy as np


def available() -> bool:
    try:
        from jax.experimental import pallas  # noqa: F401
        from jax.experimental.pallas import tpu  # noqa: F401
        return True
    except Exception:  # noqa: BLE001 — no pallas on this jax build
        return False


def _band(c: int, n: int, transpose: bool = False) -> np.ndarray:
    """The window matrix — shared single source with the XLA form
    (ops/lrn.py band_matrix; the parity-sensitive tap convention must
    never live in two places)."""
    from veles_tpu.ops.lrn import band_matrix
    return band_matrix(c, n, transpose)


#: VMEM bytes one f32 (rows, C) working buffer may occupy; the
#: kernels keep ~5 live plus pallas's own block double-buffers
_TILE_BUDGET = 512 * 1024


def _tile_rows(n_rows: int, c: int) -> Optional[int]:
    """Rows per VMEM tile: a divisor of n_rows, multiple of 8 (f32
    sublane), sized so the kernel's ~6 live f32 (rows, C) buffers stay
    well under VMEM.  None = no usable divisor; caller falls back."""
    budget = max(8, _TILE_BUDGET // (4 * c) // 8 * 8)
    t = min(n_rows, budget)
    t -= t % 8
    while t >= 8:
        if n_rows % t == 0:
            return t
        t -= 8
    return None


def usable(shape, n: int, beta: float) -> bool:
    """True when these kernels implement this config: beta=3/4 (the
    rsqrt chain; every real config), channels last and small enough
    that a full-channel tile fits VMEM, and the row count tiles."""
    if beta != 0.75 or len(shape) < 2:
        return False
    c = shape[-1]
    n_rows = int(np.prod(shape[:-1]))
    return 0 < n <= c <= 1024 and _tile_rows(n_rows, c) is not None


def _fwd_kernel(x_ref, band_ref, y_ref, *, k, alpha):
    import jax
    import jax.numpy as jnp
    x = x_ref[:]
    # the dot stays in the INPUT dtype (bf16 on TPU) with f32
    # accumulation — the MXU's native mode and exactly what the XLA
    # banded form computes; an f32 x f32 matmul is several times
    # slower and was the whole kernel's bottleneck
    s = jnp.dot(x * x, band_ref[:],
                preferred_element_type=jnp.float32)
    r = jax.lax.rsqrt(k + alpha * s)
    y_ref[:] = (x.astype(jnp.float32)
                * (r * jnp.sqrt(r))).astype(y_ref.dtype)


def _bwd_kernel(x_ref, err_ref, band_ref, bandt_ref, out_ref,
                *, k, alpha):
    import jax
    import jax.numpy as jnp
    x = x_ref[:]
    e = err_ref[:]
    s = jnp.dot(x * x, band_ref[:],
                preferred_element_type=jnp.float32)
    xf = x.astype(jnp.float32)
    ef = e.astype(jnp.float32)
    r = jax.lax.rsqrt(k + alpha * s)       # den^-0.5
    d = r * jnp.sqrt(r)                    # den^-0.75
    t = ef * xf * (d * r * r)              # err * x * den^-1.75
    wt = jnp.dot(t.astype(x.dtype), bandt_ref[:],
                 preferred_element_type=jnp.float32)
    out = ef * d - (2.0 * alpha * 0.75) * xf * wt
    out_ref[:] = out.astype(out_ref.dtype)


@functools.lru_cache(maxsize=None)
def _specs(n_rows: int, c: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    tile = _tile_rows(n_rows, c)
    row_spec = pl.BlockSpec((tile, c), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    band_spec = pl.BlockSpec((c, c), lambda i: (0, 0),
                             memory_space=pltpu.VMEM)
    return n_rows // tile, row_spec, band_spec


def lrn_fwd(x: Any, n: int, k: float, alpha: float,
            interpret: bool = False) -> Any:
    """y = x * (k + alpha * window_sum(x^2)) ** -0.75, one VMEM pass."""
    import jax
    from jax.experimental import pallas as pl
    c = x.shape[-1]
    xr = x.reshape(-1, c)
    grid, row_spec, band_spec = _specs(xr.shape[0], c)
    y = pl.pallas_call(
        functools.partial(_fwd_kernel, k=float(k), alpha=float(alpha)),
        out_shape=jax.ShapeDtypeStruct(xr.shape, x.dtype),
        grid=(grid,),
        in_specs=[row_spec, band_spec],
        out_specs=row_spec,
        interpret=interpret,
    )(xr, _band(c, n).astype(x.dtype))  # 0/1 taps: exact in bf16
    return y.reshape(x.shape)


def lrn_bwd(x: Any, err_output: Any, n: int, k: float, alpha: float,
            interpret: bool = False) -> Any:
    """err_input for the forward above, recomputing den in-kernel
    instead of loading a stored residual."""
    import jax
    from jax.experimental import pallas as pl
    c = x.shape[-1]
    xr = x.reshape(-1, c)
    er = err_output.reshape(-1, c)
    grid, row_spec, band_spec = _specs(xr.shape[0], c)
    band = _band(c, n)
    out = pl.pallas_call(
        functools.partial(_bwd_kernel, k=float(k), alpha=float(alpha)),
        out_shape=jax.ShapeDtypeStruct(xr.shape, err_output.dtype),
        grid=(grid,),
        in_specs=[row_spec, row_spec, band_spec, band_spec],
        out_specs=row_spec,
        interpret=interpret,
    )(xr, er, band.astype(x.dtype),
      _band(c, n, transpose=True).astype(x.dtype))
    return out.reshape(err_output.shape)
